"""Release tooling tests: image inventory, command rendering, and the
build->push->manifest DAG run hermetically with a recording runner."""

import json
import os

from kubeflow_tpu.release import IMAGES, ImageSpec, build_commands, release_workflow
from kubeflow_tpu.release.releaser import image_ref, push_commands


def test_image_inventory_files_exist():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for spec in IMAGES:
        assert os.path.exists(os.path.join(repo, spec.context, spec.dockerfile)), spec


def test_build_command_rendering():
    spec = ImageSpec("jax-notebook-tpu", ".", "images/notebook/Dockerfile",
                     (("JAX_EXTRA", "tpu"),))
    [cmd] = build_commands(spec, "gcr.io/kf-tpu", "v1")
    assert cmd[:4] == ["docker", "build", "-t", "gcr.io/kf-tpu/jax-notebook-tpu:v1"]
    assert "--build-arg" in cmd and "JAX_EXTRA=tpu" in cmd
    assert cmd[-1] == "."
    [push] = push_commands(spec, "gcr.io/kf-tpu", "v1")
    assert push == ["docker", "push", "gcr.io/kf-tpu/jax-notebook-tpu:v1"]


def test_release_workflow_dag(tmp_path):
    ran = []
    wf = release_workflow("reg.local/kf", "v0", runner=ran.append,
                          artifacts_dir=str(tmp_path))
    res = wf.run()
    assert res.succeeded, {k: s.error for k, s in res.steps.items()}
    builds = [c for c in ran if c[1] == "build"]
    pushes = [c for c in ran if c[1] == "push"]
    assert len(builds) == len(IMAGES) and len(pushes) == len(IMAGES)
    # every push happens after its build (ran list is append-ordered)
    for spec in IMAGES:
        ref = image_ref(spec, "reg.local/kf", "v0")
        b = next(i for i, c in enumerate(ran) if c[1] == "build" and ref in c)
        p = next(i for i, c in enumerate(ran) if c[1] == "push" and ref in c)
        assert b < p
    manifest = json.load(open(tmp_path / "release-v0.json"))
    assert len(manifest["images"]) == len(IMAGES)


def test_release_workflow_build_failure_skips_push(tmp_path):
    def runner(cmd):
        if cmd[1] == "build" and "jaxrt" in cmd[3]:
            raise RuntimeError("build broke")

    wf = release_workflow("reg.local/kf", "v0", runner=runner,
                          artifacts_dir=str(tmp_path))
    res = wf.run()
    assert not res.succeeded
    assert res.steps["build-jaxrt"].status == "Failed"
    assert res.steps["push-jaxrt"].status == "Skipped"
    assert res.steps["release-manifest"].status == "Skipped"
    assert res.steps["push-platform"].status == "Succeeded"


class TestMirror:
    """release/mirror.py — the hubsync analogue (reference:
    releasing/hubsync/hubsync.py:1 GCR->DockerHub sync)."""

    SPECS = (ImageSpec("app", ".", "Dockerfile", ()),
             ImageSpec("web", ".", "Dockerfile", ()))

    def test_mirror_commands_triplet(self):
        from kubeflow_tpu.release.mirror import mirror_commands

        cmds = mirror_commands(self.SPECS[0], "gcr.io/kf", "docker.io/kf", "v1")
        assert cmds == [
            ["docker", "pull", "gcr.io/kf/app:v1"],
            ["docker", "tag", "gcr.io/kf/app:v1", "docker.io/kf/app:v1"],
            ["docker", "push", "docker.io/kf/app:v1"],
        ]

    def test_mirror_skips_destination_fresh_images(self):
        from kubeflow_tpu.release.mirror import mirror

        # app already mirrored (same digest both sides); web missing
        digests = {"gcr.io/kf/app:v1": "d1", "docker.io/kf/app:v1": "d1",
                   "gcr.io/kf/web:v1": "d2"}
        ran = []
        out = mirror("gcr.io/kf", "docker.io/kf", "v1", images=self.SPECS,
                     runner=ran.append, probe=digests.get)
        assert out == {"mirrored": ["docker.io/kf/web:v1"],
                       "skipped": ["docker.io/kf/app:v1"]}
        assert [c[1] for c in ran] == ["pull", "tag", "push"]

    def test_mirror_resyncs_on_digest_mismatch(self):
        from kubeflow_tpu.release.mirror import mirror

        digests = {"gcr.io/kf/app:v1": "d1", "docker.io/kf/app:v1": "STALE"}
        ran = []
        out = mirror("gcr.io/kf", "docker.io/kf", "v1",
                     images=self.SPECS[:1], runner=ran.append,
                     probe=digests.get)
        assert out["mirrored"] == ["docker.io/kf/app:v1"]
        assert len(ran) == 3

    def test_mirror_workflow_dag(self):
        from kubeflow_tpu.release.mirror import mirror_workflow

        ran = []
        wf = mirror_workflow("gcr.io/kf", "docker.io/kf", "v1",
                             images=self.SPECS, runner=ran.append,
                             probe=lambda ref: None)
        res = wf.run()
        assert all(s.status == "Succeeded" for s in res.steps.values())
        assert res.steps["mirror-summary"].output["images"] == [
            "docker.io/kf/app:v1", "docker.io/kf/web:v1"]
        # one pull/tag/push triplet per image
        assert sorted(c[1] for c in ran) == sorted(
            ["pull", "tag", "push"] * 2)

    def test_default_probe_extracts_content_digest(self, monkeypatch):
        """The digest must be the registry-independent Descriptor digest
        — hashing the raw verbose output would embed the queried Ref and
        the destination-fresh skip would never fire across registries."""
        import json as _json
        import subprocess as _sp

        from kubeflow_tpu.release import mirror as M

        def fake_run(cmd, capture_output=True, text=True):
            ref = cmd[-1]

            class R:
                returncode = 0
                stdout = _json.dumps({
                    "Ref": ref,  # differs per registry — must be ignored
                    "Descriptor": {"digest": "sha256:abc"},
                })
            return R()

        monkeypatch.setattr(_sp, "run", fake_run)
        assert (M._default_probe("gcr.io/kf/app:v1")
                == M._default_probe("docker.io/kf/app:v1")
                == "sha256:abc")
