"""Parallelism primitives: meshes, shardings, distributed bootstrap.

TPU-native replacement for the reference's parallelism matrix (SURVEY.md
§2.5): parameter-server data parallelism and MPI/NCCL allreduce become XLA
collectives over ICI, compiled into the step function by GSPMD.

Re-exports are lazy (PEP 562): `mesh` imports jax/numpy at module top,
but the control plane (which runs in a jax-free image) only needs
`parallel.dist` — `from kubeflow_tpu.parallel import dist` must not drag
jax in. Tests pin this invariant (test_dist.py).
"""

_MESH_NAMES = {"AXIS_DATA", "AXIS_DCN", "AXIS_EXPERT", "AXIS_FSDP",
               "AXIS_MODEL", "AXIS_PIPELINE", "AXIS_SEQ", "BATCH_AXES",
               "MeshSpec", "build_mesh"}
_DIST_NAMES = {"DistConfig", "initialize_from_env", "is_coordinator",
               "slice_env"}

__all__ = sorted(_MESH_NAMES | _DIST_NAMES)


def __getattr__(name: str):
    if name in _MESH_NAMES:
        from kubeflow_tpu.parallel import mesh

        return getattr(mesh, name)
    if name in _DIST_NAMES:
        from kubeflow_tpu.parallel import dist

        return getattr(dist, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
