"""tpulint resource-lifecycle rules (RES7xx): acquire/release pairing
over the exception-edge CFG (analysis/cfg.py).

The fleet's worst real bugs were lifecycle leaks on exceptional paths:
the router shed-race double-enqueue (PR 8) and the KV over-admission
that ``fail_all``-ed every in-flight request (PR 9) both put an
acquire and its release on the same happy path and leaked when a
``raise`` landed between them. The RES family makes the pairing a
static property, per real resource:

- **RES701** KV pages: ``PageAllocator.admit`` (the COW-copy plan
  included) must be balanced by ``free``/``reset`` on every path out
  of the acquiring function, or the admission plan must be handed to
  an owner.
- **RES702** router tickets: a ``submit`` that returns a ticket the
  caller keeps must ``complete``/``fail`` it (or hand it off) on
  every path — the token-accounting ledger leaks otherwise.
- **RES703** capacity transactions: every ``CapacityTxn.fork`` must
  ``commit``/``rollback`` (or escape to an owner); a trial fork
  dropped on a raise silently diverges the planner's ledger from the
  parent's.
- **RES704** detached spans: ``Tracer.begin`` must reach ``finish``
  (span passed as the argument) or be stored/handed off — a dropped
  span never exports and orphans its children.
- **RES705** manual locks: ``.acquire()`` on a lock-ish receiver with
  ``.release()`` missing on SOME path out — the CFG upgrade of
  LOCK201's statement-level model (``with`` blocks are inherently
  balanced and never flagged).

Ownership model (RacerD-flavored, resolution-bounded): the token dies
when it is released (receiver-paired call, or the bound variable
passed to a release method — ``tracer.finish(span)``), escapes
(returned, yielded, stored into an attribute/container), or is handed
off — passed bare to an unresolvable call (benefit of the doubt) or
to a program function whose **summary** says it consumes that
parameter (releases/stores/returns it, a bounded call-graph
fixpoint). A resolved callee that does NOT consume the argument keeps
the token live — ``self._log(ticket)`` is not a release. Publishing
ownership to a keyed table ALSO kills: when the acquire call's first
bare-Name positional argument is the resource's key (``plan =
alloc.admit(slot, ...)``), a later ``owners[slot] = ...`` store hands
the slot to whatever owns that table — the canonical serving-plane
idiom for transferring a page to the decode batch. Kills apply
before exception edges (a release that throws has still released);
the acquire's own exception edge carries no token (if ``admit``
raised, nothing was admitted).

Findings land on the acquire line; the message names the first
leaking exit. Fix by releasing in ``finally``/the handler, or by
handing the token to an owning helper — suppress only with an audited
justification (HYG004 keeps it honest).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterator

from kubeflow_tpu.analysis import cfg
from kubeflow_tpu.analysis.core import (
    Finding, ProgramRule, call_name, dotted, register,
)

# Container-mutator names: passing the token bare into one of these
# stores it somewhere that outlives the function — ownership transfer.
_SINKS = {"append", "appendleft", "add", "put", "put_nowait", "push",
          "heappush", "insert", "setdefault", "extend", "update",
          "send", "publish", "record", "enqueue"}

_FIXPOINT_CAP = 32


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    rule: str
    noun: str                      # human name for the resource
    classes: frozenset             # receiver class simple names
    recv_re: re.Pattern            # receiver-name fallback (last part)
    acquire: frozenset
    release: frozenset
    value_bound: bool              # the call RESULT is the handle
    track_discarded: bool          # flag `recv.acquire(...)` w/o binding
    hint: str


_SPECS = (
    ResourceSpec(
        "RES701", "KV page admission",
        frozenset({"PageAllocator"}), re.compile(r"alloc"),
        frozenset({"admit"}), frozenset({"free", "reset"}),
        value_bound=True, track_discarded=True,
        hint="free the slot (or reset) in a finally/handler, or hand "
             "the plan to the owner that frees on completion"),
    ResourceSpec(
        "RES702", "router ticket",
        frozenset({"TokenRouter"}), re.compile(r"router"),
        frozenset({"submit"}), frozenset({"complete", "fail", "shed"}),
        value_bound=True, track_discarded=False,
        hint="complete/fail the ticket in a finally/handler, or hand "
             "it to the queue that owns its lifecycle"),
    ResourceSpec(
        "RES703", "capacity transaction fork",
        frozenset({"CapacityTxn"}), re.compile(r"txn|trial|credits"),
        frozenset({"fork"}), frozenset({"commit", "rollback"}),
        value_bound=True, track_discarded=True,
        hint="commit or rollback the fork on every path (rollback in "
             "a handler), or return it to the caller that owns it"),
    ResourceSpec(
        "RES704", "detached span",
        frozenset({"Tracer"}), re.compile(r"tracer"),
        frozenset({"begin"}), frozenset({"finish"}),
        value_bound=True, track_discarded=True,
        hint="finish the span in a finally, store it where the "
             "finisher finds it, or use the tracer.span context "
             "manager"),
)

_LOCK_SPEC = ResourceSpec(
    "RES705", "lock",
    frozenset(), re.compile(r"lock|mutex|cond|(^|_)(mu|cv)$"),
    frozenset({"acquire"}), frozenset({"release"}),
    value_bound=False, track_discarded=True,
    hint="release in a finally, or use `with` which is inherently "
         "balanced")


@dataclasses.dataclass
class _Token:
    tid: int
    node: int                      # CFG node index of the acquire
    var: str | None                # bound variable, if any
    recv: str                      # receiver dotted text ("self.alloc")
    meth: str
    line: int
    col: int
    key: str | None = None         # first bare-Name positional arg of
                                   # the acquire call — `t[key] = ...`
                                   # publishes ownership (kill)


# -- per-program caches ------------------------------------------------------

def _cache(program) -> dict:
    got = getattr(program, "_res_cache", None)
    if got is None:
        got = {"cfg": {}, "consumed": None}
        program._res_cache = got
    return got


def _cfg_for(program, qual: str) -> cfg.CFG:
    table = _cache(program)["cfg"]
    if qual not in table:
        table[qual] = cfg.build_cfg(program.functions[qual].node)
    return table[qual]


# -- consumption summaries ---------------------------------------------------

_ALL_RELEASE = frozenset().union(*(s.release for s in _SPECS),
                                 _LOCK_SPEC.release)


def _bare_args(call: ast.Call) -> list[str]:
    out = [a.id for a in call.args if isinstance(a, ast.Name)]
    out += [kw.value.id for kw in call.keywords
            if isinstance(kw.value, ast.Name)]
    return out


def _param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


def _arg_param_pairs(call: ast.Call, callee_fi) -> Iterator[tuple[str, str]]:
    """(bare-arg-name, callee-param-name) pairs, positional + keyword.
    Method calls through a receiver skip the callee's ``self``."""
    params = _param_names(callee_fi.node)
    skip = 1 if (callee_fi.owner is not None and params
                 and params[0] in ("self", "cls")
                 and isinstance(call.func, ast.Attribute)) else 0
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Name) and i + skip < len(params):
            yield a.id, params[i + skip]
    for kw in call.keywords:
        if isinstance(kw.value, ast.Name) and kw.arg in params:
            yield kw.value.id, kw.arg


def _directly_consumed(fi) -> set[str]:
    """Params this function releases/escapes without looking at
    callees (the seed facts of the fixpoint)."""
    params = set(_param_names(fi.node))
    out: set[str] = set()
    for node in ast.walk(fi.node):
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            v = node.value
            if v is not None:
                out |= {s.id for s in ast.walk(v)
                        if isinstance(s, ast.Name) and s.id in params}
        elif isinstance(node, ast.Assign):
            stored = any(isinstance(t, (ast.Attribute, ast.Subscript))
                         for t in node.targets)
            if stored:
                out |= {s.id for s in ast.walk(node.value)
                        if isinstance(s, ast.Name) and s.id in params}
        elif isinstance(node, ast.Call):
            name = call_name(node)
            meth = name.rsplit(".", 1)[-1] if name else ""
            if meth in _ALL_RELEASE or meth in _SINKS:
                out |= set(_bare_args(node)) & params
            if meth in _ALL_RELEASE and name and "." in name:
                recv = name.rsplit(".", 1)[0]
                if recv in params:
                    out.add(recv)  # e.g. def done(txn): txn.commit()
    return out


def _consumed(program) -> dict[str, frozenset]:
    """qual -> params the function consumes (releases/escapes/hands
    off), propagated through resolved calls — bounded union fixpoint
    in the style of ``Program.may_held``."""
    cache = _cache(program)
    if cache["consumed"] is not None:
        return cache["consumed"]
    consumed: dict[str, set[str]] = {}
    passes: dict[str, list[tuple[ast.Call, str]]] = {}
    for qual, fi in program.functions.items():
        consumed[qual] = _directly_consumed(fi)
        params = set(_param_names(fi.node))
        fwd: list[tuple[ast.Call, str]] = []
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call) and (
                    set(_bare_args(node)) & params):
                callee = program._resolve_call(node, fi)
                if callee is None:
                    # handoff into the unknown: benefit of the doubt
                    consumed[qual] |= set(_bare_args(node)) & params
                else:
                    fwd.append((node, callee))
        if fwd:
            passes[qual] = fwd
    for _ in range(_FIXPOINT_CAP):
        changed = False
        for qual, fwd in passes.items():
            params = set(_param_names(program.functions[qual].node))
            for call, callee in fwd:
                sink = consumed.get(callee, set())
                for arg, param in _arg_param_pairs(
                        call, program.functions[callee]):
                    if arg in params and param in sink \
                            and arg not in consumed[qual]:
                        consumed[qual].add(arg)
                        changed = True
        if not changed:
            break
    out = {q: frozenset(s) for q, s in consumed.items()}
    cache["consumed"] = out
    return out


# -- the engine --------------------------------------------------------------

def _receiver_matches(spec: ResourceSpec, recv: str, fi, program) -> bool:
    parts = recv.split(".")
    if parts[0] in fi.param_classes:
        cq = fi.param_classes[parts[0]]
        if len(parts) == 1:
            if cq.rsplit(":", 1)[-1] in spec.classes:
                return True
        elif len(parts) == 2:
            aq = program.classes[cq].attr_classes.get(parts[1])
            if aq and aq.rsplit(":", 1)[-1] in spec.classes:
                return True
    last = parts[-1]
    return last != "self" and bool(spec.recv_re.search(last))


def _acquire_tokens(spec: ResourceSpec, fi, graph: cfg.CFG,
                    program) -> list[_Token]:
    tokens: list[_Token] = []
    for n in graph.stmt_nodes():
        stmt = n.stmt
        if isinstance(stmt, ast.Assign):
            value, targets = stmt.value, stmt.targets
        elif isinstance(stmt, ast.Expr):
            value, targets = stmt.value, None
        else:
            continue
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr in spec.acquire):
            continue
        recv = dotted(value.func.value)
        if recv is None or not _receiver_matches(spec, recv, fi, program):
            continue
        var: str | None = None
        if targets is not None:
            if len(targets) != 1:
                continue
            t = targets[0]
            if isinstance(t, ast.Name):
                var = t.id
            else:
                continue  # self.x = acquire(): escaped at birth
        elif spec.value_bound and not spec.track_discarded:
            continue  # discarded result: the callee owns it
        key = (value.args[0].id if value.args
               and isinstance(value.args[0], ast.Name) else None)
        tokens.append(_Token(len(tokens), n.idx, var, recv,
                             value.func.attr, stmt.lineno,
                             stmt.col_offset, key))
    return tokens


def _node_kills(spec: ResourceSpec, tokens: list[_Token],
                stmt: ast.stmt, node_idx: int, fi, program,
                consumed: dict[str, frozenset]) -> frozenset:
    killed: set[int] = set()
    for t in tokens:
        if t.tid in killed:
            continue
        if _stmt_kills(spec, t, stmt, node_idx, fi, program, consumed):
            killed.add(t.tid)
    return frozenset(killed)


def _own_exprs(stmt: ast.stmt) -> list[ast.expr]:
    """The expressions a CFG node itself evaluates. A compound
    statement's node is its HEADER — a release inside its body belongs
    to the body's own nodes, never to the branch point (walking the
    whole ``ast.If`` would kill the token on both arms at once)."""
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    if isinstance(stmt, ast.Assert):
        return [stmt.test] + ([stmt.msg] if stmt.msg is not None else [])
    return cfg._header_exprs(stmt)


def _stmt_kills(spec: ResourceSpec, t: _Token, stmt: ast.stmt,
                node_idx: int, fi, program,
                consumed: dict[str, frozenset]) -> bool:
    for call in (c for root in _own_exprs(stmt)
                 for c in ast.walk(root)):
        if not isinstance(call, ast.Call):
            continue
        name = call_name(call)
        meth = name.rsplit(".", 1)[-1] if name else ""
        recv = name.rsplit(".", 1)[0] if name and "." in name else None
        bare = _bare_args(call) if spec.value_bound and t.var else []
        if meth in spec.release and (recv == t.recv
                                     or (t.var and recv == t.var)
                                     or (t.var and t.var in bare)):
            # released: paired with the acquiring receiver, called ON
            # the handle itself (`trial.commit()`), or the handle is
            # the bare argument (`tracer.finish(span)`)
            return True
        if t.var and t.var in bare:
            if meth in _SINKS:
                return True  # stored into a container/queue: escaped
            callee = program._resolve_call(call, fi)
            if callee is None:
                return True  # handoff into the unknown
            sink = consumed.get(callee, frozenset())
            for arg, param in _arg_param_pairs(
                    call, program.functions[callee]):
                if arg == t.var and param in sink:
                    return True
    if t.key and isinstance(stmt, ast.Assign) and any(
            isinstance(tt, ast.Subscript)
            and isinstance(tt.slice, ast.Name)
            and tt.slice.id == t.key
            for tt in stmt.targets):
        return True  # `owners[slot] = ...`: ownership published under
                     # the resource's own key (discarded results too)
    if not (spec.value_bound and t.var):
        return False
    if isinstance(stmt, (ast.Return, ast.Expr)):
        v = stmt.value
        if isinstance(v, (ast.Yield, ast.YieldFrom)):
            v = v.value
        if isinstance(stmt, ast.Expr) and not isinstance(
                stmt.value, (ast.Yield, ast.YieldFrom)):
            v = None
        if v is not None and any(
                isinstance(s, ast.Name) and s.id == t.var
                for s in ast.walk(v)):
            return True  # returned/yielded: the caller owns it now
    if isinstance(stmt, ast.Assign):
        if any(isinstance(tt, (ast.Attribute, ast.Subscript))
               for tt in stmt.targets) and any(
                isinstance(s, ast.Name) and s.id == t.var
                for s in ast.walk(stmt.value)):
            return True  # stored into an attribute/container: escaped
        if node_idx != t.node and any(
                isinstance(tt, ast.Name) and tt.id == t.var
                for tt in stmt.targets):
            return True  # rebound: this token's binding is gone
    return False


_EXIT_DESC = {"return": "a return", "end": "the fall-through exit",
              "exc": "an exception path", "raise": "a raise",
              "break": "a break", "loop": "a loop back-edge"}


def _function_findings(spec: ResourceSpec, program, qual: str,
                       consumed: dict[str, frozenset]
                       ) -> Iterator[Finding]:
    fi = program.functions[qual]
    graph = _cfg_for(program, qual)
    tokens = _acquire_tokens(spec, fi, graph, program)
    if not tokens:
        return
    gen: dict[int, frozenset] = {}
    for t in tokens:
        gen.setdefault(t.node, frozenset())
        gen[t.node] = gen[t.node] | {t.tid}
    kill = {n.idx: _node_kills(spec, tokens, n.stmt, n.idx, fi,
                               program, consumed)
            for n in graph.stmt_nodes()}
    kill = {i: k for i, k in kill.items() if k}
    ins = cfg.solve_forward(graph, gen, kill)
    leaks: dict[int, list[tuple[str, int]]] = {}
    for edge, fact in cfg.exit_facts(graph, ins, gen, kill):
        for tid in fact:
            leaks.setdefault(tid, []).append(
                (edge.kind, graph.nodes[edge.src].line))
    for t in tokens:
        exits = leaks.get(t.tid)
        if not exits:
            continue
        exc = sorted(x for x in exits if x[0] in cfg.EXIT_EXC)
        pick = exc[0] if exc else sorted(exits)[0]
        kind, line = pick
        handle = f"`{t.var}`" if t.var else f"the {t.recv}.{t.meth}() result"
        yield Finding(
            spec.rule, fi.module.path, t.line, t.col,
            f"{spec.noun} {handle} acquired by {t.recv}.{t.meth}() can "
            f"escape unreleased via {_EXIT_DESC.get(kind, kind)} "
            f"(exit at line {line}): {spec.hint}")


def _spec_findings(spec: ResourceSpec, program) -> Iterator[Finding]:
    consumed = _consumed(program)
    probes = tuple(f".{m}(" for m in spec.acquire)
    for qual in sorted(program.functions):
        fi = program.functions[qual]
        if not any(p in fi.module.source for p in probes):
            continue
        yield from _function_findings(spec, program, qual, consumed)


def _make_rule(spec: ResourceSpec, doc: str):
    @register
    class _ResourceRule(ProgramRule):
        id = spec.rule
        name = f"leaked-{spec.noun.replace(' ', '-')}"
        short = (f"{spec.noun} can escape unreleased on some path "
                 "(exception edges included)")

        def check_program(self, program) -> Iterator[Finding]:
            yield from _spec_findings(spec, program)

    _ResourceRule.__doc__ = doc
    _ResourceRule.__name__ = f"ResourceLeak{spec.rule}"
    return _ResourceRule


for _spec in _SPECS:
    _make_rule(_spec, f"{_spec.rule}: {_spec.noun} acquire/release "
                      "pairing over the exception-edge CFG.")


@register
class LockReleaseSubset(ProgramRule):
    """RES705: a lock acquired manually and released on only a subset
    of paths out — the path-sensitive upgrade of LOCK201's statement
    model. ``with`` blocks never fire (inherently balanced)."""

    id = "RES705"
    name = "lock-released-on-subset-of-paths"
    short = "manual .acquire() not matched by .release() on every path"

    def check_program(self, program) -> Iterator[Finding]:
        yield from _spec_findings(_LOCK_SPEC, program)
