"""tpulint — JAX/TPU-aware static analysis for this tree, whole-program.

Three rule families, all distilled from bugs this repo actually shipped
(VERDICT.md) or could only catch probabilistically at runtime:

- ``TPU1xx`` (rules_jax, rules_sharding): closure-captured arrays in
  jitted programs, host syncs inside traced functions, import-time
  device work, missing buffer donation on train steps, and mesh-axis
  drift in ``in_shardings``/``NamedSharding`` specs.
- ``LOCK2xx`` (rules_lockset, rules_order): an Eraser-style lockset
  checker for the hand-rolled mutex idiom of the control plane (now
  propagating lock context across modules through the call graph in
  ``callgraph.py``), lock-order-cycle (ABBA deadlock) detection,
  check-then-act atomicity, and blocking-call detection in reconciles.
- ``HYG00x`` (hygiene + core): parse/debugger/conflict gates and the
  stale-suppression audit (HYG004).

``dyntrace.py`` is the dynamic half: an opt-in happens-before tracer
that instruments control-plane classes during the race tier and diffs
observed locksets against LOCK201's static guarded-attribute map.

CLI: ``python -m kubeflow_tpu.analysis [paths...]`` — exits nonzero on
findings; ``--format sarif`` for CI uploads, ``--baseline``/
``--write-baseline`` for the ratchet. Suppress a finding in-line with
``# tpulint: disable=RULE  <justification>``. docs/static-analysis.md
documents every rule.
"""

from kubeflow_tpu.analysis.core import (  # noqa: F401
    Finding, Module, ProgramRule, Rule, all_rules, register, scan_paths,
    scan_source, scan_sources,
)
from kubeflow_tpu.analysis.report import (  # noqa: F401
    render_json, render_sarif, render_text,
)

__all__ = ["Finding", "Module", "ProgramRule", "Rule", "all_rules",
           "register", "scan_paths", "scan_source", "scan_sources",
           "render_json", "render_sarif", "render_text"]
