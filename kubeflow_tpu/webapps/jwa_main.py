"""Entry: python -m kubeflow_tpu.webapps.jwa_main."""
import argparse

from kubeflow_tpu.control.k8s.rest import RestClient
from kubeflow_tpu.webapps.jwa import JupyterWebApp

p = argparse.ArgumentParser("jwa")
p.add_argument("--port", type=int, default=5000)
p.add_argument("--apiserver", default="")
args = p.parse_args()
svc = JupyterWebApp(RestClient(base_url=args.apiserver or None)).serve(port=args.port)
print(f"jwa on :{svc.port}")
svc.serve_forever()
