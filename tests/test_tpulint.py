"""tpulint regression corpus + tree gate (ISSUE 1 tentpole wiring).

Three layers:

1. Corpus: for every registered rule, a known-bad fragment asserting
   the rule fires with the right id AND line number, and a known-clean
   near-miss fragment asserting it stays silent (false-positive pin).
   The clean fragments encode the real idioms of this tree (params as
   jit arguments, scan bodies capturing within a trace, helpers called
   with the lock held) so rule tightening can't regress them.
2. Mechanics: suppression comments, reporters, CLI exit codes.
3. Tree gate: every kubeflow_tpu/ module is scanned parametrically —
   a new finding fails CI like any other test.
"""

import ast
import json
import pathlib
import textwrap

import pytest

from kubeflow_tpu.analysis import all_rules, render_json, render_text, scan_source
from kubeflow_tpu.analysis.core import scan_sources
from kubeflow_tpu.analysis.__main__ import main as tpulint_main
from kubeflow_tpu.analysis import hygiene

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO / "kubeflow_tpu"


def _scan(src: str):
    return scan_source("<corpus>", textwrap.dedent(src))


# --------------------------------------------------------------------------
# corpus: (rule id) -> [(bad source, expected line)], [clean sources]
# line numbers are 1-based within the dedented fragment
# --------------------------------------------------------------------------

BAD = {
    "TPU101": [
        # the 700MB class: weight tree captured across the jit boundary
        ("""\
import jax


def make(model, variables):
    def fwd(x):
        return model.apply(variables, x)
    return jax.jit(fwd)
""", 6),
        # array built on host, closed over by the jitted fn
        ("""\
import jax
import jax.numpy as jnp


def build():
    table = jnp.arange(65536)
    def lookup(i):
        return table[i]
    return jax.jit(lookup)
""", 8),
    ],
    "TPU102": [
        ("""\
import jax


@jax.jit
def step(state, batch):
    loss = (state - batch).sum()
    print(loss)
    return loss
""", 7),
        ("""\
import jax


@jax.jit
def step(state, batch):
    return (state - batch).sum().item()
""", 6),
    ],
    "TPU103": [
        ("""\
import jax.numpy as jnp

NEG_MASK = jnp.full((1024,), -1e9)
""", 3),
    ],
    "TPU104": [
        ("""\
import jax


def train_step(state, batch):
    return state


step = jax.jit(train_step)
""", 8),
        ("""\
import functools

import jax


@functools.partial(jax.jit, static_argnames=("lr",))
def train_step(state, batch, lr):
    return state
""", 6),
    ],
    "LOCK201": [
        ("""\
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = {}

    def add(self, k, v):
        with self._lock:
            self.jobs[k] = v

    def drop(self, k):
        del self.jobs[k]
""", 14),
        ("""\
import threading


class Counter:
    def __init__(self):
        self._mu = threading.Lock()
        self.n = 0

    def bump(self):
        with self._mu:
            self.n += 1

    def reset(self):
        self.n = 0
""", 14),
    ],
    "LOCK202": [
        ("""\
import time


class NodeReconciler:
    def reconcile(self, client, req):
        time.sleep(5.0)
        return None
""", 6),
    ],
    "LOCK203": [
        # ABBA: _cv then _lock on one path, the reverse on another
        ("""\
import threading


class Pair:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()

    def a(self):
        with self._cv:
            with self._lock:
                pass

    def b(self):
        with self._lock:
            with self._cv:
                pass
""", 11),
    ],
    "LOCK204": [
        # classic check-then-act: unlocked read decides a locked write
        ("""\
import threading


class Flag:
    def __init__(self):
        self._lock = threading.Lock()
        self.ready = False

    def arm(self):
        with self._lock:
            self.ready = True

    def ensure(self):
        if not self.ready:
            with self._lock:
                self.ready = True
""", 14),
    ],
    "TPU105": [
        # jit sharding kwarg names an axis the Mesh doesn't define
        ("""\
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make(devices, fn):
    mesh = Mesh(devices, ("data", "model"))
    return jax.jit(fn, in_shardings=NamedSharding(mesh, P("modle")))
""", 7),
    ],
    "TPU106": [
        # NamedSharding spec drifts from the mesh axis vocabulary,
        # resolved through a module-level constant
        ("""\
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "model")


def shardings(devices):
    mesh = Mesh(devices, AXES)
    return NamedSharding(mesh, P("fsdp"))
""", 8),
    ],
    "OBS301": [
        # the classic: wall-clock stopwatch around a measured section
        ("""\
import time


def measure(fn):
    t0 = time.time()
    fn()
    return time.time() - t0
""", 7),
        # direct subtraction against a wall deadline
        ("""\
import time


def remaining(deadline):
    return deadline - time.time()
""", 5),
    ],
    "COLL401": [
        # a second jax.distributed lifecycle call site forks the
        # backend contract (the loopback tier stops covering it)
        ("""\
import jax


def boot(coord, n, rank):
    jax.distributed.initialize(coord, num_processes=n, process_id=rank)
""", 5),
        # re-spelled MEGASCALE env key outside the backend module
        ("""\
import os


def slice_block(num):
    os.environ["MEGASCALE_NUM_SLICES"] = str(num)
""", 5),
    ],
    "NET501": [
        # urlopen with no timeout: the stdlib default is the process
        # socket timeout, i.e. "forever" — a brownout wedges the thread
        ("""\
from urllib.request import urlopen


def fetch(url):
    with urlopen(url) as r:
        return r.read()
""", 5),
        # bare event park on the request path
        ("""\
import threading


def rendezvous(ev: threading.Event):
    ev.wait()
    return True
""", 5),
    ],
}

CLEAN = {
    "TPU101": [
        # params flow through jit arguments (speculative.py idiom)
        """\
import jax


def make(model):
    def fwd(params, x):
        return model.apply(params, x)
    return jax.jit(fwd)
""",
        # scan body capturing from its enclosing function with no jit
        # boundary: the capture is a tracer in the caller's trace
        # (flash_attention.py _flash_bwd_xla idiom)
        """\
import jax
import jax.numpy as jnp


def bwd(q, lse):
    positions = jnp.arange(q.shape[1])

    def kv_block(carry, jb):
        return carry + positions[jb], None

    out, _ = jax.lax.scan(kv_block, jnp.zeros(()), jnp.arange(4))
    return out
""",
        # arrays built INSIDE the jit root are part of the trace
        """\
import jax
import jax.numpy as jnp


def build(model):
    def fwd(x):
        scale = jnp.float32(2.0)

        def inner(y):
            return y * scale
        return inner(x)
    return jax.jit(fwd)
""",
    ],
    "TPU102": [
        """\
import jax
import numpy as np


@jax.jit
def step(state, batch):
    jax.debug.print("loss {l}", l=state.sum())
    return (state - batch).sum()


def host_epilogue(metrics):
    return float(np.asarray(metrics))
""",
        # float() on a static arg is concretization-safe
        """\
import functools

import jax


@functools.partial(jax.jit, static_argnames=("lr",))
def scale(x, lr):
    return x * float(lr)
""",
    ],
    "TPU103": [
        """\
import jax.numpy as jnp
import numpy as np

HOST_TABLE = np.arange(16)  # np at import is host-only: allowed


def masked(x):
    return x + jnp.full((8,), -1e9)
""",
        # the unaliased spelling gets the same host-numpy exemption
        """\
import numpy

HOST_TABLE = numpy.arange(16)
""",
    ],
    "TPU104": [
        """\
import jax


def train_step(state, batch):
    return state


def eval_step(state, batch):
    return state


step = jax.jit(train_step, donate_argnums=(0,))
evaluate = jax.jit(eval_step)
""",
    ],
    "LOCK201": [
        # private helper only called with the lock held (leases.py
        # _became idiom): no re-acquire required, no finding
        """\
import threading


class Elector:
    def __init__(self):
        self._lock = threading.Lock()
        self.held = False

    def acquire(self):
        with self._lock:
            return self._round()

    def _round(self):
        self.held = True
        return self.held
""",
        # recursive helper cycle whose every external entry holds the
        # lock (FakeCluster _delete_now <-> _gc_orphans shape): internal
        # cycle edges are lock-held, so the unlocked-looking writes are
        # fine and must not fire
        """\
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = {}

    def delete(self, k):
        with self._lock:
            self._delete_now(k)

    def _delete_now(self, k):
        self.items.pop(k, None)
        self._cascade(k)

    def _cascade(self, k):
        for child in list(self.items):
            if child.startswith(k):
                self._delete_now(child)
""",
        # mutually-recursive private helpers with NO locked entry point
        # must not vouch for each other (entry-point pass):
        # no finding because nothing here is ever mutated under the lock
        """\
import threading


class Orphans:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def _a(self, depth):
        self.n += 1
        if depth:
            self._b(depth - 1)

    def _b(self, depth):
        self._a(depth)

    def reset(self):
        self.n = 0
""",
        # .update() on an API client object is a call, not a container
        # mutation: must not make 'client' a guarded attribute
        """\
import threading


class Syncer:
    def __init__(self, client):
        self._lock = threading.Lock()
        self.client = client

    def push(self, obj):
        with self._lock:
            self.client.update(obj)

    def push_unlocked(self, obj):
        self.client.update(obj)
""",
    ],
    "LOCK202": [
        """\
import time


class NodeReconciler:
    def reconcile(self, client, req):
        return Result(requeue_after=5.0)

    def helper(self):
        time.sleep(0.1)  # not a reconcile body


class Result:
    def __init__(self, requeue_after=None):
        self.requeue_after = requeue_after
""",
    ],
    "LOCK203": [
        # consistent global order (always _cv before _lock): no cycle
        """\
import threading


class Pair:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()

    def a(self):
        with self._cv:
            with self._lock:
                pass

    def b(self):
        with self._cv:
            with self._lock:
                pass
""",
        # re-acquiring the SAME lock through a helper is the locked-
        # context idiom, not an order cycle
        """\
import threading


class Solo:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self._apply()

    def _apply(self):
        self.n += 1
""",
    ],
    "LOCK204": [
        # double-checked locking: the decision is re-made under the lock
        """\
import threading


class Flag:
    def __init__(self):
        self._lock = threading.Lock()
        self.ready = False

    def arm(self):
        with self._lock:
            self.ready = True

    def ensure(self):
        if not self.ready:
            with self._lock:
                if not self.ready:
                    self.ready = True
""",
        # check already under the lock (leases.py try_acquire idiom)
        """\
import threading


class Flag:
    def __init__(self):
        self._lock = threading.Lock()
        self.ready = False

    def arm(self):
        with self._lock:
            self.ready = True

    def ensure(self):
        with self._lock:
            if not self.ready:
                self.ready = True
""",
    ],
    "TPU105": [
        # axis present in the Mesh built in the same slice
        """\
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make(devices, fn):
    mesh = Mesh(devices, ("data", "model"))
    return jax.jit(fn, in_shardings=NamedSharding(mesh, P("model")))
""",
        # no Mesh constructed in the slice: the rule must not guess
        """\
import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def make(mesh, fn):
    return jax.jit(fn, in_shardings=NamedSharding(mesh, P("rows")))
""",
    ],
    "TPU106": [
        # tuple axes and None dims within the vocabulary stay quiet
        """\
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "model")


def shardings(devices):
    mesh = Mesh(devices, AXES)
    return NamedSharding(mesh, P(("data", "model"), None))
""",
        # unresolvable axis names (runtime values) are skipped, not
        # flagged
        """\
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shardings(devices, axis):
    mesh = Mesh(devices, ("data", "model"))
    return NamedSharding(mesh, P(axis))
""",
    ],
    "OBS301": [
        # the correct stopwatch: perf_counter deltas
        """\
import time


def measure(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
""",
        # deadline ARITHMETIC on wall clock is a timestamp, not a
        # duration (gatekeeper/auth.py token-expiry idiom)
        """\
import time


def expiry(ttl):
    return int(time.time() + ttl)
""",
        # expiry COMPARISON against wall clock: also not a duration
        """\
import time


def expired(exp):
    return int(exp) < time.time()
""",
        # a same-named local in another function must not taint this one
        """\
import time


def stamp():
    t0 = time.time()
    return t0


def diff(t0, t1):
    return t1 - t0
""",
    ],
    "COLL401": [
        # the sanctioned route: world formation through the backend seam
        """\
from kubeflow_tpu.parallel import backends as B


def boot(cfg):
    return B.get_backend().join(cfg, wait=True)
""",
        # JAXJOB_* keys and prose mentions of megascale are not the
        # transport contract; is_initialized is not a lifecycle call
        """\
import os

import jax


def status(n):
    os.environ["JAXJOB_NUM_SLICES"] = str(n)
    note = "megascale transport handles cross-slice reduce"
    return jax.distributed.is_initialized(), note
""",
    ],
    "NET501": [
        # explicit timeouts, kwarg and third-positional spellings; a
        # bounded event wait is the sanctioned park
        """\
import threading
from urllib.request import urlopen


def fetch(url, ev: threading.Event):
    with urlopen(url, None, 5.0) as r:
        body = r.read()
    with urlopen(url, timeout=2.5) as r:
        body += r.read()
    ev.wait(timeout=0.05)
    return body
""",
        # wait() on a non-event object with arguments is not a park
        """\
def gather(pool, futures):
    return [f.wait(10.0) for f in futures]
""",
    ],
}


def _bad_cases():
    return [(rule, src, line)
            for rule, cases in sorted(BAD.items())
            for src, line in cases]


def _clean_cases():
    return [(rule, src)
            for rule, cases in sorted(CLEAN.items())
            for src in cases]


@pytest.mark.parametrize("rule,src,line", _bad_cases(),
                         ids=lambda v: v if isinstance(v, str) and
                         v.startswith(("TPU", "LOCK", "OBS", "COLL")) else None)
def test_rule_fires_with_id_and_line(rule, src, line):
    findings = _scan(src)
    hits = [f for f in findings if f.rule == rule]
    assert hits, f"{rule} did not fire; got {[f.render() for f in findings]}"
    assert line in [f.line for f in hits], (
        f"{rule} fired at {[f.line for f in hits]}, expected line {line}")


@pytest.mark.parametrize("rule,src", _clean_cases(),
                         ids=lambda v: v if isinstance(v, str) and
                         v.startswith(("TPU", "LOCK", "OBS", "COLL")) else None)
def test_clean_fragment_stays_clean(rule, src):
    findings = [f for f in _scan(src) if f.rule == rule]
    assert not findings, [f.render() for f in findings]


#: Rules with path semantics ("<corpus>" is out of their scope), pinned
#: in dedicated classes below instead of the generic BAD/CLEAN tables.
#: DET/CTL deliberately do not fire outside the replay-critical /
#: control-plane subtrees, so their corpora scan under scoped paths.
_SCOPED_RULES = {
    "OBS302",
    "DET601", "DET602", "DET603", "DET604",
    "CTL501", "CTL502", "CTL503", "CTL504",
    # ISSUE 17: whole-program dataflow + wire-ownership rules — their
    # corpora pin real package paths (RES_PATH/WIRE_PATH tables below)
    "RES701", "RES702", "RES703", "RES704", "RES705",
    "WIRE801", "WIRE802", "WIRE803",
}


def test_at_least_ten_rules_each_with_both_cases():
    ids = {r.id for r in all_rules()}
    assert len(ids) >= 10, ids
    # Scoped rules (OBS302's injected catalog, the DET6xx/CTL5xx path
    # scopes) can't be expressed under the generic "<corpus>" path —
    # their firing AND non-firing pins live in the dedicated classes
    # below (TestOBS302, TestDET6xxCorpus, TestCTL5xxCorpus).
    assert ids - _SCOPED_RULES == set(BAD) == set(CLEAN), (
        "every registered rule needs a firing AND a non-firing corpus case")
    assert _SCOPED_RULES <= ids, "scoped-rule pin list names unknown rules"


# -- suppression mechanics ---------------------------------------------------

_SUPPRESSIBLE = """\
import time


class R:
    def reconcile(self, client, req):
        time.sleep(1.0){comment}
"""


def test_line_suppression_silences_only_named_rule():
    src = _SUPPRESSIBLE.format(
        comment="  # tpulint: disable=LOCK202  corpus justification")
    assert _scan(src) == []
    wrong = _SUPPRESSIBLE.format(comment="  # tpulint: disable=TPU101")
    # LOCK202 still fires; the TPU101 suppression is itself stale
    assert [f.rule for f in _scan(wrong)] == ["HYG004", "LOCK202"]


def test_line_suppression_all():
    src = _SUPPRESSIBLE.format(comment="  # tpulint: disable=all")
    assert _scan(src) == []


def test_file_suppression():
    src = ("# tpulint: disable-file=LOCK202  corpus justification\n"
           + _SUPPRESSIBLE.format(comment=""))
    assert _scan(src) == []


def test_single_space_justification_still_suppresses():
    """A one-space separator must not swallow the justification into the
    rule list (which would silently disable the suppression)."""
    src = _SUPPRESSIBLE.format(
        comment="  # tpulint: disable=LOCK202 requeue handled by caller")
    assert _scan(src) == []


def test_parse_error_is_reported_not_raised():
    findings = scan_source("<corpus>", "def broken(:\n")
    assert [f.rule for f in findings] == ["TPU000"]


# -- stale suppressions (HYG004) ---------------------------------------------

def test_stale_suppression_unknown_rule_fires():
    src = "x = 1  # tpulint: disable=LOCK999  long-gone rule\n"
    findings = _scan(src)
    assert [f.rule for f in findings] == ["HYG004"]
    assert "LOCK999" in findings[0].message and findings[0].line == 1


def test_stale_suppression_rule_never_fires_on_line():
    src = "x = 1  # tpulint: disable=LOCK202  nothing blocks here\n"
    assert [f.rule for f in _scan(src)] == ["HYG004"]


def test_stale_file_suppression_fires():
    src = ("# tpulint: disable-file=LOCK202  no reconciles in this module\n"
           "x = 1\n")
    findings = _scan(src)
    assert [f.rule for f in findings] == ["HYG004"]
    assert "never fires in this module" in findings[0].message


def test_live_suppression_is_not_stale():
    src = _SUPPRESSIBLE.format(
        comment="  # tpulint: disable=LOCK202  corpus justification")
    assert _scan(src) == []


def test_suppression_quoted_in_docstring_is_not_stale():
    src = '"""Suppress with ``# tpulint: disable=LOCK202  why``."""\n'
    assert _scan(src) == []


def test_stale_suppression_only_on_full_scans():
    """A partial rule run cannot prove a suppression dead."""
    rules = [r for r in all_rules() if r.id == "LOCK202"]
    src = "x = 1  # tpulint: disable=TPU101  stale on purpose\n"
    assert scan_source("<corpus>", src, rules) == []


def test_hyg004_is_itself_suppressible():
    src = ("x = 1  # tpulint: disable=LOCK999,HYG004  "
           "kept for a vendored checkout\n")
    assert _scan(src) == []


# -- whole-program: cross-module call graph ----------------------------------

_REGISTRY_MOD = """\
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = {}

    def add(self, k, v):
        with self._lock:
            self.jobs[k] = v
"""


def test_lock201_sees_writes_through_annotated_params_cross_module():
    findings = scan_sources({
        "reg": _REGISTRY_MOD,
        "helpers": """\
from reg import Registry


def prune(r: Registry):
    r.jobs.clear()
""",
    })
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("LOCK201", "helpers.py", 5)]
    assert "'r.jobs'" in findings[0].message
    assert "reg.py:11" in findings[0].message  # names the locked site


def test_lock201_locked_context_crosses_modules():
    """A private helper in another module whose only call site holds the
    lock must not be forced to re-acquire (the cross-module analogue of
    the leases.py _became idiom)."""
    findings = scan_sources({
        "reg2": """\
import threading

from helpers2 import _prune_locked


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = {}

    def add(self, k, v):
        with self._lock:
            self.jobs[k] = v

    def gc(self):
        with self._lock:
            _prune_locked(self)
""",
        "helpers2": """\
from reg2 import Registry


def _prune_locked(r: Registry):
    r.jobs.pop("dead", None)
""",
    })
    assert findings == []


def test_lock201_unlocked_cross_module_entry_defeats_helper():
    """Same helper, but a second call site WITHOUT the lock: the helper
    can no longer be assumed locked, so its write is flagged."""
    findings = scan_sources({
        "reg3": """\
import threading

from helpers3 import _prune

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = {}

    def add(self, k, v):
        with self._lock:
            self.jobs[k] = v

    def gc(self):
        with self._lock:
            _prune(self)

    def gc_unlocked(self):
        _prune(self)
""",
        "helpers3": """\
from reg3 import Registry


def _prune(r: Registry):
    r.jobs.pop("dead", None)
""",
    })
    assert [(f.rule, f.path, f.line) for f in findings] == [
        ("LOCK201", "helpers3.py", 5)]


def test_lock203_cycle_across_classes_and_modules():
    """_cv-then-_lock through a cross-module call on one path and
    _lock-then-_cv on the other: the ABBA cycle spans both files."""
    findings = scan_sources({
        "eng": """\
import threading

from gate import Gate


class Engine:
    def __init__(self):
        self._cv = threading.Condition()
        self.gate = Gate()

    def tick(self):
        with self._cv:
            self.gate.open_()

    def flush(self):
        with self._cv:
            pass
""",
        "gate": """\
import threading

from eng import Engine


class Gate:
    def __init__(self):
        self._lock = threading.Lock()

    def open_(self):
        with self._lock:
            pass

    def shut(self, eng: Engine):
        with self._lock:
            eng.flush()
""",
    })
    by_rule = [f for f in findings if f.rule == "LOCK203"]
    assert {f.path for f in by_rule} == {"eng.py", "gate.py"}
    assert any("Engine._cv" in f.message and "Gate._lock" in f.message
               for f in by_rule)


def test_tpu106_canonical_vocabulary_from_mesh_helper_import():
    """A module importing parallel/mesh helpers is checked against the
    canonical axis vocabulary even with no Mesh ctor in the scan."""
    findings = scan_sources({
        "layers": """\
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import AXIS_MODEL


def shard(mesh):
    good = NamedSharding(mesh, P(AXIS_MODEL))
    bad = NamedSharding(mesh, P("tensor"))
    return good, bad
""",
    })
    assert [(f.rule, f.line) for f in findings] == [("TPU106", 8)]
    assert "'tensor'" in findings[0].message


def test_unresolvable_mesh_elsewhere_does_not_silence_resolved_module():
    """A runtime-built Mesh in one module must skip only THAT module,
    not turn the sharding rules off for the whole program."""
    findings = scan_sources({
        "dyn": """\
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make(devices, axes):
    mesh = Mesh(devices, axes)
    return NamedSharding(mesh, P("whatever"))
""",
        "fixed": """\
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make(devices):
    mesh = Mesh(devices, ("data", "model"))
    return NamedSharding(mesh, P("tpyo"))
""",
    })
    assert [(f.rule, f.path) for f in findings] == [("TPU106", "fixed.py")]


def test_lock204_quiet_for_write_inside_nested_def():
    """Defining a closure performs no write: the locked write inside a
    nested def runs at call time, so there is no check-then-act window
    at the branch (mirrors lex_tokens' nested-def rule)."""
    src = """\
import threading


class Flag:
    def __init__(self):
        self._lock = threading.Lock()
        self.ready = False

    def arm(self):
        with self._lock:
            self.ready = True

    def maker(self):
        if not self.ready:
            def later():
                with self._lock:
                    self.ready = True
            return later
        return None
"""
    assert [f.rule for f in _scan(src) if f.rule == "LOCK204"] == []


def test_canonical_axes_mirror_parallel_mesh():
    """rules_sharding hardcodes the axis vocabulary (analysis must not
    import jax); pin it to parallel/mesh.py's _AXIS_ORDER by AST."""
    from kubeflow_tpu.analysis.rules_sharding import CANONICAL_AXES

    src = (PACKAGE / "parallel" / "mesh.py").read_text()
    tree = ast.parse(src)
    consts = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.targets[0], ast.Name):
            consts[node.targets[0].id] = node.value
    order = consts["_AXIS_ORDER"]
    axes = tuple(
        consts[e.id].value if isinstance(e, ast.Name) else e.value
        for e in order.elts)
    assert axes == CANONICAL_AXES


# -- reporters ---------------------------------------------------------------

def test_json_reporter_schema():
    findings = _scan(BAD["LOCK202"][0][0])
    doc = json.loads(render_json(findings))
    assert doc["version"] == 1
    assert doc["count"] == len(findings) == len(doc["findings"])
    entry = doc["findings"][0]
    assert set(entry) == {"rule", "path", "line", "col", "message"}
    assert entry["rule"] == "LOCK202"


def test_text_reporter_mentions_rule_and_location():
    f = _scan(BAD["LOCK202"][0][0])[0]
    text = render_text([f])
    assert "LOCK202" in text and f":{f.line}:" in text
    assert render_text([]) == "tpulint: clean"


def test_sarif_reporter_schema():
    from kubeflow_tpu.analysis.report import render_sarif

    findings = _scan(BAD["LOCK202"][0][0])
    doc = json.loads(render_sarif(findings))
    assert doc["version"] == "2.1.0" and "sarif-2.1.0" in doc["$schema"]
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpulint"
    rules = {r["id"]: r for r in run["tool"]["driver"]["rules"]}
    assert "LOCK202" in rules
    assert rules["LOCK202"]["shortDescription"]["text"]
    res = run["results"][0]
    assert res["ruleId"] == "LOCK202" and res["level"] == "warning"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "<corpus>"
    # SARIF columns are 1-based; tpulint cols are 0-based
    assert loc["region"]["startLine"] == findings[0].line
    assert loc["region"]["startColumn"] == findings[0].col + 1


def test_sarif_empty_run_is_valid():
    from kubeflow_tpu.analysis.report import render_sarif

    doc = json.loads(render_sarif([]))
    assert doc["runs"][0]["results"] == []


# -- baseline ratchet --------------------------------------------------------

def test_baseline_roundtrip_and_new_finding_detection():
    from kubeflow_tpu.analysis.report import (
        load_baseline, new_findings, render_baseline,
    )

    old = _scan(BAD["LOCK202"][0][0])
    baseline = load_baseline(render_baseline(old))
    assert new_findings(old, baseline) == []
    extra = _scan(BAD["TPU104"][0][0])
    assert new_findings(old + extra, baseline) == extra
    # multiset semantics: a second identical finding is NEW
    assert new_findings(old + old, baseline) == old


# -- CLI ---------------------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(BAD["TPU104"][0][0]))
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert tpulint_main([str(good)]) == 0
    assert tpulint_main([str(bad)]) == 1
    assert tpulint_main(["--select", "NOPE999", str(bad)]) == 2
    assert tpulint_main(["--select", "LOCK202", str(bad)]) == 0  # filtered
    assert tpulint_main([str(tmp_path / "no_such_dir")]) == 2  # path typo
    capsys.readouterr()
    assert tpulint_main(["--json", str(bad)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"][0]["rule"] == "TPU104"


def test_cli_rules_alias_and_format(tmp_path, capsys):
    """--rules is an alias for --select; --format sarif/json both work."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(BAD["TPU104"][0][0]))
    assert tpulint_main(["--rules", "LOCK202", str(bad)]) == 0
    capsys.readouterr()
    assert tpulint_main(["--rules", "TPU104", str(bad)]) == 1
    capsys.readouterr()
    assert tpulint_main(["--format", "sarif", str(bad)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"][0]["ruleId"] == "TPU104"


def test_cli_baseline_ratchet(tmp_path, capsys):
    """--write-baseline pins today's findings; --baseline fails only on
    NEW findings (ratchet, not flag-day)."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(BAD["TPU104"][0][0]))
    base = tmp_path / "baseline.json"
    assert tpulint_main(["--write-baseline", str(base), str(bad)]) == 0
    doc = json.loads(base.read_text())
    assert doc["version"] == 1 and len(doc["findings"]) == 1
    capsys.readouterr()
    # unchanged tree: ratchet passes despite the pre-existing finding
    assert tpulint_main(["--baseline", str(base), str(bad)]) == 0
    capsys.readouterr()
    # a new finding appears: ratchet fails and reports ONLY the new one
    worse = tmp_path / "worse.py"
    worse.write_text(textwrap.dedent(BAD["LOCK202"][0][0]))
    assert tpulint_main(["--baseline", str(base), str(bad),
                         str(worse)]) == 1
    out = capsys.readouterr().out
    assert "LOCK202" in out and "TPU104" not in out
    # a missing baseline is a usage error, not a silent pass
    assert tpulint_main(["--baseline", str(tmp_path / "nope.json"),
                         str(bad)]) == 2
    capsys.readouterr()


def test_cli_selecting_hygiene_rule_implies_hygiene_pass(tmp_path, capsys):
    """--select HYG002 without --hygiene must still run the hygiene
    pass (not silently scan nothing and exit 0)."""
    p = tmp_path / "hooked.py"
    p.write_text("breakpoint()\n")
    assert tpulint_main(["--select", "HYG002", str(p)]) == 1
    capsys.readouterr()


def test_cli_list_rules(capsys):
    assert tpulint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in list(BAD) + ["HYG001", "HYG002", "HYG003", "HYG004"]:
        assert rid in out


# -- hygiene gates -----------------------------------------------------------

def test_hygiene_catches_debugger_and_conflict_markers(tmp_path):
    (tmp_path / "hooked.py").write_text("x = 1\nbreakpoint()\n")
    (tmp_path / "torn.py").write_text("x = 1\n" + "<<" + "<<<<< HEAD\n")
    rules = {f.rule for f in hygiene.run_hygiene([str(tmp_path)])}
    # the conflict marker also breaks the parse gate, hence HYG001
    assert rules == {"HYG001", "HYG002", "HYG003"}


def test_hygiene_yaml_gate(tmp_path):
    p = tmp_path / "m.yaml"
    p.write_text("a: [1, 2\n")
    assert [f.rule for f in hygiene.run_hygiene([str(p)])] == ["HYG001"]


def test_hygiene_skips_explicit_non_gated_file(tmp_path):
    p = tmp_path / "watch.sh"
    p.write_text("#!/bin/bash\nwhile true; do date; done\n")
    assert hygiene.run_hygiene([str(p)]) == []


def test_hygiene_only_select_filters_parse_findings(tmp_path, capsys):
    """--select HYG002 must not leak TPU000 parse findings (and must not
    even run the tpulint parse pass)."""
    (tmp_path / "broken.py").write_text("def broken(:\n")
    (tmp_path / "hooked.py").write_text("breakpoint()\n")
    assert tpulint_main(["--select", "HYG002", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "HYG002" in out and "TPU000" not in out and "HYG001" not in out


# -- the tree gate: the shipped package must lint clean ----------------------

TREE_FILES = sorted(
    p for p in PACKAGE.rglob("*.py") if "__pycache__" not in p.parts)


@pytest.mark.parametrize("path", TREE_FILES,
                         ids=lambda p: str(p.relative_to(REPO)))
def test_tree_file_lints_clean(path):
    findings = scan_source(str(path), path.read_text())
    assert not findings, "\n".join(f.render() for f in findings)


def test_suppressions_in_tree_carry_justification():
    """Inline suppressions are allowed only with a why: prose must follow
    the rule list. Uses the framework's own suppression regex, so doc
    mentions of the syntax that core would not honor are not checked.
    Covers every python target tools/lint_all.sh scans, not just the
    package."""
    from kubeflow_tpu.analysis.core import _SUPPRESS_RE

    gated = TREE_FILES + sorted(
        (REPO / "tools").rglob("*.py")) + sorted(
        (REPO / "tests").rglob("*.py")) + [
        REPO / "bench.py", REPO / "__graft_entry__.py"]
    for path in gated:
        for i, line in enumerate(path.read_text().splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            justification = line[m.end():].strip().strip("#").strip()
            assert justification, (
                f"{path}:{i}: suppression without justification text")


def test_whole_program_scan_of_tree_is_clean():
    """The cross-module pass (LOCK201 with call-graph context, LOCK203,
    LOCK204, TPU105, TPU106, HYG004) over the package as ONE program —
    per-file cleanliness above does not imply this."""
    from kubeflow_tpu.analysis import scan_paths

    findings = scan_paths([str(PACKAGE)])
    assert not findings, "\n".join(f.render() for f in findings)


def test_cli_new_rules_run_clean_on_tree(capsys):
    """The ISSUE 2 acceptance command, pinned."""
    assert tpulint_main(["--rules", "LOCK203,LOCK204,TPU105,TPU106",
                         str(PACKAGE)]) == 0
    capsys.readouterr()


def test_program_guarded_map_for_control_runtime():
    """The static lockset map the dynamic validator diffs against:
    Controller's queue state is guarded by _cv, the elector's flags by
    _lock. If this pins differently, dyntrace comparisons are vacuous."""
    from kubeflow_tpu.analysis.dyntrace import static_guarded_map

    static = static_guarded_map([
        str(PACKAGE / "control" / "runtime.py"),
        str(PACKAGE / "control" / "leases.py"),
    ])
    ctl = static["Controller"]
    assert ctl["_queue"] == {"_cv"}
    assert ctl["_delayed"] == {"_cv"}
    assert ctl["_failures"] == {"_cv"}
    assert static["LeaderElector"]["_held"] == {"_lock"}


# -- dyntrace: the happens-before validator (unit level; the race tier
#    wires it against the real controllers behind TPU_RACE_TRACE=1) ----------

def _run_threads(*fns):
    import threading as _t

    ts = [_t.Thread(target=f) for f in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


def test_dyntrace_confirms_locked_class_and_flags_unlocked_one():
    import threading

    from kubeflow_tpu.analysis.dyntrace import Tracer

    class Good:
        def __init__(self):
            self._lock = threading.Lock()
            self.jobs = {}

        def add(self, k):
            with self._lock:
                self.jobs[k] = 1

    class Bad:
        def __init__(self):
            self._lock = threading.Lock()
            self.jobs = {}

        def add(self, k):
            self.jobs[k] = 1  # no lock: the race LOCK201 catches

    static = {"Good": {"jobs": {"_lock"}}, "Bad": {"jobs": {"_lock"}}}
    tr = Tracer()
    tr.instrument(Good)
    tr.instrument(Bad)
    try:
        with tr:
            g, b = Good(), Bad()
            _run_threads(lambda: [g.add(f"a{i}") for i in range(50)],
                         lambda: [g.add(f"b{i}") for i in range(50)])
            _run_threads(lambda: [b.add(f"a{i}") for i in range(50)],
                         lambda: [b.add(f"b{i}") for i in range(50)])
    finally:
        tr.uninstrument_all()
    assert tr.confirmed(static) == ["Good.jobs"]
    div = tr.divergences(static)
    assert len(div) == 1 and div[0].startswith("Bad.jobs")


def test_dyntrace_exclusive_thread_writes_are_vacuous():
    """Writes from a single thread (construction, test-mode drains)
    never refine the lockset — happens-before, not lock discipline."""
    import threading

    from kubeflow_tpu.analysis.dyntrace import Tracer

    class Solo:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = []

        def push(self, x):
            self.items.append(x)  # single-threaded by construction

    tr = Tracer()
    tr.instrument(Solo)
    try:
        with tr:
            s = Solo()
            for i in range(10):
                s.push(i)
    finally:
        tr.uninstrument_all()
    assert tr.divergences({"Solo": {"items": {"_lock"}}}) == []
    rec = tr.observed()[("Solo", "items")]
    assert rec["shared"] is False and rec["writes"] >= 10


def test_dyntrace_condition_and_rebind_tracking():
    """Condition locks (the Controller._cv shape) and attribute rebinds
    are tracked, including across cv.wait()'s release/reacquire."""
    import threading

    from kubeflow_tpu.analysis.dyntrace import Tracer

    class Q:
        def __init__(self):
            self._cv = threading.Condition()
            self.pending = []
            self.sealed = False

        def put(self, x):
            with self._cv:
                self.pending.append(x)
                self._cv.notify_all()

        def drain(self):
            with self._cv:
                if not self.pending:
                    self._cv.wait(timeout=0.05)
                self.pending = []  # rebind under the lock

        def seal(self):
            self.sealed = True  # rebind WITHOUT the lock

    static = {"Q": {"pending": {"_cv"}, "sealed": {"_cv"}}}
    tr = Tracer()
    tr.instrument(Q)
    try:
        with tr:
            q = Q()
            _run_threads(lambda: [q.put(i) for i in range(30)],
                         lambda: [q.drain() for _ in range(30)],
                         lambda: [q.seal() for _ in range(30)],
                         lambda: [q.seal() for _ in range(30)])
    finally:
        tr.uninstrument_all()
    assert tr.confirmed(static) == ["Q.pending"]
    div = tr.divergences(static)
    assert len(div) == 1 and div[0].startswith("Q.sealed")


# -- OBS302: metrics-catalog drift (ISSUE 10 satellite) ----------------------


class TestOBS302:
    """Corpus pins for the catalog-drift rule. OBS302 is a ProgramRule
    with path semantics (only ``kubeflow_tpu/`` registrations count)
    and an external catalog, so it gets a dedicated harness instead of
    the generic BAD/CLEAN tables: tests inject ``catalog_override``
    (which also waives the full-scan size floor for the doc-side
    direction)."""

    CATALOG = """\
## Metrics catalog

| Series | Type | Labels | Meaning |
|---|---|---|---|
| `known_metric_total` | counter | — | documented |
| `jaxrt_family_*` | gauge | — | dynamic family row |
| `ghost_metric_seconds` | histogram | — | stale: nothing registers it |

## Next section

| `not_a_catalog_row` | x | y | tables outside the section are ignored |
"""

    @pytest.fixture(autouse=True)
    def _catalog(self):
        from kubeflow_tpu.analysis.core import REGISTRY

        all_rules()  # REGISTRY populates lazily
        rule = REGISTRY["OBS302"]
        rule.catalog_override = self.CATALOG
        try:
            yield
        finally:
            rule.catalog_override = None

    def _scan(self, sources):
        from kubeflow_tpu.analysis.core import REGISTRY

        return scan_sources(sources, rules=[REGISTRY["OBS302"]])

    def test_uncatalogued_registration_fires(self):
        findings = self._scan({"kubeflow_tpu.widget": """\
from kubeflow_tpu.runtime.metrics import REGISTRY


def publish():
    REGISTRY.counter_inc("rogue_metric_total", by=1.0)
"""})
        assert [(f.rule, f.line) for f in findings] == [("OBS302", 5)]
        assert "rogue_metric_total" in findings[0].message

    def test_catalogued_registrations_clean(self):
        findings = self._scan({"kubeflow_tpu.widget": """\
import prometheus_client as prom

from kubeflow_tpu.runtime.metrics import REGISTRY, prom_metric


def publish(k, name, doc):
    REGISTRY.counter_inc("known_metric_total", by=1.0)
    REGISTRY.gauge(f"jaxrt_family_{k}", 1.0)      # glob row covers it
    prom_metric(name, prom.Counter, doc)           # passthrough: unknowable
"""})
        assert findings == []

    def test_outside_package_is_exempt(self):
        findings = self._scan({"tools.bench_helper": """\
from kubeflow_tpu.runtime.metrics import REGISTRY


def publish():
    REGISTRY.gauge("bench_only_metric", 1.0)
"""})
        assert findings == []

    def test_stale_doc_row_fires_on_full_scan(self):
        # the sentinel module marks a full-package scan: the doc-side
        # direction runs and flags the row with no live registration
        findings = self._scan({
            "kubeflow_tpu.runtime.metrics": "x = 1\n",
            "kubeflow_tpu.widget": """\
from kubeflow_tpu.runtime.metrics import REGISTRY


def publish(k):
    REGISTRY.counter_inc("known_metric_total", by=1.0)
    REGISTRY.gauge(f"jaxrt_family_{k}", 1.0)
"""})
        assert [(f.rule, f.path) for f in findings] == \
            [("OBS302", "docs/observability.md")]
        assert "ghost_metric_seconds" in findings[0].message
        assert "no metric registration" in findings[0].message
        # rows outside the "## Metrics catalog" section never count:
        # not_a_catalog_row is unregistered too, yet only ghost fires

    def test_partial_scan_skips_doc_side(self):
        findings = self._scan({"kubeflow_tpu.widget": """\
from kubeflow_tpu.runtime.metrics import REGISTRY


def publish():
    REGISTRY.counter_inc("known_metric_total", by=1.0)
"""})
        assert findings == []  # stale rows unprovable without full scan

    def test_real_tree_catalog_is_in_sync(self):
        """THE gate: the committed package and the committed catalog
        agree in both directions (also enforced by tools/lint_all.sh
        pass 1)."""
        from kubeflow_tpu.analysis.core import REGISTRY, scan_paths

        REGISTRY["OBS302"].catalog_override = None
        findings = scan_paths(["kubeflow_tpu"], select={"OBS302"})
        assert findings == []


# ===========================================================================
# ISSUE 16: scoped corpora for the DET6xx / CTL5xx families. These rules
# carry path semantics (replay-critical modules / the control plane), so
# their pins scan under in-scope paths instead of "<corpus>".
# ===========================================================================

DET_PATH = "kubeflow_tpu/control/scheduler/_det_corpus.py"
CTL_PATH = "kubeflow_tpu/control/_ctl_corpus.py"


def _scan_at(path: str, src: str):
    return scan_source(path, textwrap.dedent(src))


DET_BAD = {
    "DET601": [
        # ambient monotonic read deciding an admission deadline
        ("""\
import time


def admission_deadline(queue):
    deadline = time.monotonic() + 5.0
    return deadline
""", 5),
        # datetime alias resolves through the import table
        ("""\
from datetime import datetime


class Router:
    def pick(self, replicas):
        stamp = datetime.now()
        return sorted(replicas), stamp
""", 6),
        # wall-returning helper by name: fires at the call site even in
        # a per-file scan (keeps suppressions HYG004-coherent with the
        # whole-tree pass)
        ("""\
from kubeflow_tpu.control.k8s import objects as ob


def stamp_event(ev):
    ev["ts"] = ob.now_iso()
    return ev
""", 5),
    ],
    "DET602": [
        # default-constructed RNG: seeded by the process, not the bench
        ("""\
import random


class Jitter:
    def __init__(self):
        self._rng = random.Random()
""", 6),
        # ambient module-level draw from the process-global generator
        ("""\
import random


def spread(pods):
    random.shuffle(pods)
    return pods
""", 5),
    ],
    "DET603": [
        ("""\
import time


def backoff(attempt):
    time.sleep(0.5 * attempt)
""", 5),
        # module alias still canonicalizes to time.sleep
        ("""\
import time as _t


def settle():
    _t.sleep(1.0)
""", 5),
    ],
    "DET604": [
        ("""\
import uuid


def trace_id():
    return uuid.uuid4().hex
""", 5),
        # id()-keyed ordering leaks allocation addresses into decisions
        ("""\
def order(pods):
    return sorted(pods, key=id)
""", 2),
        ("""\
import os


def salt():
    return os.urandom(8)
""", 5),
    ],
}

DET_CLEAN = {
    "DET601": [
        # THE injectable-clock idiom: the default is a *reference*, the
        # read goes through the attribute the bench substitutes
        """\
import time


class Pacer:
    def __init__(self, clock=time.monotonic):
        self.clock = clock

    def due(self, deadline):
        return self.clock() >= deadline
""",
        # injectable param form of the same idiom
        """\
import time


def tick(handler, clock=time.monotonic):
    handler(clock())
""",
        # converting an injected timestamp is not a wall read
        """\
import datetime


EPOCH = datetime.timezone.utc


def label(ts):
    return datetime.datetime.fromtimestamp(ts, EPOCH).isoformat()
""",
    ],
    "DET602": [
        # seeded default: replayable without caller wiring
        """\
import random


class Jitter:
    def __init__(self):
        self._rng = random.Random(0)
""",
        # inject-or-seed: the in-tree queue.py / rest.py idiom
        """\
import random


class Jitter:
    def __init__(self, rng=None):
        self._rng = rng if rng is not None else random.Random(17)
""",
        # draws from an injected rng are the point, not a finding
        """\
def pick(replicas, rng):
    return replicas[rng.randrange(len(replicas))]
""",
    ],
    "DET603": [
        # injectable sleeper attribute (the jaxservice watch idiom)
        """\
import time


class Loop:
    def __init__(self, sleep=time.sleep):
        self._sleep = sleep

    def run_once(self, fn):
        fn()
        self._sleep(0.0)
""",
        # injectable sleeper parameter
        """\
import time


def drain(q, sleep=time.sleep):
    while q:
        q.pop()
        sleep(0.01)
""",
        # event waits are interruptible coordination, not raw sleeps
        """\
import threading


def pause(stop, interval):
    stop.wait(interval)
""",
    ],
    "DET604": [
        # uuid5 is a pure function of its inputs: replayable
        """\
import uuid


def stable_id(name):
    return uuid.uuid5(uuid.NAMESPACE_URL, name).hex
""",
        # ordering on a stable field
        """\
def order(pods):
    return sorted(pods, key=lambda p: p["uid"])
""",
        # a key= that is a Name but not id
        """\
def shortest_first(names):
    names.sort(key=len)
    return names
""",
    ],
}

CTL_BAD = {
    "CTL501": [
        # delete ordered before the durable record write
        ("""\
class Reconciler:
    def reconcile(self, client, job):
        client.delete("v1", "Pod", "p0")
        job["status"]["phase"] = "Restarting"
        client.update_status(job)
""", 3),
        # call-graph: the helper transitively deletes, so its call site
        # counts as the delete
        ("""\
class Reconciler:
    def _purge(self, client, pods):
        for p in pods:
            client.delete("v1", "Pod", p)

    def restart(self, client, job, pods):
        self._purge(client, pods)
        client.update_status(job)
""", 7),
    ],
    "CTL502": [
        # unconditional status write per pass: the PR 5 status storm
        ("""\
class Reconciler:
    def reconcile(self, client, job):
        job["status"]["phase"] = "Active"
        client.update_status(job)
""", 4),
        # unguarded private helper whose one call site is unguarded too
        ("""\
class Reconciler:
    def _flush(self, client, job):
        client.update_status(job)

    def reconcile(self, client, job):
        self._flush(client, job)
""", 3),
    ],
    "CTL503": [
        # bare-statement patch in a cache-wired controller
        ("""\
class Reconciler:
    def __init__(self, cache):
        self.cache = cache

    def reconcile(self, client, pod):
        client.patch("v1", "Pod", pod["name"], {"spec": {}})
        self.cache.note_write(pod)
""", 6),
        # one folded write does not excuse the discarded one
        ("""\
class Reconciler:
    def ensure(self, client, pod):
        obj = client.create(pod)
        self.cache.note_write(obj)

    def ensure_again(self, client, pod):
        client.create(pod)
""", 7),
    ],
    "CTL504": [
        ("""\
class Minter:
    def mint(self, client, obj, tp):
        client.patch("v1", "Pod", obj["name"], {
            "metadata": {"annotations": {"obs.kubeflow.org/traceparent": tp}},
        })
""", 3),
        # the key spelled through a module constant still counts
        ("""\
TRACEPARENT = "obs.kubeflow.org/traceparent"


class Minter:
    def mint(self, client, obj, tp):
        client.replace("v1", "Pod", obj["name"], {
            "metadata": {"annotations": {TRACEPARENT: tp}},
        })
""", 6),
    ],
}

CTL_CLEAN = {
    "CTL501": [
        # record-first: the committed gang-restart discipline
        """\
class Reconciler:
    def restart(self, client, job, pods):
        job["status"]["restarts"] = job["status"].get("restarts", 0) + 1
        if job["status"]:
            client.update_status(job)
        for p in pods:
            client.delete("v1", "Pod", p)
""",
        # a helper that both records and deletes is a self-contained
        # transaction: its call site is neither a delete nor a record
        """\
class Reconciler:
    def _gang_restart(self, client, job, pods):
        client.update_status(job)
        for p in pods:
            client.delete("v1", "Pod", p)

    def reconcile(self, client, job, pods, changed):
        self._gang_restart(client, job, pods)
        if changed:
            client.update_status(job)
""",
        # deletes with no record write here: the caller owns the record
        """\
class Sweeper:
    def sweep(self, client, pods):
        for p in pods:
            client.delete("v1", "Pod", p)
""",
    ],
    "CTL502": [
        # the changed-guard idiom
        """\
class Reconciler:
    def reconcile(self, client, job):
        changed = cond_set(job, "Ready", "True")
        if changed:
            client.update_status(job)
""",
        # the double-checked early-return idiom
        """\
class Reconciler:
    def reconcile(self, client, job, prev):
        if prev == job["status"]:
            return
        client.update_status(job)
""",
        # unguarded private helper, but every resolved call site guards
        """\
class Reconciler:
    def _flush(self, client, job):
        client.update_status(job)

    def reconcile(self, client, job, changed):
        if changed:
            self._flush(client, job)
""",
        # pure delegation: the caller owns the guard
        """\
class Proxy:
    def update_status(self, client, obj):
        return client.update_status(obj)
""",
    ],
    "CTL503": [
        # folded inline through the note helper
        """\
class Reconciler:
    def reconcile(self, client, pod):
        self.cache.note_write(client.patch("v1", "Pod", pod["name"], {}))
""",
        # assigned then folded
        """\
class Reconciler:
    def ensure(self, client, pod):
        created = client.create(pod)
        self.cache.note_write(created)
        return created
""",
        # a class with no cache wiring has nothing to fold into
        """\
class Pusher:
    def push(self, client, obj):
        client.patch("v1", "Pod", obj["name"], {})
""",
    ],
    "CTL504": [
        # rv precondition present: concurrent minters 409 instead of
        # overwriting each other's trace roots
        """\
class Minter:
    def mint(self, client, obj, tp):
        client.patch("v1", "Pod", obj["name"], {
            "metadata": {
                "resourceVersion": obj["metadata"]["resourceVersion"],
                "annotations": {"obs.kubeflow.org/traceparent": tp},
            },
        })
""",
        # annotation patches without a traceparent key are out of scope
        """\
class Annotator:
    def annotate(self, client, obj):
        client.patch("v1", "Pod", obj["name"], {
            "metadata": {"annotations": {"kubeflow.org/owner": "sched"}},
        })
""",
        # reading the annotation is not a mint
        """\
class Reader:
    def trace_of(self, obj):
        return obj["metadata"]["annotations"].get(
            "obs.kubeflow.org/traceparent")
""",
    ],
}


def _scoped_bad_cases():
    cases = [(rule, src, line, DET_PATH)
             for rule, cs in sorted(DET_BAD.items()) for src, line in cs]
    cases += [(rule, src, line, CTL_PATH)
              for rule, cs in sorted(CTL_BAD.items()) for src, line in cs]
    return cases


def _scoped_clean_cases():
    cases = [(rule, src, DET_PATH)
             for rule, cs in sorted(DET_CLEAN.items()) for src in cs]
    cases += [(rule, src, CTL_PATH)
              for rule, cs in sorted(CTL_CLEAN.items()) for src in cs]
    return cases


@pytest.mark.parametrize("rule,src,line,path", _scoped_bad_cases(),
                         ids=lambda v: v if isinstance(v, str) and
                         v.startswith(("DET", "CTL")) else None)
def test_scoped_rule_fires_with_id_and_line(rule, src, line, path):
    findings = _scan_at(path, src)
    hits = [f for f in findings if f.rule == rule]
    assert hits, f"{rule} did not fire; got {[f.render() for f in findings]}"
    assert line in [f.line for f in hits], (
        f"{rule} fired at {[f.line for f in hits]}, expected line {line}")


@pytest.mark.parametrize("rule,src,path", _scoped_clean_cases(),
                         ids=lambda v: v if isinstance(v, str) and
                         v.startswith(("DET", "CTL")) else None)
def test_scoped_clean_fragment_stays_clean(rule, src, path):
    findings = [f for f in _scan_at(path, src) if f.rule == rule]
    assert not findings, [f.render() for f in findings]


def test_scoped_corpus_floor():
    """The ISSUE 16 coverage floor: every DET/CTL rule carries >= 2 bad
    pins and >= 3 clean FP pins."""
    assert set(DET_BAD) == set(DET_CLEAN) == {
        "DET601", "DET602", "DET603", "DET604"}
    assert set(CTL_BAD) == set(CTL_CLEAN) == {
        "CTL501", "CTL502", "CTL503", "CTL504"}
    for table in (DET_BAD, CTL_BAD):
        for rule, cases in table.items():
            assert len(cases) >= 2, f"{rule}: need >= 2 bad pins"
    for table in (DET_CLEAN, CTL_CLEAN):
        for rule, cases in table.items():
            assert len(cases) >= 3, f"{rule}: need >= 3 clean pins"


def test_det601_call_graph_propagation_fires_at_call_site():
    """A helper outside the replay scope that *returns* a wall read
    taints its in-scope call site — the fix (or audited suppression)
    belongs where the value enters the decision path."""
    findings = scan_sources({
        "kubeflow_tpu.control.k8s.clockutil": (
            "import time\n"
            "\n"
            "\n"
            "def wall_stamp():\n"
            "    return time.time()\n"),
        "kubeflow_tpu.control.scheduler.core": (
            "from kubeflow_tpu.control.k8s.clockutil import wall_stamp\n"
            "\n"
            "\n"
            "def admit(job):\n"
            "    job[\"ts\"] = wall_stamp()\n"
            "    return job\n"),
    })
    hits = [f for f in findings if f.rule == "DET601"]
    assert [(f.path, f.line) for f in hits] == \
        [("kubeflow_tpu/control/scheduler/core.py", 5)]
    assert "call-graph" in hits[0].message


def test_det601_injection_seam_helper_does_not_taint_callers():
    """A helper with a clock-ish parameter is the injection seam: its
    internal wall read is the *default*, so in-scope callers stay
    clean."""
    findings = scan_sources({
        "kubeflow_tpu.control.k8s.clockutil": (
            "import time\n"
            "\n"
            "\n"
            "def stamp(clock=None):\n"
            "    return time.time() if clock is None else clock()\n"),
        "kubeflow_tpu.control.scheduler.core": (
            "from kubeflow_tpu.control.k8s.clockutil import stamp\n"
            "\n"
            "\n"
            "def admit(job):\n"
            "    job[\"ts\"] = stamp()\n"
            "    return job\n"),
    })
    assert [f for f in findings if f.rule == "DET601"] == []


def test_det_rules_ignore_modules_outside_replay_scope():
    findings = _scan_at("kubeflow_tpu/control/k8s/rest_frag.py", """\
        import random
        import time


        def jitter(base):
            time.sleep(base * random.random())
    """)
    assert not [f for f in findings if f.rule.startswith("DET")]


def test_ctl_rules_ignore_modules_outside_control_plane():
    findings = _scan_at("kubeflow_tpu/runtime/gc_frag.py", """\
        class Gc:
            def sweep(self, client, job):
                client.delete("v1", "Pod", "p0")
                client.update_status(job)
    """)
    assert not [f for f in findings if f.rule.startswith("CTL")]


# -- the per-family real-tree gates (ISSUE 16 acceptance) --------------------


def test_determinism_family_clean_on_real_tree():
    """Every in-tree DET true positive is fixed or carries an audited
    suppression: the family scan of the shipped package is empty."""
    from kubeflow_tpu.analysis import scan_paths

    findings = scan_paths([str(PACKAGE)],
                          select={"DET601", "DET602", "DET603", "DET604"})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_reconcile_family_clean_on_real_tree():
    from kubeflow_tpu.analysis import scan_paths

    findings = scan_paths([str(PACKAGE)],
                          select={"CTL501", "CTL502", "CTL503", "CTL504"})
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_det_ctl_families_run_clean_on_tree(capsys):
    assert tpulint_main([
        "--select",
        "DET601,DET602,DET603,DET604,CTL501,CTL502,CTL503,CTL504",
        str(PACKAGE)]) == 0
    capsys.readouterr()


# -- SARIF round-trip for the new ids ----------------------------------------


def test_sarif_roundtrip_for_det_and_ctl_ids():
    from kubeflow_tpu.analysis.report import render_sarif

    findings = []
    for rule, cases in sorted(DET_BAD.items()):
        findings += [f for f in _scan_at(DET_PATH, cases[0][0])
                     if f.rule == rule]
    for rule, cases in sorted(CTL_BAD.items()):
        findings += [f for f in _scan_at(CTL_PATH, cases[0][0])
                     if f.rule == rule]
    doc = json.loads(render_sarif(findings))
    run = doc["runs"][0]
    rules = {r["id"]: r for r in run["tool"]["driver"]["rules"]}
    want = {"DET601", "DET602", "DET603", "DET604",
            "CTL501", "CTL502", "CTL503", "CTL504"}
    assert want <= set(rules)
    for rid in want:
        assert rules[rid]["shortDescription"]["text"]
    assert {r["ruleId"] for r in run["results"]} == want
    for res in run["results"]:
        region = res["locations"][0]["physicalLocation"]["region"]
        assert region["startLine"] >= 1 and region["startColumn"] >= 1


# -- the --jobs output law: parallel == serial, byte for byte ----------------


def test_parallel_scan_is_byte_identical_to_serial(tmp_path, capsys):
    """The pinned output law for the fork-pool engine: a --jobs N scan
    of a multi-module corpus (both rule families firing across several
    modules) produces byte-identical output and the same exit code as
    the serial scan."""
    corpus = {
        "control/scheduler/admit.py": DET_BAD["DET601"][0][0],
        "control/scheduler/jitter.py": DET_BAD["DET602"][0][0],
        "control/scheduler/pace.py": DET_BAD["DET603"][0][0],
        "control/scheduler/ids.py": DET_BAD["DET604"][0][0],
        "control/reconcile.py": CTL_BAD["CTL501"][0][0],
        "control/status.py": CTL_BAD["CTL502"][0][0],
        "control/cachefold.py": CTL_BAD["CTL503"][0][0],
        "control/mint.py": CTL_BAD["CTL504"][0][0],
    }
    for rel, src in corpus.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))

    from kubeflow_tpu.analysis import scan_paths

    serial = scan_paths([str(tmp_path)])
    par = scan_paths([str(tmp_path)], jobs=4)
    assert par == serial
    assert {f.rule for f in serial} >= {
        "DET601", "DET602", "DET603", "DET604",
        "CTL501", "CTL502", "CTL503", "CTL504"}

    rc_serial = tpulint_main(["--json", str(tmp_path)])
    out_serial = capsys.readouterr().out
    rc_par = tpulint_main(["--jobs", "4", "--json", str(tmp_path)])
    out_par = capsys.readouterr().out
    assert rc_serial == rc_par == 1
    assert out_par == out_serial


def test_cli_rejects_negative_jobs(tmp_path, capsys):
    (tmp_path / "m.py").write_text("x = 1\n")
    assert tpulint_main(["--jobs", "-1", str(tmp_path)]) == 2
    assert "--jobs" in capsys.readouterr().err


def test_stale_det_ctl_suppressions_are_flagged():
    """HYG004 extends to the new families: a disable on a line where the
    rule does not fire is an orphaned suppression, and a live one is
    honored without going stale."""
    stale = _scan_at(DET_PATH, """\
        def quiet():
            return 1  # tpulint: disable=DET601  nothing fires here
    """)
    assert [f.rule for f in stale] == ["HYG004"]
    assert "DET601 does not fire" in stale[0].message

    live = _scan_at(DET_PATH, """\
        import time


        def admit():
            return time.time()  # tpulint: disable=DET601  corpus pin
    """)
    assert live == [], [f.render() for f in live]

    stale_ctl = _scan_at(CTL_PATH, """\
        def quiet():
            return 1  # tpulint: disable=CTL502  nothing fires here
    """)
    assert [f.rule for f in stale_ctl] == ["HYG004"]


# ==========================================================================
# ISSUE 17: RES7xx resource-lifecycle (exception-edge CFG) and WIRE8xx
# wire-contract one-spelling corpora. RES rules are whole-program and
# path-insensitive to module location; WIRE corpora sit at a non-owner
# path so re-spelling fires, with owner-side shapes tested separately.
# ==========================================================================

RES_PATH = "kubeflow_tpu/serving/_res_corpus.py"
WIRE_PATH = "kubeflow_tpu/control/_wire_corpus.py"

RES_BAD = {
    "RES701": [
        # the motivating shape: a throwing install between admit and
        # free leaks every claimed page on the exception edge
        ("""\
class Decoder:
    def admit_one(self, slot, row):
        plan = self.alloc.admit(slot, row, 0, 8)
        self.install(plan.pages)
        self.alloc.free(slot)
""", 3),
        # the continuous.py bug: the handler recycles the slot id but
        # never frees the admission's pages
        ("""\
class Decoder:
    def admit_one(self, slot, row, item):
        plan = self.alloc.admit(slot, row, 0, 8)
        try:
            self.install(plan.pages)
        except Exception as e:
            self.free_slots.append(slot)
            self.fail_all(e)
            return
        self.owners[slot] = plan
""", 3),
    ],
    "RES702": [
        # ledger leak: the model call can raise between submit and
        # complete
        ("""\
class Plane:
    def handle(self, req):
        t = self.router.submit(req)
        out = self.model.run(req)
        self.router.complete(t)
        return out
""", 3),
        # a narrow handler returns without completing OR failing (and
        # other exception types escape past it entirely)
        ("""\
class Plane:
    def handle(self, req):
        t = self.router.submit(req)
        try:
            out = self.model.run(req)
        except TimeoutError:
            return None
        self.router.complete(t)
        return out
""", 3),
    ],
    "RES703": [
        # a take() that raises abandons the fork: the planner ledger
        # silently diverges from what was placed
        ("""\
class Planner:
    def place(self, txn, pods):
        trial = txn.fork()
        for pod in pods:
            trial.take(pod, 1)
        trial.commit()
""", 3),
        # early return drops the trial with neither commit nor rollback
        ("""\
class Planner:
    def place(self, txn):
        trial = txn.fork()
        if self.flag:
            return None
        trial.commit()
""", 3),
    ],
    "RES704": [
        # the runtime.py window: a throwing statement between begin()
        # and the try whose finally finishes the span orphans it
        ("""\
class Loop:
    def process(self, req):
        span = self.tracer.begin("work")
        t0 = self.clock()
        try:
            self.handle(req)
        finally:
            self.tracer.finish(span)
""", 3),
        # early return never finishes: the span never exports
        ("""\
class Loop:
    def process(self, req):
        span = self.tracer.begin("work")
        if self.skip:
            return None
        self.tracer.finish(span)
""", 3),
    ],
    "RES705": [
        # released on only one branch: the False path returns holding
        # the lock
        ("""\
class Guard:
    def tick(self):
        self.lock.acquire()
        if self.flag:
            self.lock.release()
            return True
        return False
""", 3),
        # a throwing call between acquire and release leaks on the
        # exception edge (the CFG upgrade over LOCK201's statements)
        ("""\
class Guard:
    def bump(self):
        self.mu.acquire()
        self.refresh()
        self.mu.release()
""", 3),
    ],
}

RES_CLEAN = {
    "RES701": [
        # release-in-finally is proven across every continuation
        """\
class Decoder:
    def admit_one(self, slot, row):
        plan = self.alloc.admit(slot, row, 0, 8)
        try:
            self.install(plan.pages)
        finally:
            self.alloc.free(slot)
""",
        # the fixed continuous.py shape: pages freed in the handler,
        # ownership published to the keyed table on success
        """\
class Decoder:
    def admit_one(self, slot, row):
        plan = self.alloc.admit(slot, row, 0, 8)
        try:
            self.install(plan.pages)
        except Exception:
            self.alloc.free(slot)
            raise
        self.owners[slot] = plan
""",
        # release-via-helper: the consumption summary proves _hand_off
        # stores the plan somewhere that outlives the function
        """\
class Decoder:
    def admit_one(self, slot, row):
        plan = self.alloc.admit(slot, row, 0, 8)
        self._hand_off(slot, plan)

    def _hand_off(self, slot, plan):
        self.ring.append(plan)
""",
        # discarded result + key-store publication (the bench leak
        # drill): `live[s] = ...` hands the slot to the table's owner
        """\
class Bench:
    def drill(self, s, row, live):
        self.alloc.admit(s, row, 0, 8)
        live[s] = (32, 8)
""",
    ],
    "RES702": [
        # finally completes or fails on every path out
        """\
class Plane:
    def handle(self, req):
        t = self.router.submit(req)
        ok = False
        try:
            out = self.model.run(req)
            ok = True
        finally:
            if ok:
                self.router.complete(t)
            else:
                self.router.fail(t)
        return out
""",
        # ticket handed to the owning queue: ownership transferred
        """\
class Plane:
    def handle(self, req):
        t = self.router.submit(req)
        self.inflight.put(t)
""",
        # discarded ticket: the router owns its own lifecycle
        """\
class Plane:
    def handle(self, req):
        self.router.submit(req)
        return self.model.run(req)
""",
    ],
    "RES703": [
        # the full discipline: rollback in the handler, commit or
        # rollback on the two normal paths
        """\
class Planner:
    def place(self, txn):
        trial = txn.fork()
        try:
            ok = self.score()
        except Exception:
            trial.rollback()
            raise
        if ok:
            trial.commit()
            return True
        trial.rollback()
        return False
""",
        # returned to the caller, which owns it now
        """\
class Planner:
    def begin(self, txn):
        trial = txn.fork()
        return trial
""",
        # closed by a helper the consumption summary resolves
        """\
class Planner:
    def place(self, txn):
        trial = txn.fork()
        self._close(trial)

    def _close(self, trial):
        trial.commit()
""",
    ],
    "RES704": [
        # begin -> try/finally finish, nothing in the window
        """\
class Loop:
    def process(self, req):
        span = self.tracer.begin("work")
        try:
            self.handle(req)
        finally:
            self.tracer.finish(span)
""",
        # stored where the finisher finds it: escaped to an owner
        """\
class Loop:
    def start(self, key):
        span = self.tracer.begin("work")
        self.open_spans[key] = span
""",
        # the context manager is not a detached begin at all
        """\
class Loop:
    def process(self, req):
        with self.tracer.span("work"):
            self.handle(req)
""",
    ],
    "RES705": [
        # release in finally covers the exception edge
        """\
class Guard:
    def tick(self):
        self.lock.acquire()
        try:
            self.mutate()
        finally:
            self.lock.release()
""",
        # `with` is inherently balanced and never tokenized
        """\
class Guard:
    def tick(self):
        with self.lock:
            self.mutate()
""",
        # released on BOTH branches (no throwing statement while held)
        """\
class Guard:
    def tick(self):
        self.lock.acquire()
        if self.flag:
            self.lock.release()
            return True
        self.lock.release()
        return False
""",
    ],
}

WIRE_BAD = {
    "WIRE801": [
        # domain-prefix ownership: the jaxjob domain belongs to its
        # types module, even for a module-level constant elsewhere
        ("""\
GANG = "jaxjob.kubeflow.org/replica-type"
""", 1),
        # inline key at a use site outside the owner
        ("""\
def stamp(meta):
    meta["obs.kubeflow.org/traceparent"] = "00-1"
    return meta
""", 2),
        # a key in a domain nobody claimed must be claimed in the map
        ("""\
KNOB = "mystery.kubeflow.org/knob"
""", 1),
    ],
    "WIRE802": [
        # env read through a re-spelled literal
        ("""\
import os

ADDR = os.environ.get("JAXJOB_COORDINATOR_ADDRESS", "")
""", 3),
        # constant re-defined outside the owning module
        ("""\
RATE = "TPU_CHAOS_RATE"
""", 1),
    ],
    "WIRE803": [
        ("""\
DEADLINE = "x-request-deadline"
""", 1),
        ("""\
def tag(h):
    h["x-request-hedge"] = "1"
    return h
""", 2),
    ],
}

WIRE_CLEAN = {
    "WIRE801": [
        # group/version coordinates are not annotation keys
        """\
API_VERSION = "scheduler.kubeflow.org/v1alpha1"
""",
        # a bare string statement is prose, not a contract site
        """\
def doc():
    "jaxjob.kubeflow.org/replica-type"
    return None
""",
        # non-kubeflow domains are out of scope
        """\
KEY = "config.example.com/key"
""",
    ],
    "WIRE802": [
        # unmapped prefixes are opt-in: bare TPU_* stays unclaimed
        """\
KNOB = "TPU_CUSTOM_KNOB"
""",
        # log templates are not full-string matches
        """\
MSG = "TPU_CHAOS_SEED=%s"
""",
        # lowercase strings are not env names
        """\
name = "jaxjob_process_id"
""",
    ],
    "WIRE803": [
        # a format template is not a header literal
        """\
PAT = "x-request-%s"
""",
        # near-miss header outside the x-request- namespace
        """\
H = "x-requested-with"
""",
        # prose mention
        """\
def doc():
    "x-request-deadline"
    return None
""",
    ],
}


def _issue17_bad_cases():
    cases = [(rule, src, line, RES_PATH)
             for rule, cs in sorted(RES_BAD.items()) for src, line in cs]
    cases += [(rule, src, line, WIRE_PATH)
              for rule, cs in sorted(WIRE_BAD.items()) for src, line in cs]
    return cases


def _issue17_clean_cases():
    cases = [(rule, src, RES_PATH)
             for rule, cs in sorted(RES_CLEAN.items()) for src in cs]
    cases += [(rule, src, WIRE_PATH)
              for rule, cs in sorted(WIRE_CLEAN.items()) for src in cs]
    return cases


@pytest.mark.parametrize("rule,src,line,path", _issue17_bad_cases(),
                         ids=lambda v: v if isinstance(v, str) and
                         v.startswith(("RES", "WIRE")) else None)
def test_res_wire_rule_fires_with_id_and_line(rule, src, line, path):
    findings = _scan_at(path, src)
    hits = [f for f in findings if f.rule == rule]
    assert hits, f"{rule} did not fire; got {[f.render() for f in findings]}"
    assert line in [f.line for f in hits], (
        f"{rule} fired at {[f.line for f in hits]}, expected line {line}")


@pytest.mark.parametrize("rule,src,path", _issue17_clean_cases(),
                         ids=lambda v: v if isinstance(v, str) and
                         v.startswith(("RES", "WIRE")) else None)
def test_res_wire_clean_fragment_stays_clean(rule, src, path):
    findings = [f for f in _scan_at(path, src) if f.rule == rule]
    assert not findings, [f.render() for f in findings]


def test_res_wire_corpus_floor():
    """The ISSUE 17 coverage floor: every RES/WIRE rule carries >= 2
    bad pins and >= 3 clean FP pins."""
    assert set(RES_BAD) == set(RES_CLEAN) == {
        "RES701", "RES702", "RES703", "RES704", "RES705"}
    assert set(WIRE_BAD) == set(WIRE_CLEAN) == {
        "WIRE801", "WIRE802", "WIRE803"}
    for table in (RES_BAD, WIRE_BAD):
        for rule, cases in table.items():
            assert len(cases) >= 2, f"{rule}: need >= 2 bad pins"
    for table in (RES_CLEAN, WIRE_CLEAN):
        for rule, cases in table.items():
            assert len(cases) >= 3, f"{rule}: need >= 3 clean pins"


def test_res701_leak_message_names_the_exception_exit():
    findings = [f for f in _scan_at(RES_PATH, RES_BAD["RES701"][0][0])
                if f.rule == "RES701"]
    assert len(findings) == 1
    assert "exception path" in findings[0].message
    assert "free the slot" in findings[0].message


def test_res_release_via_unresolved_call_gets_benefit_of_doubt():
    """A token passed bare to a call the program cannot resolve is a
    handoff, not a leak — cross-module noise stays impossible."""
    findings = _scan_at(RES_PATH, """\
        from somewhere import publish


        class Plane:
            def handle(self, req):
                t = self.router.submit(req)
                publish(t)
    """)
    assert [f for f in findings if f.rule == "RES702"] == []


def test_res_resolved_nonconsuming_callee_keeps_token_live():
    """The flip side: a resolved helper that only LOOKS at the token
    does not count as a release."""
    findings = _scan_at(RES_PATH, """\
        class Plane:
            def handle(self, req):
                t = self.router.submit(req)
                self._log(t)

            def _log(self, t):
                self.n += 1
    """)
    hits = [f for f in findings if f.rule == "RES702"]
    assert [(f.line,) for f in hits] == [(3,)]


def test_wire_exact_key_override_beats_domain_prefix():
    """jaxservice.kubeflow.org/endpoints belongs to the serving router
    even though the jaxservice domain belongs to its types module."""
    hits = [f for f in _scan_at(WIRE_PATH, """\
        ENDPOINTS = "jaxservice.kubeflow.org/endpoints"
    """) if f.rule == "WIRE801"]
    assert len(hits) == 1
    assert "kubeflow_tpu/serving/router.py" in hits[0].message


def test_wire_inline_literal_in_owner_module_is_flagged():
    hits = [f for f in _scan_at("kubeflow_tpu/tune/studyjob.py", """\
        def annotate(meta):
            meta["studyjob.kubeflow.org/parameters"] = "{}"
            return meta
    """) if f.rule == "WIRE801"]
    assert [f.line for f in hits] == [2]
    assert "owning module" in hits[0].message


def test_wire_duplicate_definition_in_owner_is_flagged():
    hits = [f for f in _scan_at("kubeflow_tpu/control/k8s/chaos.py", """\
        ENV_SEED = "TPU_CHAOS_SEED"
        ENV_SEED2 = "TPU_CHAOS_SEED"
    """) if f.rule == "WIRE802"]
    assert [f.line for f in hits] == [2]
    assert "duplicate definition" in hits[0].message


def test_wire_owner_definition_site_is_clean():
    findings = _scan_at("kubeflow_tpu/control/k8s/chaos.py", """\
        ENV_SEED = "TPU_CHAOS_SEED"
        ENV_RATE = "TPU_CHAOS_RATE"
    """)
    assert [f for f in findings if f.rule.startswith("WIRE")] == []


def test_wire_analysis_package_is_exempt():
    findings = _scan_at("kubeflow_tpu/analysis/_frag.py", """\
        OWNERS = {"jaxjob.kubeflow.org/replica-type": "somewhere"}
    """)
    assert [f for f in findings if f.rule.startswith("WIRE")] == []


# -- the per-family real-tree gates (ISSUE 17 acceptance) --------------------


RES_IDS = {"RES701", "RES702", "RES703", "RES704", "RES705"}
WIRE_IDS = {"WIRE801", "WIRE802", "WIRE803"}


def test_resource_family_clean_on_real_tree():
    """Every in-tree RES true positive is fixed (not suppressed): the
    family scan of the shipped package is empty."""
    from kubeflow_tpu.analysis import scan_paths

    findings = scan_paths([str(PACKAGE)], select=RES_IDS)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_wire_family_clean_on_real_tree():
    from kubeflow_tpu.analysis import scan_paths

    findings = scan_paths([str(PACKAGE)], select=WIRE_IDS)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_family_prefix_expansion_selects_whole_families(capsys):
    """--rules RES,WIRE expands to every registered RES7xx/WIRE8xx id
    (the ISSUE 17 CLI contract) and runs clean on the shipped tree."""
    assert tpulint_main(["--rules", "RES,WIRE", str(PACKAGE)]) == 0
    capsys.readouterr()


def test_cli_family_prefix_expansion_fires_on_corpus(tmp_path, capsys):
    p = tmp_path / "leak.py"
    p.write_text(RES_BAD["RES701"][0][0])
    assert tpulint_main(["--rules", "RES", str(p)]) == 1
    out = capsys.readouterr().out
    assert "RES701" in out
    # an unknown family token still fails fast as an unknown id
    assert tpulint_main(["--rules", "ZZZ", str(p)]) == 2
    assert "unknown rule id: ZZZ" in capsys.readouterr().err


def test_cli_sarif_file_writes_artifact_alongside_stdout(tmp_path, capsys):
    """--sarif-file emits a parseable SARIF artifact while stdout keeps
    the selected format (the lint_all.sh --sarif-dir contract)."""
    src = tmp_path / "leak.py"
    src.write_text(RES_BAD["RES701"][0][0])
    artifact = tmp_path / "out.sarif"
    rc = tpulint_main(["--rules", "RES",
                       "--sarif-file", str(artifact), str(src)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "RES701" in out and not out.lstrip().startswith("{")
    doc = json.loads(artifact.read_text())
    assert {r["ruleId"] for r in doc["runs"][0]["results"]} == {"RES701"}


def test_parallel_res_wire_scan_is_byte_identical(tmp_path, capsys):
    """The --jobs output law extends to the new families: program-rule
    (RES, CFG dataflow) and file-rule (WIRE) findings from a fork-pool
    scan are byte-identical to the serial run."""
    corpus = {
        "serving/decode.py": RES_BAD["RES701"][0][0],
        "serving/plane.py": RES_BAD["RES702"][1][0],
        "control/planner.py": RES_BAD["RES703"][0][0],
        "control/loop.py": RES_BAD["RES704"][0][0],
        "control/guard.py": RES_BAD["RES705"][0][0],
        "control/keys.py": WIRE_BAD["WIRE801"][0][0],
        "control/envs.py": WIRE_BAD["WIRE802"][0][0],
        "serving/headers.py": WIRE_BAD["WIRE803"][0][0],
        "serving/clean.py": RES_CLEAN["RES701"][0],
    }
    for rel, src in corpus.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))

    from kubeflow_tpu.analysis import scan_paths

    serial = scan_paths([str(tmp_path)], select=RES_IDS | WIRE_IDS)
    par = scan_paths([str(tmp_path)], select=RES_IDS | WIRE_IDS, jobs=4)
    assert par == serial
    assert {f.rule for f in serial} == {
        "RES701", "RES702", "RES703", "RES704", "RES705",
        "WIRE801", "WIRE802", "WIRE803"}

    rc_serial = tpulint_main(["--rules", "RES,WIRE", "--json",
                              str(tmp_path)])
    out_serial = capsys.readouterr().out
    rc_par = tpulint_main(["--rules", "RES,WIRE", "--jobs", "4",
                           "--json", str(tmp_path)])
    out_par = capsys.readouterr().out
    assert rc_serial == rc_par == 1
    assert out_par == out_serial


def test_stale_res_suppressions_are_flagged():
    """HYG004 extends to the RES family: an orphaned disable goes
    stale, a live pin is honored."""
    stale = _scan_at(RES_PATH, """\
        def quiet():
            return 1  # tpulint: disable=RES701  nothing fires here
    """)
    assert [f.rule for f in stale] == ["HYG004"]
    assert "RES701 does not fire" in stale[0].message

    live = _scan_at(RES_PATH, """\
        class Guard:
            def bump(self):
                self.mu.acquire()  # tpulint: disable=RES705  corpus pin
                self.refresh()
                self.mu.release()
    """)
    assert live == [], [f.render() for f in live]
