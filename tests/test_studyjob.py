"""StudyJob sweep semantics — preserves the condition contract the
reference's E2E polls (testing/katib_studyjob_test.py:128-194)."""

import pytest

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxjob.controller import build_controller as build_jaxjob
from kubeflow_tpu.control.jaxjob.controller import worker_name
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
from kubeflow_tpu.control.runtime import seed_controller
from kubeflow_tpu.tune import studyjob as SJ


@pytest.fixture()
def world():
    cluster = FakeCluster()
    study_ctl = seed_controller(SJ.build_controller(cluster))
    jaxjob_ctl = seed_controller(build_jaxjob(cluster, record_events=False))
    kubelet = FakeKubelet(cluster)
    return cluster, study_ctl, jaxjob_ctl, kubelet


def drain(*ctls):
    for _ in range(8):
        for c in ctls:
            c.run_until_idle(advance_delayed=True)


PARAMS = [
    {"name": "lr", "parameterType": "double",
     "feasible": {"min": 0.01, "max": 0.03, "steps": 3}},
    {"name": "opt", "parameterType": "categorical",
     "feasible": {"list": ["sgd", "adamw"]}},
]

TRIAL_TEMPLATE = {
    "spec": {
        "replicas": 1,
        "template": {"spec": {"containers": [{
            "name": "jax", "image": "kubeflow-tpu/jaxrt:latest",
            "command": ["python", "-m", "kubeflow_tpu.runtime.launcher",
                        "--learning-rate=${lr}", "--optimizer=${opt}"],
        }]}},
    }
}


class TestSuggestions:
    def test_grid(self):
        out = SJ.grid_suggestions(PARAMS, max_trials=6)
        assert len(out) == 6
        assert {s["opt"] for s in out} == {"sgd", "adamw"}
        assert all(0.01 <= s["lr"] <= 0.03 for s in out)

    def test_grid_truncates_to_max(self):
        assert len(SJ.grid_suggestions(PARAMS, max_trials=2)) == 2

    def test_random_deterministic_by_seed(self):
        a = SJ.random_suggestions(PARAMS, 4, seed=7)
        b = SJ.random_suggestions(PARAMS, 4, seed=7)
        assert a == b

    def test_template_substitution(self):
        trial = SJ.StudyJobReconciler().generate_trial(
            SJ.new_studyjob("s", parameters=PARAMS, trial_template=TRIAL_TEMPLATE),
            0, {"lr": 0.02, "opt": "adamw"},
        )
        cmd = trial["spec"]["template"]["spec"]["containers"][0]["command"]
        assert "--learning-rate=0.02" in cmd and "--optimizer=adamw" in cmd
        # full-token substitution keeps native types (usable for replicas etc.)
        sub = SJ._substitute({"replicas": "${n}"}, {"n": 4})
        assert sub["replicas"] == 4


class TestSweepLifecycle:
    def run_all_trials(self, cluster, study_ctl, jaxjob_ctl, kubelet, objective):
        """Drive trials to completion, reporting `objective(params)`."""
        import json

        for _ in range(30):
            drain(study_ctl, jaxjob_ctl)
            kubelet.step()
            drain(study_ctl, jaxjob_ctl)
            jobs = cluster.list(JT.API_VERSION, JT.KIND, namespace="default")
            progressed = False
            for job in jobs:
                if ob.cond_is_true(job, JT.COND_SUCCEEDED):
                    continue
                if not ob.cond_is_true(job, JT.COND_RUNNING):
                    continue
                params = json.loads(ob.annotations_of(job)[
                    "studyjob.kubeflow.org/parameters"])
                fresh = cluster.get(JT.API_VERSION, JT.KIND,
                                    ob.meta(job)["name"], "default")
                ob.set_annotation(fresh, SJ.ANNO_OBJECTIVE,
                                  str(objective(params)))
                cluster.update(fresh)
                kubelet.succeed(worker_name(ob.meta(job)["name"], 0))
                progressed = True
            drain(study_ctl, jaxjob_ctl)
            study = cluster.get(SJ.API_VERSION, SJ.KIND, "sweep", "default")
            if ob.cond_is_true(study, SJ.COND_SUCCEEDED):
                return study
            if not progressed and not jobs:
                continue
        return cluster.get(SJ.API_VERSION, SJ.KIND, "sweep", "default")

    def test_full_sweep_finds_best(self, world):
        cluster, study_ctl, jaxjob_ctl, kubelet = world
        cluster.create(SJ.new_studyjob(
            "sweep", parameters=PARAMS, trial_template=TRIAL_TEMPLATE,
            max_trials=4, parallel_trials=2))
        drain(study_ctl, jaxjob_ctl)
        # katib contract: Running condition while trials execute
        study = cluster.get(SJ.API_VERSION, SJ.KIND, "sweep", "default")
        assert ob.cond_is_true(study, SJ.COND_RUNNING)
        # parallelism cap respected
        jobs = cluster.list(JT.API_VERSION, JT.KIND, namespace="default")
        assert len(jobs) == 2

        study = self.run_all_trials(cluster, study_ctl, jaxjob_ctl, kubelet,
                                    objective=lambda p: p["lr"])
        assert ob.cond_is_true(study, SJ.COND_SUCCEEDED)
        assert not ob.cond_is_true(study, SJ.COND_RUNNING)
        assert study["status"]["trials"]["completed"] == 4
        best = study["status"]["bestTrial"]
        # minimize lr -> best has the smallest lr among the 4 grid points
        assert best["objective"] == min(
            s["lr"] for s in SJ.grid_suggestions(PARAMS, 4))

    def test_maximize_direction(self, world):
        cluster, study_ctl, jaxjob_ctl, kubelet = world
        sj = SJ.new_studyjob("sweep", parameters=PARAMS,
                             trial_template=TRIAL_TEMPLATE,
                             max_trials=3, parallel_trials=3, goal="maximize")
        cluster.create(sj)
        study = self.run_all_trials(cluster, study_ctl, jaxjob_ctl, kubelet,
                                    objective=lambda p: p["lr"])
        best = study["status"]["bestTrial"]
        assert best["objective"] == max(
            s["lr"] for s in SJ.grid_suggestions(PARAMS, 3))

    def test_bad_algorithm_fails(self, world):
        cluster, study_ctl, _, _ = world
        sj = SJ.new_studyjob("sweep", algorithm="bayes", parameters=PARAMS)
        cluster.create(sj)
        drain(study_ctl)
        study = cluster.get(SJ.API_VERSION, SJ.KIND, "sweep", "default")
        assert ob.cond_is_true(study, SJ.COND_FAILED)

    def test_study_delete_cascades_to_trials(self, world):
        cluster, study_ctl, jaxjob_ctl, _ = world
        cluster.create(SJ.new_studyjob(
            "sweep", parameters=PARAMS, trial_template=TRIAL_TEMPLATE,
            max_trials=4, parallel_trials=2))
        drain(study_ctl, jaxjob_ctl)
        assert cluster.list(JT.API_VERSION, JT.KIND, namespace="default")
        cluster.delete(SJ.API_VERSION, SJ.KIND, "sweep", "default")
        assert cluster.list(JT.API_VERSION, JT.KIND, namespace="default") == []
