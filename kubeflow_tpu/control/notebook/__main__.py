from kubeflow_tpu.control.mains import run_controller
from kubeflow_tpu.control.notebook.controller import build_controller

run_controller("notebook-controller", lambda client, args: build_controller(client))
