"""KV-cache generation: decode path must agree exactly with the full
(training) forward — the teacher-forcing consistency check."""

import jax
import jax.numpy as jnp
import numpy as np
from flax.core import meta

from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.runtime.generate import generate, init_cache


def make_model_and_params(seed=0, **kw):
    model = get_model("transformer-test", max_seq_len=64, **kw)
    tok = jnp.zeros((2, 8), jnp.int32)
    variables = meta.unbox(model.init(jax.random.PRNGKey(seed), tok))
    return model, variables


def test_greedy_matches_full_forward():
    model, variables = make_model_and_params()
    rng = jax.random.PRNGKey(1)
    prompt = jax.random.randint(rng, (2, 8), 0, 256, jnp.int32)
    out = generate(model, variables, prompt, max_new_tokens=6)
    assert out.shape == (2, 14)
    np.testing.assert_array_equal(np.asarray(out[:, :8]), np.asarray(prompt))

    # teacher forcing: each generated token is the argmax of the FULL
    # (non-cached) forward at its position -> cache semantics are exact.
    logits = model.apply(variables, out[:, :-1], train=False)
    for i in range(6):
        pos = 8 + i - 1  # logits at pos predict token pos+1
        want = jnp.argmax(logits[:, pos], axis=-1)
        np.testing.assert_array_equal(
            np.asarray(out[:, 8 + i]), np.asarray(want),
            err_msg=f"generated token {i} diverges from full forward")


def test_sampling_is_seeded_and_in_range():
    model, variables = make_model_and_params()
    prompt = jnp.ones((2, 4), jnp.int32)
    a = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=1.0, top_k=10, seed=3)
    b = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=1.0, top_k=10, seed=3)
    c = generate(model, variables, prompt, max_new_tokens=5,
                 temperature=1.0, top_k=10, seed=4)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (np.asarray(a)[:, 4:] >= 0).all()
    assert (np.asarray(a)[:, 4:] < 256).all()
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_gqa_cache_shapes():
    model, variables = make_model_and_params()
    cache = init_cache(model, variables, batch=3)
    leaves = jax.tree.leaves(cache)
    assert leaves, "no cache variables created"
    for leaf in leaves:
        assert leaf.shape[0] == 3 and leaf.shape[1] == 64  # B, max_seq
        assert leaf.shape[2] == 2  # n_kv_heads of transformer-test
