"""PodDefault mutation logic + AdmissionReview webhook server.

Mirrors admission-webhook/main.go:
- filterPodDefaults (:69-96): namespace PodDefaults whose selector
  matches the pod's labels;
- safeToApplyPodDefaultsOnPod (:98-145): conflict detection — an env var
  or volumeMount required by two defaults with different values rejects
  the whole set rather than corrupting the pod;
- merge functions (:147-316) for env, envFrom, volumes, volumeMounts,
  tolerations;
- applyPodDefaultsOnPod (:321-387): mutation + the applied-annotation
  `poddefault.admission.kubeflow.org/poddefault-<name>`;
- mutatePods (:389-486): AdmissionReview -> JSONPatch response.

The HTTP server speaks admission/v1 AdmissionReview JSON; in tests the
same mutator is wired straight into FakeCluster.add_admission_hook —
exactly where the real admission chain sits.
"""

from __future__ import annotations

import base64
import copy
import json
import logging
import os
import time

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.utils.httpd import HttpReq, HttpService, Router, json_resp

log = logging.getLogger("kubeflow_tpu.poddefault")

GROUP = "kubeflow.org"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "PodDefault"

ANNOTATION_PREFIX = "poddefault.admission.kubeflow.org"  # main.go:44


def new_poddefault(
    name: str,
    namespace: str = "default",
    *,
    selector: dict | None = None,
    desc: str = "",
    env: list[dict] | None = None,
    env_from: list[dict] | None = None,
    volumes: list[dict] | None = None,
    volume_mounts: list[dict] | None = None,
    tolerations: list[dict] | None = None,
    labels: dict | None = None,
    annotations: dict | None = None,
) -> dict:
    spec: dict = {"selector": selector or {}, "desc": desc or name}
    if env:
        spec["env"] = env
    if env_from:
        spec["envFrom"] = env_from
    if volumes:
        spec["volumes"] = volumes
    if volume_mounts:
        spec["volumeMounts"] = volume_mounts
    if tolerations:
        spec["tolerations"] = tolerations
    if labels:
        spec["labels"] = labels
    if annotations:
        spec["annotations"] = annotations
    return ob.new_object(API_VERSION, KIND, name, namespace, spec=spec)


def crd_manifest() -> dict:
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"poddefaults.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {"kind": KIND, "listKind": "PodDefaultList",
                      "plural": "poddefaults", "singular": "poddefault"},
            "scope": "Namespaced",
            "versions": [{
                "name": VERSION, "served": True, "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "x-kubernetes-preserve-unknown-fields": True}},
            }],
        },
    }


# ---------------------------------------------------------------------------
# selection + conflict checks


def filter_poddefaults(pod: dict, poddefaults: list[dict]) -> list[dict]:
    """filterPodDefaults (:69-96): selector match against pod labels;
    pods that opted out via annotation are skipped."""
    annos = ob.annotations_of(pod)
    if annos.get(f"{ANNOTATION_PREFIX}/exclude") == "true":
        return []
    labels = ob.labels_of(pod)
    return [
        pd for pd in poddefaults
        if ob.match_labels(labels, (pd.get("spec") or {}).get("selector"))
    ]


def _merge_keyed(existing: list[dict], addition: list[dict], key: str,
                 what: str) -> list[dict]:
    """Shared merge: same key + equal value = skip, same key + different
    value = conflict (mergeEnv/mergeVolumeMounts/… semantics, :147-316)."""
    out = list(existing)
    by_key = {e[key]: e for e in existing if key in e}
    for item in addition:
        cur = by_key.get(item.get(key))
        if cur is None:
            out.append(copy.deepcopy(item))
            by_key[item[key]] = item
        elif cur != item:
            raise ValueError(
                f"conflict on {what} {item.get(key)!r}: "
                f"existing {cur} != injected {item}"
            )
    return out


def safe_to_apply(pod: dict, poddefaults: list[dict]) -> str | None:
    """safeToApplyPodDefaultsOnPod (:98-145): dry-run the merge; returns an
    error string on conflict, None when safe."""
    try:
        apply_poddefaults(copy.deepcopy(pod), poddefaults)
        return None
    except ValueError as e:
        return str(e)


def apply_poddefaults(pod: dict, poddefaults: list[dict]) -> dict:
    """applyPodDefaultsOnPod (:321-387): mutate pod in place and return it."""
    spec = pod.setdefault("spec", {})
    containers = spec.setdefault("containers", [])
    for pd in poddefaults:
        ps = pd.get("spec") or {}
        for c in containers:
            if ps.get("env"):
                c["env"] = _merge_keyed(c.get("env") or [], ps["env"], "name", "env var")
            if ps.get("envFrom"):
                c["envFrom"] = (c.get("envFrom") or []) + copy.deepcopy(ps["envFrom"])
            if ps.get("volumeMounts"):
                c["volumeMounts"] = _merge_keyed(
                    c.get("volumeMounts") or [], ps["volumeMounts"],
                    "mountPath", "volumeMount",
                )
        if ps.get("volumes"):
            spec["volumes"] = _merge_keyed(
                spec.get("volumes") or [], ps["volumes"], "name", "volume")
        if ps.get("tolerations"):
            existing = spec.get("tolerations") or []
            for tol in ps["tolerations"]:
                if tol not in existing:
                    existing.append(copy.deepcopy(tol))
            spec["tolerations"] = existing
        for k, v in (ps.get("labels") or {}).items():
            ob.set_label(pod, k, v)
        for k, v in (ps.get("annotations") or {}).items():
            ob.set_annotation(pod, k, v)
        ob.set_annotation(
            pod, f"{ANNOTATION_PREFIX}/poddefault-{ob.meta(pd)['name']}",
            ob.meta(pd).get("resourceVersion", ""),
        )
    return pod


class PodDefaultMutator:
    """The webhook core, usable in-process (FakeCluster admission hook) or
    behind the AdmissionReview HTTP server."""

    def __init__(self, client):
        self.client = client
        self.certs = None  # set by serve(certs_dir=...)

    def lookup(self, namespace: str) -> list[dict]:
        return self.client.list(API_VERSION, KIND, namespace=namespace)

    def mutate(self, pod: dict) -> dict:
        ns = ob.meta(pod).get("namespace") or "default"
        matched = filter_poddefaults(pod, self.lookup(ns))
        if not matched:
            return pod
        err = safe_to_apply(pod, matched)
        if err is not None:
            # reference behavior: log and admit unmodified (:433-440) —
            # admission must never brick pod creation
            log.warning("poddefaults not applied to %s: %s",
                        ob.meta(pod).get("name"), err)
            return pod
        return apply_poddefaults(pod, matched)

    def admission_hook(self, verb: str, obj: dict) -> dict:
        if verb == "CREATE" and obj.get("kind") == "Pod":
            return self.mutate(obj)
        return obj

    # -- AdmissionReview over HTTP (mutatePods :389-486) -------------------

    def review(self, body: dict) -> dict:
        req = body.get("request") or {}
        pod = req.get("object") or {}
        pod.setdefault("metadata", {}).setdefault(
            "namespace", req.get("namespace", "default"))
        mutated = self.mutate(copy.deepcopy(pod))
        patch = _json_patch_diff(pod, mutated)
        resp: dict = {"uid": req.get("uid", ""), "allowed": True}
        if patch:
            resp["patchType"] = "JSONPatch"
            resp["patch"] = base64.b64encode(json.dumps(patch).encode()).decode()
        return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
                "response": resp}

    def serve(self, host: str = "0.0.0.0", port: int = 0,
              certs_dir: str | None = None) -> HttpService:
        """Serve the AdmissionReview endpoint. With ``certs_dir`` the
        server speaks HTTPS (bootstrapping a CA + serving cert there if
        absent) — the only form a kube apiserver will call
        (main.go:541-542's --tlsCertFile/--tlsKeyFile equivalent)."""
        router = Router("poddefault-webhook")

        def handle(req: HttpReq):
            return json_resp(self.review(req.json()))

        router.route("POST", "/apply-poddefault", handle)
        router.route("POST", "/mutate", handle)
        from kubeflow_tpu.utils.httpd import add_health_routes, add_metrics_route

        add_health_routes(router)
        add_metrics_route(router)
        tls = None
        if certs_dir:
            from kubeflow_tpu.utils import tlscerts

            self.certs = tlscerts.ensure_certs(
                certs_dir, "poddefault-webhook",
                namespace=os.environ.get("POD_NAMESPACE", "kubeflow"))
            tls = tlscerts.server_context(self.certs.cert, self.certs.key)
        return HttpService(router, host, port, tls=tls)

    def publish_ca_bundle(self, registration: str = "poddefault-webhook",
                          retries: int | None = None,
                          delay: float = 2.0) -> bool:
        """Patch this pod's bootstrapped CA into the live
        MutatingWebhookConfiguration so the apiserver can verify us —
        the in-cluster replacement for the reference's out-of-band
        cert-gen step (README.md:66 'caBundle: ...'). Retries because the
        registration may be applied after the pod starts; ``retries=None``
        (the server default) retries forever with capped backoff — giving
        up would leave admission silently skipped under
        failurePolicy: Ignore."""
        if self.certs is None:
            return False
        bundle = self.certs.ca_bundle_b64
        attempt = 0
        while retries is None or attempt < retries:
            attempt += 1
            try:
                hook = self.client.get(
                    "admissionregistration.k8s.io/v1",
                    "MutatingWebhookConfiguration", registration)
                changed = False
                for wh in hook.get("webhooks") or []:
                    cc = wh.setdefault("clientConfig", {})
                    if cc.get("caBundle") != bundle:
                        cc["caBundle"] = bundle
                        changed = True
                if changed:
                    self.client.update(hook)
                return True
            except Exception as e:  # registration not applied yet / conflict
                level = log.warning if attempt % 30 == 0 else log.info
                level("caBundle publish attempt %d: %s", attempt, e)
                time.sleep(min(delay * min(attempt, 8), 15.0))
        log.error("caBundle never published after %d attempts: admission "
                  "will be silently skipped (failurePolicy: Ignore)", attempt)
        return False


def _json_patch_diff(old: dict, new: dict) -> list[dict]:
    """Whole-document replace ops where top-level sections differ — the
    same JSONPatch shape the reference emits (it patches spec and
    metadata wholesale, :477-486)."""
    ops = []
    for section in ("metadata", "spec"):
        if old.get(section) != new.get(section):
            ops.append({"op": "replace", "path": f"/{section}",
                        "value": new.get(section)})
    return ops
