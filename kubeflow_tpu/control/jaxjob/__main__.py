from kubeflow_tpu.control.jaxjob.controller import build_controller
from kubeflow_tpu.control.mains import run_controller

run_controller("jaxjob-controller", lambda client, args: build_controller(client))
