"""TSDB durability: snapshot + segment persistence and remote-write.

PR 10's ``TimeSeriesStore`` is a bounded in-memory ring — a process
restart (the preemption steady state) loses every series, alert
history included. This module makes the plane durable without giving
up the store's boundedness or determinism:

- ``TsdbPersister`` — a flush loop that writes *segments* (the samples
  appended since the last flush) and, every ``snapshot_every``
  flushes, a full *snapshot* that supersedes them. Every file — both
  kinds — goes through ``utils/fsatomic.atomic_write_text``, the ONE
  spelling of temp + fsync + rename: a kill mid-write leaves at worst
  a stale ``.tmp`` sibling, never a torn live file, so
  ``restore()`` never sees a partial document. The recovery contract
  is therefore exactly the flush interval: samples appended after the
  last completed flush are the only ones a kill can lose.
- ``RemoteWriteExporter`` — batched JSONL POST of new samples to a
  fleet-level aggregator, with the PR 5 capped-jittered backoff
  (``delay = min(cap, base * 2^attempt)`` then full jitter), so many
  per-process planes can feed one fleet TSDB without thundering herds.

Format notes: the staleness marker is a specific NaN *bit pattern*
(``expofmt.STALE_NAN``) that a JSON float roundtrip destroys, so
points encode it as the string ``"stale"``; ordinary NaN/Inf data uses
Python's JSON literals. Snapshot/segment documents are versioned
(``"v": 1``) single JSON objects.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable

from kubeflow_tpu.obs import expofmt
from kubeflow_tpu.obs.tsdb import STALE, TimeSeriesStore
from kubeflow_tpu.utils.fsatomic import atomic_write_text

log = logging.getLogger("kubeflow_tpu.obs.persist")

SNAPSHOT_FILE = "snapshot.json"
SEGMENT_PREFIX = "segment-"


def _encode_value(v: float):
    if expofmt.is_stale(v):
        return "stale"
    return v


def _decode_value(v) -> float:
    if v == "stale":
        return STALE
    return float(v)


def _encode_samples(dump) -> list:
    """``dump_since`` output -> JSON-safe nested lists."""
    return [[name, labels, [[t, _encode_value(v)] for t, v in pts]]
            for name, labels, pts in dump]


class TsdbPersister:
    """Snapshot + segment persistence for one ``TimeSeriesStore``.

    ``flush(at=)`` writes one segment holding every sample with
    ``watermark < t <= at``; ``snapshot_every`` flushes, the persister
    writes a full snapshot instead and deletes the segments it
    subsumes. ``restore()`` (call before the scrape loop starts)
    replays snapshot + segments in order, skipping any unparseable
    file (an interrupted write's ``.tmp`` sibling is not even
    considered — only completed renames are visible).

    The loop shell (``start``/``stop``) mirrors ``ScrapeLoop``:
    injectable clock, daemon thread, deterministic when driven
    manually via ``flush(at=...)``."""

    def __init__(self, store: TimeSeriesStore, directory: str,
                 clock: Callable[[], float] = time.time,
                 flush_interval_s: float = 15.0,
                 snapshot_every: int = 20,
                 registry=None):
        self.store = store
        self.directory = directory
        self.clock = clock
        self.flush_interval_s = flush_interval_s
        self.snapshot_every = max(1, int(snapshot_every))
        self.registry = registry
        self._watermark: float | None = None  # highest persisted t
        self._seq = 0           # next segment sequence number
        self._flushes = 0
        self._samples_written = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- paths ---------------------------------------------------------------

    def _snapshot_path(self) -> str:
        return os.path.join(self.directory, SNAPSHOT_FILE)

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.directory, f"{SEGMENT_PREFIX}{seq:08d}.json")

    def _segment_files(self) -> list[str]:
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        return sorted(n for n in names
                      if n.startswith(SEGMENT_PREFIX)
                      and n.endswith(".json"))

    # -- restore -------------------------------------------------------------

    def restore(self) -> dict:
        """Replay snapshot + segments into the store. Returns counts;
        tolerates a missing directory (first boot) and skips corrupt
        documents (which atomic writes make unreachable in practice —
        belt and braces for operator-copied files)."""
        restored = {"snapshot_samples": 0, "segment_samples": 0,
                    "segments": 0}
        snap = self._read_doc(self._snapshot_path())
        if snap is not None:
            restored["snapshot_samples"] = self._replay(snap)
        for fname in self._segment_files():
            doc = self._read_doc(os.path.join(self.directory, fname))
            if doc is None:
                continue
            restored["segments"] += 1
            restored["segment_samples"] += self._replay(doc)
            seq = doc.get("seq")
            if isinstance(seq, int) and seq >= self._seq:
                self._seq = seq + 1
        if self.registry is not None:
            self.registry.counter_inc(
                "obs_persist_restored_samples_total",
                help_="samples replayed into the store on restore",
                by=restored["snapshot_samples"]
                + restored["segment_samples"])
        return restored

    def _read_doc(self, path: str) -> dict | None:
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            log.warning("persist: skipping unreadable %s", path)
            return None
        if not isinstance(doc, dict) or doc.get("v") != 1:
            log.warning("persist: skipping unknown-format %s", path)
            return None
        return doc

    def _replay(self, doc: dict) -> int:
        n = 0
        # the doc-start watermark, NOT the running one: within a doc,
        # series replay sequentially and one series' newest points must
        # not mask another's older ones
        floor = self._watermark
        for entry in doc.get("series") or []:
            try:
                name, labels, pts = entry
            except (TypeError, ValueError):
                continue
            for t, v in pts:
                t = float(t)
                # skip points at/below the floor: a kill between the
                # snapshot rename and segment cleanup leaves segments
                # the snapshot subsumes, and replaying them must be
                # idempotent (scrape time is globally monotonic across
                # docs, so the doc-start high-water mark is exact)
                if floor is not None and t <= floor:
                    continue
                self.store.append(name, labels, _decode_value(v), t)
                if self._watermark is None or t > self._watermark:
                    self._watermark = t
                n += 1
        return n

    # -- flush / snapshot ----------------------------------------------------

    def flush(self, at: float | None = None) -> dict:
        """One persistence step at ``at``: a segment of new samples, or
        (every ``snapshot_every``-th call) a superseding snapshot."""
        now = self.clock() if at is None else at
        self._flushes += 1
        if self._flushes % self.snapshot_every == 0:
            return self.snapshot_now(at=now)
        dump = self.store.dump_since(self._watermark)
        samples = sum(len(pts) for _, _, pts in dump)
        if samples:
            doc = {"v": 1, "seq": self._seq, "at": now,
                   "series": _encode_samples(dump)}
            os.makedirs(self.directory, exist_ok=True)
            atomic_write_text(self._segment_path(self._seq),
                              json.dumps(doc))
            self._seq += 1
            self._samples_written += samples
            self._watermark = max(
                (t for _, _, pts in dump for t, _ in pts),
                default=self._watermark)
        self._publish()
        return {"kind": "segment", "samples": samples, "at": now}

    def snapshot_now(self, at: float | None = None) -> dict:
        """Full snapshot superseding every segment: written first (so a
        kill between write and cleanup only leaves redundant segments,
        re-replayed idempotently into the rings), segments deleted
        after."""
        now = self.clock() if at is None else at
        dump = self.store.dump_since(None)
        samples = sum(len(pts) for _, _, pts in dump)
        doc = {"v": 1, "at": now, "series": _encode_samples(dump)}
        os.makedirs(self.directory, exist_ok=True)
        atomic_write_text(self._snapshot_path(), json.dumps(doc))
        for fname in self._segment_files():
            try:
                os.unlink(os.path.join(self.directory, fname))
            except OSError:
                pass
        self._samples_written += samples
        self._watermark = max(
            (t for _, _, pts in dump for t, _ in pts),
            default=self._watermark)
        self._publish()
        return {"kind": "snapshot", "samples": samples, "at": now}

    def _publish(self) -> None:
        if self.registry is None:
            return
        self.registry.gauge("obs_persist_flushes_total", self._flushes,
                            help_="persistence flush passes")
        self.registry.gauge("obs_persist_samples_total",
                            self._samples_written,
                            help_="samples written to disk")
        self.registry.gauge("obs_persist_segments",
                            len(self._segment_files()),
                            help_="live segment files awaiting the "
                                  "next snapshot")

    # -- thread shell (mirrors ScrapeLoop) -----------------------------------

    def start(self) -> "TsdbPersister":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="tsdb-persist", daemon=True)
            self._thread.start()
        return self

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_flush:
            try:
                self.flush()
            except Exception:
                log.exception("persist: final flush failed")

    def _run(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            try:
                self.flush()
            except Exception:  # durability must never kill the plane
                log.exception("persist: flush failed")


# -- remote write -------------------------------------------------------------


def _default_post(url: str, body: bytes) -> None:
    import urllib.request

    req = urllib.request.Request(
        url, data=body,
        headers={"Content-Type": "application/x-ndjson"},
        method="POST")
    with urllib.request.urlopen(req, timeout=10) as resp:
        if resp.status >= 300:
            raise OSError(f"remote write: HTTP {resp.status}")


class RemoteWriteExporter:
    """Ship new samples to a fleet aggregator as batched JSONL.

    Each ``export_once(at=)`` collects samples past the watermark,
    splits them into ``batch`` -sized JSONL bodies (one sample per
    line: ``{"name","labels","t","v"}``), and POSTs each with the PR 5
    retry shape — capped exponential backoff with full jitter
    (``random.uniform(0, min(cap, base * 2^attempt))``). A batch that
    exhausts retries is dropped and counted, and the watermark still
    advances: remote write is lossy-by-design telemetry, local
    persistence (``TsdbPersister``) is the durable copy."""

    def __init__(self, store: TimeSeriesStore, url: str,
                 post: Callable[[str, bytes], None] | None = None,
                 batch: int = 500,
                 clock: Callable[[], float] = time.time,
                 retry_base: float = 0.1, retry_cap: float = 2.0,
                 max_retries: int = 5,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Callable[[], float] | None = None,
                 registry=None):
        import random

        self.store = store
        self.url = url
        self.post = post or _default_post
        self.batch = max(1, int(batch))
        self.clock = clock
        self.retry_base = retry_base
        self.retry_cap = retry_cap
        self.max_retries = max_retries
        self.sleep = sleep
        self.rng = rng if rng is not None else random.random
        self.registry = registry
        self._watermark: float | None = None
        self._sent = 0
        self._dropped = 0

    def export_once(self, at: float | None = None) -> int:
        now = self.clock() if at is None else at
        dump = self.store.dump_since(self._watermark)
        lines: list[str] = []
        newest = self._watermark
        for name, labels, pts in dump:
            for t, v in pts:
                lines.append(json.dumps(
                    {"name": name, "labels": labels, "t": t,
                     "v": _encode_value(v)}, sort_keys=True))
                if newest is None or t > newest:
                    newest = t
        sent = 0
        for i in range(0, len(lines), self.batch):
            body = ("\n".join(lines[i:i + self.batch]) + "\n").encode()
            if self._post_with_backoff(body):
                sent += self.batch if i + self.batch <= len(lines) \
                    else len(lines) - i
            else:
                self._dropped += len(lines[i:i + self.batch])
        # lossy-by-design: the watermark advances past failures too
        self._watermark = newest
        self._sent += sent
        if self.registry is not None:
            self.registry.gauge("obs_remote_write_sent_total", self._sent,
                                help_="samples shipped to the remote "
                                      "aggregator")
            self.registry.gauge("obs_remote_write_dropped_total",
                                self._dropped,
                                help_="samples dropped after retry "
                                      "exhaustion")
        return sent

    def _post_with_backoff(self, body: bytes) -> bool:
        for attempt in range(self.max_retries + 1):
            try:
                self.post(self.url, body)
                return True
            except Exception as e:
                if attempt >= self.max_retries:
                    log.warning("remote write: dropping batch after "
                                "%d attempts: %s", attempt + 1, e)
                    return False
                delay = min(self.retry_cap,
                            self.retry_base * (2 ** attempt))
                self.sleep(self.rng() * delay)
        return False

    @property
    def dropped(self) -> int:
        return self._dropped
