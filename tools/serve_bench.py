#!/usr/bin/env python3
"""Serving benchmark: decode throughput + latency percentiles under load.

Drives the in-process serving stack (no HTTP overhead) with a Poisson-ish
open-loop arrival stream of pre-tokenized prompts and reports ONE JSON
line per mode:

  {"mode": "continuous", "tokens_per_sec": ..., "p50_ms": ...,
   "p95_ms": ..., "requests": N, "slots": S, ...}

Modes: `micro` (MicroBatcher + whole-batch generate) vs `continuous`
(slot decoder). Run on real TPU for the numbers that matter; runs on the
CPU mesh for plumbing validation. The training headline stays bench.py;
this is the serving-side ledger (reference had none — TF-Serving was an
integration, never measured in-tree).

  python tools/serve_bench.py --model gpt-350m --param-dtype bfloat16 \\
      --prompt-len 512 --max-new-tokens 64 --requests 64 --concurrency 16
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_mode(mode: str, args) -> dict:
    from kubeflow_tpu.serving.server import serve_lm_generator

    served = serve_lm_generator(
        "bench", args.model, prompt_len=args.prompt_len,
        max_new_tokens=args.max_new_tokens,
        continuous_batching=(mode == "continuous"),
        decode_slots=args.slots,
        batch_window_ms=(args.window_ms if mode == "micro" else 0.0),
        param_dtype=args.param_dtype or None,
        mesh=args.mesh or None,
        vocab_size=args.vocab_size,
        **({"kv_cache_dtype": args.kv_cache_dtype}
           if args.kv_cache_dtype else {}),
        **({"attention_window": args.attention_window}
           if args.attention_window else {}),
        **({"rolling_kv_cache": True} if args.rolling_kv_cache else {}))
    try:
        rng = __import__("random").Random(0)
        prompts = [[rng.randrange(1, args.vocab_size)
                    for _ in range(rng.randrange(4, args.prompt_len))]
                   for _ in range(args.requests)]
        # warmup: compile every program the measured window can hit —
        # micro-batching dispatches pow2-padded GROUPS, so warm each
        # pow2 batch size up to the concurrency cap (otherwise first-
        # compile latencies pollute the percentiles)
        k = 1
        while k <= max(1, args.concurrency):
            served.predict([{"tokens": prompts[i % len(prompts)]}
                            for i in range(k)])
            k *= 2

        latencies: list[float] = []
        lat_lock = threading.Lock()
        sem = threading.Semaphore(args.concurrency)
        threads = []

        def one(p):
            t0 = time.perf_counter()
            served.predict([{"tokens": p}])
            dt = time.perf_counter() - t0
            with lat_lock:
                latencies.append(dt)
            sem.release()

        t_start = time.perf_counter()
        for p in prompts:
            sem.acquire()  # closed-loop at `concurrency` outstanding
            th = threading.Thread(target=one, args=(p,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        wall = time.perf_counter() - t_start
        latencies.sort()

        def pct(q):
            return round(
                latencies[min(len(latencies) - 1,
                              int(q * len(latencies)))] * 1e3, 1)

        return {
            "mode": mode,
            "requests": args.requests,
            "concurrency": args.concurrency,
            "slots": args.slots,
            "tokens_per_sec": round(
                args.requests * args.max_new_tokens / wall, 1),
            "requests_per_sec": round(args.requests / wall, 2),
            "p50_ms": pct(0.50),
            "p95_ms": pct(0.95),
            "p99_ms": pct(0.99),
            "wall_s": round(wall, 2),
            "model": args.model,
            "max_new_tokens": args.max_new_tokens,
            "param_dtype": args.param_dtype or "f32",
            **({"kv_cache_dtype": args.kv_cache_dtype}
               if args.kv_cache_dtype else {}),
            **({"attention_window": args.attention_window,
                "rolling_kv_cache": bool(args.rolling_kv_cache)}
               if args.attention_window else {}),
        }
    finally:
        served.close()


def main() -> int:
    p = argparse.ArgumentParser("serve_bench")
    p.add_argument("--model", default="gpt-350m")
    p.add_argument("--vocab-size", type=int, default=32000)
    p.add_argument("--prompt-len", type=int, default=512)
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--slots", type=int, default=16)
    p.add_argument("--window-ms", type=float, default=5.0,
                   help="micro-batching window for the micro mode")
    p.add_argument("--param-dtype", default="bfloat16",
                   choices=["bfloat16", "float32", "int8", ""])
    p.add_argument("--attention-window", type=int, default=0,
                   help="sliding-window width for the served model "
                        "(0 = full causal)")
    p.add_argument("--rolling-kv-cache", action="store_true",
                   help="bound the KV cache to the window (O(window) "
                        "memory + per-step cache stream)")
    p.add_argument("--kv-cache-dtype", default="",
                   choices=["", "auto", "int8"],
                   help="int8 quantizes the decode KV cache (per-token-"
                        "head scales) — the long-context decode lever")
    p.add_argument("--mesh", default="",
                   help="axis=n[,axis=n...] to shard the served params")
    p.add_argument("--modes", default="micro,continuous")
    args = p.parse_args()
    if args.mesh:
        args.mesh = {k: int(v) for k, v in
                     (kv.split("=", 1) for kv in args.mesh.split(","))}
    for mode in args.modes.split(","):
        print(json.dumps(run_mode(mode.strip(), args)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
