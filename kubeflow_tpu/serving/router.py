"""Token-aware serving router — the JAXService front door.

A single replica server (``serving/server.py``) saturates at one
decoder's throughput (BENCH_r05: 1.07 req/s); the serving plane runs N
replicas behind this router. Replica choice is least-outstanding-TOKENS,
not least-connections: decode cost scales with tokens (prompt prefill +
requested continuation), so one 2k-token request weighs as much as
thirty short ones — balancing on request counts would pile long prompts
onto one replica while its neighbors idle.

Design mirrors the gang scheduler's split (``scheduler/queue.py``): a
DETERMINISTIC synchronous core (``TokenRouter`` — every transition
happens in an explicit call under one lock, clock injectable) with a
thin threaded/HTTP shell (``RouterFrontend``) for production. The core
is what the JAXService benchmark (``tools/serve_bench.py``) replays
decision-for-decision per seed, and what the drain/kill drills prove
zero-drop on:

- bounded admission queue: ``submit`` beyond ``max_queue`` raises
  ``RouterBusy`` (the HTTP shell's 429) — backpressure instead of an
  unbounded latency cliff;
- membership is CONTROLLER-FED through the JAXService endpoints
  annotation (``ANNOTATION_ENDPOINTS``, the ONE spelling — the
  jaxservice controller re-exports it): only replicas the controller
  reports Ready receive work, a cordoned replica finishes its in-flight
  tokens but admits nothing new (connection draining), and a replica
  REMOVED from membership (killed) has its in-flight requests shed back
  to the queue FRONT and re-dispatched to survivors — zero drops;
- every dispatch opens a ``router.dispatch`` span parented on the
  request's W3C traceparent, so a request timeline connects through the
  router hop to the replica's serving spans (docs/observability.md).

Metrics go to BOTH sinks (the PR 4 convention): the MetricsRegistry
(``router_queue_depth``, ``router_tokens_inflight{replica}``,
``router_request_seconds`` native histogram, ``router_tokens_total``)
that the JAXService autoscaler reads its signals from, and
prometheus_client for the scrape surface.

jax-free by design: the control plane imports this module (the
endpoints wire contract and ``RegistrySignals``) without pulling a jax
runtime in.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.runtime.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("kubeflow_tpu.serving.router")

# The controller -> router membership wire contract: a JSON list of
# {"name", "addr", "state"} stamped on the JAXService object. "active"
# members take new work; "cordoned" members only drain. The jaxservice
# controller writes it, the router consumes it — one spelling, here
# (control/jaxservice/types.py re-exports it, the dist.py pattern).
ANNOTATION_ENDPOINTS = "jaxservice.kubeflow.org/endpoints"
STATE_ACTIVE = "active"
STATE_CORDONED = "cordoned"

# Request-latency buckets: sub-second cache hits up to multi-minute
# long-context decodes under queueing.
REQUEST_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, 120.0, 300.0)

# Criticality bands (the ROADMAP #3 multi-tenancy bridge): under
# overload the router sheds the HIGHEST rank first, so interactive
# traffic survives a batch-traffic wave. Namespace-defaulted through the
# JAXService spec (control/jaxservice/types.py resilience_spec).
BAND_CRITICAL = "critical"
BAND_DEFAULT = "default"
BAND_SHEDDABLE = "sheddable"
BAND_RANK = {BAND_CRITICAL: 0, BAND_DEFAULT: 1, BAND_SHEDDABLE: 2}
BANDS = tuple(BAND_RANK)

# Circuit-breaker states (gauge values for router_breaker_state)
BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half-open"
BREAKER_OPEN = "open"
_BREAKER_GAUGE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}

# Request headers the shell understands (and forwards replica-ward):
# the remaining deadline budget in seconds — it SHRINKS across retry
# hops — the criticality band, and the tenant the request bills to
# (defaulted from the JAXService namespace; the chargeback dimension).
HEADER_DEADLINE = "x-request-deadline-s"
HEADER_BAND = "x-request-band"
HEADER_TENANT = "x-request-tenant"

# A tenant is a kubernetes namespace (or an explicit header override
# spelled the same way): DNS-1123 label. Anything else is a 400 at the
# shell — unbounded attacker-chosen label values would otherwise flow
# straight into the metric exposition.
TENANT_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")

# The outcomes every tenant's router_requests_total series is
# pre-registered at 0 for on first sight (rate() needs a 0-sample
# BEFORE the first error, or a fresh tenant's first failure never
# fires its burn rule — the PR 10 lesson).
TENANT_OUTCOMES = ("completed", "failed", "rejected", "deadline",
                   "shed", "shed_band")

# "argument not provided" sentinel for set_members(canary=...): None
# means "clear the split", absence means "leave it alone"
_KEEP = object()

def _prom_metric(name, kind, doc, **kw):
    from kubeflow_tpu.runtime.metrics import prom_metric

    return prom_metric(name, kind, doc, **kw)


def prom_queue_depth():
    import prometheus_client as prom

    return _prom_metric("router_queue_depth", prom.Gauge,
                        "requests waiting in the router admission queue",
                        labelnames=("service",))


def prom_tokens_inflight():
    import prometheus_client as prom

    return _prom_metric("router_tokens_inflight", prom.Gauge,
                        "outstanding token estimate per replica",
                        labelnames=("service", "replica"))


def prom_request_seconds():
    import prometheus_client as prom

    return _prom_metric("router_request_seconds", prom.Histogram,
                        "submit -> completion latency through the router",
                        labelnames=("service", "revision"),
                        buckets=REQUEST_BUCKETS)


def prom_requests_total():
    import prometheus_client as prom

    return _prom_metric("router_requests_total", prom.Counter,
                        "requests by outcome (completed/rejected/shed)",
                        labelnames=("service", "outcome", "revision"))


def prom_tokens_total():
    import prometheus_client as prom

    return _prom_metric("router_tokens_total", prom.Counter,
                        "tokens completed through the router "
                        "(rate = the autoscaler's tokens/sec signal)",
                        labelnames=("service",))


def prom_hedges_total():
    import prometheus_client as prom

    return _prom_metric("router_hedges_total", prom.Counter,
                        "hedged dispatches by outcome "
                        "(started/won/canceled)",
                        labelnames=("service", "outcome"))


def prom_deadline_exceeded_total():
    import prometheus_client as prom

    return _prom_metric("router_deadline_exceeded_total", prom.Counter,
                        "requests dropped because their deadline elapsed",
                        labelnames=("service",))


def prom_breaker_state():
    import prometheus_client as prom

    return _prom_metric("router_breaker_state", prom.Gauge,
                        "per-replica circuit breaker "
                        "(0=closed 1=half-open 2=open)",
                        labelnames=("service", "replica"))


def prom_shed_total():
    import prometheus_client as prom

    return _prom_metric("router_shed_total", prom.Counter,
                        "queued requests evicted by criticality band "
                        "under overload",
                        labelnames=("service", "band"))


def prom_retry_budget():
    import prometheus_client as prom

    return _prom_metric("router_retry_budget", prom.Gauge,
                        "retry/hedge token bucket level — 0 means the "
                        "fleet is failing faster than it refills",
                        labelnames=("service", "tenant"))


class RouterBusy(Exception):
    """Admission queue full — the HTTP shell's 429 Too Many Requests.
    ``retry_after`` (seconds, derived from the queue drain rate) rides
    along so the 429 response can carry a Retry-After header."""

    retry_after: float | None = None


class DeadlineExceeded(Exception):
    """The request's deadline elapsed before it could be served — the
    HTTP shell's 504. Raised by ``submit`` for dead-on-arrival requests
    and by the continuous batcher when it cancels an expired slot."""


@dataclass
class ResilienceConfig:
    """Tuning for the request-resilience layer. ``TokenRouter`` built
    WITHOUT one (the default) behaves exactly like the pre-resilience
    router — same pick key, same FIFO drain, no breakers/hedges — so
    banked decision replays (BENCH_SERVE_r01) stay byte-identical."""

    # EWMA smoothing for per-replica completion latency
    ewma_alpha: float = 0.3
    # consecutive transport failures that trip a breaker open
    breaker_failures: int = 3
    # open -> half-open probe delay (seconds on the router clock)
    breaker_cooloff_s: float = 5.0
    # hedge after this quantile of recent completion latencies...
    hedge_quantile: float = 0.95
    # ...but never sooner than this (protects against hedging every
    # request when the fleet is uniformly fast)
    hedge_min_s: float = 0.25
    # minimum completed samples before hedging activates
    hedge_min_samples: int = 16
    # token-bucket retry budget: refilled per ADMITTED request, spent
    # 1.0 per retry or hedge — a failing fleet cannot amplify its own
    # load beyond ~ratio of offered traffic
    retry_budget_ratio: float = 0.1
    retry_budget_cap: float = 32.0
    # completion-latency window feeding the hedge quantile
    latency_window: int = 128


class _Health:
    """Per-replica health the breaker and scorer read. Lives outside
    membership so a replica that flaps out and back keeps its history."""

    __slots__ = ("lat", "fails", "state", "opened_at", "probing")

    def __init__(self) -> None:
        self.lat: float | None = None   # EWMA completion latency (s)
        self.fails = 0                  # consecutive transport failures
        self.state = BREAKER_CLOSED
        self.opened_at = 0.0
        self.probing = False            # half-open probe outstanding


@dataclass
class Member:
    """One routable replica. ``transport`` is whatever the shell uses
    to reach it (an HTTP base URL, an in-process callable, a bench
    stub) — the core never calls it, it only hands it back on
    dispatch. ``revision`` is the JAXService revision label the
    controller stamped on the replica's pod ("" for pre-rollout
    endpoints) — the canary split routes on it."""

    name: str
    transport: Any = None
    state: str = STATE_ACTIVE
    revision: str = ""


@dataclass
class Ticket:
    """One request's journey through the router. ``member`` is set at
    dispatch (None while queued); ``done`` fires on dispatch AND on
    completion so a blocking shell can wait on either transition.
    ``tried`` holds replicas whose transport already FAILED this
    ticket — re-dispatch prefers anyone else (the name-tie-break would
    otherwise send every retry straight back to the dead replica)."""

    tokens: int
    item: Any = None
    context: "obs_trace.SpanContext | None" = None
    member: Member | None = None
    done: threading.Event = field(default_factory=threading.Event,
                                  repr=False)
    tried: set = field(default_factory=set, repr=False)
    _t0: float = 0.0
    _span: "obs_trace.Span | None" = field(default=None, repr=False)
    _queued_at: float = 0.0
    # -- resilience layer -----------------------------------------------
    band: str = BAND_DEFAULT
    deadline: float | None = None       # absolute, on the router clock
    # the namespace this request bills to (chargeback attribution);
    # "" means "the router's own namespace" — submit() resolves it
    tenant: str = ""
    hedge_member: Member | None = field(default=None, repr=False)
    # why the router dropped this ticket without the shell asking
    # ("deadline" / "shed_band" / "retry_budget"); the shell maps it to
    # 504 / 429 / 503 after its done-event fires
    dropped_reason: str | None = None
    retry_after: float | None = None    # rides with "shed_band" drops
    # terminally resolved (completed, or failed without requeue) — the
    # shell's last-resort abandon path keys off this so an exception
    # AFTER resolution never double-resolves the ticket
    resolved: bool = False
    _dispatched_at: float = 0.0
    _hedge_at: float = 0.0
    # -- rollout layer ---------------------------------------------------
    # the revision of the replica that served (or is serving) this
    # request — stamped at dispatch, re-stamped if a hedge leg wins, and
    # carried into the revision label on router_requests_total /
    # router_request_seconds (the canary-vs-baseline burn dimension)
    revision: str = ""
    # the canary draw: (canary_revision, wants_canary) decided ONCE at
    # admission from the deterministic seeded sequence; None = no canary
    # active. A soft preference — availability beats the ladder.
    _canary_pref: Any = field(default=None, repr=False)


def estimate_tokens(instances: list, max_new_tokens: int) -> int:
    """The in-flight cost estimate for a predict body: prompt tokens
    (prefill) plus the full requested continuation per row. An estimate
    on purpose — the router needs relative weight, not billing."""
    total = 0
    for inst in instances or [None]:
        row = inst.get("tokens") if isinstance(inst, dict) else inst
        total += (len(row) if hasattr(row, "__len__") else 1)
        total += max_new_tokens
    return max(total, 1)


class TokenRouter:
    """Deterministic least-outstanding-tokens dispatcher.

    All state lives under one lock and is mutated only in locked
    methods (the LOCK201-provable fresh-container idiom); transports
    are never invoked here, so no I/O happens under the lock.
    """

    def __init__(self, service: str = "default", namespace: str = "default",
                 max_queue: int = 256,
                 replica_token_budget: int | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: MetricsRegistry | None = None,
                 tracer=None, prom_sink: bool = True,
                 resilience: ResilienceConfig | None = None,
                 on_decision: Callable[[dict], None] | None = None,
                 canary_seed: int = 0):
        self.service = service
        self.namespace = namespace
        self.max_queue = max_queue
        # max outstanding tokens a replica accepts before the router
        # queues instead (None = always eligible; the least-loaded
        # replica still wins). Roughly slots * (prompt + continuation).
        self.replica_token_budget = replica_token_budget
        self.clock = clock
        self.registry = registry if registry is not None else REGISTRY
        self.tracer = tracer if tracer is not None else obs_trace.TRACER
        # prometheus is process-global; the deterministic bench runs
        # many routers per process and opts out of the shared sink
        self._prom = prom_sink
        # None = legacy behavior, decision-for-decision (the banked
        # BENCH_SERVE_r01 replay depends on it)
        self.resilience = resilience
        # deterministic decision tap for the resilience bench: called
        # UNDER the lock with {"kind", "t", ...} on breaker transitions,
        # hedges, band sheds, and deadline drops
        self.on_decision = on_decision
        self._lock = threading.Lock()
        self._members: dict[str, Member] = {}
        self._inflight: dict[str, dict[int, Ticket]] = {}  # name -> tickets
        self._tokens: dict[str, int] = {}                  # name -> estimate
        self._queue: list[Ticket] = []
        self._closed = False
        self._health: dict[str, _Health] = {}              # name -> health
        self._lat_samples: collections.deque = collections.deque(
            maxlen=(resilience.latency_window if resilience else 64))
        # recent completion stamps -> queue drain rate -> Retry-After
        self._completions: collections.deque = collections.deque(maxlen=64)
        # per-TENANT retry/hedge token buckets (ISSUE 20 satellite): one
        # tenant's retry storm drains only its own bucket. The sum is
        # bounded by retry_budget_cap; a new tenant seeds with whatever
        # headroom remains (the first tenant gets the full cap, so the
        # single-tenant banked replays are unchanged).
        self._retry_tokens: dict[str, float] = {}
        # tenants whose counter families are already pre-registered
        self._tenants: set[str] = set()
        # canary split state: (revision, weight) the controller is
        # currently canarying, plus the deterministic draw sequence —
        # seeded so benches replay decision-for-decision
        self._canary: tuple[str, float] | None = None
        self._canary_seed = int(canary_seed)
        self._canary_seq = 0

    # -- membership (controller-fed) ----------------------------------------

    def sync_endpoints(self, endpoints: list[dict],
                       transport_factory: Callable[[dict], Any] | None = None,
                       ) -> list[Ticket]:
        """Apply a controller-published endpoint list (the parsed
        ``ANNOTATION_ENDPOINTS`` value). Returns the tickets re-DISPATCHED
        after shedding removed members (see ``set_members``). Endpoint
        entries may carry ``revision`` (the pod's revision label) and a
        ``canary`` weight — present on the canaried revision's entries
        while a rollout analyzes; absent entries mean no split."""
        members = []
        canary: tuple[str, float] | None = None
        for ep in endpoints:
            name = ep.get("name")
            if not name:
                continue
            rev = ep.get("revision") or ""
            members.append(Member(
                name=name,
                transport=(transport_factory(ep) if transport_factory
                           else ep.get("addr")),
                state=(STATE_CORDONED if ep.get("state") == STATE_CORDONED
                       else STATE_ACTIVE),
                revision=rev))
            w = ep.get("canary")
            if rev and isinstance(w, (int, float)) \
                    and not isinstance(w, bool):
                canary = (rev, float(w))
        return self.set_members(members, canary=canary)

    def sync_from_object(self, service_obj: dict,
                         transport_factory=None) -> list[Ticket]:
        """Membership straight from a JAXService object (a watch-driven
        shell calls this per event)."""
        return self.sync_endpoints(
            parse_endpoints(service_obj), transport_factory)

    def set_members(self, members: list[Member],
                    canary: "tuple[str, float] | None | object" = _KEEP,
                    ) -> list[Ticket]:
        """Replace membership. A member that disappears sheds its
        in-flight tickets back to the queue FRONT (oldest first) and a
        drain pass re-dispatches to survivors — the zero-drop half of
        the replica-kill drill. Returns the newly dispatched tickets so
        a synchronous driver can start their work on the survivors.
        ``canary`` sets the (revision, weight) split alongside the
        membership swap (None clears it); omitted = left unchanged, so
        pre-rollout callers keep their exact behavior."""
        with self._lock:
            now = self.clock()
            new = {m.name: m for m in members}
            shed: list[Ticket] = []
            if canary is not _KEEP:
                self._canary = canary  # type: ignore[assignment]
            for name in list(self._members):
                if name not in new:
                    shed.extend(self._shed_member_locked(name, now))
            for name, m in new.items():
                cur = self._members.get(name)
                if cur is None:
                    self._members[name] = m
                    self._inflight.setdefault(name, {})
                    self._tokens.setdefault(name, 0)
                    self._publish_inflight_locked(name)
                else:
                    cur.state = m.state
                    cur.transport = m.transport
                    cur.revision = m.revision
            # requeue shed tickets at the FRONT, original order. done is
            # CLEARED: a blocking shell waiting on this ticket must park
            # until the re-dispatch below (or a later drain) fires it
            # again — a stale set() would busy-spin its retry loop
            for t in reversed(shed):
                t.member = None
                t.done.clear()
                self._queue.insert(0, t)
            dispatched = self._drain_locked(now)
            self._publish_queue_locked()
        for t in dispatched:
            t.done.set()
        return dispatched

    def cordon(self, name: str) -> None:
        """Stop NEW dispatch to a replica; in-flight work finishes
        (connection draining). The controller cordons before delete."""
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.state = STATE_CORDONED

    def uncordon(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.state = STATE_ACTIVE
        self.kick()

    def set_canary(self, revision: str | None,
                   weight: float = 0.0) -> None:
        """Set (or clear, with ``revision=None``) the canary split: new
        admissions draw from the seeded sequence and prefer the canary
        revision with probability ``weight``. A preference, not a
        partition — when the preferred side has no eligible replica the
        other side serves (availability beats the ladder)."""
        with self._lock:
            self._canary = (None if revision is None
                            else (revision, float(weight)))

    def canary(self) -> "tuple[str, float] | None":
        with self._lock:
            return self._canary

    def _shed_member_locked(self, name: str, now: float) -> list[Ticket]:
        """Remove a member; return its in-flight tickets oldest-first."""
        self._members.pop(name, None)
        tickets = sorted(self._inflight.pop(name, {}).values(),
                         key=lambda t: t._t0)
        self._tokens.pop(name, None)
        for t in tickets:
            if t._span is not None:
                # the dispatch to the dead replica exports as ERROR; the
                # re-dispatch below opens a fresh span in the same trace
                t._span.status = "ERROR"
                t._span.error = f"replica {name} lost; shed to survivors"
                self.tracer.finish(t._span)
                t._span = None
            self._count_locked("shed", t.tenant, t.revision)
        self.registry.gauge(
            "router_tokens_inflight", 0,
            help_="outstanding token estimate per replica",
            namespace=self.namespace, service=self.service, replica=name)
        if self._prom:
            prom_tokens_inflight().labels(self.service, name).set(0)
        return tickets

    # -- admission -----------------------------------------------------------

    def submit(self, tokens: int, item: Any = None,
               context: "obs_trace.SpanContext | None" = None,
               band: str = BAND_DEFAULT,
               deadline: float | None = None,
               tenant: str | None = None) -> Ticket:
        """Admit one request of ``tokens`` estimated cost. Dispatches
        immediately to the least-loaded eligible replica, else queues;
        raises ``RouterBusy`` (429) when the bounded queue is full —
        unless a strictly-less-critical ticket is queued, in which case
        THAT one is shed instead (band shedding; resilience mode only).
        ``deadline`` is absolute on the router clock; a dead-on-arrival
        request raises ``DeadlineExceeded`` (504) without queueing.
        ``tenant`` is the namespace this request bills to (chargeback
        attribution); None/empty defaults to the router's namespace."""
        t = Ticket(tokens=int(tokens), item=item, context=context,
                   band=band if band in BAND_RANK else BAND_DEFAULT,
                   deadline=deadline, tenant=tenant or self.namespace)
        victim: Ticket | None = None
        expired: list[Ticket] = []
        try:
            with self._lock:
                if self._closed:
                    raise RouterBusy("router is shut down")
                now = self.clock()
                t._t0 = t._queued_at = now
                self._register_tenant_locked(t.tenant)
                if self.resilience is not None:
                    self._refill_budget_locked(t.tenant)
                if self._canary is not None:
                    t._canary_pref = self._canary_draw_locked()
                if t.deadline is not None and now >= t.deadline:
                    self._drop_deadline_locked(t, now)
                    raise DeadlineExceeded(
                        "deadline elapsed before admission")
                expired = self._sweep_deadlines_locked(now)
                member = self._pick_locked(t.tokens, pref=t._canary_pref)
                if member is not None:
                    self._dispatch_locked(t, member, now)
                elif len(self._queue) >= self.max_queue:
                    victim = self._shed_band_locked(t, now)
                    if victim is None:
                        self._count_locked("rejected", t.tenant)
                        e = RouterBusy(
                            f"admission queue full ({self.max_queue})")
                        e.retry_after = self._retry_after_locked(now)
                        self._publish_queue_locked()
                        raise e
                    self._queue.append(t)
                else:
                    self._queue.append(t)
                self._publish_queue_locked()
        finally:
            # fire drop notifications even on the raise paths — a shell
            # thread parked on a swept/shed ticket must wake regardless
            # of how THIS submit exits
            for dead in expired:
                dead.done.set()
            if victim is not None:
                victim.done.set()
        if t.member is not None:
            t.done.set()
        return t

    def _shed_band_locked(self, t: Ticket, now: float) -> Ticket | None:
        """Full queue + new arrival: evict the NEWEST queued ticket of
        the most-sheddable band strictly less critical than the
        arrival. Returns the victim (caller fires its done event), or
        None when nothing queued is less critical — then the ARRIVAL is
        the right thing to reject."""
        if self.resilience is None or not self._queue:
            return None
        my_rank = BAND_RANK.get(t.band, BAND_RANK[BAND_DEFAULT])
        ranks = [BAND_RANK.get(q.band, BAND_RANK[BAND_DEFAULT])
                 for q in self._queue]
        worst = max(ranks)
        if worst <= my_rank:
            return None
        idx = len(ranks) - 1 - ranks[::-1].index(worst)
        victim = self._queue.pop(idx)
        victim.dropped_reason = "shed_band"
        victim.retry_after = self._retry_after_locked(now)
        self._count_locked("shed_band", victim.tenant)
        self.registry.counter_inc(
            "router_shed_total",
            help_="queued requests evicted by criticality band under "
                  "overload",
            namespace=self.namespace, service=self.service,
            tenant=victim.tenant or self.namespace, band=victim.band)
        if self._prom:
            prom_shed_total().labels(self.service, victim.band).inc()
        self._decide_locked("shed", now, band=victim.band)
        return victim

    def complete(self, ticket: Ticket, tokens_done: int | None = None,
                 winner: str | None = None) -> list[Ticket]:
        """Mark a dispatched ticket finished; drain the queue into the
        freed capacity. Returns newly dispatched tickets (their
        ``member`` set) for synchronous drivers. ``winner`` names the
        replica whose response was used (a hedged ticket has two legs;
        the loser's accounting is released here and its leg canceled).

        Shed-race safe, symmetric to ``fail``: if a concurrent
        membership sync shed this ticket back into the queue while its
        transport call was succeeding, the queued copy is removed here
        — the handler thread has already returned the response, so a
        re-dispatch would permanently inflate the survivor's in-flight
        accounting (nobody is left to complete it) and wedge its drain
        gate."""
        with self._lock:
            now = self.clock()
            if ticket.member is None:
                self._queue = [t for t in self._queue if t is not ticket]
            hedge_won = self._resolve_hedge_locked(ticket, winner, now)
            if self.resilience is not None and ticket.member is not None:
                wname = winner or ticket.member.name
                start = ticket._hedge_at if hedge_won \
                    else ticket._dispatched_at
                sample = max(now - start, 0.0)
                self._record_success_locked(wname, sample, now)
                self._lat_samples.append(sample)
            self._completions.append(now)
            self._finish_locked(ticket, now, tokens_done)
            expired = self._sweep_deadlines_locked(now)
            dispatched = self._drain_locked(now)
            self._publish_queue_locked()
        for t in expired:
            t.done.set()
        for t in dispatched:
            t.done.set()
        return dispatched

    def _resolve_hedge_locked(self, ticket: Ticket, winner: str | None,
                              now: float) -> bool:
        """Release the hedge leg's accounting; True when the hedge leg
        is the winner (latency/health credit then belongs to it)."""
        h = ticket.hedge_member
        if h is None:
            return False
        ticket.hedge_member = None
        if h.name in self._tokens:
            self._tokens[h.name] = max(
                0, self._tokens.get(h.name, 0) - ticket.tokens)
            self._publish_inflight_locked(h.name)
        won = winner is not None and winner == h.name
        self._hedge_count_locked("won" if won else "canceled")
        if won:
            # the hedge replica served the response: its revision is
            # the one the latency/outcome labels should bill
            ticket.revision = h.revision
            self._decide_locked("hedge_win", now, replica=h.name)
        return won

    def fail(self, ticket: Ticket, requeue: bool = True) -> list[Ticket]:
        """A transport-level failure for one dispatched ticket: take it
        off its replica and (by default) requeue it at the FRONT for a
        retry on whoever is least loaded now. ``requeue=False`` drops
        it (the caller is surfacing the error to its client).

        Safe against the shed race: if a concurrent membership sync
        already shed this ticket back into the queue (``member`` is
        None), a requeue is a no-op — inserting it AGAIN would have it
        dispatched twice and permanently inflate a replica's in-flight
        accounting — and a drop removes it from the queue so nothing
        ghost-dispatches a request whose handler thread has given up."""
        with self._lock:
            now = self.clock()
            member = ticket.member
            if member is not None:
                # remember the failed transport: the retry must prefer
                # any OTHER replica (least-loaded + name-tie would
                # otherwise re-pick the dead one forever)
                ticket.tried.add(member.name)
                bucket = self._inflight.get(member.name)
                if bucket is not None and bucket.pop(id(ticket), None) \
                        is not None:
                    self._tokens[member.name] = max(
                        0, self._tokens.get(member.name, 0) - ticket.tokens)
                    self._publish_inflight_locked(member.name)
                if self.resilience is not None:
                    self._record_failure_locked(member.name, now)
            # a hedged ticket fails as a WHOLE (the shell only calls
            # fail after both legs failed or it is giving up): release
            # the hedge leg's accounting and penalize it too
            h = ticket.hedge_member
            if h is not None:
                ticket.hedge_member = None
                ticket.tried.add(h.name)
                if h.name in self._tokens:
                    self._tokens[h.name] = max(
                        0, self._tokens.get(h.name, 0) - ticket.tokens)
                    self._publish_inflight_locked(h.name)
                if self.resilience is not None:
                    self._record_failure_locked(h.name, now)
                self._hedge_count_locked("canceled")
            if ticket._span is not None:
                ticket._span.status = "ERROR"
                ticket._span.error = "transport failure"
                self.tracer.finish(ticket._span)
                ticket._span = None
            ticket.member = None
            if requeue and self.resilience is not None:
                # retries draw on the deadline AND the retry budget: an
                # expired or budget-less ticket drops instead, with the
                # reason stamped for the shell (504 / 503)
                if ticket.deadline is not None and now >= ticket.deadline:
                    requeue = False
                    ticket.dropped_reason = "deadline"
                elif not self._spend_budget_locked(1.0, ticket.tenant):
                    requeue = False
                    ticket.dropped_reason = "retry_budget"
                    ticket.retry_after = self._retry_after_locked(now)
                    self._decide_locked("retry_budget_drop", now)
                else:
                    # the retry really spent a budget token: charge it
                    # to the tenant whose request is retrying
                    self._tenant_spend_locked(ticket.tenant, "retry", 1.0)
            queued = any(t is ticket for t in self._queue)
            if requeue:
                ticket.done.clear()
                if not queued:
                    self._queue.insert(0, ticket)
                    self._count_locked("shed", ticket.tenant,
                                       ticket.revision)
            else:
                ticket.resolved = True
                if queued:
                    self._queue = [t for t in self._queue
                                   if t is not ticket]
                if ticket.dropped_reason == "deadline":
                    self._drop_deadline_locked(ticket, now)
                else:
                    self._count_locked("failed", ticket.tenant,
                                       ticket.revision)
            expired = self._sweep_deadlines_locked(now)
            dispatched = self._drain_locked(now)
            self._publish_queue_locked()
        for t in expired:
            t.done.set()
        for t in dispatched:
            t.done.set()
        return dispatched

    def kick(self) -> list[Ticket]:
        """Re-try queued dispatch (capacity may have appeared through a
        membership edit rather than a completion)."""
        with self._lock:
            now = self.clock()
            expired = self._sweep_deadlines_locked(now)
            dispatched = self._drain_locked(now)
            self._publish_queue_locked()
        for t in expired:
            t.done.set()
        for t in dispatched:
            t.done.set()
        return dispatched

    # -- resilience: hedging and introspection --------------------------------

    def hedge_delay(self) -> float | None:
        """Seconds a shell should wait on the primary leg before
        hedging: the configured quantile of recent completion
        latencies, floored at ``hedge_min_s``. None = hedging off
        (no config, or not enough samples yet)."""
        with self._lock:
            r = self.resilience
            if r is None or len(self._lat_samples) < r.hedge_min_samples:
                return None
            lat = sorted(self._lat_samples)
            q = lat[min(int(len(lat) * r.hedge_quantile), len(lat) - 1)]
            return max(q, r.hedge_min_s)

    def try_hedge(self, ticket: Ticket) -> Member | None:
        """Open a second leg for a slow dispatched ticket: charges the
        retry budget, accounts the ticket's tokens against the hedge
        replica too (it really is doing the work twice), and returns
        the hedge member for the shell to call — or None when hedging
        is off, no distinct eligible replica exists, the deadline
        already passed, or the budget is dry."""
        with self._lock:
            r = self.resilience
            if r is None or self._closed:
                return None
            primary = ticket.member
            if primary is None or ticket.hedge_member is not None:
                return None
            now = self.clock()
            if ticket.deadline is not None and now >= ticket.deadline:
                return None
            exclude = set(ticket.tried) | {primary.name}
            m = self._pick_locked(ticket.tokens, exclude=exclude,
                                  pref=ticket._canary_pref)
            # _pick treats exclude as a soft preference (retry beats
            # starvation); a hedge to the SAME replica is pointless, so
            # enforce it hard here
            if m is None or m.name in exclude:
                return None
            if not self._spend_budget_locked(1.0, ticket.tenant):
                return None
            self._tenant_spend_locked(ticket.tenant, "hedge", 1.0)
            ticket.hedge_member = m
            ticket._hedge_at = now
            self._tokens[m.name] = \
                self._tokens.get(m.name, 0) + ticket.tokens
            self._publish_inflight_locked(m.name)
            self._hedge_count_locked("started")
            self._decide_locked("hedge", now, replica=m.name)
            return m

    def retry_after(self) -> float:
        """Seconds a rejected client should back off, from the current
        queue depth over the recent completion rate."""
        with self._lock:
            return self._retry_after_locked(self.clock())

    def breaker_states(self) -> dict[str, str]:
        with self._lock:
            return {n: h.state for n, h in self._health.items()}

    def retry_budget(self, tenant: str | None = None) -> float:
        """The fleet-wide retry/hedge budget (sum over tenant buckets),
        or one tenant's bucket level when ``tenant`` is given."""
        with self._lock:
            if tenant is not None:
                return self._retry_tokens.get(tenant, 0.0)
            return sum(self._retry_tokens.values())

    def close(self) -> list[Ticket]:
        """Reject everything still queued (shell shutdown)."""
        with self._lock:
            self._closed = True
            orphans, self._queue = self._queue, []
            self._publish_queue_locked()
        for t in orphans:
            t.done.set()
        return orphans

    # -- introspection (the controller's drain checks ride on these) ---------

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def inflight_tokens(self, name: str | None = None) -> int:
        with self._lock:
            if name is not None:
                return self._tokens.get(name, 0)
            return sum(self._tokens.values())

    def drained(self, name: str) -> bool:
        """True when a cordoned replica holds no in-flight work — the
        controller's delete gate."""
        with self._lock:
            return not self._inflight.get(name)

    def members(self) -> dict[str, str]:
        with self._lock:
            return {n: m.state for n, m in self._members.items()}

    # -- locked internals ----------------------------------------------------

    def _canary_draw_locked(self) -> "tuple[str, bool] | None":
        """One deterministic draw from the seeded sequence: returns
        (canary_revision, wants_canary). A 32-bit avalanche finalizer
        over (sequence, seed) — no RNG state beyond the counter, so an
        identical admission order replays identically, and distinct
        seeds give decorrelated accept sequences (an additive offset
        would leave every seed drawing the same splits)."""
        c = self._canary
        if c is None:
            return None
        rev, weight = c
        seq = self._canary_seq
        self._canary_seq += 1
        x = (seq + 1 + self._canary_seed * 0x9E3779B9) & 0xFFFFFFFF
        x = ((x ^ (x >> 16)) * 0x7FEB352D) & 0xFFFFFFFF
        x = ((x ^ (x >> 15)) * 0x846CA68B) & 0xFFFFFFFF
        u = (x ^ (x >> 16)) / 4294967296.0
        return (rev, u < weight)

    @staticmethod
    def _canary_mismatch(m: Member, pref) -> bool:
        """True when member ``m`` sits on the wrong side of the
        ticket's canary draw — a SOFT penalty in the pick key."""
        if pref is None:
            return False
        rev, want = pref
        return (m.revision == rev) != want

    def _pick_locked(self, tokens: int,
                     exclude: set | frozenset = frozenset(),
                     pref=None) -> Member | None:
        """Least-outstanding-tokens over ACTIVE members; name breaks
        ties so replays are order-independent. Budget-full replicas are
        skipped (the request queues for the next completion). Members
        in ``exclude`` (a retrying ticket's failed transports) are
        avoided — unless they are ALL that's left, in which case a
        retry beats starvation. ``pref`` is the ticket's canary draw:
        the wrong side of the split is penalized AFTER the tried
        penalty (a retry avoids the dead replica first) but before
        load — with no canary active the element is constant and the
        legacy ordering is untouched.

        With resilience on, the key becomes (breaker-rank, tried,
        canary-mismatch, score-adjusted load, name): open breakers are
        ineligible, a half-open breaker admits exactly one probe, and
        load is scaled by EWMA latency relative to the fleet's fastest
        replica — a browned-out (slow but alive) member looks
        proportionally more expensive and drains naturally instead of
        wedging."""
        best = None
        best_key = None
        resilient = self.resilience is not None
        min_lat = None
        if resilient:
            lats = [h.lat for n, h in self._health.items()
                    if h.lat is not None and n in self._members]
            min_lat = min(lats) if lats else None
        now = self.clock() if resilient else 0.0
        for name, m in self._members.items():
            if m.state != STATE_ACTIVE:
                continue
            load = self._tokens.get(name, 0)
            if self.replica_token_budget is not None and load > 0 \
                    and load + tokens > self.replica_token_budget:
                continue
            mismatch = self._canary_mismatch(m, pref)
            if not resilient:
                key = (0, name in exclude, mismatch, load, name)
            else:
                rank = self._breaker_rank_locked(name, now)
                if rank >= 3:  # open (or probe already out): ineligible
                    continue
                score = 1.0
                h = self._health.get(name)
                if h is not None and h.lat is not None and min_lat:
                    score = max(h.lat / min_lat, 1.0)
                key = (rank, name in exclude, mismatch, load * score, name)
            if best_key is None or key < best_key:
                best, best_key = m, key
        return best

    def _dispatch_locked(self, t: Ticket, member: Member,
                         now: float) -> None:
        t.member = member
        t.revision = member.revision
        t._dispatched_at = now
        self._inflight.setdefault(member.name, {})[id(t)] = t
        self._tokens[member.name] = \
            self._tokens.get(member.name, 0) + t.tokens
        if self.resilience is not None:
            h = self._health.get(member.name)
            if h is not None and h.state == BREAKER_HALF_OPEN:
                h.probing = True  # exactly one probe per half-open
        # detached: finish() runs in a LATER call (complete/fail/shed),
        # so this span must never install itself as the ambient parent —
        # an out-of-order reset would pollute the caller's contextvar
        t._span = self.tracer.begin(
            "router.dispatch", parent=t.context, detached=True,
            service=self.service, namespace=self.namespace,
            tenant=t.tenant or self.namespace,
            replica=member.name, tokens=t.tokens,
            queue_wait_s=round(max(now - t._queued_at, 0.0), 6))
        self._publish_inflight_locked(member.name)

    def _finish_locked(self, t: Ticket, now: float,
                       tokens_done: int | None) -> None:
        t.resolved = True
        member = t.member
        if member is not None:
            bucket = self._inflight.get(member.name)
            if bucket is not None:
                bucket.pop(id(t), None)
            self._tokens[member.name] = max(
                0, self._tokens.get(member.name, 0) - t.tokens)
            self._publish_inflight_locked(member.name)
        if t._span is not None:
            self.tracer.finish(t._span)
            t._span = None
        latency = max(now - t._t0, 0.0)
        done = t.tokens if tokens_done is None else int(tokens_done)
        tenant = t.tenant or self.namespace
        hist_labels = dict(namespace=self.namespace, service=self.service,
                           tenant=tenant)
        if t.revision:  # unrevisioned traffic keeps its old series
            hist_labels["revision"] = t.revision
        self.registry.histogram(
            "router_request_seconds", latency,
            help_="submit -> completion latency through the router",
            buckets=REQUEST_BUCKETS, **hist_labels)
        self.registry.counter_inc(
            "router_tokens_total",
            help_="tokens completed through the router (rate = the "
                  "autoscaler's tokens/sec signal)",
            by=float(done), namespace=self.namespace, service=self.service,
            tenant=tenant)
        self._count_locked("completed", t.tenant, t.revision)
        if self._prom:
            prom_request_seconds().labels(
                self.service, t.revision).observe(latency)
            prom_tokens_total().labels(self.service).inc(done)

    def _drain_locked(self, now: float) -> list[Ticket]:
        """Drain the queue into whatever capacity exists. Legacy mode
        is strict FIFO; resilience mode drains by (band, FIFO) so a
        critical request never waits behind a sheddable backlog —
        band-priority dispatch is the other half of band shedding."""
        dispatched: list[Ticket] = []
        if self.resilience is None:
            remaining: list[Ticket] = []
            for t in self._queue:
                member = self._pick_locked(t.tokens, exclude=t.tried,
                                       pref=t._canary_pref)
                if member is None:
                    remaining.append(t)
                    continue
                self._dispatch_locked(t, member, now)
                dispatched.append(t)
            self._queue = remaining
            return dispatched
        order = sorted(
            range(len(self._queue)),
            key=lambda i: (BAND_RANK.get(self._queue[i].band,
                                         BAND_RANK[BAND_DEFAULT]), i))
        taken: set[int] = set()
        for i in order:
            t = self._queue[i]
            member = self._pick_locked(t.tokens, exclude=t.tried,
                                       pref=t._canary_pref)
            if member is None:
                continue
            self._dispatch_locked(t, member, now)
            dispatched.append(t)
            taken.add(i)
        if taken:
            self._queue = [t for i, t in enumerate(self._queue)
                           if i not in taken]
        return dispatched

    # -- locked resilience internals ------------------------------------------

    def _sweep_deadlines_locked(self, now: float) -> list[Ticket]:
        """Shed queued tickets whose deadline passed BEFORE spending
        replica capacity on them. Caller fires each one's done event
        outside the lock; the shell reads ``dropped_reason``."""
        if not self._queue or all(t.deadline is None for t in self._queue):
            return []
        expired = [t for t in self._queue
                   if t.deadline is not None and now >= t.deadline]
        if not expired:
            return []
        dead = set(map(id, expired))
        self._queue = [t for t in self._queue if id(t) not in dead]
        for t in expired:
            t.dropped_reason = "deadline"
            self._drop_deadline_locked(t, now)
        return expired

    def _drop_deadline_locked(self, t: Ticket, now: float) -> None:
        t.dropped_reason = "deadline"
        self._count_locked("deadline", t.tenant)
        self.registry.counter_inc(
            "router_deadline_exceeded_total",
            help_="requests dropped because their deadline elapsed",
            namespace=self.namespace, service=self.service,
            tenant=t.tenant or self.namespace)
        if self._prom:
            prom_deadline_exceeded_total().labels(self.service).inc()
        self._decide_locked("deadline", now, band=t.band)

    def _refill_budget_locked(self, tenant: str) -> None:
        """Refill the admitting TENANT'S bucket — so refill is
        proportional to each tenant's admitted traffic. The SUM across
        buckets never exceeds retry_budget_cap: when the fleet-wide
        pool is full, the refill reclaims from the fullest OTHER bucket
        (deterministic tie-break) so an idle tenant's hoard cannot
        starve an active one — but a storming tenant still only ever
        SPENDS its own bucket."""
        r = self.resilience
        tenant = tenant or self.namespace
        buckets = self._retry_tokens
        buckets.setdefault(tenant, 0.0)
        need = r.retry_budget_ratio
        headroom = r.retry_budget_cap - sum(buckets.values())
        add = min(need, max(headroom, 0.0))
        short = need - add
        if short > 1e-12:
            others = sorted(((v, k) for k, v in buckets.items()
                             if k != tenant and v > 0.0), reverse=True)
            for v, k in others:
                take = min(v, short)
                buckets[k] = v - take
                add += take
                short -= take
                if short <= 1e-12:
                    break
        if add > 0.0:
            buckets[tenant] += add
        self._publish_budget_locked()

    def _spend_budget_locked(self, cost: float, tenant: str = "") -> bool:
        """Spend from the tenant's OWN bucket only (the isolation
        half: a retry storm cannot drain a neighbor's budget)."""
        if self.resilience is None:
            return True
        tenant = tenant or self.namespace
        level = self._retry_tokens.get(tenant, 0.0)
        if level < cost:
            return False
        self._retry_tokens[tenant] = level - cost
        self._publish_budget_locked()
        return True

    def _publish_budget_locked(self) -> None:
        for tenant, level in self._retry_tokens.items():
            self.registry.gauge(
                "router_retry_budget", round(level, 6),
                help_="retry/hedge token bucket level — 0 means the "
                      "fleet is failing faster than it refills",
                namespace=self.namespace, service=self.service,
                tenant=tenant)
        if self._prom:
            # the prometheus surface keeps a fleet-level view per
            # tenant bucket (cardinality is tenant-bounded either way)
            for tenant, level in self._retry_tokens.items():
                prom_retry_budget().labels(self.service, tenant).set(level)

    def _health_locked(self, name: str) -> _Health:
        h = self._health.get(name)
        if h is None:
            h = self._health[name] = _Health()
        return h

    def _record_success_locked(self, name: str, sample: float,
                               now: float) -> None:
        h = self._health_locked(name)
        a = self.resilience.ewma_alpha
        h.lat = sample if h.lat is None else a * sample + (1 - a) * h.lat
        h.fails = 0
        h.probing = False
        if h.state != BREAKER_CLOSED:
            self._set_breaker_locked(name, h, BREAKER_CLOSED, now)

    def _record_failure_locked(self, name: str, now: float) -> None:
        h = self._health_locked(name)
        h.fails += 1
        h.probing = False
        if h.state == BREAKER_HALF_OPEN or (
                h.state == BREAKER_CLOSED
                and h.fails >= self.resilience.breaker_failures):
            h.opened_at = now
            self._set_breaker_locked(name, h, BREAKER_OPEN, now)

    def _breaker_rank_locked(self, name: str, now: float) -> int:
        """0 = closed, 1 = half-open probe slot free, 3 = ineligible
        (open and cooling off, or probe already dispatched). The
        open -> half-open transition is time-driven and happens on the
        first pick after cooloff."""
        h = self._health.get(name)
        if h is None or h.state == BREAKER_CLOSED:
            return 0
        if h.state == BREAKER_OPEN:
            if now - h.opened_at < self.resilience.breaker_cooloff_s:
                return 3
            self._set_breaker_locked(name, h, BREAKER_HALF_OPEN, now)
            h.probing = False
        return 3 if h.probing else 1

    def _set_breaker_locked(self, name: str, h: _Health, state: str,
                            now: float) -> None:
        h.state = state
        self.registry.gauge(
            "router_breaker_state", _BREAKER_GAUGE[state],
            help_="per-replica circuit breaker "
                  "(0=closed 1=half-open 2=open)",
            namespace=self.namespace, service=self.service, replica=name)
        if self._prom:
            prom_breaker_state().labels(self.service, name).set(
                _BREAKER_GAUGE[state])
        self._decide_locked("breaker", now, replica=name, state=state)

    def _hedge_count_locked(self, outcome: str) -> None:
        self.registry.counter_inc(
            "router_hedges_total",
            help_="hedged dispatches by outcome (started/won/canceled)",
            namespace=self.namespace, service=self.service,
            outcome=outcome)
        if self._prom:
            prom_hedges_total().labels(self.service, outcome).inc()

    def _retry_after_locked(self, now: float) -> float:
        """Queue depth over the recent completion rate, clamped to
        [1, 120] whole seconds — what a 429/503 Retry-After should
        say. With no completion history yet, 1s (the optimistic
        floor beats telling clients to go away for minutes)."""
        depth = len(self._queue) + 1
        dq = self._completions
        if len(dq) >= 2 and dq[-1] > dq[0]:
            rate = (len(dq) - 1) / (dq[-1] - dq[0])
            est = depth / rate if rate > 0 else 1.0
        else:
            est = 1.0
        return float(min(max(math.ceil(est), 1), 120))

    def _decide_locked(self, kind: str, now: float, **kv: Any) -> None:
        if self.on_decision is not None:
            self.on_decision(dict(kind=kind, t=round(now, 6), **kv))

    def _publish_queue_locked(self) -> None:
        self.registry.gauge(
            "router_queue_depth", len(self._queue),
            help_="requests waiting in the router admission queue",
            namespace=self.namespace, service=self.service)
        if self._prom:
            prom_queue_depth().labels(self.service).set(len(self._queue))
        # the per-tenant cut is a SEPARATE family: RegistrySignals sums
        # router_queue_depth by label SUBSET, so tenant series on the
        # fleet gauge would double-count the autoscaler's signal
        if self._tenants:
            depth: dict[str, int] = {t: 0 for t in self._tenants}
            for q in self._queue:
                tenant = q.tenant or self.namespace
                depth[tenant] = depth.get(tenant, 0) + 1
            for tenant, n in depth.items():
                self.registry.gauge(
                    "router_tenant_queue_depth", n,
                    help_="requests waiting in the router admission "
                          "queue, by billing tenant",
                    namespace=self.namespace, service=self.service,
                    tenant=tenant)

    def _publish_inflight_locked(self, name: str) -> None:
        self.registry.gauge(
            "router_tokens_inflight", self._tokens.get(name, 0),
            help_="outstanding token estimate per replica",
            namespace=self.namespace, service=self.service, replica=name)
        if self._prom:
            prom_tokens_inflight().labels(self.service, name).set(
                self._tokens.get(name, 0))

    def _count_locked(self, outcome: str, tenant: str = "",
                      revision: str = "") -> None:
        # the revision label exists only while revisions are in play —
        # unrevisioned traffic keeps its pre-rollout series identity
        labels = dict(namespace=self.namespace, service=self.service,
                      tenant=tenant or self.namespace, outcome=outcome)
        if revision:
            labels["revision"] = revision
        self.registry.counter_inc(
            "router_requests_total",
            help_="requests by outcome (completed/rejected/shed/failed)",
            **labels)
        if self._prom:
            prom_requests_total().labels(
                self.service, outcome, revision).inc()

    def _register_tenant_locked(self, tenant: str) -> None:
        """First sight of a tenant: pre-register its counter families
        at 0 so ``rate()``/``increase()`` have a sample BEFORE the
        first error — a fresh tenant's very first failure must trip
        its burn/storm rules (the PR 10 zero-sample lesson)."""
        tenant = tenant or self.namespace
        if tenant in self._tenants:
            return
        self._tenants.add(tenant)
        if self.resilience is not None:
            # seed the tenant's retry bucket with the pool's remaining
            # headroom, topped up to a fair share (cap / tenants seen)
            # reclaimed from the fullest buckets when headroom is
            # short. The FIRST tenant still starts at the full cap
            # (single-tenant behavior unchanged — banked replays hold);
            # a late arrival gets a working share immediately instead
            # of having its very first retry denied, yet the sum across
            # buckets never exceeds the cap and nobody's bucket is
            # touched while the pool has headroom.
            cap = self.resilience.retry_budget_cap
            buckets = self._retry_tokens
            seed = max(cap - sum(buckets.values()), 0.0)
            share = cap / (len(buckets) + 1)
            short = share - seed
            if short > 1e-12:
                others = sorted(((v, k) for k, v in buckets.items()
                                 if v > 0.0), reverse=True)
                for v, k in others:
                    take = min(v, short)
                    buckets[k] = v - take
                    seed += take
                    short -= take
                    if short <= 1e-12:
                        break
            buckets[tenant] = seed
            self._publish_budget_locked()
        for outcome in TENANT_OUTCOMES:
            self.registry.counter_inc(
                "router_requests_total", by=0.0,
                help_="requests by outcome "
                      "(completed/rejected/shed/failed)",
                namespace=self.namespace, service=self.service,
                tenant=tenant, outcome=outcome)
        self.registry.counter_inc(
            "router_tokens_total", by=0.0,
            help_="tokens completed through the router (rate = the "
                  "autoscaler's tokens/sec signal)",
            namespace=self.namespace, service=self.service, tenant=tenant)
        for kind in ("retry", "hedge"):
            self.registry.counter_inc(
                "router_tenant_retry_tokens_total", by=0.0,
                help_="retry-budget tokens spent on retries and hedges, "
                      "by billing tenant",
                namespace=self.namespace, service=self.service,
                tenant=tenant, kind=kind)
        self.registry.gauge(
            "router_tenant_queue_depth", 0,
            help_="requests waiting in the router admission queue, by "
                  "billing tenant",
            namespace=self.namespace, service=self.service, tenant=tenant)

    def _tenant_spend_locked(self, tenant: str, kind: str,
                             cost: float) -> None:
        """Attribute a retry-budget spend (a retry or a hedge leg) to
        the tenant whose request drew it — the retry-storm signal."""
        self.registry.counter_inc(
            "router_tenant_retry_tokens_total", by=cost,
            help_="retry-budget tokens spent on retries and hedges, "
                  "by billing tenant",
            namespace=self.namespace, service=self.service,
            tenant=tenant or self.namespace, kind=kind)


# -- endpoints annotation helpers -------------------------------------------


def render_endpoints(endpoints: list[dict]) -> str:
    """Canonical JSON for the annotation (sorted, compact) so an
    unchanged endpoint set patches to an identical string — the
    controller's no-op write guard compares it byte-for-byte."""
    return json.dumps(sorted(endpoints, key=lambda e: e.get("name", "")),
                      separators=(",", ":"), sort_keys=True)


def parse_endpoints(service_obj: dict) -> list[dict]:
    """The endpoint list a JAXService object currently publishes."""
    raw = ((service_obj.get("metadata") or {}).get("annotations") or {}) \
        .get(ANNOTATION_ENDPOINTS)
    if not raw:
        return []
    try:
        eps = json.loads(raw)
    except ValueError:
        log.warning("malformed %s annotation ignored", ANNOTATION_ENDPOINTS)
        return []
    return [e for e in eps if isinstance(e, dict) and e.get("name")]


# -- autoscaler signal source -----------------------------------------------


class RegistrySignals:
    """The JAXService autoscaler's signal reader: parses the router- and
    replica-exported series back out of a MetricsRegistry's text
    exposition (the PR 4 histograms ARE the wire — in production the
    same text arrives by scraping the router's /metrics; hermetically
    the registry is shared in-process). Series names are the catalog in
    docs/observability.md."""

    def __init__(self, registry):
        # a MetricsRegistry (shared-process fast path), or a zero-arg
        # callable returning an exposition body — the scraped-/metrics
        # source for a controller running out-of-process from the router
        self.registry = registry

    def _series(self, name: str) -> list[tuple[dict, float]]:
        # in-process fast path: structured samples straight off the
        # registry (O(metric) instead of rendering + parsing the whole
        # exposition per signal read). Scraped bodies go through the
        # ONE exposition parser (obs/expofmt.py) shared with the fleet
        # scrape plane — no second spelling.
        reader = getattr(self.registry, "series", None)
        if reader is not None:
            return reader(name)
        from kubeflow_tpu.obs import expofmt

        text = self.registry() if callable(self.registry) \
            else self.registry.render()
        return expofmt.samples(text, name)

    def _sum(self, name: str, **match) -> float:
        total = 0.0
        for labels, value in self._series(name):
            if all(labels.get(k) == v for k, v in match.items()):
                total += value
        return total

    def queue_depth(self, namespace: str, service: str) -> float:
        return self._sum("router_queue_depth",
                         namespace=namespace, service=service)

    def tokens_total(self, namespace: str, service: str) -> float:
        return self._sum("router_tokens_total",
                         namespace=namespace, service=service)

    def inflight_tokens(self, namespace: str, service: str,
                        replica: str | None = None) -> float:
        match = {"namespace": namespace, "service": service}
        if replica is not None:
            match["replica"] = replica
        return self._sum("router_tokens_inflight", **match)

    def replica_drained(self, namespace: str, service: str,
                        replica: str) -> bool:
        return self.inflight_tokens(namespace, service, replica) <= 0


# -- threaded/HTTP shell ----------------------------------------------------


class TransportError(Exception):
    """A replica answered with an HTTP error. Carries the status and
    the parsed Retry-After (seconds) so the frontend's retry loop can
    honor the replica's backpressure as a backoff FLOOR instead of
    hammering it on a fixed schedule (the PR 5 RestClient discipline)."""

    def __init__(self, status: int, message: str,
                 retry_after: float | None = None):
        super().__init__(message)
        self.status = status
        self.retry_after = retry_after


class HttpTransport:
    """POST a predict body to a replica server (urllib; stdlib-only,
    the RestClient discipline)."""

    def __init__(self, base_url: str, timeout: float = 300.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def predict(self, model: str, body: bytes,
                headers: dict | None = None) -> bytes:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"{self.base_url}/v1/models/{model}:predict", data=body,
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST")
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            ra = None
            try:
                raw_ra = e.headers.get("Retry-After") if e.headers else None
                if raw_ra is not None:
                    ra = max(float(raw_ra), 0.0)
            except (TypeError, ValueError):
                ra = None
            raise TransportError(
                e.code, f"replica returned {e.code}: {e.reason}",
                retry_after=ra) from e


def _retry_after_headers(retry_after: float | None) -> dict | None:
    if retry_after is None:
        return None
    return {"Retry-After": str(int(math.ceil(retry_after)))}


class RouterFrontend:
    """The blocking HTTP face over the deterministic core: one handler
    thread carries its request end-to-end (submit -> wait for dispatch
    -> call the replica transport -> complete), so the router itself
    never blocks under its lock.

    Resilience responsibilities live here too: parse the deadline/band
    headers, forward the SHRINKING deadline budget replica-ward on
    every attempt, honor Retry-After as a backoff floor between
    retries, race a hedge leg when the core says the primary is slow,
    and map router drop reasons to 504/429/503."""

    def __init__(self, router: TokenRouter, max_new_tokens: int = 32,
                 dispatch_timeout: float = 120.0,
                 default_deadline_s: float | None = None,
                 default_band: str = BAND_DEFAULT,
                 sleep: Callable[[float], None] = time.sleep):
        self.router = router
        self.max_new_tokens = max_new_tokens
        self.dispatch_timeout = dispatch_timeout
        self.default_deadline_s = default_deadline_s
        self.default_band = default_band
        self.hedging = True
        self.retry_backoff_s = 0.05   # doubles per failure
        self.retry_backoff_cap_s = 5.0
        self._sleep = sleep

    def apply_spec(self, service_obj: dict) -> None:
        """Adopt the JAXService spec's resilience defaults (namespace-
        defaulted band/deadline — the multi-tenancy bridge). The
        endpoints watch calls this per event, so a spec edit takes
        effect without a router restart."""
        from kubeflow_tpu.control.jaxservice.types import resilience_spec

        r = resilience_spec((service_obj or {}).get("spec") or {})
        self.default_band = r["defaultBand"]
        self.default_deadline_s = r["deadlineSeconds"] or None
        self.hedging = bool(r["hedge"])

    @staticmethod
    def _drop_error(ticket: Ticket):
        """Map a router-side drop to the client-facing status."""
        from kubeflow_tpu.utils.httpd import ApiHttpError

        if ticket.dropped_reason == "deadline":
            return ApiHttpError(504, "deadline exceeded")
        if ticket.dropped_reason == "shed_band":
            return ApiHttpError(
                429, f"shed under overload (band={ticket.band})",
                headers=_retry_after_headers(ticket.retry_after))
        if ticket.dropped_reason == "retry_budget":
            return ApiHttpError(
                503, "retry budget exhausted",
                headers=_retry_after_headers(ticket.retry_after))
        return None

    def _abandon(self, ticket: Ticket) -> None:
        """Last-resort resolution when the dispatch loop exits on an
        unexpected exception: a ticket the router already resolved
        (completed, or dropped with a reason) is left alone; anything
        else is failed WITHOUT requeue so the replica's in-flight
        token accounting is released before the error propagates."""
        if ticket.resolved or ticket.dropped_reason is not None:
            return
        self.router.fail(ticket, requeue=False)

    def predict(self, req):
        from kubeflow_tpu.utils.httpd import ApiHttpError

        model = req.params["model"]
        body = req.json() or {}
        instances = body.get("instances")
        if instances is None:
            raise ApiHttpError(400, 'request body must contain "instances"')
        ctx = obs_trace.parse_traceparent(req.header("traceparent"))
        tokens = estimate_tokens(instances, self.max_new_tokens)
        band = req.header(HEADER_BAND) or self.default_band
        if band not in BAND_RANK:
            band = BAND_DEFAULT
        # the billing tenant: an explicit header override, else the
        # JAXService namespace (submit() applies the default). Garbage
        # is a 400, not a label value — header text must never flow
        # unchecked into the metric exposition.
        tenant = (req.header(HEADER_TENANT) or "").strip() or None
        if tenant is not None and not TENANT_RE.match(tenant):
            raise ApiHttpError(
                400, f"bad {HEADER_TENANT} header: must be a DNS-1123 "
                     f"label")
        # the real HTTP shell returns "" for a missing header (httpd
        # HttpReq.header default) while stubs return None — both mean
        # "no deadline requested"
        raw_deadline = req.header(HEADER_DEADLINE)
        if raw_deadline:
            try:
                deadline_s = float(raw_deadline)
            except ValueError:
                raise ApiHttpError(
                    400, f"bad {HEADER_DEADLINE} header: {raw_deadline!r}")
        else:
            deadline_s = self.default_deadline_s
        deadline = (self.router.clock() + deadline_s
                    if deadline_s is not None and deadline_s > 0 else None)
        try:
            ticket = self.router.submit(tokens, item=model, context=ctx,
                                        band=band, deadline=deadline,
                                        tenant=tenant)
        except DeadlineExceeded:
            raise ApiHttpError(504, "deadline exceeded")
        except RouterBusy as e:
            raise ApiHttpError(
                429, str(e),
                headers=_retry_after_headers(e.retry_after))
        # every path below must resolve the ticket (complete, or fail
        # with/without requeue). The blanket handler is the last-resort
        # resolution for anything unexpected thrown mid-dispatch --
        # without it the replica's in-flight accounting would hold this
        # ticket's tokens forever (RES702).
        try:
            last_err: Exception | None = None
            failures = 0
            while failures < 3:
                if ticket.member is None:
                    wait_s = self.dispatch_timeout
                    if deadline is not None:
                        wait_s = min(
                            wait_s,
                            max(deadline - self.router.clock(), 0.0) + 0.05)
                    fired = ticket.done.wait(wait_s)
                    err = self._drop_error(ticket)
                    if err is not None:
                        raise err
                    if not fired:
                        self.router.fail(ticket, requeue=False)
                        err = self._drop_error(ticket)
                        if err is not None:  # fail() resolved it as a drop
                            raise err
                        if deadline is not None \
                                and self.router.clock() >= deadline:
                            raise ApiHttpError(504, "deadline exceeded")
                        raise ApiHttpError(503, "no replica capacity")
                member = ticket.member
                if member is None:  # shed mid-wait; loop waits again
                    continue
                hdrs: dict[str, str] = {}
                if req.header("traceparent"):
                    hdrs["traceparent"] = req.header("traceparent")
                if band != BAND_DEFAULT:
                    hdrs[HEADER_BAND] = band
                if deadline is not None:
                    remaining = deadline - self.router.clock()
                    if remaining <= 0:
                        self.router.fail(ticket, requeue=False)
                        raise ApiHttpError(504, "deadline exceeded")
                    # the budget SHRINKS across retries: each hop sees only
                    # what's left, so a retried request cannot overstay
                    hdrs[HEADER_DEADLINE] = f"{remaining:.3f}"
                try:
                    delay = (self.router.hedge_delay()
                             if self.hedging else None)
                    if delay is None:
                        raw = member.transport.predict(
                            model, req.body, headers=hdrs or None)
                        winner = None
                    else:
                        raw, winner = self._hedged_predict(
                            ticket, member, model, req.body, hdrs, delay,
                            deadline)
                except Exception as e:  # replica died mid-request: retry
                    last_err = e
                    failures += 1
                    self.router.fail(ticket, requeue=True)
                    err = self._drop_error(ticket)
                    if err is not None:  # deadline/budget ended the retries
                        raise err
                    floor = getattr(e, "retry_after", None) or 0.0
                    backoff = max(
                        self.retry_backoff_s * (2 ** (failures - 1)), floor)
                    if backoff > 0:
                        self._sleep(min(backoff, self.retry_backoff_cap_s))
                    continue
                self.router.complete(ticket, winner=winner)
                return json.loads(raw)
            self.router.fail(ticket, requeue=False)
            raise ApiHttpError(502, f"replica transport failed: {last_err}")
        except BaseException:
            self._abandon(ticket)
            raise

    def _hedged_predict(self, ticket: Ticket, member: Member, model: str,
                        body: bytes, hdrs: dict, delay: float,
                        deadline: float | None):
        """Race the primary transport against a hedge leg opened after
        ``delay`` seconds of silence. First SUCCESS wins; the loser is
        abandoned (its replica-side deadline cancels it and frees its
        pages — the core already released its token accounting via
        ``complete(winner=...)``). Raises the primary's error when
        every started leg failed."""
        box: dict[str, Any] = {"raw": None, "winner": None, "errors": []}
        box_lock = threading.Lock()
        wake = threading.Event()
        legs: list[Member] = [member]

        def leg(m: Member, leg_hdrs: dict | None) -> None:
            try:
                out = m.transport.predict(model, body, headers=leg_hdrs)
            except Exception as e:
                with box_lock:
                    box["errors"].append(e)
                wake.set()
                return
            with box_lock:
                if box["winner"] is None:
                    box["winner"] = m.name
                    box["raw"] = out
            wake.set()

        threading.Thread(target=leg, args=(member, dict(hdrs) or None),
                         daemon=True, name="router-hedge-primary").start()
        if not wake.wait(delay):
            hedge = self.router.try_hedge(ticket)
            if hedge is not None:
                leg_hdrs = dict(hdrs)
                if deadline is not None:
                    leg_hdrs[HEADER_DEADLINE] = \
                        f"{max(deadline - self.router.clock(), 0.0):.3f}"
                legs.append(hedge)
                threading.Thread(
                    target=leg, args=(hedge, leg_hdrs or None),
                    daemon=True, name="router-hedge-secondary").start()
        # wait for a winner or for every started leg to fail, bounded
        # by the deadline (plus grace for the replica-side cancel)
        t_end = None
        if deadline is not None:
            t_end = deadline + 1.0
        while True:
            with box_lock:
                if box["winner"] is not None:
                    return box["raw"], box["winner"]
                if len(box["errors"]) >= len(legs):
                    raise box["errors"][0]
                wake.clear()
            budget = self.dispatch_timeout
            if t_end is not None:
                budget = min(budget,
                             max(t_end - self.router.clock(), 0.0))
            if not wake.wait(budget):
                with box_lock:
                    if box["winner"] is not None:
                        return box["raw"], box["winner"]
                raise TransportError(
                    504, "all legs exceeded the request deadline")

    def build(self):
        from kubeflow_tpu.utils import httpd

        r = httpd.Router("jaxservice-router")
        r.route("POST", "/v1/models/{model}:predict", self.predict)
        httpd.add_health_routes(r)
        httpd.add_metrics_route(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 8600):
        from kubeflow_tpu.utils import httpd

        return httpd.HttpService(self.build(), host, port)


def main() -> None:  # pragma: no cover - container entry
    import argparse
    import os

    p = argparse.ArgumentParser("kubeflow-tpu-router")
    p.add_argument("--port", type=int, default=8600)
    p.add_argument("--service", default=os.environ.get("JAXSERVICE_NAME",
                                                       "default"))
    p.add_argument("--namespace", default=os.environ.get("POD_NAMESPACE",
                                                         "default"))
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--endpoints", default="",
                   help="static bootstrap: name=url[,name=url...] "
                        "(the controller watch takes over in-cluster)")
    p.add_argument("--apiserver", default="",
                   help="watch the JAXService endpoints annotation")
    p.add_argument("--no-resilience", action="store_true",
                   help="disable deadlines/hedging/breakers/band "
                        "shedding (legacy dispatch)")
    p.add_argument("--default-deadline-s", type=float, default=0.0,
                   help="deadline for requests without an "
                        "x-request-deadline-s header (0 = none)")
    p.add_argument("--default-band", default=BAND_DEFAULT,
                   choices=BANDS,
                   help="criticality band for unlabeled requests")
    args = p.parse_args()
    router = TokenRouter(service=args.service, namespace=args.namespace,
                         max_queue=args.max_queue,
                         resilience=(None if args.no_resilience
                                     else ResilienceConfig()))
    if args.endpoints:
        eps = [{"name": n, "addr": u, "state": STATE_ACTIVE}
               for n, _, u in (e.partition("=")
                               for e in args.endpoints.split(","))]
        router.sync_endpoints(
            eps, transport_factory=lambda ep: HttpTransport(ep["addr"]))
    frontend = RouterFrontend(
        router, max_new_tokens=args.max_new_tokens,
        default_deadline_s=args.default_deadline_s or None,
        default_band=args.default_band)
    if args.apiserver:
        from kubeflow_tpu.control.jaxservice import watch_endpoints

        threading.Thread(
            target=watch_endpoints,
            args=(args.apiserver, args.namespace, args.service, router),
            kwargs={"frontend": frontend},
            daemon=True, name="router-endpoints-watch").start()
    svc = frontend.serve(port=args.port)
    log.info("jaxservice router %s/%s on :%d", args.namespace,
             args.service, svc.port)
    svc.serve_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
