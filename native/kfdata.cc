// kfdata: native record-file loader for the TPU training runtime.
//
// The reference platform has no in-tree native IO (every compiled
// component is Go; data loading is delegated to TF inside payload
// images). For the TPU build the input pipeline is in-scope: TPUs are
// fed from host RAM over PCIe, and the feed must come off the Python
// critical path or MXU utilization drops with it. This library is the
// hot host-side loop: file reading, checksum validation, shuffling and
// batch assembly run in a background C++ thread; Python sees only
// filled numpy buffers via ctypes (kubeflow_tpu/runtime/records.py).
//
// File format ("KFR1"): fixed-size-record shards for tensor data.
//   header : magic "KFR1" | u32 version | u64 record_bytes | u64 n_records
//   records: n_records x (record_bytes payload | u32 crc32)
// Fixed-size records make batch assembly a memcpy and random access
// trivial (offset arithmetic), which is what tensor datasets (token
// sequences, decoded images) want.
//
// Concurrency model: one producer thread per loader streams shards
// sequentially (the fast path for spinning or networked storage),
// validates CRCs, runs an N-record shuffle pool (reservoir swap, the
// same algorithm as TF's ShuffleDataset), assembles batches, and pushes
// them into a bounded queue. The consumer (Python) pops complete
// batches. Bounded queue => bounded memory; blocking push => backpressure.

#include <algorithm>
#include <array>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kVersion = 1;

uint32_t Crc32(const uint8_t* data, size_t n) {
  static const std::array<uint32_t, 256> table = [] {
    std::array<uint32_t, 256> t{};
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
    return t;
  }();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

#pragma pack(push, 1)
struct Header {
  char magic[4];
  uint32_t version;
  uint64_t record_bytes;
  uint64_t n_records;
};
#pragma pack(pop)

struct Loader {
  // config
  std::vector<std::string> paths;
  uint64_t record_bytes = 0;
  int batch = 1;
  int shuffle_buffer = 0;
  uint64_t seed = 0;
  bool loop = false;
  bool drop_remainder = true;
  size_t queue_capacity = 4;

  // state
  std::deque<std::vector<uint8_t>> queue;  // ready batches
  std::mutex mu;
  std::condition_variable cv_space, cv_data;
  std::thread worker;
  std::atomic<bool> stop{false};
  bool done = false;
  std::string error;  // guarded by mu; non-empty => failed

  ~Loader() { Shutdown(); }

  void Shutdown() {
    stop.store(true);
    {
      std::lock_guard<std::mutex> lk(mu);
      cv_space.notify_all();
      cv_data.notify_all();
    }
    if (worker.joinable()) worker.join();
  }

  void Fail(const std::string& msg) {
    std::lock_guard<std::mutex> lk(mu);
    if (error.empty()) error = msg;
    done = true;
    cv_data.notify_all();
  }

  // Blocking bounded push; returns false when shutting down.
  bool Push(std::vector<uint8_t>&& b) {
    std::unique_lock<std::mutex> lk(mu);
    cv_space.wait(lk, [&] { return queue.size() < queue_capacity || stop.load(); });
    if (stop.load()) return false;
    queue.push_back(std::move(b));
    cv_data.notify_one();
    return true;
  }

  void Run() {
    std::mt19937_64 rng(seed);
    std::vector<std::vector<uint8_t>> pool;  // shuffle reservoir
    if (shuffle_buffer > 1) pool.reserve(shuffle_buffer);
    std::vector<uint8_t> cur;  // batch under assembly
    cur.reserve(static_cast<size_t>(batch) * record_bytes);
    int in_batch = 0;

    auto emit = [&](std::vector<uint8_t>&& rec) -> bool {
      cur.insert(cur.end(), rec.begin(), rec.end());
      if (++in_batch == batch) {
        std::vector<uint8_t> full;
        full.swap(cur);
        cur.reserve(static_cast<size_t>(batch) * record_bytes);
        in_batch = 0;
        return Push(std::move(full));
      }
      return true;
    };
    auto deliver = [&](std::vector<uint8_t>&& rec) -> bool {
      if (shuffle_buffer > 1) {
        if (static_cast<int>(pool.size()) < shuffle_buffer) {
          pool.push_back(std::move(rec));
          return true;
        }
        size_t j = rng() % pool.size();
        std::swap(pool[j], rec);
      }
      return emit(std::move(rec));
    };

    std::vector<uint8_t> buf(record_bytes + 4);
    do {
      for (const auto& path : paths) {
        if (stop.load()) return;
        FILE* f = std::fopen(path.c_str(), "rb");
        if (!f) {
          Fail("kfdata: cannot open " + path);
          return;
        }
        Header h{};
        if (std::fread(&h, sizeof(h), 1, f) != 1 ||
            std::memcmp(h.magic, "KFR1", 4) != 0 || h.version != kVersion) {
          std::fclose(f);
          Fail("kfdata: bad header in " + path);
          return;
        }
        if (h.record_bytes != record_bytes) {
          std::fclose(f);
          Fail("kfdata: record_bytes mismatch in " + path + ": file has " +
               std::to_string(h.record_bytes) + ", loader expects " +
               std::to_string(record_bytes));
          return;
        }
        for (uint64_t r = 0; r < h.n_records && !stop.load(); ++r) {
          if (std::fread(buf.data(), 1, record_bytes + 4, f) != record_bytes + 4) {
            std::fclose(f);
            Fail("kfdata: truncated record in " + path);
            return;
          }
          uint32_t want;
          std::memcpy(&want, buf.data() + record_bytes, 4);
          if (Crc32(buf.data(), record_bytes) != want) {
            std::fclose(f);
            Fail("kfdata: crc mismatch in " + path + " record " +
                 std::to_string(r));
            return;
          }
          std::vector<uint8_t> rec(buf.begin(), buf.begin() + record_bytes);
          if (!deliver(std::move(rec))) {
            std::fclose(f);
            return;
          }
        }
        std::fclose(f);
      }
    } while (loop && !stop.load());

    // End of (non-loop) data: drain the shuffle pool, then the partial batch.
    std::shuffle(pool.begin(), pool.end(), rng);
    for (auto& rec : pool) {
      if (!emit(std::move(rec))) return;
    }
    if (in_batch > 0 && !drop_remainder) {
      Push(std::move(cur));
    }
    {
      std::lock_guard<std::mutex> lk(mu);
      done = true;
      cv_data.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// Create a loader and start its producer thread. Returns NULL on bad args.
void* kfdl_open(const char** paths, int n_paths, uint64_t record_bytes,
                int batch, int shuffle_buffer, uint64_t seed, int loop,
                int drop_remainder, int queue_capacity) {
  if (n_paths <= 0 || record_bytes == 0 || batch <= 0) return nullptr;
  auto* l = new Loader();
  l->paths.assign(paths, paths + n_paths);
  l->record_bytes = record_bytes;
  l->batch = batch;
  l->shuffle_buffer = shuffle_buffer;
  l->seed = seed;
  l->loop = loop != 0;
  l->drop_remainder = drop_remainder != 0;
  if (queue_capacity > 0) l->queue_capacity = queue_capacity;
  l->worker = std::thread([l] { l->Run(); });
  return l;
}

// Pop the next batch into out (capacity bytes). Returns bytes written
// (batch*record_bytes, or less for a final partial batch), 0 at end of
// data, -1 on error (see kfdl_error).
int64_t kfdl_next(void* handle, uint8_t* out, int64_t capacity) {
  auto* l = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(l->mu);
  l->cv_data.wait(lk, [&] {
    return !l->queue.empty() || l->done || l->stop.load();
  });
  // Drain queued (pre-error) batches before reporting the error, matching
  // the Python oracle: every good batch is delivered deterministically,
  // THEN the failure surfaces.
  if (l->queue.empty()) {
    if (!l->error.empty()) return -1;
    return 0;  // done or stopping
  }
  auto& front = l->queue.front();
  if (static_cast<int64_t>(front.size()) > capacity) {
    l->error = "kfdata: output buffer too small";
    return -1;
  }
  std::memcpy(out, front.data(), front.size());
  int64_t n = static_cast<int64_t>(front.size());
  l->queue.pop_front();
  l->cv_space.notify_one();
  return n;
}

const char* kfdl_error(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  std::lock_guard<std::mutex> lk(l->mu);
  return l->error.c_str();  // valid until kfdl_close
}

void kfdl_close(void* handle) {
  auto* l = static_cast<Loader*>(handle);
  delete l;  // ~Loader joins the worker
}

// Checksum helper exported for the Python writer/tests (must match the
// reader's polynomial).
uint32_t kfdl_crc32(const uint8_t* data, uint64_t n) { return Crc32(data, n); }

}  // extern "C"
