"""Idle culling: scale notebooks to zero when Jupyter reports no activity.

Reference: notebook-controller/pkg/culler/culler.go —
- env knobs (:24-27): ENABLE_CULLING (default off), CULL_IDLE_TIME
  (1440 min), IDLENESS_CHECK_PERIOD (1 min);
- probe (:138): GET http://<nb>.<ns>.svc/notebook/<ns>/<nb>/api/status,
  parse Jupyter's last_activity;
- idle decision (:171-191) and the stop annotation write (:91), which the
  next reconcile turns into replicas=0 (notebook_controller.go:284-286).

The HTTP probe is injectable so controller tests drive idleness without a
live Jupyter (the fake-backend stance of SURVEY.md §4).
"""

from __future__ import annotations

import datetime
import logging
import os
from typing import Callable

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.notebook import types as T

log = logging.getLogger("kubeflow_tpu.culler")

TIME_FMT = "%Y-%m-%dT%H:%M:%SZ"


def enabled() -> bool:
    return os.environ.get("ENABLE_CULLING", "false").lower() == "true"


def idle_time_minutes() -> float:
    return float(os.environ.get("CULL_IDLE_TIME", "1440"))


def check_period_minutes() -> float:
    return float(os.environ.get("IDLENESS_CHECK_PERIOD", "1"))


def requeue_seconds() -> float:
    """GetRequeueTime analogue (culler.go:61)."""
    return check_period_minutes() * 60.0


def default_probe(notebook: dict) -> str | None:
    """GET the Jupyter status API; returns last_activity or None.

    Address goes through the in-cluster Service DNS exactly like
    getNotebookApiStatus (culler.go:138-169).
    """
    import requests

    m = ob.meta(notebook)
    url = (
        f"http://{m['name']}.{m['namespace']}.svc.cluster.local"
        f"/notebook/{m['namespace']}/{m['name']}/api/status"
    )
    try:
        r = requests.get(url, timeout=5)
        if r.status_code != 200:
            return None
        return r.json().get("last_activity")
    except Exception as e:
        log.debug("status probe failed for %s: %s", m["name"], e)
        return None


def is_idle(last_activity: str | None, now: datetime.datetime | None = None) -> bool:
    """notebookIsIdle (culler.go:171-189)."""
    if not last_activity:
        return False
    try:
        last = datetime.datetime.strptime(
            last_activity.split(".")[0].rstrip("Z") + "Z", TIME_FMT
        ).replace(tzinfo=datetime.timezone.utc)
    except ValueError:
        return False
    now = now or datetime.datetime.now(datetime.timezone.utc)
    return (now - last).total_seconds() > idle_time_minutes() * 60.0


def is_stopped(notebook: dict) -> bool:
    return T.STOP_ANNOTATION in ob.annotations_of(notebook)


def set_stop_annotation(notebook: dict) -> None:
    """SetStopAnnotation (culler.go:91)."""
    ob.set_annotation(notebook, T.STOP_ANNOTATION, ob.now_iso())


def needs_culling(
    notebook: dict,
    probe: Callable[[dict], str | None] = default_probe,
    now: datetime.datetime | None = None,
) -> bool:
    """NotebookNeedsCulling (culler.go:191-206)."""
    if not enabled():
        return False
    if is_stopped(notebook):
        return False
    return is_idle(probe(notebook), now=now)
