import socket
import threading

import pytest

from kubeflow_tpu.parallel import dist

from kubeflow_tpu.parallel.dist import (
    ENV_COORD,
    ENV_NPROC,
    ENV_PID,
    DistConfig,
    initialize_from_env,
    is_coordinator,
    wait_for_coordinator,
)


def test_config_defaults_single_process():
    cfg = DistConfig.from_env({})
    assert not cfg.distributed
    assert cfg.process_id == 0 and cfg.num_processes == 1
    assert is_coordinator(cfg)


def test_config_from_env_roundtrip():
    env = {ENV_COORD: "job-0.svc:1234", ENV_NPROC: "4", ENV_PID: "2"}
    cfg = DistConfig.from_env(env)
    assert cfg.distributed
    assert cfg.coordinator_address == "job-0.svc:1234"
    assert cfg.process_id == 2
    out = cfg.to_env()
    assert out[ENV_COORD] == "job-0.svc:1234"
    assert out[ENV_PID] == "2"


def test_config_default_port_appended():
    cfg = DistConfig.from_env({ENV_COORD: "job-0.svc", ENV_NPROC: "2", ENV_PID: "1"})
    assert cfg.coordinator_address.endswith(":8476")


def test_initialize_noop_single_process():
    # num_processes==1 must not touch jax.distributed
    cfg = initialize_from_env({})
    assert cfg.num_processes == 1


def test_initialize_requires_coordinator():
    with pytest.raises(ValueError):
        initialize_from_env({ENV_NPROC: "2", ENV_PID: "1", }, wait=False)


def test_wait_for_coordinator_success():
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    def accept_quietly():
        try:
            srv.accept()
        except OSError:
            pass

    t = threading.Thread(target=accept_quietly, daemon=True)
    t.start()
    try:
        wait_for_coordinator(f"127.0.0.1:{port}", timeout_s=5)
    finally:
        srv.close()


def test_wait_for_coordinator_timeout():
    with pytest.raises(TimeoutError):
        wait_for_coordinator("127.0.0.1:1", timeout_s=0.3)


class TestMultislice:
    """SURVEY §2.5 "DCN across slices": the JAXJOB_NUM_SLICES /
    JAXJOB_SLICE_ID contract plus the MEGASCALE_* vars libtpu's DCN
    transport reads."""

    def test_slice_env_block(self):
        env = dist.slice_env(2, 1, "job-worker-0.job.ns.svc:8476")
        assert env[dist.ENV_NUM_SLICES] == "2"
        assert env[dist.ENV_SLICE_ID] == "1"
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env["MEGASCALE_SLICE_ID"] == "1"
        assert env["MEGASCALE_COORDINATOR_ADDRESS"] == \
            f"job-worker-0.job.ns.svc:{dist.MEGASCALE_PORT}"

    def test_config_roundtrip_with_slices(self):
        cfg = dist.DistConfig(
            coordinator_address="c:8476", num_processes=4, process_id=3,
            num_slices=2, slice_id=1)
        assert cfg.multislice
        back = dist.DistConfig.from_env(cfg.to_env())
        assert back.num_slices == 2 and back.slice_id == 1
        assert back.num_processes == 4 and back.process_id == 3

    def test_single_slice_emits_no_megascale(self):
        cfg = dist.DistConfig(
            coordinator_address="c:8476", num_processes=2, process_id=0)
        env = cfg.to_env()
        assert not any(k.startswith("MEGASCALE") for k in env)
        assert dist.ENV_NUM_SLICES not in env

    def test_initialize_derives_megascale_env(self, monkeypatch):
        import os

        for k in list(os.environ):
            if k.startswith("MEGASCALE"):
                monkeypatch.delenv(k)
        cfg_env = {dist.ENV_NPROC: "1", dist.ENV_NUM_SLICES: "2",
                   dist.ENV_SLICE_ID: "1",
                   dist.ENV_COORD: "coord-host:8476"}
        try:
            dist.initialize_from_env(cfg_env)
            assert os.environ["MEGASCALE_SLICE_ID"] == "1"
            assert os.environ["MEGASCALE_COORDINATOR_ADDRESS"] == \
                f"coord-host:{dist.MEGASCALE_PORT}"
        finally:
            for k in list(os.environ):
                if k.startswith("MEGASCALE"):
                    del os.environ[k]

    def test_dist_import_is_jax_free(self):
        """The JAXJob controller image has no jax; importing
        kubeflow_tpu.parallel.dist (as generate_pod does for slice_env)
        must not pull it in. The lazy parallel/__init__ guards this."""
        import subprocess
        import sys

        code = ("import sys\n"
                "from kubeflow_tpu.parallel import dist\n"
                "dist.slice_env(2, 1, 'c:8476')\n"
                "from kubeflow_tpu.control.jaxjob.controller import "
                "JAXJobReconciler\n"
                "assert 'jax' not in sys.modules, 'jax leaked into "
                "the control-plane import graph'\n"
                "print('jax-free')\n")
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            env={"PATH": "/usr/bin:/bin", "PYTHONPATH": "."})
        assert out.returncode == 0, out.stderr
        assert "jax-free" in out.stdout
