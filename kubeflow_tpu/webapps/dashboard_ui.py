"""Central-dashboard frontend: the browser UI over the dashboard API.

The reference ships a Polymer 3 SPA (centraldashboard/public/components/:
dashboard-view.js, namespace-selector.js, resource-chart.js,
manage-users-view.js, registration-page.js) behind an Express server.
Here the same views are one dependency-free page served by the dashboard
backend itself:

- registration-page.js -> a multi-step walkthrough (welcome -> choose a
  RFC-1123-validated namespace -> confirm -> provisioning -> done)
- manage-users-view.js -> the Contributors view: list, add (email
  -validated), remove — wired to /api/workgroup/{add,remove}-contributor
- resource-chart.js -> tabbed bar charts over /api/metrics/{type}
  (tpu-chips / node-cpu / node-memory)
- dashboard-view.js activity feed -> /api/activities/{ns} with event
  -type badges and auto-refresh
- notebooks-card.js -> per-namespace notebook list with status badge and
  Connect link (/api/namespaces/{ns}/notebooks)
- main-page.js + iframe-container.js -> hash-routed app nav embedding
  the Jupyter spawner and Tensorboards in an iframe
- not-found-view.js -> unknown hash routes render a 404 view
"""

from __future__ import annotations

from kubeflow_tpu.utils.httpd import HttpReq, HttpResp

PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>kubeflow-tpu</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 0; background: #f5f6f8; }
  header { background: #1a73e8; color: #fff; padding: 10px 20px;
           display: flex; align-items: center; gap: 16px; }
  header h1 { font-size: 18px; margin: 0; flex: 1; }
  select, button, input { font-size: 14px; padding: 6px 10px;
                          border-radius: 4px; border: 1px solid #ccc; }
  button { background: #fff; cursor: pointer; }
  button.primary { background: #1a73e8; color: #fff; border-color: #1a73e8; }
  button:disabled { opacity: .5; cursor: default; }
  main { display: grid; grid-template-columns: 1fr 1fr; gap: 16px;
         padding: 20px; max-width: 1100px; margin: auto; }
  .card { background: #fff; border-radius: 8px; padding: 16px;
          box-shadow: 0 1px 3px rgba(0,0,0,.15); }
  .card h2 { margin: 0 0 10px; font-size: 15px; color: #333; }
  ul { margin: 0; padding-left: 18px; }
  li { margin: 3px 0; font-size: 13px; }
  .muted { color: #777; font-size: 12px; }
  .error { color: #c5221f; font-size: 12px; }
  svg { width: 100%; height: 140px; }
  .badge { display: inline-block; border-radius: 3px; padding: 0 6px;
           font-size: 11px; color: #fff; background: #5f6368; }
  .badge.Warning { background: #e37400; }
  .contrib { display: flex; align-items: center; gap: 8px; margin: 4px 0;
             font-size: 13px; }
  .contrib button { font-size: 11px; padding: 2px 8px; }
  .tabs { display: flex; gap: 6px; margin-bottom: 8px; }
  .tabs button.active { background: #e8f0fe; border-color: #1a73e8;
                        color: #1a73e8; }
  /* registration walkthrough (registration-page.js analogue) */
  #register { grid-column: 1 / -1; display: none; }
  .step { display: none; }
  .step.active { display: block; }
  .stepdots { margin-bottom: 12px; }
  .stepdots span { display: inline-block; width: 10px; height: 10px;
                   border-radius: 50%; background: #dadce0; margin-right: 6px; }
  .stepdots span.done { background: #1a73e8; }
  /* app nav + iframe embedding (main-page.js / iframe-container.js) */
  nav#appnav { display: flex; gap: 4px; }
  nav#appnav a { color: #fff; text-decoration: none; padding: 4px 10px;
                 border-radius: 4px; font-size: 13px; opacity: .85; }
  nav#appnav a.active { background: rgba(255,255,255,.2); opacity: 1; }
  #iframe-view iframe { width: 100%; border: 0;
                        height: calc(100vh - 60px); display: block; }
  table.nbs { width: 100%; border-collapse: collapse; font-size: 13px; }
  table.nbs td, table.nbs th { text-align: left; padding: 4px 6px;
                               border-bottom: 1px solid #eee; }
  .badge.running { background: #188038; }
  .badge.waiting { background: #e37400; }
  .badge.stopped, .badge.terminated { background: #5f6368; }
</style>
</head>
<body>
<header>
  <h1>kubeflow-tpu</h1>
  <nav id="appnav">
    <a href="#/" class="active">Dashboard</a>
    <a href="#/notebooks">Notebooks</a>
    <a href="#/tensorboards">Tensorboards</a>
  </nav>
  <span class="muted" id="user"></span>
  <select id="ns" title="namespace"></select>
</header>
<div id="iframe-view" style="display:none">
  <iframe id="app-frame" title="embedded app"></iframe>
</div>
<div id="notfound-view" style="display:none">
  <div class="card" style="margin:40px auto;max-width:400px;text-align:center">
    <h2>Page not found</h2>
    <p class="muted" id="notfound-path"></p>
    <a href="#/">Back to the dashboard</a>
  </div>
</div>
<main>
  <div class="card" id="register">
    <div class="stepdots" id="dots"></div>
    <div class="step" data-step="0">
      <h2>Welcome to Kubeflow on TPU</h2>
      <p class="muted">Your account has no workspace yet. This short
        walkthrough provisions a namespace with service accounts, RBAC
        and a TPU resource quota.</p>
      <button class="primary" id="reg-start">Start setup</button>
    </div>
    <div class="step" data-step="1">
      <h2>Name your namespace</h2>
      <input id="reg-ns" placeholder="e.g. team-ml" autocomplete="off">
      <p class="error" id="reg-err"></p>
      <p class="muted">Lowercase letters, digits and dashes; must start
        and end alphanumeric (RFC 1123).</p>
      <button id="reg-back1">Back</button>
      <button class="primary" id="reg-next" disabled>Next</button>
    </div>
    <div class="step" data-step="2">
      <h2>Confirm</h2>
      <p>Namespace <b id="reg-confirm-name"></b> will be created and owned
        by <b id="reg-confirm-user"></b>.</p>
      <button id="reg-back2">Back</button>
      <button class="primary" id="reg-create">Create workspace</button>
    </div>
    <div class="step" data-step="3">
      <h2>Provisioning…</h2>
      <p class="muted" id="reg-msg">Creating profile and waiting for the
        controller…</p>
      <button id="reg-retry" style="display:none">Back</button>
    </div>
    <div class="step" data-step="4">
      <h2>All set 🎉</h2>
      <p class="muted">Your workspace is ready.</p>
      <button class="primary" onclick="location.reload()">Open dashboard</button>
    </div>
  </div>
  <div class="card">
    <h2>Notebooks</h2>
    <table class="nbs"><tbody id="notebooks">
      <tr><td class="muted">select a namespace</td></tr>
    </tbody></table>
  </div>
  <div class="card">
    <h2>Training jobs</h2>
    <table class="nbs"><tbody id="jaxjobs">
      <tr><td class="muted">select a namespace</td></tr>
    </tbody></table>
  </div>
  <div class="card">
    <h2>Activity</h2>
    <ul id="activities"><li class="muted">select a namespace</li></ul>
  </div>
  <div class="card">
    <h2>Contributors</h2>
    <div id="contributors"></div>
    <div class="contrib">
      <input id="contrib-email" placeholder="teammate@example.com">
      <button class="primary" id="contrib-add">Add</button>
    </div>
    <p class="error" id="contrib-err"></p>
    <p class="muted">Contributors get the kubeflow-edit role via the
      access-management (KFAM) API.</p>
  </div>
  <div class="card">
    <h2>Workgroup settings</h2>
    <div id="admin-ns" style="display:none">
      <h3>All namespaces <span class="muted">(cluster admin)</span></h3>
      <ul id="all-ns"></ul>
    </div>
    <div class="danger">
      <h3>Danger zone</h3>
      <p class="muted">Deletes every workgroup you own; namespaces and
        their workloads are garbage-collected by the profile controller.</p>
      <button id="nuke-btn">Delete my workgroups…</button>
      <span id="nuke-confirm" style="display:none">
        Really delete everything?
        <button id="nuke-yes" class="warn">Yes, delete</button>
        <button id="nuke-no">Cancel</button>
      </span>
      <p class="muted" id="nuke-msg"></p>
    </div>
  </div>
  <div class="card">
    <h2>Cluster resources</h2>
    <div class="tabs" id="metric-tabs">
      <button data-m="tpu-chips" class="active">TPU chips</button>
      <button data-m="node-cpu">CPU</button>
      <button data-m="node-memory">Memory</button>
    </div>
    <svg id="chart" viewBox="0 0 300 110" preserveAspectRatio="none"></svg>
    <p class="muted" id="chart-note"></p>
  </div>
  <div class="card">
    <h2>Served models</h2>
    <table class="nbs"><tbody id="served">
      <tr><td class="muted">loading…</td></tr>
    </tbody></table>
  </div>
  <div class="card">
    <h2>Platform</h2>
    <ul id="envinfo"></ul>
  </div>
</main>
<script>
const $ = (id) => document.getElementById(id);
const api = (p, opt) => fetch(p, opt).then(async r => {
  if (!r.ok) throw new Error((await r.json().catch(() => ({}))).error || r.status);
  return r.json();
});
const NS_RGX = /^[a-z0-9]([-a-z0-9]*[a-z0-9])?$/;
let currentNs = null;

/* ---- registration walkthrough ---- */
let regStep = 0;
function showStep(i) {
  regStep = i;
  document.querySelectorAll('#register .step').forEach(s =>
    s.classList.toggle('active', Number(s.dataset.step) === i));
  $('dots').innerHTML = [0,1,2,3,4].map(j =>
    `<span class="${j <= i ? 'done' : ''}"></span>`).join('');
}
$('reg-start').addEventListener('click', () => showStep(1));
$('reg-back1').addEventListener('click', () => showStep(0));
$('reg-back2').addEventListener('click', () => showStep(1));
$('reg-ns').addEventListener('input', () => {
  const v = $('reg-ns').value.trim();
  const ok = NS_RGX.test(v) && v.length <= 63;
  $('reg-err').textContent = v && !ok ? 'invalid namespace name' : '';
  $('reg-next').disabled = !ok;
});
$('reg-next').addEventListener('click', () => {
  $('reg-confirm-name').textContent = $('reg-ns').value.trim();
  $('reg-confirm-user').textContent = $('user').textContent;
  showStep(2);
});
$('reg-retry').addEventListener('click', () => {
  $('reg-retry').style.display = 'none';
  $('reg-msg').textContent = 'Creating profile and waiting for the controller…';
  showStep(1);
});
$('reg-create').addEventListener('click', async () => {
  showStep(3);
  try {
    await api('/api/workgroup/create', {
      method: 'POST', headers: {'Content-Type': 'application/json'},
      body: JSON.stringify({namespace: $('reg-ns').value.trim()}),
    });
    showStep(4);
  } catch (e) {
    // dead-end guard: surface the error and offer a way back to step 1
    $('reg-msg').textContent = 'failed: ' + e.message;
    $('reg-retry').style.display = '';
  }
});

/* ---- env + namespace selector ---- */
async function loadEnv() {
  const info = await api('/api/workgroup/env-info');
  $('user').textContent = info.user || '';
  const ul = $('envinfo');
  ul.innerHTML = '';
  for (const [k, v] of Object.entries(info.platform || {})) {
    const li = document.createElement('li');
    li.textContent = k + ': ' + v;
    ul.appendChild(li);
  }
  const sel = $('ns');
  sel.innerHTML = '';
  for (const ns of info.namespaces || []) {
    const o = document.createElement('option');
    o.value = o.textContent = typeof ns === 'string' ? ns : ns.namespace;
    sel.appendChild(o);
  }
  if (!(info.namespaces || []).length) {
    $('register').style.display = 'block';
    showStep(0);
  } else {
    currentNs = sel.value;
    await loadNamespace(currentNs);
  }
}

/* ---- activity feed ---- */
async function loadActivities(ns) {
  const acts = await api('/api/activities/' + ns).catch(() => ({events: []}));
  const ul = $('activities');
  ul.innerHTML = '';
  for (const a of (acts.events || []).slice(0, 12)) {
    // DOM-built rows: event fields are namespace-contributor data and
    // must never be interpolated into HTML (stored-XSS vector)
    const li = document.createElement('li');
    const badge = document.createElement('span');
    badge.className = 'badge' + (a.type === 'Warning' ? ' Warning' : '');
    badge.textContent = a.reason || 'Event';
    const ts = document.createElement('span');
    ts.className = 'muted';
    ts.textContent = ' ' + (a.lastTimestamp || '');
    li.appendChild(badge);
    li.appendChild(document.createTextNode(' ' + (a.message || '')));
    li.appendChild(ts);
    ul.appendChild(li);
  }
  if (!ul.children.length) ul.innerHTML = '<li class="muted">no events</li>';
}

/* ---- contributors (manage-users-view.js analogue) ---- */
function renderContributors(list) {
  const box = $('contributors');
  box.innerHTML = '';
  for (const c of list) {
    const email = typeof c === 'string' ? c : c.user;
    const row = document.createElement('div');
    row.className = 'contrib';
    const rm = document.createElement('button');
    rm.textContent = 'Remove';
    rm.addEventListener('click', async () => {
      $('contrib-err').textContent = '';
      try {
        const out = await api('/api/workgroup/remove-contributor/' + currentNs, {
          method: 'DELETE', headers: {'Content-Type': 'application/json'},
          body: JSON.stringify({contributor: email}),
        });
        renderContributors(out.contributors || []);
      } catch (e) { $('contrib-err').textContent = e.message; }
    });
    const label = document.createElement('span');
    label.textContent = email;
    row.appendChild(label);
    row.appendChild(rm);
    box.appendChild(row);
  }
  if (!list.length) box.innerHTML = '<p class="muted">owner only</p>';
}
async function loadContributors(ns) {
  const out = await api('/api/workgroup/get-contributors/' + ns)
    .catch(() => ({contributors: []}));
  renderContributors(out.contributors || []);
}
/* ---- workgroup settings: admin all-namespaces + nuke-self ---- */
async function loadAdminNamespaces() {
  // 403 for non-admins: the card stays hidden (namespace-selector's
  // all-namespaces view is an admin affordance in the reference)
  try {
    const out = await api('/api/workgroup/get-all-namespaces');
    const ul = $('all-ns');
    ul.innerHTML = '';
    for (const ns of out.namespaces || []) {
      const li = document.createElement('li');
      li.textContent = ns;
      ul.appendChild(li);
    }
    $('admin-ns').style.display = 'block';
  } catch (e) { /* not an admin */ }
}
$('nuke-btn').addEventListener('click', () => {
  $('nuke-confirm').style.display = '';
  $('nuke-btn').style.display = 'none';
});
$('nuke-no').addEventListener('click', () => {
  $('nuke-confirm').style.display = 'none';
  $('nuke-btn').style.display = '';
});
$('nuke-yes').addEventListener('click', async () => {
  try {
    const out = await api('/api/workgroup/nuke-self', {method: 'DELETE'});
    $('nuke-msg').textContent = out.message || 'deleted';
    $('nuke-confirm').style.display = 'none';
    $('nuke-btn').style.display = '';
  } catch (e) {
    $('nuke-msg').textContent = 'failed: ' + e.message;
    return;
  }
  // deletion succeeded: a refresh failure must not overwrite that fact
  await loadEnv().catch(() => {});
});

$('contrib-add').addEventListener('click', async () => {
  $('contrib-err').textContent = '';
  try {
    const out = await api('/api/workgroup/add-contributor/' + currentNs, {
      method: 'POST', headers: {'Content-Type': 'application/json'},
      body: JSON.stringify({contributor: $('contrib-email').value.trim()}),
    });
    $('contrib-email').value = '';
    renderContributors(out.contributors || []);
  } catch (e) { $('contrib-err').textContent = e.message; }
});

/* ---- notebooks card (notebooks-card.js analogue) ---- */
async function loadNotebooks(ns) {
  const out = await api('/api/namespaces/' + ns + '/notebooks')
    .catch(() => ({notebooks: []}));
  const tb = $('notebooks');
  tb.innerHTML = '';
  for (const nb of out.notebooks || []) {
    // DOM-built rows: notebook names are user data, never HTML
    const tr = document.createElement('tr');
    const name = document.createElement('td');
    name.textContent = nb.name;
    const status = document.createElement('td');
    const badge = document.createElement('span');
    badge.className = 'badge ' + (nb.status || 'unknown');
    badge.textContent = nb.status || 'unknown';
    status.appendChild(badge);
    const chips = document.createElement('td');
    chips.textContent = nb.tpu_chips ? nb.tpu_chips + ' TPU' : '';
    const link = document.createElement('td');
    const a = document.createElement('a');
    a.href = nb.connect;
    a.textContent = 'Connect';
    link.appendChild(a);
    tr.append(name, status, chips, link);
    tb.appendChild(tr);
  }
  if (!tb.children.length)
    tb.innerHTML = '<tr><td class="muted">no notebooks — create one under ' +
      'the Notebooks tab</td></tr>';
}

/* ---- training jobs card (JAXJob status at a glance) ---- */
async function loadJaxjobs(ns) {
  const out = await api('/api/namespaces/' + ns + '/jaxjobs')
    .catch(() => ({jaxjobs: []}));
  const tb = $('jaxjobs');
  tb.innerHTML = '';
  for (const j of out.jaxjobs || []) {
    const tr = document.createElement('tr');
    const name = document.createElement('td');
    name.textContent = j.name;
    const phase = document.createElement('td');
    const badge = document.createElement('span');
    badge.className = 'badge ' + (j.phase === 'succeeded' ? 'running' :
                                  j.phase === 'failed' ? 'Warning' : j.phase);
    badge.textContent = j.phase;
    phase.appendChild(badge);
    const shape = document.createElement('td');
    shape.className = 'muted';
    shape.textContent = j.replicas + '×' +
      (j.chips_per_worker ? j.chips_per_worker + ' chips' : 'cpu');
    const restarts = document.createElement('td');
    restarts.className = 'muted';
    restarts.textContent = (j.restarts ? j.restarts + ' restarts ' : '') +
      (j.preemptions ? j.preemptions + ' preemptions' : '');
    tr.append(name, phase, shape, restarts);
    tb.appendChild(tr);
  }
  if (!tb.children.length)
    tb.innerHTML = '<tr><td class="muted">no training jobs</td></tr>';
}

/* ---- served models card ---- */
async function loadServing() {
  const out = await api('/api/serving/models')
    .catch((e) => ({models: [], error: String(e && e.message || e)}));
  const tb = $('served');
  tb.innerHTML = '';
  for (const m of out.models || []) {
    const tr = document.createElement('tr');
    const name = document.createElement('td');
    name.textContent = m.name;
    const method = document.createElement('td');
    const badge = document.createElement('span');
    badge.className = 'badge running';
    badge.textContent = m.method;
    method.appendChild(badge);
    const vers = document.createElement('td');
    vers.className = 'muted';
    vers.textContent = 'v' + (m.versions || []).join(', v');
    tr.append(name, method, vers);
    tb.appendChild(tr);
  }
  if (!tb.children.length)
    tb.innerHTML = '<tr><td class="muted">' +
      (out.error ? 'serving unreachable' : 'no models') + '</td></tr>';
}

async function loadNamespace(ns) {
  currentNs = ns;
  route();  // re-point an embedded app iframe at the selected namespace
  await Promise.all([loadActivities(ns), loadContributors(ns),
                     loadNotebooks(ns), loadJaxjobs(ns)]);
}

/* ---- hash routing: main-page.js + iframe-container.js + not-found ---- */
const APP_ROUTES = {
  '#/notebooks': '/jupyter/',
  '#/tensorboards': '/tensorboards/',
};
function route() {
  const h = location.hash || '#/';
  const main = document.querySelector('main');
  const known = h === '#/' || h in APP_ROUTES;
  main.style.display = h === '#/' ? '' : 'none';
  $('iframe-view').style.display = h in APP_ROUTES ? '' : 'none';
  $('notfound-view').style.display = known ? 'none' : '';
  if (h in APP_ROUTES) {
    const src = APP_ROUTES[h] + '?ns=' + encodeURIComponent(currentNs || '');
    if ($('app-frame').getAttribute('src') !== src)
      $('app-frame').setAttribute('src', src);
  }
  if (!known) $('notfound-path').textContent = h;
  document.querySelectorAll('#appnav a').forEach(a =>
    a.classList.toggle('active', a.getAttribute('href') === h));
}
window.addEventListener('hashchange', route);

/* ---- resource charts (resource-chart.js analogue) ---- */
let metric = 'tpu-chips';
const QTY_SUFFIX = {Ki: 2**10, Mi: 2**20, Gi: 2**30, Ti: 2**40,
                    k: 1e3, M: 1e6, G: 1e9, T: 1e12, m: 1e-3};
function parseQty(v) {
  // Kubernetes quantity strings: "16", "16Gi", "3977500Ki", "500m"
  if (typeof v === 'number') return v;
  const m = /^([0-9.]+)\\s*([A-Za-z]*)$/.exec(String(v || ''));
  if (!m) return 0;
  return Number(m[1]) * (QTY_SUFFIX[m[2]] || 1);
}
async function loadChart() {
  try {
    const m = await api('/api/metrics/' + metric);
    const rows = (m.values || []).map(v => ({
      label: v.node || '',
      value: parseQty(v.chips ?? v.capacity ?? v.value ?? 0),
      extra: v.accelerator || '',
    }));
    const svg = $('chart');
    if (!rows.length) {
      svg.innerHTML = '';
      $('chart-note').textContent = 'no nodes report this resource';
      return;
    }
    const max = Math.max(...rows.map(r => r.value), 1);
    const bw = 300 / rows.length;
    svg.innerHTML = rows.map((r, i) => {
      const h = r.value / max * 90;
      return `<rect x="${(i * bw + 2).toFixed(1)}" y="${(100 - h).toFixed(1)}"` +
        ` width="${(bw - 4).toFixed(1)}" height="${h.toFixed(1)}"` +
        ` fill="#1a73e8"><title>${r.label}: ${r.value}</title></rect>`;
    }).join('');
    $('chart-note').textContent = rows.map(r =>
      r.label + '=' + r.value + (r.extra ? ' (' + r.extra + ')' : '')).join('  ');
  } catch (e) { $('chart-note').textContent = 'metrics unavailable'; }
}
$('metric-tabs').addEventListener('click', (e) => {
  if (e.target.dataset.m) {
    metric = e.target.dataset.m;
    document.querySelectorAll('#metric-tabs button').forEach(b =>
      b.classList.toggle('active', b === e.target));
    loadChart();
  }
});

$('ns').addEventListener('change', (e) => loadNamespace(e.target.value));
loadEnv().catch(e => { $('user').textContent = 'not signed in'; });
loadAdminNamespaces();
loadChart();
loadServing();
route();
setInterval(() => {
  if (currentNs && (location.hash || '#/') === '#/') {
    loadActivities(currentNs);
    loadNotebooks(currentNs);
    loadJaxjobs(currentNs);
  }
}, 15000);
</script>
</body>
</html>
"""


def page(req: HttpReq) -> HttpResp:
    return HttpResp(200, PAGE.encode(), "text/html")


def add_ui_routes(router) -> None:
    router.route("GET", "/", page)
    router.route("GET", "/dashboard", page)
