"""Entry: python -m kubeflow_tpu.webapps.dashboard_main."""
import argparse

from kubeflow_tpu.control.k8s.rest import RestClient
from kubeflow_tpu.webapps.dashboard import Dashboard

p = argparse.ArgumentParser("dashboard")
p.add_argument("--port", type=int, default=8082)
p.add_argument("--apiserver", default="")
args = p.parse_args()
svc = Dashboard(RestClient(base_url=args.apiserver or None)).serve(port=args.port)
print(f"dashboard on :{svc.port}")
svc.serve_forever()
