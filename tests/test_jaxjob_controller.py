"""JAXJob controller semantics against the fake cluster.

The behaviors the reference delegated to the external tf-operator +
launcher.py, specified by their consumers (SURVEY.md §3.2): gang pod
creation, env-var topology injection, condition lifecycle matching the
katib polling contract, and gang restart (which the reference's
per-replica restartPolicy never provided).
"""

import pytest

from kubeflow_tpu.control.jaxjob import types as T
from kubeflow_tpu.control.jaxjob.controller import build_controller, worker_name
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
from kubeflow_tpu.control.runtime import seed_controller


@pytest.fixture()
def world():
    cluster = FakeCluster()
    ctl = seed_controller(build_controller(cluster, record_events=True))
    kubelet = FakeKubelet(cluster)
    return cluster, ctl, kubelet


def drain(ctl):
    # a few advance rounds so requeue_after paths fire without sleeping
    for _ in range(6):
        ctl.run_until_idle(advance_delayed=True)


def make_job(cluster, **kw):
    # slice geometry must be consistent (validate() enforces
    # replicas x chipsPerWorker == topology chips): 4 chips/worker,
    # topology sized to the gang
    replicas = kw.pop("replicas", 4)
    default_topo = {1: "2x2", 2: "2x4", 3: "3x4", 4: "4x4"}[replicas]
    job = T.new_jaxjob("train", replicas=replicas,
                       accelerator=kw.pop("accelerator", "tpu-v5-lite-podslice"),
                       topology=kw.pop("topology", default_topo), **kw)
    return cluster.create(job)


class TestGangCreation:
    def test_creates_service_and_full_gang(self, world):
        cluster, ctl, _ = world
        make_job(cluster, replicas=4)
        drain(ctl)
        svc = cluster.get("v1", "Service", "train", "default")
        assert svc["spec"]["clusterIP"] == "None"
        pods = cluster.list("v1", "Pod", namespace="default")
        assert len(pods) == 4
        names = {ob.meta(p)["name"] for p in pods}
        assert names == {worker_name("train", i) for i in range(4)}

    def test_env_injection_contract(self, world):
        cluster, ctl, _ = world
        make_job(cluster, replicas=2)
        drain(ctl)
        pod1 = cluster.get("v1", "Pod", worker_name("train", 1), "default")
        env = {e["name"]: e["value"] for e in pod1["spec"]["containers"][0]["env"]}
        assert env[T.ENV_COORD] == "train-worker-0.train.default.svc:8476"
        assert env[T.ENV_NPROC] == "2"
        assert env[T.ENV_PID] == "1"
        assert env[T.ENV_NAME] == "train"
        # stable DNS wiring
        assert pod1["spec"]["hostname"] == "train-worker-1"
        assert pod1["spec"]["subdomain"] == "train"

    def test_tpu_resources_and_node_selectors(self, world):
        cluster, ctl, _ = world
        make_job(cluster, replicas=1)
        drain(ctl)
        pod = cluster.get("v1", "Pod", worker_name("train", 0), "default")
        limits = pod["spec"]["containers"][0]["resources"]["limits"]
        assert limits[T.RESOURCE_TPU] == 4
        sel = pod["spec"]["nodeSelector"]
        assert sel[T.NODESELECTOR_ACCEL] == "tpu-v5-lite-podslice"
        assert sel[T.NODESELECTOR_TOPOLOGY] == "2x2"

    def test_no_tpu_block_means_no_tpu_resources(self, world):
        cluster, ctl, _ = world
        job = T.new_jaxjob("cpu-job", replicas=1)
        cluster.create(job)
        drain(ctl)
        pod = cluster.get("v1", "Pod", worker_name("cpu-job", 0), "default")
        assert "resources" not in pod["spec"]["containers"][0] or (
            T.RESOURCE_TPU
            not in pod["spec"]["containers"][0].get("resources", {}).get("limits", {})
        )

    def test_validation_failure_sets_failed_condition(self, world):
        cluster, ctl, _ = world
        bad = T.new_jaxjob("bad", replicas=0)
        cluster.create(bad)
        drain(ctl)
        got = cluster.get(T.API_VERSION, T.KIND, "bad", "default")
        c = ob.cond_get(got, T.COND_FAILED)
        assert c and c["status"] == "True" and c["reason"] == "ValidationFailed"
        assert not cluster.list("v1", "Pod", namespace="default")


class TestLifecycle:
    def test_conditions_follow_pod_phases(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_CREATED)
        assert not ob.cond_is_true(job, T.COND_RUNNING)

        kubelet.step()  # Pending -> Running
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_RUNNING)
        assert job["status"]["replicaStatuses"]["active"] == 2
        assert "startTime" in job["status"]

        kubelet.succeed(worker_name("train", 0))
        kubelet.succeed(worker_name("train", 1))
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_SUCCEEDED)
        assert not ob.cond_is_true(job, T.COND_RUNNING)  # katib contract: flips off
        assert "completionTime" in job["status"]

    def test_events_recorded(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=1)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        reasons = {e["reason"] for e in cluster.list("v1", "Event", namespace="default")}
        assert "JAXJobCreated" in reasons
        assert "JAXJobRunning" in reasons

    def test_deleting_job_cascades_to_pods(self, world):
        cluster, ctl, _ = world
        make_job(cluster, replicas=2)
        drain(ctl)
        assert len(cluster.list("v1", "Pod", namespace="default")) == 2
        cluster.delete(T.API_VERSION, T.KIND, "train", "default")
        assert cluster.list("v1", "Pod", namespace="default") == []
        assert cluster.get_or_none("v1", "Service", "train", "default") is None


class TestGangRestart:
    def test_worker_failure_restarts_whole_gang(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=3)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        kubelet.fail(worker_name("train", 1))
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"]["restarts"] == 1
        # the whole gang was recreated: all pods fresh (Pending again)
        pods = cluster.list("v1", "Pod", namespace="default")
        assert len(pods) == 3
        assert all((p.get("status") or {}).get("phase", "Pending") == "Pending"
                   for p in pods)
        c = ob.cond_get(job, T.COND_RESTARTING)
        assert c and c["status"] == "True"

    def test_restart_never_policy_fails_immediately(self, world):
        cluster, ctl, kubelet = world
        job = T.new_jaxjob("train", replicas=2, restart_policy=T.RESTART_NEVER)
        cluster.create(job)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        kubelet.fail(worker_name("train", 0))
        drain(ctl)
        got = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(got, T.COND_FAILED)
        assert got["status"].get("restarts", 0) == 0

    def test_restarts_exhaust_to_failed(self, world):
        cluster, ctl, kubelet = world
        job = T.new_jaxjob("train", replicas=1, max_restarts=2)
        cluster.create(job)
        for i in range(3):
            drain(ctl)
            kubelet.step()
            drain(ctl)
            kubelet.fail(worker_name("train", 0))
            drain(ctl)
        got = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(got, T.COND_FAILED)
        assert got["status"]["restarts"] == 2

    def test_deleted_worker_triggers_gang_restart(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=3)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        cluster.delete("v1", "Pod", worker_name("train", 2), "default")
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"]["restarts"] >= 1
        assert len(cluster.list("v1", "Pod", namespace="default")) == 3


class TestIdempotency:
    def test_reconcile_is_idempotent(self, world):
        """The kfctl_second_apply.py analogue: re-reconciling a settled job
        changes nothing."""
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        pods_before = {
            ob.meta(p)["name"]: ob.meta(p)["resourceVersion"]
            for p in cluster.list("v1", "Pod", namespace="default")
        }
        from kubeflow_tpu.control.runtime import Request

        for _ in range(3):
            ctl.reconciler.reconcile(cluster, Request("default", "train"))
        pods_after = {
            ob.meta(p)["name"]: ob.meta(p)["resourceVersion"]
            for p in cluster.list("v1", "Pod", namespace="default")
        }
        assert pods_before == pods_after


class TestPreemptionAwareRestart:
    """EX_TEMPFAIL (75) = graceful preemption: gang restarts without
    consuming the maxRestarts crash budget (launcher contract,
    runtime/preemption.py)."""

    def test_preemption_exit_does_not_burn_restart_budget(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2, max_restarts=1)
        # preempt the gang more times than maxRestarts allows for crashes
        for round_ in range(3):
            drain(ctl)
            kubelet.step()
            drain(ctl)
            for i in range(2):
                kubelet.fail(worker_name("train", i),
                             exit_code=T.EXIT_PREEMPTED,
                             message="preempted")
            drain(ctl)
            job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
            assert not ob.cond_is_true(job, T.COND_FAILED), round_
        assert job["status"]["preemptions"] == 3
        assert job["status"].get("restarts", 0) == 0
        # the gang keeps getting recreated
        drain(ctl)
        kubelet.step()
        drain(ctl)
        pods = cluster.list("v1", "Pod", namespace="default")
        assert len(pods) == 2

    def test_mixed_crash_and_preemption_counts_as_crash(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2, max_restarts=3)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        kubelet.fail(worker_name("train", 0), exit_code=T.EXIT_PREEMPTED)
        kubelet.fail(worker_name("train", 1), exit_code=1)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"]["restarts"] == 1
        assert job["status"].get("preemptions", 0) == 0


class TestSliceHealth:
    """A NotReady or maintenance-tainted node under a running gang
    triggers a proactive gang restart (counted as preemption)."""

    def _schedule_onto_node(self, cluster, node_name):
        node = ob.new_object("v1", "Node", node_name)
        node["status"] = {"conditions": [{"type": "Ready", "status": "True"}]}
        cluster.create(node)
        for p in cluster.list("v1", "Pod", namespace="default"):
            p["spec"]["nodeName"] = node_name
            cluster.update(p)

    def test_node_not_ready_restarts_gang(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2)
        drain(ctl)
        self._schedule_onto_node(cluster, "tpu-node-0")
        kubelet.step()
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_RUNNING)
        # the node goes NotReady (TPU maintenance drain)
        node = cluster.get("v1", "Node", "tpu-node-0")
        node["status"]["conditions"] = [{"type": "Ready", "status": "False"}]
        cluster.update_status(node)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"]["preemptions"] == 1
        assert ob.cond_is_true(job, T.COND_RESTARTING)

    def test_maintenance_taint_restarts_gang(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2)
        drain(ctl)
        self._schedule_onto_node(cluster, "tpu-node-1")
        kubelet.step()
        drain(ctl)
        node = cluster.get("v1", "Node", "tpu-node-1")
        node["spec"] = {"taints": [
            {"key": T.TAINT_IMPENDING_TERMINATION, "effect": "NoSchedule"}]}
        cluster.update(node)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"]["preemptions"] == 1

    def test_healthy_node_no_restart(self, world):
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2)
        drain(ctl)
        self._schedule_onto_node(cluster, "tpu-node-2")
        kubelet.step()
        drain(ctl)
        node = cluster.get("v1", "Node", "tpu-node-2")
        cluster.update(node)  # touch: node event with nothing wrong
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"].get("preemptions", 0) == 0
        assert ob.cond_is_true(job, T.COND_RUNNING)


class TestSliceHealthOrdering:
    def test_succeeded_gang_on_draining_node_stays_succeeded(self, world):
        """Node drain right after the workload completes must not re-run
        the finished job (success branch precedes the health check)."""
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2)
        drain(ctl)
        node = ob.new_object("v1", "Node", "tpu-node-9")
        node["status"] = {"conditions": [{"type": "Ready", "status": "True"}]}
        cluster.create(node)
        for p in cluster.list("v1", "Pod", namespace="default"):
            p["spec"]["nodeName"] = "tpu-node-9"
            cluster.update(p)
        kubelet.step()
        drain(ctl)
        for i in range(2):
            kubelet.succeed(worker_name("train", i))
        # node drains in the same instant the workers finish
        node = cluster.get("v1", "Node", "tpu-node-9")
        node["status"]["conditions"] = [{"type": "Ready", "status": "False"}]
        cluster.update_status(node)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_SUCCEEDED)
        assert job["status"].get("preemptions", 0) == 0


class TestPreemptionClassification:
    def test_eviction_without_container_status_is_preemption(self, world):
        """Kubelet evictions (reason=Evicted, no containerStatuses) are
        node preemptions, not crashes — no maxRestarts burn."""
        cluster, ctl, kubelet = world
        make_job(cluster, replicas=2, max_restarts=1)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        for i in range(2):
            pod = cluster.get("v1", "Pod", worker_name("train", i), "default")
            pod.setdefault("status", {}).update(
                {"phase": "Failed", "reason": "Evicted",
                 "containerStatuses": []})
            cluster.update_status(pod)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"]["preemptions"] == 1
        assert job["status"].get("restarts", 0) == 0

    def test_sidecar_exit_code_does_not_mask_main(self, world):
        """Main container crash (exit 1) with a sidecar that terminated
        75 must classify as crash: main container's code wins."""
        cluster, ctl, kubelet = world
        job = T.new_jaxjob("train", replicas=1)
        job["spec"]["template"] = {"spec": {"containers": [
            {"name": "main", "image": "jaxrt"},
            {"name": "sidecar", "image": "logger"}]}}
        cluster.create(job)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        pod = cluster.get("v1", "Pod", worker_name("train", 0), "default")
        pod.setdefault("status", {}).update({
            "phase": "Failed",
            "containerStatuses": [
                {"name": "sidecar",
                 "state": {"terminated": {"exitCode": T.EXIT_PREEMPTED}}},
                {"name": "main",
                 "state": {"terminated": {"exitCode": 1}}},
            ]})
        cluster.update_status(pod)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"].get("restarts", 0) == 1
        assert job["status"].get("preemptions", 0) == 0

    def test_sidecar_75_with_unterminated_main_is_crash(self, world):
        """Main container has no terminated record (e.g. OOMKilled with
        status not yet populated) while a sidecar exited 75: must NOT
        classify as graceful preemption — the restart budget applies."""
        cluster, ctl, kubelet = world
        job = T.new_jaxjob("train", replicas=1)
        job["spec"]["template"] = {"spec": {"containers": [
            {"name": "main", "image": "jaxrt"},
            {"name": "sidecar", "image": "logger"}]}}
        cluster.create(job)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        pod = cluster.get("v1", "Pod", worker_name("train", 0), "default")
        pod.setdefault("status", {}).update({
            "phase": "Failed",
            "containerStatuses": [
                {"name": "sidecar",
                 "state": {"terminated": {"exitCode": T.EXIT_PREEMPTED}}},
                {"name": "main", "state": {"waiting": {}}},
            ]})
        cluster.update_status(pod)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert job["status"].get("restarts", 0) == 1
        assert job["status"].get("preemptions", 0) == 0

    def test_preemption_budget_backstop(self, world):
        """An always-preempting gang eventually fails instead of
        restarting forever."""
        cluster, ctl, kubelet = world
        job = make_job(cluster, replicas=1, max_restarts=1)
        job["spec"]["maxPreemptions"] = 2
        cluster.update(job)
        for _ in range(4):
            drain(ctl)
            kubelet.step()
            drain(ctl)
            pods = cluster.list("v1", "Pod", namespace="default")
            if not pods:
                break
            for p in pods:
                if (p.get("status") or {}).get("phase") == "Running":
                    kubelet.fail(ob.meta(p)["name"],
                                 exit_code=T.EXIT_PREEMPTED)
            drain(ctl)
            job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
            if ob.cond_is_true(job, T.COND_FAILED):
                break
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_FAILED)
        assert job["status"]["preemptions"] == 2


class TestMultislice:
    """spec.sliceCount: one gang, one jax.distributed world, the dcn mesh
    axis across slices (SURVEY §2.5 'DCN across slices')."""

    def test_gang_spans_slices_with_env_and_labels(self, world):
        cluster, ctl, kubelet = world
        job = T.new_jaxjob("ms", replicas=2, slice_count=2,
                           accelerator="tpu-v5-lite-podslice",
                           topology="2x4", chips_per_worker=4)
        cluster.create(job)
        drain(ctl)
        pods = sorted(cluster.list("v1", "Pod", namespace="default"),
                      key=lambda p: ob.meta(p)["name"])
        assert len(pods) == 4  # replicas(2) x sliceCount(2), one gang
        for g, pod in enumerate(pods):
            env = {e["name"]: e["value"]
                   for e in pod["spec"]["containers"][0]["env"]}
            assert env[T.ENV_NPROC] == "4"       # world spans both slices
            assert env[T.ENV_PID] == str(g)
            assert env[T.ENV_NUM_SLICES] == "2"
            assert env[T.ENV_SLICE_ID] == str(g // 2)  # contiguous ranks
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["MEGASCALE_SLICE_ID"] == str(g // 2)
            assert env["MEGASCALE_COORDINATOR_ADDRESS"].startswith(
                "ms-worker-0.ms.default.svc:")
            assert ob.labels_of(pod)[T.LABEL_SLICE_INDEX] == str(g // 2)

    def test_slice_worker_failure_restarts_whole_multislice_gang(self, world):
        cluster, ctl, kubelet = world
        job = T.new_jaxjob("ms", replicas=2, slice_count=2,
                           accelerator="tpu-v5-lite-podslice",
                           topology="2x4", chips_per_worker=4)
        job["spec"]["maxRestarts"] = 3
        cluster.create(job)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        # kill one worker in slice 1 -> ALL FOUR pods restart (gang)
        kubelet.fail("ms-worker-3", exit_code=1)
        drain(ctl)
        kubelet.step()
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "ms", "default")
        assert job["status"].get("restarts") == 1
        pods = cluster.list("v1", "Pod", namespace="default")
        assert len(pods) == 4

    def test_per_slice_topology_validation_unchanged(self, world):
        """replicas is PER SLICE: 2 workers x 4 chips tile a 2x4 slice
        regardless of sliceCount."""
        errs = T.validate(T.new_jaxjob(
            "ms", replicas=2, slice_count=4,
            accelerator="tpu-v5-lite-podslice", topology="2x4",
            chips_per_worker=4))
        assert errs == []
        errs = T.validate(T.new_jaxjob(
            "ms", replicas=2, slice_count=2,
            accelerator="tpu-v5-lite-podslice", topology="4x4",
            chips_per_worker=4))
        assert errs  # 2 workers x 4 chips != the 16-chip 4x4 slice

    def test_bad_slice_count_rejected(self, world):
        cluster, ctl, _ = world
        job = T.new_jaxjob("ms", replicas=1)
        job["spec"]["sliceCount"] = 0
        cluster.create(job)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "ms", "default")
        assert ob.cond_is_true(job, T.COND_FAILED)


class TestTopologyValidation:
    def test_inconsistent_geometry_fails_fast(self, world):
        cluster, ctl, _ = world
        job = T.new_jaxjob("train", replicas=4,
                           accelerator="tpu-v5-lite-podslice",
                           topology="2x4", chips_per_worker=4)  # 16 != 8
        cluster.create(job)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_FAILED)
        msg = ob.cond_get(job, T.COND_FAILED)["message"]
        assert "cannot tile the slice" in msg

    def test_malformed_topology_string(self, world):
        cluster, ctl, _ = world
        job = T.new_jaxjob("train", replicas=2,
                           accelerator="tpu-v5-lite-podslice",
                           topology="2xbad", chips_per_worker=4)
        cluster.create(job)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert ob.cond_is_true(job, T.COND_FAILED)

    def test_3d_topology_ok(self, world):
        cluster, ctl, kubelet = world
        job = T.new_jaxjob("train", replicas=4,
                           accelerator="tpu-v4-podslice",
                           topology="2x2x4", chips_per_worker=4)  # 16 == 16
        cluster.create(job)
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert not ob.cond_is_true(job, T.COND_FAILED)

    def test_no_tpu_block_skips_check(self, world):
        cluster, ctl, _ = world
        cluster.create(T.new_jaxjob("train", replicas=3))
        drain(ctl)
        job = cluster.get(T.API_VERSION, T.KIND, "train", "default")
        assert not ob.cond_is_true(job, T.COND_FAILED)


def test_node_mapper_indexes_by_node_not_full_fanout(world):
    """A Node event must enqueue exactly the jobs with gang pods ON that
    node (fieldSelector spec.nodeName), not every job in the cluster."""
    from kubeflow_tpu.control.jaxjob.controller import _node_mapper

    cluster, ctl, kubelet = world
    make_job(cluster, replicas=1)          # "train"
    job2 = T.new_jaxjob("other", replicas=1,
                        accelerator="tpu-v5-lite-podslice",
                        topology="2x2", chips_per_worker=4)
    cluster.create(job2)
    drain(ctl)
    # bind train's pod to node-a, other's to node-b
    for jobname, node in [("train", "node-a"), ("other", "node-b")]:
        pod = cluster.get("v1", "Pod", worker_name(jobname, 0), "default")
        pod["spec"]["nodeName"] = node
        cluster.update(pod)
    mapper = _node_mapper(cluster)
    reqs = mapper(ob.new_object("v1", "Node", "node-a"))
    assert [(r.namespace, r.name) for r in reqs] == [("default", "train")]
    assert mapper(ob.new_object("v1", "Node", "node-c")) == []
