"""Autoregressive generation with KV-cache decode.

The reference's serving story is TF-Serving REST over exported models;
for LM families the TPU build needs actual decoding. This is the
jit-compiled loop: prefill writes the prompt into each layer's KV cache
in GEMM-shaped position chunks (PREFILL_CHUNK wide; cache-correct by
construction), then the sampling scan feeds each new token back in.
Every decode step is the model's `decode_index` path — [B, 1] tokens
against the cached K/V, so cost per token is O(L) attention reads
instead of O(L^2) recompute.

Sampling: greedy (temperature=0), temperature softmax, optional top-k
truncation. Everything is static-shaped: prompts are right-aligned by
the caller padding to a fixed length; `prompt_len` may be a traced
scalar.
"""

from __future__ import annotations

import functools
import os
from typing import Any

import jax
import jax.numpy as jnp


def init_cache(model, batch: int) -> Any:
    """Zero KV caches shaped for `batch` rows (eval_shape: no FLOPs).
    Shapes come from the model config alone, never from live params."""
    tok1 = jnp.zeros((batch, 1), jnp.int32)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), tok1, decode_index=0)
    )
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        shapes.get("cache", {}))


def check_decode_geometry(model, prompt_len: int, max_new_tokens: int) -> None:
    """Decode past max_seq_len is silent garbage (the scalar cache write
    clamps; the vector one-hot write drops) — refuse the geometry up
    front, identically for generate() and the slot decoder."""
    limit = model.cfg.max_seq_len
    if prompt_len + max_new_tokens > limit:
        raise ValueError(
            f"prompt_len + max_new_tokens = {prompt_len + max_new_tokens} "
            f"exceeds the model's max_seq_len {limit}")


# Prefill chunk width: each tick feeds this many positions through the
# model's chunked decode path. Per-token prefill is a GEMV that
# re-streams the full weights once PER POSITION; 128-wide chunks make
# every projection a real GEMM and cut the weight stream ~128x — the
# dominant term of served prompt latency.
PREFILL_CHUNK = 128


def prefill_scan(model, params, cache, prompts, pad_len, chunk=0):
    """Run a [B, P] prompt through the KV cache in position chunks
    (cache-correct by construction: each chunk writes its K/V before
    attending, and the causal mask covers within-chunk order); returns
    (cache, last_logits [B, V]). Full-width chunks scan; a remainder
    chunk (P % width) runs as one extra apply, so EVERY prompt length
    gets GEMM-shaped prefill — never a per-token GEMV tail. The ONE
    prefill implementation — generate(), the slot decoder, and
    speculative decode must never drift apart here.

    `chunk` is the static chunk width (0 = KFTPU_PREFILL_CHUNK env, else
    PREFILL_CHUNK). NOTE the env var is read at TRACE time: jitted
    callers bake it into their compiled program and changing it later in
    the same process has no effect (the jit cache key does not include
    it) — pass `chunk` explicitly for in-process A/Bs; the env hook is
    for per-process sweeps like tools/serve_bench.py."""
    b, lp = prompts.shape
    width = chunk or int(os.environ.get("KFTPU_PREFILL_CHUNK", PREFILL_CHUNK))
    c = min(max(width, 1), lp)
    n_full, rem = (lp // c, lp % c) if c else (0, 0)
    logits = jnp.zeros((b, model.cfg.vocab_size), jnp.float32)
    pad_kw = {} if pad_len is None else {"pad_len": pad_len}

    def chunk_apply(cache, toks, start):
        out, mut = model.apply(
            params | {"cache": cache}, toks, train=False,
            decode_index=start, mutable=["cache"], **pad_kw)
        return mut["cache"], out[:, -1]

    if n_full:
        def tick(carry, xs):
            cache, _ = carry
            toks, start = xs
            return chunk_apply(cache, toks, start), None

        (cache, logits), _ = jax.lax.scan(
            tick, (cache, logits),
            (prompts[:, :n_full * c].reshape(b, n_full, c).swapaxes(0, 1),
             jnp.arange(n_full, dtype=jnp.int32) * c))
    if rem:
        cache, logits = chunk_apply(
            cache, prompts[:, n_full * c:], jnp.int32(n_full * c))
    return cache, logits


def prefill_per_token(model, params, cache, prompts, pad_len):
    """The original one-position-per-tick prefill, kept as the
    differential-test oracle for the chunked implementation."""
    b, lp = prompts.shape

    def tick(carry, xs):
        cache, _ = carry
        tok_col, idx = xs
        out, mut = model.apply(
            params | {"cache": cache}, tok_col[:, None], train=False,
            decode_index=idx, mutable=["cache"],
            **({} if pad_len is None else {"pad_len": pad_len}))
        return (mut["cache"], out[:, 0]), None

    (cache, logits), _ = jax.lax.scan(
        tick,
        (cache, jnp.zeros((b, model.cfg.vocab_size), jnp.float32)),
        (prompts.T, jnp.arange(lp)))
    return cache, logits


def _sample(logits, temperature: float, top_k: int, rng):
    """logits [B, V] -> token ids [B]."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("model", "max_new_tokens",
                                             "temperature", "top_k"))
def generate(model, variables, prompt: jax.Array, *,
             max_new_tokens: int, temperature: float = 0.0, top_k: int = 0,
             seed: int | jax.Array = 0, pad_len: jax.Array | None = None
             ) -> jax.Array:
    """Generate `max_new_tokens` continuations.

    prompt: [B, Lp] int32 (full prompt; all rows same length). For
    ragged batches, LEFT-pad each row to Lp and pass `pad_len` [B] (the
    number of pad positions per row): padded positions are masked out of
    decode attention, and RoPE being relative makes masked left-padding
    exact. `seed` may be a traced scalar (vary per call for independent
    samples). Returns [B, Lp + N].
    """
    b, lp = prompt.shape
    check_decode_geometry(model, lp, max_new_tokens)
    params = {"params": variables["params"]}
    cache = init_cache(model, b)

    # kwarg only when needed: models without ragged-prompt support keep
    # their existing apply signature
    pad_kw = {} if pad_len is None else {"pad_len": pad_len}

    def step(cache, tok_col, idx):
        out, mut = model.apply(
            params | {"cache": cache},
            tok_col[:, None],
            train=False,
            decode_index=idx,
            mutable=["cache"],
            **pad_kw,
        )
        return mut["cache"], out[:, 0]                 # logits [B, V]

    cache, logits = prefill_scan(model, params, cache, prompt, pad_len)

    # decode: sample, feed back
    rng = jax.random.PRNGKey(seed)

    def decode_tick(carry, i):
        cache, logits, rng = carry
        rng, sub = jax.random.split(rng)
        tok = _sample(logits, temperature, top_k, sub)
        cache, logits = step(cache, tok, lp + i)
        return (cache, logits, rng), tok

    (_, _, _), toks = jax.lax.scan(
        decode_tick, (cache, logits, rng), jnp.arange(max_new_tokens))
    return jnp.concatenate([prompt, toks.T], axis=1)
