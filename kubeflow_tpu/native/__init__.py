"""Native (C++) runtime components, loaded via ctypes.

The reference has no native source in-tree (every compiled component is
Go — SURVEY.md §2); its data plane lives inside TF payload images. The
TPU build keeps the runtime's host-side hot loops native: this package
holds the compiled artifacts (built from /native at the repo root) and
the loader glue. Everything degrades gracefully to pure-Python
implementations when the shared library is absent (e.g. no toolchain),
so the framework stays importable everywhere while the native path is
the default in built images.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

log = logging.getLogger("kubeflow_tpu.native")

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(_PKG_DIR)), "native")
_LIB_NAME = "libkfdata.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def library_path() -> str | None:
    """Path to the built shared library, building it from source on first
    use when a toolchain is available (dev checkouts); None if absent."""
    p = os.path.join(_PKG_DIR, _LIB_NAME)
    makefile = os.path.join(_SRC_DIR, "Makefile")
    if os.path.exists(makefile):
        # Always invoke make (it no-ops when the .so is newer than the
        # source): a stale library silently masking source edits is worse
        # than the ~10ms make overhead on first use.
        try:
            subprocess.run(
                ["make", "-C", _SRC_DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (OSError, subprocess.SubprocessError) as e:
            if os.path.exists(p):
                log.warning("native rebuild failed (%s); using existing %s",
                            e, p)
                return p
            log.warning("native build failed (%s); using Python fallbacks", e)
            return None
    return p if os.path.exists(p) else None


def load() -> ctypes.CDLL | None:
    """The kfdata library with argtypes configured, or None (cached)."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        path = library_path()
        if path is None:
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            log.warning("cannot dlopen %s (%s); using Python fallbacks", path, e)
            _load_failed = True
            return None
        lib.kfdl_open.restype = ctypes.c_void_p
        lib.kfdl_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.c_uint64,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_uint64,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.kfdl_next.restype = ctypes.c_int64
        lib.kfdl_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
        ]
        lib.kfdl_error.restype = ctypes.c_char_p
        lib.kfdl_error.argtypes = [ctypes.c_void_p]
        lib.kfdl_close.restype = None
        lib.kfdl_close.argtypes = [ctypes.c_void_p]
        lib.kfdl_crc32.restype = ctypes.c_uint32
        lib.kfdl_crc32.argtypes = [ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
        _lib = lib
        return _lib
