"""Basic-auth "authservice": the Istio ext-authz check endpoint.

Mirrors gatekeeper/auth/AuthServer.go:
- ServeHTTP (:62): allow when basic-auth matches (authpwd :118) or a
  valid session cookie is presented (authCookie :143); otherwise 302 to
  the login page (redirectToLogin :161) for browser clients and 401 for
  API clients.
- Cookie minting on successful login (:183).

Password hashing: SHA-256 (the reference stores a passhash + salt via
its kflogin config). On allow, the identity is propagated in the
``kubeflow-userid`` header — the attach_user_middleware contract the
dashboard and KFAM consume.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import logging
import os
import secrets
import time

from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import HttpReq, HttpResp, Router

log = logging.getLogger("kubeflow_tpu.gatekeeper")

COOKIE_NAME = "kubeflow-auth"
USER_HEADER = "kubeflow-userid"
DEFAULT_TTL_S = 24 * 3600


def pwhash(password: str, salt: str = "") -> str:
    return hashlib.sha256((salt + password).encode()).hexdigest()


class AuthServer:
    def __init__(
        self,
        username: str | None = None,
        passhash: str | None = None,
        salt: str = "",
        login_url: str = "/kflogin",
        cookie_key: bytes | None = None,
        ttl_s: int = DEFAULT_TTL_S,
        clock=time.time,
    ):
        self.username = username or os.environ.get("GATEKEEPER_USERNAME", "admin")
        self.passhash = passhash or os.environ.get("GATEKEEPER_PASSHASH", "")
        self.salt = salt or os.environ.get("GATEKEEPER_SALT", "")
        self.login_url = login_url
        self.cookie_key = cookie_key or secrets.token_bytes(32)
        self.ttl_s = ttl_s
        # one clock for every expiry decision: mint and verify read the
        # same injectable source, so tests (and replays) drive token
        # lifecycles without monkey-patching time.time
        self.clock = clock

    # -- cookie minting/verification (:143-199) -----------------------------

    def mint_cookie(self, user: str, now: float | None = None) -> str:
        exp = int((self.clock() if now is None else now) + self.ttl_s)
        payload = f"{user}|{exp}"
        sig = hmac.new(self.cookie_key, payload.encode(), hashlib.sha256).hexdigest()
        return base64.urlsafe_b64encode(f"{payload}|{sig}".encode()).decode()

    def verify_cookie(self, cookie: str, now: float | None = None) -> str | None:
        try:
            payload = base64.urlsafe_b64decode(cookie.encode()).decode()
            user, exp, sig = payload.rsplit("|", 2)
            want = hmac.new(self.cookie_key, f"{user}|{exp}".encode(),
                            hashlib.sha256).hexdigest()
            if not hmac.compare_digest(sig, want):
                return None
            if int(exp) < (self.clock() if now is None else now):
                return None
            return user
        except Exception:
            return None

    # -- checks -------------------------------------------------------------

    def auth_basic(self, req: HttpReq) -> str | None:
        """authpwd (:118)."""
        hdr = req.header("authorization")
        if not hdr.startswith("Basic "):
            return None
        try:
            user, _, pw = base64.b64decode(hdr[6:]).decode().partition(":")
        except Exception:
            return None
        if user == self.username and hmac.compare_digest(
            pwhash(pw, self.salt), self.passhash
        ):
            return user
        return None

    def auth_cookie(self, req: HttpReq) -> str | None:
        cookies = {}
        for part in req.header("cookie").split(";"):
            if "=" in part:
                k, v = part.strip().split("=", 1)
                cookies[k] = v
        tok = cookies.get(COOKIE_NAME)
        return self.verify_cookie(tok) if tok else None

    # -- endpoints ----------------------------------------------------------

    def check(self, req: HttpReq):
        """The ext-authz endpoint (ServeHTTP :62). 200 + identity header
        on allow; 302 to login for browsers; 401 for API clients."""
        user = self.auth_basic(req) or self.auth_cookie(req)
        if user:
            return HttpResp(status=200, body=b"{}",
                            headers={USER_HEADER: user})
        accepts = req.header("accept", "")
        if "text/html" in accepts:
            return HttpResp(status=302, body=b"",
                            headers={"Location": self.login_url})  # :161
        return HttpResp(status=401, body=b'{"error": "unauthorized"}')

    def login(self, req: HttpReq):
        """POST {username, password} -> Set-Cookie (:183)."""
        body = req.json() or {}
        user, pw = body.get("username", ""), body.get("password", "")
        if user == self.username and hmac.compare_digest(
            pwhash(pw, self.salt), self.passhash
        ):
            cookie = self.mint_cookie(user)
            return HttpResp(
                status=200, body=b'{"status": "ok"}',
                headers={"Set-Cookie":
                         f"{COOKIE_NAME}={cookie}; Path=/; HttpOnly"},
            )
        return HttpResp(status=401, body=b'{"error": "bad credentials"}')

    def logout(self, req: HttpReq):
        return HttpResp(status=200, body=b'{"status": "ok"}',
                        headers={"Set-Cookie":
                                 f"{COOKIE_NAME}=; Path=/; Max-Age=0"})

    def router(self) -> Router:
        r = Router("gatekeeper")
        r.route("GET", "/auth", self.check)
        r.route("POST", "/login", self.login)
        r.route("POST", "/logout", self.logout)
        httpd.add_health_routes(r)
        httpd.add_metrics_route(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 0) -> httpd.HttpService:
        return httpd.HttpService(self.router(), host, port)
