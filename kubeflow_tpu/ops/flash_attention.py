"""Pallas TPU flash attention (forward kernel + blockwise backward).

The hot op of the transformer path, built for the MXU:

- Forward is a Pallas kernel: grid (batch*heads, q_blocks, kv_blocks),
  streaming-softmax accumulators (running max / sum / output) in VMEM
  scratch that persist across the sequential kv-block grid dimension, so
  attention memory is O(BLOCK_Q x BLOCK_K) instead of O(L^2). Logits and
  accumulation in f32 on the MXU (`preferred_element_type`), inputs bf16.
- Causal blocks above the diagonal are predicated off with `@pl.when`
  (skipped entirely, ~2x speedup), diagonal blocks masked with
  `broadcasted_iota` (TPU needs >=2D iota).
- Sequence packing: optional per-position segment ids mask q->k pairs
  across document boundaries inside the same kernels (a separate
  custom_vjp variant, so the unsegmented hot path is untouched).
- Backward is fused Pallas too: a dq kernel (accumulates over kv blocks)
  and a dk/dv kernel (accumulates over q blocks), both recomputing
  probabilities from the saved logsumexp (the flash trick) so memory is
  O(BLOCK_Q x BLOCK_K); all matmuls on the MXU in f32. A blockwise XLA
  backward (`_flash_bwd_xla`) remains as the differential-test oracle.

On non-TPU platforms the kernel runs in Pallas interpret mode (tests on
the virtual CPU mesh exercise the same code path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

# Hardware-swept defaults (BASELINE.md round 3): on a v5e, 512x512
# blocks more than double train MFU vs 128x128 (llama-1b bs16 seq2048:
# 0.227 -> 0.467) — bigger blocks amortize the per-block HBM re-reads of
# K/V across 4x more MXU work and still fit VMEM comfortably. Blocks
# clamp to the sequence length, so short-seq callers are unaffected;
# override per-run with KFTPU_FLASH_BLOCK_Q/K.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() not in ("tpu",)


def _vmem_spec(shape, imap) -> "pl.BlockSpec":
    return pl.BlockSpec(shape, imap, memory_space=pltpu.VMEM)


def _block_mask(*, causal, block_q, block_k, qi, ki, offset,
                qseg_row=None, kseg_row=None, window=0):
    """The block's combined validity mask: causal diagonal, sliding
    window (query i sees keys in (i - window, i]), and/or segment
    equality (sequence packing). None = nothing masked."""
    mask = None
    rows = cols = None
    if causal or window > 0:
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    if causal:
        mask = (qi * block_q + rows + offset) >= (ki * block_k + cols)
    if window > 0:
        near = ((qi * block_q + rows + offset)
                - (ki * block_k + cols)) < window
        mask = near if mask is None else mask & near
    if qseg_row is not None:
        seg = qseg_row[:, None] == kseg_row[None, :]   # [BQ, BK]
        mask = seg if mask is None else mask & seg
    return mask


def _block_runs(*, causal, block_q, block_k, qi, ki, offset, window=0):
    """Whether a (qi, ki) block pair can contain ANY valid logits —
    blocks past the causal diagonal or entirely left of the sliding
    window are skipped outright (never computed)."""
    run = True
    if causal:
        # the block's lowest k column vs its highest causal q row
        run = ki * block_k <= qi * block_q + (block_q - 1) + offset
    if window > 0:
        # smallest (qpos - kpos) over the block pair = LOWEST q row vs
        # HIGHEST k column; if even that closest pair is >= window away,
        # no pair in the block is inside the window => skip
        closest = ((qi * block_q + offset)            # lowest q row
                   - (ki * block_k + block_k - 1))    # highest k col
        run = jnp.logical_and(run, closest < window) if causal \
            else closest < window
    return run


def _recompute_p_ds(q, k, v, g, lse_row, delta_row, *, scale, causal,
                    block_q, block_k, qi, ki, offset,
                    qseg_row=None, kseg_row=None, window=0):
    """Shared backward block math: recompute probabilities from the saved
    lse and form ds = p * (dp - delta) * scale. Used by BOTH backward
    kernels so the masking/scaling convention can never diverge between
    dq and dk/dv."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                          # [BQ, BK]
    mask = _block_mask(causal=causal, block_q=block_q, block_k=block_k,
                       qi=qi, ki=ki, offset=offset,
                       qseg_row=qseg_row, kseg_row=kseg_row, window=window)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse_row[:, None])                  # [BQ, BK]
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta_row[:, None]) * scale
    return p, ds


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------

def _kb_lo(qi, *, block_q, block_k, offset, window):
    """First k-block the sliding window can reach for q-block `qi`:
    the lowest q row's earliest in-window key position, floor-divided
    to blocks. Shared by the kernel and the BlockSpec index maps so the
    loaded block and the mask arithmetic can never disagree."""
    lo_pos = qi * block_q + offset - (window - 1)
    return jnp.maximum(0, lo_pos // block_k)


def _fwd_kernel(*refs, scale: float, causal: bool, block_q: int,
                block_k: int, offset: int, has_seg: bool, window: int = 0,
                nk_total: int = 0, pruned: bool = False):
    # offset = lk - lq: causality is end-aligned (query row i may attend
    # keys <= i + offset), matching reference_attention's tril(k=lk-lq) —
    # the KV-cache decode / chunked-prefill convention.
    if has_seg:
        (q_ref, k_ref, v_ref, qseg_ref, kseg_ref,
         o_ref, lse_ref, m_s, l_s, acc_s) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    if pruned:
        # windowed grid: axis 2 walks only the k-blocks the window can
        # reach (the BlockSpec index map loads block kb_lo + j, clamped);
        # ki here is the UNclamped logical block for the mask arithmetic
        ki = _kb_lo(qi, block_q=block_q, block_k=block_k, offset=offset,
                    window=window) + j
    else:
        ki = j

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # blocks past the causal diagonal / outside the sliding window
    # contribute nothing and are skipped outright
    run = _block_runs(causal=causal, block_q=block_q, block_k=block_k,
                      qi=qi, ki=ki, offset=offset, window=window)
    if pruned:
        # clamped duplicate loads past the last real k block never run
        run = jnp.logical_and(run, ki <= nk_total - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                   # [BQ, D]
        k = k_ref[0]                                   # [BK, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                      # [BQ, BK]
        mask = _block_mask(
            causal=causal, block_q=block_q, block_k=block_k,
            qi=qi, ki=ki, offset=offset,
            qseg_row=None if qseg_ref is None else qseg_ref[0, 0],
            kseg_row=None if kseg_ref is None else kseg_ref[0, 0],
            window=window)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_prev = m_s[:]                                # [BQ, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                         # [BQ, BK]
        l_new = l_s[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_s[:] = m_new
        l_s[:] = l_new

    @pl.when(j == nj - 1)
    def _finalize():
        l = jnp.maximum(l_s[:], 1e-20)
        o_ref[0] = (acc_s[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_s[:] + jnp.log(l))[:, 0]


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               qseg=None, kseg=None, window=0):
    """q,k,v: [BH, L, D] (kv already repeated to q heads); qseg/kseg:
    optional [BH, 1, L] int32 segment ids (sequence packing)."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    nq = pl.cdiv(lq, block_q)
    nk = pl.cdiv(lk, block_k)
    offset = lk - lq
    has_seg = qseg is not None
    # Windowed grid pruning: with a sliding window only the k-blocks in
    # (qpos - window, qpos] are reachable, so the k axis of the grid
    # shrinks from nk to the window span — out-of-window blocks are
    # never DMA'd at all (round 3 skipped their COMPUTE but still
    # streamed them from HBM). Index maps load kb_lo(qi) + j, clamped;
    # the kernel re-derives the logical ki for its masks.
    pruned = causal and window > 0 and lq > 1
    nkw = min(nk, pl.cdiv(block_q + window, block_k) + 1) if pruned else nk

    def kj(b, i, j):
        if not pruned:
            return (b, j, 0)
        lo = _kb_lo(i, block_q=block_q, block_k=block_k, offset=offset,
                    window=window)
        return (b, jnp.minimum(lo + j, nk - 1), 0)

    def kj_seg(b, i, j):
        bj, kb, _ = kj(b, i, j)
        return (bj, 0, kb)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, offset=offset, has_seg=has_seg,
        window=window, nk_total=nk, pruned=pruned,
    )
    if not _HAS_PLTPU:
        raise ImportError(
            "jax.experimental.pallas.tpu unavailable in this JAX build; "
            "use attention(impl='reference') instead of the flash kernel"
        )
    scratch = [
        pltpu.VMEM((block_q, 1), jnp.float32),   # running max
        pltpu.VMEM((block_q, 1), jnp.float32),   # running sum
        pltpu.VMEM((block_q, d), jnp.float32),   # output accumulator
    ]
    bs = _vmem_spec

    in_specs = [
        bs((1, block_q, d), lambda b, i, j: (b, i, 0)),
        bs((1, block_k, d), kj),
        bs((1, block_k, d), kj),
    ]
    operands = [q, k, v]
    if has_seg:
        in_specs += [
            bs((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            bs((1, 1, block_k), kj_seg),
        ]
        operands += [qseg, kseg]

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nkw),
        in_specs=in_specs,
        out_specs=[
            bs((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse rides as [BH, 1, L] so the block's trailing dims are
            # (1, block_q) — legal under Mosaic's (8, 128) tiling rule
            # (1 == the full middle dim; block_q % 128 == 0).
            bs((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, lq), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)
    return out, lse.reshape(bh, lq)


# --------------------------------------------------------------------------
# backward: fused Pallas kernels (dq; dk/dv), with the saved-lse flash
# trick — probabilities are recomputed blockwise, memory stays
# O(BLOCK_Q x BLOCK_K). Two kernels because the two gradients accumulate
# over different grid axes (dq over kv blocks, dk/dv over q blocks);
# each keeps its accumulator in VMEM scratch across the sequential inner
# grid dimension, exactly like the forward.
# --------------------------------------------------------------------------

def _bwd_dq_kernel(*refs, scale, causal, block_q, block_k, offset, has_seg,
                   window=0, nk_total=0, pruned=False):
    if has_seg:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
         qseg_ref, kseg_ref, dq_ref, acc_s) = refs
    else:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
         dq_ref, acc_s) = refs
        qseg_ref = kseg_ref = None
    qi = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    if pruned:
        # windowed grid (see _flash_fwd): axis 2 walks only the
        # window-reachable k blocks; the index map loads kb_lo + j
        ki = _kb_lo(qi, block_q=block_q, block_k=block_k, offset=offset,
                    window=window) + j
    else:
        ki = j

    @pl.when(j == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)

    run = _block_runs(causal=causal, block_q=block_q, block_k=block_k,
                      qi=qi, ki=ki, offset=offset, window=window)
    if pruned:
        run = jnp.logical_and(run, ki <= nk_total - 1)

    @pl.when(run)
    def _compute():
        k = k_ref[0]                                   # [BK, D]
        _, ds = _recompute_p_ds(
            q_ref[0], k, v_ref[0], g_ref[0], lse_ref[0, 0], delta_ref[0, 0],
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            qi=qi, ki=ki, offset=offset,
            qseg_row=None if qseg_ref is None else qseg_ref[0, 0],
            kseg_row=None if kseg_ref is None else kseg_ref[0, 0],
            window=window)
        acc_s[:] = acc_s[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nj - 1)
    def _finalize():
        dq_ref[0] = acc_s[:].astype(dq_ref.dtype)


def _qb_lo(ki, *, block_q, block_k, offset):
    """First q-block the CAUSAL constraint lets attend k-block `ki`
    (qpos + offset >= kpos). The window bounds the other end: q rows
    further than window-1 past a key can't see it, so the valid q span
    per k block is at most cdiv(block_k + window, block_q) + 1 blocks."""
    return jnp.maximum(0, (ki * block_k - offset) // block_q)


def _bwd_dkv_kernel(*refs, scale, causal, block_q, block_k, offset, has_seg,
                    window=0, nq_total=0, pruned=False):
    if has_seg:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
         qseg_ref, kseg_ref, dk_ref, dv_ref, dk_s, dv_s) = refs
    else:
        (q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_s, dv_s) = refs
        qseg_ref = kseg_ref = None
    ki = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    if pruned:
        qi = _qb_lo(ki, block_q=block_q, block_k=block_k, offset=offset) + j
    else:
        qi = j

    @pl.when(j == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    run = _block_runs(causal=causal, block_q=block_q, block_k=block_k,
                      qi=qi, ki=ki, offset=offset, window=window)
    if pruned:
        run = jnp.logical_and(run, qi <= nq_total - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                   # [BQ, D]
        g = g_ref[0]
        p, ds = _recompute_p_ds(
            q, k_ref[0], v_ref[0], g, lse_ref[0, 0], delta_ref[0, 0],
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            qi=qi, ki=ki, offset=offset,
            qseg_row=None if qseg_ref is None else qseg_ref[0, 0],
            kseg_row=None if kseg_ref is None else kseg_ref[0, 0],
            window=window)
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [BK, D]
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nj - 1)
    def _finalize():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, scale, causal, block_q, block_k,
                      interpret, qseg=None, kseg=None, window=0):
    """Fused backward: q,k,v,out,g [BH, L, D]; lse [BH, L]; qseg/kseg
    optional [BH, 1, L] int32."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    nq = pl.cdiv(lq, block_q)
    nk = pl.cdiv(lk, block_k)
    offset = lk - lq
    has_seg = qseg is not None
    # delta_i = sum_d(do_i * o_i): one cheap rowwise reduction in XLA.
    # lse/delta ride as [BH, 1, L] for Mosaic's (8, 128) tiling rule.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = delta.reshape(bh, 1, lq)
    lse = lse.reshape(bh, 1, lq)

    bs = _vmem_spec
    # windowed grid pruning, mirrored from _flash_fwd: out-of-window
    # blocks are never DMA'd in the backward either (it carries ~2x the
    # forward's attention HBM traffic)
    pruned = causal and window > 0 and lq > 1
    nkw = min(nk, pl.cdiv(block_q + window, block_k) + 1) if pruned else nk
    nqw = min(nq, pl.cdiv(block_k + window, block_q) + 1) if pruned else nq

    def kj(b, i, j):
        if not pruned:
            return (b, j, 0)
        lo = _kb_lo(i, block_q=block_q, block_k=block_k, offset=offset,
                    window=window)
        return (b, jnp.minimum(lo + j, nk - 1), 0)

    def kj_seg(b, i, j):
        bj, kb, _ = kj(b, i, j)
        return (bj, 0, kb)

    dq_specs = [
        bs((1, block_q, d), lambda b, i, j: (b, i, 0)),   # q
        bs((1, block_k, d), kj),                          # k
        bs((1, block_k, d), kj),                          # v
        bs((1, block_q, d), lambda b, i, j: (b, i, 0)),   # g
        bs((1, 1, block_q), lambda b, i, j: (b, 0, i)),   # lse
        bs((1, 1, block_q), lambda b, i, j: (b, 0, i)),   # delta
    ]
    dq_operands = [q, k, v, g, lse, delta]
    if has_seg:
        dq_specs += [
            bs((1, 1, block_q), lambda b, i, j: (b, 0, i)),   # qseg
            bs((1, 1, block_k), kj_seg),                      # kseg
        ]
        dq_operands += [qseg, kseg]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=offset,
                          has_seg=has_seg, window=window, nk_total=nk,
                          pruned=pruned),
        grid=(bh, nq, nkw),
        in_specs=dq_specs,
        out_specs=bs((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*dq_operands)

    def qi_map(b, kb, j):
        # dkv grid is (bh, k-block, q-walk): q block loaded = qb_lo + j
        if not pruned:
            return (b, j, 0)
        lo = _qb_lo(kb, block_q=block_q, block_k=block_k, offset=offset)
        return (b, jnp.minimum(lo + j, nq - 1), 0)

    def qi_row(b, kb, j):
        bj, qb, _ = qi_map(b, kb, j)
        return (bj, 0, qb)

    dkv_specs = [
        bs((1, block_q, d), qi_map),                      # q
        bs((1, block_k, d), lambda b, j, i: (b, j, 0)),   # k
        bs((1, block_k, d), lambda b, j, i: (b, j, 0)),   # v
        bs((1, block_q, d), qi_map),                      # g
        bs((1, 1, block_q), qi_row),                      # lse
        bs((1, 1, block_q), qi_row),                      # delta
    ]
    dkv_operands = [q, k, v, g, lse, delta]
    if has_seg:
        dkv_specs += [
            bs((1, 1, block_q), qi_row),                      # qseg
            bs((1, 1, block_k), lambda b, j, i: (b, 0, j)),   # kseg
        ]
        dkv_operands += [qseg, kseg]

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=offset,
                          has_seg=has_seg, window=window, nq_total=nq,
                          pruned=pruned),
        grid=(bh, nk, nqw),
        in_specs=dkv_specs,
        out_specs=[
            bs((1, block_k, d), lambda b, j, i: (b, j, 0)),
            bs((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_operands)
    return dq, dk, dv


# --------------------------------------------------------------------------
# backward (blockwise XLA fallback / differential-test oracle)
# --------------------------------------------------------------------------

def _flash_bwd_xla(q, k, v, out, lse, g, scale, causal, block_k):
    """Recompute-p backward. All [BH, L, D]; lse [BH, L]."""
    f32 = jnp.float32
    qf, kf, vf, gf = (x.astype(f32) for x in (q, k, v, g))
    # delta_i = sum_d(do_i * o_i) (rowwise), the standard flash-bwd term
    delta = jnp.sum(gf * out.astype(f32), axis=-1)           # [BH, L]
    lk = k.shape[1]
    nk = pl.cdiv(lk, block_k)
    positions_q = jnp.arange(q.shape[1])

    def kv_block(carry, jb):
        dq_acc = carry
        ks = jax.lax.dynamic_slice_in_dim(kf, jb * block_k, block_k, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(vf, jb * block_k, block_k, axis=1)
        s = jnp.einsum("bqd,bkd->bqk", qf, ks) * scale
        if causal:
            cols = jb * block_k + jnp.arange(block_k)
            mask = (positions_q[:, None] + (lk - q.shape[1])) >= cols[None, :]
            s = jnp.where(mask[None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                      # [BH, Lq, BK]
        dv = jnp.einsum("bqk,bqd->bkd", p, gf)
        dp = jnp.einsum("bqd,bkd->bqk", gf, vs)
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, ks)
        dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_acc, (dk, dv)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        kv_block, jnp.zeros_like(qf), jnp.arange(nk)
    )
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(k.shape[0], nk * block_k, k.shape[2])
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(*dk.shape)
    dk = dk[:, :lk]
    dv = dv[:, :lk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

# qseg/kseg are None (empty pytrees) on the unsegmented hot path —
# has_seg resolves statically at trace time, so the compiled kernel is
# bit-identical to the pre-segments one.
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, qseg, kseg, scale, causal, block_q, block_k, window):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        _interpret_default(), qseg=qseg, kseg=kseg,
                        window=window)
    return out


def _flash_vjp_fwd(q, k, v, qseg, kseg, scale, causal, block_q, block_k,
                   window):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                          _interpret_default(), qseg=qseg, kseg=kseg,
                          window=window)
    # jax.checkpoint partial-eval looks THROUGH custom_vjp fwd rules, so
    # these residuals are policy-visible equations: naming them lets a
    # remat policy keep exactly (out, lse) — and with q/k/v anchored by
    # the model, the backward then runs with ZERO flash-forward replay.
    # checkpoint_name is identity outside remat; the hot path is
    # unchanged.
    from jax.ad_checkpoint import checkpoint_name

    out_r = checkpoint_name(out, "attn_flash")
    lse_r = checkpoint_name(lse, "attn_flash")
    return out, (q, k, v, qseg, kseg, out_r, lse_r)


def _flash_vjp_bwd(scale, causal, block_q, block_k, window, res, g):
    import numpy as np

    q, k, v, qseg, kseg, out, lse = res
    dq, dk, dv = _flash_bwd_pallas(
        q, k, v, out, lse, g, scale, causal, block_q, block_k,
        _interpret_default(), qseg=qseg, kseg=kseg, window=window)
    # integer segment ids take float0 cotangents (None stays None)
    zero = lambda a: (None if a is None  # noqa: E731
                      else np.zeros(a.shape, jax.dtypes.float0))
    return dq, dk, dv, zero(qseg), zero(kseg)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    segment_ids: jax.Array | None = None,
    kv_segment_ids: jax.Array | None = None,
    window: int = 0,
) -> jax.Array:
    """Fused attention. [B, L, H, D] in / out; GQA via fewer KV heads.

    window > 0 = sliding-window attention: keys further than window-1
    positions in the PAST are masked (one-sided; with causal=False,
    future keys stay fully attended — same convention as
    reference_attention). Blocks fully left of the window skip their
    COMPUTE via pl.when, so MXU work is O(L * window); their K/V blocks
    are still DMA'd (the grid shape is static), so HBM traffic stays
    O(L^2) — a window-sized k-grid with a qi-offset index map is the
    follow-up that fixes the bandwidth term.

    segment_ids: optional [B, L] int32 sequence-packing ids — query i
    attends key j only when their ids match (on top of causality), so
    one row can carry several packed documents without cross-attention.
    kv_segment_ids defaults to segment_ids (self-attention)."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    if k.shape[2] != h:
        assert h % k.shape[2] == 0, (h, k.shape[2])
        rep = h // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # Clamp to the sequence, then halve until the block divides it (not
    # below the 128-lane tile): a 640-token sequence runs at block 128
    # instead of erroring against the swept 512 default.
    block_q = min(block_q, lq)
    while block_q > 128 and lq % block_q:
        block_q //= 2
    block_k = min(block_k, lk)
    while block_k > 128 and lk % block_k:
        block_k //= 2
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"sequence lengths ({lq}, {lk}) must be multiples of the block "
            f"sizes ({block_q}, {block_k}); pad inputs or pass block sizes"
        )
    # [B, L, H, D] -> [B*H, L, D]
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    qseg = kseg = None
    if kv_segment_ids is not None and segment_ids is None:
        raise ValueError(
            "kv_segment_ids without segment_ids — key-side masking would "
            "be silently dropped; pass the query ids too")
    if segment_ids is not None:
        if kv_segment_ids is None:
            kv_segment_ids = segment_ids
        # [B, L] -> [B*H, 1, L]: per-head copies of the per-batch ids
        # (int32, ~1 MB at bench shapes — negligible next to K/V).
        qseg = jnp.repeat(segment_ids.astype(jnp.int32)[:, None], h, axis=1
                          ).reshape(b * h, 1, lq)
        kseg = jnp.repeat(kv_segment_ids.astype(jnp.int32)[:, None], h, axis=1
                          ).reshape(b * h, 1, lk)
    out = _flash(qt, kt, vt, qseg, kseg, scale, causal, block_q, block_k,
                 window)
    return out.reshape(b, h, lq, d).transpose(0, 2, 1, 3)
