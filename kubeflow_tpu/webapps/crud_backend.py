"""crud_backend: the shared backend package for CRUD web apps.

Mirrors components/crud-web-apps/common/backend/kubeflow/kubeflow/
crud_backend (SURVEY.md §2.3): the next-gen shared Flask package the
reference factors JWA-style apps onto — authn (identity header), authz
(per-verb namespace access checks), api wrappers over Kubernetes
resources (PVCs, secrets, events, storageclasses, namespaces), and the
uniform {success, status, ...} response envelope its frontends expect.

A CRUD app composes: `CrudBackend(client, authz).router(prefix)` gives
the standard resource GETs; app-specific routes are added on top (see
webapps/jwa.py for the notebook-specific equivalent).
"""

from __future__ import annotations

import logging
from typing import Callable

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import ApiHttpError, HttpReq, Router

log = logging.getLogger("kubeflow_tpu.crud_backend")

USER_HEADER = "kubeflow-userid"


def success(**kw) -> dict:
    """crud_backend/helpers success envelope."""
    return {"success": True, "status": 200, **kw}


def authn_user(req: HttpReq, required: bool = True) -> str:
    """authn.py equivalent: identity from the trusted proxy header."""
    user = req.user or req.header(USER_HEADER)
    if not user and required:
        raise ApiHttpError(401, "no user identity (missing "
                                f"{USER_HEADER} header)")
    return user or ""


class Authorizer:
    """authz.py equivalent. The reference issues SubjectAccessReviews;
    the TPU build checks against the same sources KFAM maintains: cluster
    admin, profile ownership, or contributor RoleBindings (user/role
    annotations, kfam/bindings.go:168 List semantics)."""

    READ_VERBS = ("get", "list", "watch")

    def __init__(self, client, cluster_admin: str | None = None):
        self.client = client
        self.cluster_admin = cluster_admin

    def _roles(self, user: str, namespace: str) -> set[str]:
        from kubeflow_tpu.control.profile import types as PT

        roles: set[str] = set()
        prof = self.client.get_or_none("kubeflow.org/v1", "Profile", namespace)
        if prof and PT.owner_name(prof) == user:
            roles.add("admin")
        for rb in self.client.list("rbac.authorization.k8s.io/v1",
                                   "RoleBinding", namespace=namespace):
            anno = (rb.get("metadata") or {}).get("annotations") or {}
            if anno.get(PT.ANNO_USER) == user and anno.get(PT.ANNO_ROLE):
                roles.add(anno[PT.ANNO_ROLE])
        return roles

    def check(self, user: str, verb: str, namespace: str) -> None:
        if self.cluster_admin and user == self.cluster_admin:
            return
        roles = self._roles(user, namespace)
        if "admin" in roles or "edit" in roles:
            return
        if verb in self.READ_VERBS and "view" in roles:
            return
        raise ApiHttpError(403, f"{user} cannot {verb} in {namespace}")


class CrudBackend:
    """Standard resource routes shared by all CRUD apps."""

    def __init__(self, client, authz: Authorizer | None = None):
        self.client = client
        self.authz = authz

    def _auth(self, req: HttpReq, verb: str, namespace: str) -> str:
        user = authn_user(req, required=self.authz is not None)
        if self.authz:
            self.authz.check(user, verb, namespace)
        return user

    # -- api/ wrappers ------------------------------------------------------

    def list_namespaces(self, req: HttpReq):
        # Cluster-scoped, so no per-namespace authz — but still
        # authenticated: anonymous callers must not enumerate tenants.
        authn_user(req, required=self.authz is not None)
        items = self.client.list("v1", "Namespace")
        return success(namespaces=[o["metadata"]["name"] for o in items])

    def list_pvcs(self, req: HttpReq):
        ns = req.params["ns"]
        self._auth(req, "list", ns)
        items = self.client.list("v1", "PersistentVolumeClaim", namespace=ns)
        return success(pvcs=items)

    def create_pvc(self, req: HttpReq):
        ns = req.params["ns"]
        self._auth(req, "create", ns)
        pvc = req.json()
        pvc.setdefault("apiVersion", "v1")
        pvc.setdefault("kind", "PersistentVolumeClaim")
        pvc.setdefault("metadata", {})["namespace"] = ns
        return success(pvc=self.client.create(pvc))

    def delete_pvc(self, req: HttpReq):
        ns, name = req.params["ns"], req.params["name"]
        self._auth(req, "delete", ns)
        try:
            self.client.delete("v1", "PersistentVolumeClaim", name, ns)
        except ob.NotFound:
            raise ApiHttpError(404, f"pvc {ns}/{name} not found")
        return success()

    def list_secrets(self, req: HttpReq):
        ns = req.params["ns"]
        self._auth(req, "list", ns)
        items = self.client.list("v1", "Secret", namespace=ns)
        # names only: secret *values* never transit the CRUD API
        return success(secrets=[o["metadata"]["name"] for o in items])

    def list_events(self, req: HttpReq):
        ns = req.params["ns"]
        self._auth(req, "list", ns)
        items = self.client.list("v1", "Event", namespace=ns)
        return success(events=items)

    def list_storageclasses(self, req: HttpReq):
        authn_user(req, required=self.authz is not None)
        items = self.client.list("storage.k8s.io/v1", "StorageClass")
        return success(storageClasses=[o["metadata"]["name"] for o in items])

    def add_routes(self, r: Router) -> Router:
        r.route("GET", "/api/namespaces", self.list_namespaces)
        r.route("GET", "/api/namespaces/{ns}/pvcs", self.list_pvcs)
        r.route("POST", "/api/namespaces/{ns}/pvcs", self.create_pvc)
        r.route("DELETE", "/api/namespaces/{ns}/pvcs/{name}", self.delete_pvc)
        r.route("GET", "/api/namespaces/{ns}/secrets", self.list_secrets)
        r.route("GET", "/api/namespaces/{ns}/events", self.list_events)
        r.route("GET", "/api/storageclasses", self.list_storageclasses)
        return r

    def router(self, name: str = "crud") -> Router:
        r = Router(name)
        self.add_routes(r)
        httpd.add_health_routes(r)
        httpd.add_metrics_route(r)
        return r
