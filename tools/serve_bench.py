#!/usr/bin/env python3
"""Serving benchmarks: single-replica decode modes AND the JAXService
serving plane.

Two families share this tool:

1. **Decode modes** (the original ledger): drives the in-process
   serving stack (no HTTP overhead) with an open-loop arrival stream of
   pre-tokenized prompts and reports ONE JSON line per mode — `micro`
   (MicroBatcher + whole-batch generate) vs `continuous` (slot
   decoder). Run on real TPU for numbers that matter.

     python tools/serve_bench.py --model gpt-350m --param-dtype bfloat16 \\
         --prompt-len 512 --max-new-tokens 64 --requests 64 --concurrency 16

2. **The serving plane** (`--router`, ISSUE 8): a DETERMINISTIC
   virtual-time benchmark of the token router + JAXService controller —
   manual clock, seeded arrival trace, stub replicas with a fixed
   tokens/sec service rate, zero wall-clock dependence, so every
   latency/throughput number and every autoscaling decision replays
   identically per seed. Two arms share one trace:

   - ``single`` — replicas pinned at 1 (the pre-JAXService shape);
   - ``multi``  — autoscaling 1..4 on router queue depth + tokens/sec,
     WITH the scripted drills: a replica kill mid-load (the router must
     shed its in-flight requests to survivors with zero drops and the
     controller must re-provision) and a full scale-up/scale-down cycle
     (cordon -> drain -> delete proven on the virtual clock).

   Banked as BENCH_SERVE_r01.json; ``--check`` reruns the banked config
   and gates on regression (the sched_bench.py ratchet mold):
   any dropped request, a changed decision fingerprint (determinism),
   or multi-arm throughput below 75% of the banked number fails CI.

     python tools/serve_bench.py --router          # run + bank
     python tools/serve_bench.py --check           # CI gate

3. **The per-replica decode path** (``--decode``, ISSUE 9): a
   deterministic counter benchmark of the paged KV cache, prefix
   reuse, and speculative lockstep decode on the tiny test
   transformer — dense-vs-paged concurrency at the same cache bytes,
   prefill tokens saved by the prefix cache, tokens per target
   forward under speculation, all token-identical across arms. Banked
   as BENCH_SERVE_r02.json; ``--check`` gates BOTH banks.

     python tools/serve_bench.py --decode          # run + bank r02

4. **Request-level resilience** (``--resilience``, ISSUE 14): the same
   deterministic virtual-time harness pointed at the resilience layer —
   three stub replicas, a 1-of-3 BROWNOUT (10x slower, not dead) with an
   overload wave inside it, then a flapping replica. Two arms share one
   seeded trace of banded requests with a 4s deadline: ``resilient``
   (deadlines + hedging + breakers + band shedding on) vs ``control``
   (resilience=None — the legacy router; goodput still judged against
   the same deadline). Banked as BENCH_SERVE_r03.json; ``--check``
   gates critical-band goodput during the brownout, hedge rescues, the
   breaker round-trip, the decision fingerprint, and the zero-KV-leak
   cancel drill.

     python tools/serve_bench.py --resilience      # run + bank r03
"""

from __future__ import annotations

import argparse
import heapq
import json
import math
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROUTER_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_SERVE_r01.json")
DECODE_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_SERVE_r02.json")
RESILIENCE_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_SERVE_r03.json")


def run_mode(mode: str, args) -> dict:
    from kubeflow_tpu.serving.server import serve_lm_generator

    served = serve_lm_generator(
        "bench", args.model, prompt_len=args.prompt_len,
        max_new_tokens=args.max_new_tokens,
        continuous_batching=(mode == "continuous"),
        decode_slots=args.slots,
        **({"kv_pages": args.kv_pages, "kv_page_size": args.kv_page_size}
           if args.kv_pages and mode == "continuous" else {}),
        batch_window_ms=(args.window_ms if mode == "micro" else 0.0),
        param_dtype=args.param_dtype or None,
        mesh=args.mesh or None,
        vocab_size=args.vocab_size,
        **({"kv_cache_dtype": args.kv_cache_dtype}
           if args.kv_cache_dtype else {}),
        **({"attention_window": args.attention_window}
           if args.attention_window else {}),
        **({"rolling_kv_cache": True} if args.rolling_kv_cache else {}))
    try:
        rng = __import__("random").Random(0)
        prompts = [[rng.randrange(1, args.vocab_size)
                    for _ in range(rng.randrange(4, args.prompt_len))]
                   for _ in range(args.requests)]
        # warmup: compile every program the measured window can hit —
        # micro-batching dispatches pow2-padded GROUPS, so warm each
        # pow2 batch size up to the concurrency cap (otherwise first-
        # compile latencies pollute the percentiles)
        k = 1
        while k <= max(1, args.concurrency):
            served.predict([{"tokens": prompts[i % len(prompts)]}
                            for i in range(k)])
            k *= 2

        latencies: list[float] = []
        lat_lock = threading.Lock()
        sem = threading.Semaphore(args.concurrency)
        threads = []

        def one(p):
            t0 = time.perf_counter()
            served.predict([{"tokens": p}])
            dt = time.perf_counter() - t0
            with lat_lock:
                latencies.append(dt)
            sem.release()

        t_start = time.perf_counter()
        for p in prompts:
            sem.acquire()  # closed-loop at `concurrency` outstanding
            th = threading.Thread(target=one, args=(p,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        wall = time.perf_counter() - t_start
        latencies.sort()

        def pct(q):
            return round(
                latencies[min(len(latencies) - 1,
                              int(q * len(latencies)))] * 1e3, 1)

        return {
            "mode": mode,
            "requests": args.requests,
            "concurrency": args.concurrency,
            "slots": args.slots,
            "tokens_per_sec": round(
                args.requests * args.max_new_tokens / wall, 1),
            "requests_per_sec": round(args.requests / wall, 2),
            "p50_ms": pct(0.50),
            "p95_ms": pct(0.95),
            "p99_ms": pct(0.99),
            "wall_s": round(wall, 2),
            "model": args.model,
            "max_new_tokens": args.max_new_tokens,
            "param_dtype": args.param_dtype or "f32",
            **({"kv_cache_dtype": args.kv_cache_dtype}
               if args.kv_cache_dtype else {}),
            **({"attention_window": args.attention_window,
                "rolling_kv_cache": bool(args.rolling_kv_cache)}
               if args.attention_window else {}),
        }
    finally:
        served.close()


# ---------------------------------------------------------------------------
# The deterministic per-replica decode benchmark (--decode / --check,
# ISSUE 9): dense-vs-paged KV cache density, prefix-cache prefill
# savings, greedy-vs-speculative tokens per target forward — all on the
# tiny test transformer with seeded prompts, so every claim is a
# COUNTER (array shapes, allocator stats, prefill/accept totals) that
# replays identically per seed. CPU wall seconds are banked alongside
# for context but never gated (the TPU backend is unavailable in this
# image; ROADMAP bench policy).


DECODE_CONFIG = {
    "seed": 0,
    "model": "transformer-test",
    "vocab_size": 64,
    "prompt_len": 32,          # 4 full pages of prompt
    "max_new_tokens": 16,      # server-wide ceiling
    "req_new": 8,              # per-request budget (density arms)
    "page_size": 8,
    "dense_slots": 4,
    "paged_slots": 8,
    "requests": 8,
    "shared_prefix": 24,       # 3 pages shared across all 8 prompts
    "draft_k": 4,
    "spec_requests": 4,
}


def _decode_prompts(cfg: dict, rng: random.Random) -> list[list[int]]:
    """Full-length (no padding) prompts sharing a page-aligned system
    prefix — the workload the prefix cache exists for."""
    pre = [rng.randrange(1, cfg["vocab_size"])
           for _ in range(cfg["shared_prefix"])]
    tail = cfg["prompt_len"] - cfg["shared_prefix"]
    return [pre + [rng.randrange(1, cfg["vocab_size"]) for _ in range(tail)]
            for _ in range(cfg["requests"])]


def _drive_burst(dec, prompts, max_new) -> tuple[list, float]:
    """Queue every request while admission is held, then release: the
    decoder sees one deterministic FIFO burst (admission order == list
    order), which pins prefix-hit and peak-concurrency counters."""
    results: list = [None] * len(prompts)
    held, dec._free = dec._free, []
    threads = [threading.Thread(
        target=lambda i=i: results.__setitem__(
            i, dec.submit(prompts[i], max_new)))
        for i in range(len(prompts))]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    time.sleep(0.4)  # queue fully populated while no slot is "free"
    dec._free = held
    dec._wake.set()
    for th in threads:
        th.join()
    return results, time.perf_counter() - t0


def _arm_stats(dec, wall: float) -> dict:
    keep = ("admitted", "completed", "peak_active",
            "prefill_tokens_computed", "prompt_tokens_submitted",
            "cache_bytes", "spec_rounds", "spec_tokens_emitted",
            "spec_tokens_accepted", "spec_drafted", "kv_pages_total",
            "kv_page_size", "prefix_hit_pages", "prefix_hit_tokens",
            "cow_clones", "mode")
    st = dec.stats()
    out = {k: st[k] for k in keep if k in st}
    out["wall_s"] = round(wall, 2)
    return out


def run_decode_bench(cfg: dict) -> dict:
    import hashlib

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized (tests force cpu themselves)
    import numpy as np

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.serving.continuous import SlotDecoder

    P, N, PS = cfg["prompt_len"], cfg["max_new_tokens"], cfg["page_size"]
    dense_seq = P + N
    # SAME cache-byte budget by construction: pool positions (pages x
    # page_size, trash page included) == dense positions (slots x P+N)
    kv_pages = cfg["dense_slots"] * dense_seq // PS
    rng = random.Random(cfg["seed"])
    prompts = _decode_prompts(cfg, rng)

    dense_m = get_model(cfg["model"], vocab_size=cfg["vocab_size"],
                        max_seq_len=dense_seq)
    variables = dense_m.init(jax.random.PRNGKey(cfg["seed"]),
                             np.zeros((1, 1), np.int32), train=False)

    # -- density: dense S_d slots vs paged pool at the same bytes ------
    dd = SlotDecoder(dense_m, variables, slots=cfg["dense_slots"],
                     prompt_len=P, max_new_tokens=N)
    try:
        dense_out, dense_wall = _drive_burst(dd, prompts, cfg["req_new"])
        dense = _arm_stats(dd, dense_wall)
    finally:
        dd.close()
    paged_m = get_model(cfg["model"], vocab_size=cfg["vocab_size"],
                        max_seq_len=dense_seq, kv_pages=kv_pages,
                        kv_page_size=PS)
    pd = SlotDecoder(paged_m, variables, slots=cfg["paged_slots"],
                     prompt_len=P, max_new_tokens=N)
    try:
        paged_out, paged_wall = _drive_burst(pd, prompts, cfg["req_new"])
        paged = _arm_stats(pd, paged_wall)
    finally:
        pd.close()

    # -- prefix reuse: the same paged pool with the cache disabled -----
    po = SlotDecoder(paged_m, variables, slots=cfg["paged_slots"],
                     prompt_len=P, max_new_tokens=N, prefix_cache=False)
    try:
        off_out, off_wall = _drive_burst(po, prompts, cfg["req_new"])
        off = _arm_stats(po, off_wall)
    finally:
        po.close()

    # -- speculative lockstep: draft == target weights (a perfectly
    #    agreeing draft — the tokens-per-forward ceiling) vs greedy ----
    k = cfg["draft_k"]
    spec_m = get_model(cfg["model"], vocab_size=cfg["vocab_size"],
                       max_seq_len=P + N + k)
    sprompts = prompts[:cfg["spec_requests"]]
    gd = SlotDecoder(spec_m, variables, slots=cfg["spec_requests"],
                     prompt_len=P, max_new_tokens=N)
    try:
        greedy_out, greedy_wall = _drive_burst(gd, sprompts, N)
        greedy = _arm_stats(gd, greedy_wall)
    finally:
        gd.close()
    sd = SlotDecoder(spec_m, variables, slots=cfg["spec_requests"],
                     prompt_len=P, max_new_tokens=N,
                     draft_model=spec_m, draft_variables=variables,
                     draft_k=k)
    try:
        spec_out, spec_wall = _drive_burst(sd, sprompts, N)
        spec = _arm_stats(sd, spec_wall)
    finally:
        sd.close()

    fingerprint = hashlib.sha256(json.dumps(
        [dense_out, paged_out, off_out, greedy_out, spec_out],
        sort_keys=True).encode()).hexdigest()
    tokens_per_forward = (spec["spec_tokens_emitted"]
                          / max(spec["spec_rounds"], 1))
    saving_pct = round(100.0 * (1 - paged["prefill_tokens_computed"]
                                / max(off["prefill_tokens_computed"], 1)), 1)
    return {
        "config": dict(cfg),
        "density": {
            "dense": dense, "paged": paged,
            "identical_tokens": paged_out == dense_out,
            "same_cache_bytes":
                paged["cache_bytes"] == dense["cache_bytes"],
            "concurrency_x": round(paged["peak_active"]
                                   / max(dense["peak_active"], 1), 2),
        },
        "prefix": {
            "off": off,
            "identical_tokens": off_out == paged_out,
            "prefill_tokens_with_cache": paged["prefill_tokens_computed"],
            "prefill_tokens_without": off["prefill_tokens_computed"],
            "saving_pct": saving_pct,
        },
        "speculative": {
            "greedy": greedy, "spec": spec,
            "identical_tokens": spec_out == greedy_out,
            "tokens_per_forward": round(tokens_per_forward, 2),
        },
        "fingerprint": fingerprint,
    }


def check_decode_bench(banked_path: str) -> int:
    """CI ratchet over BENCH_SERVE_r02: rerun the banked config and
    fail on any broken invariant (tokens diverging between arms, the
    paged pool admitting < 2x dense at the same bytes, prefix savings
    below 40%, speculative <= 1 token per target forward) or on a
    changed deterministic fingerprint."""
    with open(banked_path) as fh:
        banked = json.load(fh)
    section = banked.get("decode")
    if not section:
        print(f"check: no decode section in {banked_path}", file=sys.stderr)
        return 2
    now = run_decode_bench(dict(section["config"]))
    ok = True
    if not (now["density"]["identical_tokens"]
            and now["prefix"]["identical_tokens"]
            and now["speculative"]["identical_tokens"]):
        print("check: decode regression — arms no longer token-identical",
              file=sys.stderr)
        ok = False
    if not now["density"]["same_cache_bytes"]:
        print("check: decode regression — cache byte budgets diverged",
              file=sys.stderr)
        ok = False
    if now["density"]["concurrency_x"] < 2.0:
        print(f"check: decode regression — paged admits only "
              f"{now['density']['concurrency_x']}x dense (< 2x)",
              file=sys.stderr)
        ok = False
    if now["prefix"]["saving_pct"] < 40.0:
        print(f"check: decode regression — prefix cache saves only "
              f"{now['prefix']['saving_pct']}% prefill tokens (< 40%)",
              file=sys.stderr)
        ok = False
    if now["speculative"]["tokens_per_forward"] <= 1.0:
        print("check: decode regression — speculative emits <= 1 token "
              "per target forward", file=sys.stderr)
        ok = False
    if now["fingerprint"] != section["fingerprint"]:
        print("check: decode regression — deterministic token "
              "fingerprint diverged from the bank", file=sys.stderr)
        ok = False
    print(json.dumps({"check": "ok" if ok else "REGRESSED",
                      "concurrency_x": now["density"]["concurrency_x"],
                      "saving_pct": now["prefix"]["saving_pct"],
                      "tokens_per_forward":
                          now["speculative"]["tokens_per_forward"]},
                     indent=2))
    return 0 if ok else 1


def decode_main(args) -> int:
    if args.check:
        return check_decode_bench(args.decode_out)
    cfg = dict(DECODE_CONFIG)
    cfg["seed"] = args.seed
    result = {"bench": "serve_bench", "round": "r02",
              "decode": run_decode_bench(cfg)}
    with open(args.decode_out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=True)
        fh.write("\n")
    d = result["decode"]
    print(json.dumps({"out": args.decode_out,
                      "concurrency_x": d["density"]["concurrency_x"],
                      "saving_pct": d["prefix"]["saving_pct"],
                      "tokens_per_forward":
                          d["speculative"]["tokens_per_forward"],
                      "identical": d["density"]["identical_tokens"]
                      and d["prefix"]["identical_tokens"]
                      and d["speculative"]["identical_tokens"]},
                     indent=2))
    return 0


# ---------------------------------------------------------------------------
# The deterministic serving-plane benchmark (--router / --check)


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance_to(self, t: float) -> None:
        self.t = max(self.t, t)


# Virtual-time workload: (duration_s, arrivals_per_s) phases — a ramp
# from a trickle into ~3x one replica's capacity (30 req/s x ~64 tokens
# ~= 1900 tokens/s vs 600), then a lull so the scale-down half of the
# cycle runs inside the measured window. The single arm queues the
# whole overload and drains it for ~45 extra virtual seconds; the multi
# arm scales to 4 and absorbs it.
PHASES = ((5.0, 2.0), (20.0, 30.0), (25.0, 1.0))
ROUTER_CONFIG = {
    "seed": 0,
    "tokens_lo": 32, "tokens_hi": 96,      # per-request new tokens
    "replica_tokens_per_sec": 600.0,       # stub service rate
    "replica_token_budget": 256,           # router queues beyond this
    "max_queue": 2048,
    "max_replicas": 4,
    "target_queue_depth": 4,
    "target_tokens_per_sec": 450.0,
    "up_stabilization_s": 1.0,
    "down_stabilization_s": 8.0,
    "control_tick_s": 0.25,                # reconcile + endpoint sync cadence
    "kill_at_s": 15.0,                     # multi arm: replica-1 dies here
}


def build_trace(cfg: dict, rng: random.Random) -> list[tuple[float, int]]:
    """Seeded open-loop arrival trace: (time, tokens) per request."""
    out = []
    t = 0.0
    for duration, rate in PHASES:
        end = t + duration
        while True:
            t += rng.expovariate(rate)
            if t >= end:
                t = end
                break
            out.append((t, rng.randrange(cfg["tokens_lo"],
                                         cfg["tokens_hi"])))
    return out


def run_router_arm(arm: str, cfg: dict) -> dict:
    """One virtual-time run: the REAL JAXService controller against a
    FakeCluster, the REAL token router, stub replicas modeled as
    fixed-rate FIFO servers. Single-threaded event loop — every
    transition is an explicit call, so decisions replay per seed."""
    from kubeflow_tpu.control.jaxservice import types as T
    from kubeflow_tpu.control.jaxservice.controller import build_controller
    from kubeflow_tpu.control.k8s import objects as ob
    from kubeflow_tpu.control.k8s.fake import FakeCluster
    from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
    from kubeflow_tpu.control.runtime import seed_controller
    from kubeflow_tpu.runtime.metrics import MetricsRegistry
    from kubeflow_tpu.serving.router import (
        RegistrySignals, RouterBusy, TokenRouter, parse_endpoints,
    )

    rng = random.Random(cfg["seed"])
    trace = build_trace(cfg, rng)
    clock = ManualClock()
    cluster = FakeCluster(history_limit=65536)
    registry = MetricsRegistry()
    signals = RegistrySignals(registry)
    ctl = seed_controller(build_controller(
        cluster, record_events=False, registry=registry, signals=signals,
        clock=clock))
    kubelet = FakeKubelet(cluster)
    max_replicas = 1 if arm == "single" else cfg["max_replicas"]
    cluster.create(T.new_jaxservice(
        "bench", model="gpt-125m", min_replicas=1,
        max_replicas=max_replicas,
        target_queue_depth=cfg["target_queue_depth"],
        target_tokens_per_sec=cfg["target_tokens_per_sec"],
        up_stabilization_s=cfg["up_stabilization_s"],
        down_stabilization_s=cfg["down_stabilization_s"]))
    router = TokenRouter(
        service="bench", namespace="default", clock=clock,
        registry=registry, prom_sink=False,
        max_queue=cfg["max_queue"],
        replica_token_budget=cfg["replica_token_budget"])

    free_at: dict[str, float] = {}
    seq: dict[int, int] = {}          # ticket id -> dispatch generation
    events: list[tuple] = []          # (due, order, kind, payload)
    order = [0]

    def push(due: float, kind: str, payload) -> None:
        order[0] += 1
        heapq.heappush(events, (due, order[0], kind, payload))

    def schedule(ticket) -> None:
        name = ticket.member.name
        due = max(clock.t, free_at.get(name, 0.0)) \
            + ticket.tokens / cfg["replica_tokens_per_sec"]
        free_at[name] = due
        seq[id(ticket)] = seq.get(id(ticket), 0) + 1
        push(due, "complete", (ticket, name, seq[id(ticket)]))

    latencies: list[float] = []
    tokens_done = 0
    # peak-demand window (the overload phase): where capacity, not the
    # workload, bounds throughput — the multi-vs-single scaling claim
    ramp_start = PHASES[0][0]
    ramp_end = ramp_start + PHASES[1][0]
    ramp_tokens = 0
    completed = rejected = shed_redispatches = 0
    decisions: list[list] = []
    kill_done = {"t": None, "restart_seen": False}

    def control_tick() -> None:
        nonlocal shed_redispatches
        for _ in range(4):
            if ctl.run_until_idle(max_rounds=1000,
                                  advance_delayed=True) == 0:
                break
            kubelet.step()
        svc = cluster.get(T.API_VERSION, T.KIND, "bench", "default")
        target = (svc.get("status") or {}).get("targetReplicas", 1)
        if not decisions or decisions[-1][1] != target:
            # a list, not a tuple: the fingerprint must compare equal
            # after a JSON round-trip through the banked file
            decisions.append([round(clock.t, 2), target])
        eps = parse_endpoints(svc)
        live = {e["name"] for e in eps}
        for name in list(free_at):
            if name not in live:
                free_at.pop(name)
        redispatched = router.sync_endpoints(eps)
        shed_redispatches += len(redispatched)
        for t in redispatched:
            schedule(t)
        if (svc.get("status") or {}).get("restarts", 0) > 0:
            kill_done["restart_seen"] = True

    def kill_replica() -> None:
        pod = cluster.get_or_none("v1", "Pod", "bench-replica-1",
                                  "default")
        if pod is None:
            return
        pod.setdefault("status", {})["phase"] = "Failed"
        pod["status"]["reason"] = "Evicted"
        cluster.update_status(pod)
        free_at.pop("bench-replica-1", None)
        kill_done["t"] = clock.t

    # seed the event heap
    for t_arr, tokens in trace:
        push(t_arr, "arrive", tokens)
    tick = 0.0
    horizon = sum(d for d, _ in PHASES) + 120.0
    while tick < horizon:
        push(tick, "tick", None)
        tick += cfg["control_tick_s"]
    if arm == "multi":
        push(cfg["kill_at_s"], "kill", None)

    submitted: dict[int, float] = {}  # ticket id -> arrival time
    pending = len(trace)
    while events:
        due, _, kind, payload = heapq.heappop(events)
        clock.advance_to(due)
        if kind == "tick":
            control_tick()
            if pending == 0 and router.queue_depth() == 0 \
                    and router.inflight_tokens() == 0:
                # drained: let the scale-down tail keep running a bit,
                # then stop once no completion events remain
                if not any(k == "complete" for _, _, k, _ in events):
                    break
        elif kind == "arrive":
            try:
                t = router.submit(payload)
            except RouterBusy:
                rejected += 1
                pending -= 1
                continue
            submitted[id(t)] = clock.t
            if t.member is not None:
                schedule(t)
        elif kind == "kill":
            kill_replica()
        elif kind == "complete":
            ticket, name, gen = payload
            if ticket.member is None or ticket.member.name != name \
                    or seq.get(id(ticket)) != gen:
                continue  # stale: the ticket was shed and rescheduled
            latencies.append(clock.t - submitted.pop(id(ticket), clock.t))
            tokens_done += ticket.tokens
            if ramp_start <= clock.t <= ramp_end:
                ramp_tokens += ticket.tokens
            completed += 1
            pending -= 1
            for t in router.complete(ticket):
                schedule(t)

    svc = cluster.get(T.API_VERSION, T.KIND, "bench", "default")
    status = svc.get("status") or {}
    latencies.sort()

    def pct(q: float) -> float:
        if not latencies:
            return 0.0
        return round(latencies[min(len(latencies) - 1,
                                   int(q * len(latencies)))], 3)

    dropped = len(trace) - completed - rejected
    return {
        "arm": arm,
        "requests": len(trace),
        "completed": completed,
        "rejected": rejected,
        "dropped": dropped,
        "tokens_done": tokens_done,
        "virtual_makespan_s": round(clock.t, 2),
        "tokens_per_sec": round(tokens_done / clock.t, 1) if clock.t else 0,
        "peak_tokens_per_sec": round(
            ramp_tokens / (ramp_end - ramp_start), 1),
        "p50_s": pct(0.50),
        "p95_s": pct(0.95),
        "p99_s": pct(0.99),
        "max_target": max((t for _, t in decisions), default=1),
        "final_target": decisions[-1][1] if decisions else 1,
        "scales": status.get("scales", 0),
        "replica_restarts": status.get("restarts", 0),
        "shed_redispatches": shed_redispatches,
        "kill_at_s": kill_done["t"],
        "decisions": decisions,
    }


def run_router_bench(cfg: dict) -> dict:
    single = run_router_arm("single", cfg)
    multi = run_router_arm("multi", cfg)
    replay = run_router_arm("multi", cfg)  # determinism self-check
    identical = (multi["decisions"] == replay["decisions"]
                 and multi["tokens_done"] == replay["tokens_done"]
                 and multi["p95_s"] == replay["p95_s"])
    return {
        "config": dict(cfg),
        "single": single,
        "multi": multi,
        "comparison": {
            "tokens_per_sec_x": round(
                multi["tokens_per_sec"]
                / max(single["tokens_per_sec"], 1e-9), 2),
            "peak_tokens_per_sec_x": round(
                multi["peak_tokens_per_sec"]
                / max(single["peak_tokens_per_sec"], 1e-9), 2),
            "p95_speedup_x": round(
                single["p95_s"] / max(multi["p95_s"], 1e-9), 2),
            "zero_dropped": single["dropped"] == 0
            and multi["dropped"] == 0,
            "kill_drill_survived": multi["replica_restarts"] >= 1
            and multi["dropped"] == 0,
            "scale_cycle_complete": multi["max_target"] > 1
            and multi["final_target"] < multi["max_target"],
            "decisions_replay_identical": identical,
        },
    }


def check_router_bench(banked_path: str) -> int:
    """CI ratchet: rerun the banked config; fail on any dropped
    request, a broken drill, a changed decision fingerprint, or
    multi-arm throughput below 75% of the banked number."""
    with open(banked_path) as fh:
        banked = json.load(fh)
    section = banked.get("router")
    if not section:
        print(f"check: no router section in {banked_path}",
              file=sys.stderr)
        return 2
    now = run_router_bench(dict(section["config"]))
    ok = True
    cmp_ = now["comparison"]
    if not cmp_["zero_dropped"] or not cmp_["kill_drill_survived"]:
        print("check: drill regression — dropped requests or the kill "
              "drill failed", file=sys.stderr)
        ok = False
    if not cmp_["decisions_replay_identical"]:
        print("check: determinism regression — same-seed replay "
              "diverged", file=sys.stderr)
        ok = False
    if now["multi"]["decisions"] != section["multi"]["decisions"]:
        print("check: autoscaling decisions diverged from the banked "
              "fingerprint", file=sys.stderr)
        ok = False
    floor = section["multi"]["tokens_per_sec"] * 0.75
    if now["multi"]["tokens_per_sec"] < floor:
        print(f"check: multi tokens_per_sec "
              f"{now['multi']['tokens_per_sec']} below budget "
              f"{floor:.1f} (banked "
              f"{section['multi']['tokens_per_sec']})", file=sys.stderr)
        ok = False
    print(json.dumps({"check": "ok" if ok else "REGRESSED",
                      "multi_tokens_per_sec":
                          now["multi"]["tokens_per_sec"],
                      "comparison": cmp_}, indent=2))
    return 0 if ok else 1


def router_main(args) -> int:
    if args.check:
        return check_router_bench(args.out)
    cfg = dict(ROUTER_CONFIG)
    cfg["seed"] = args.seed
    result = {"bench": "serve_bench", "round": "r01",
              "router": run_router_bench(cfg)}
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps({"out": args.out,
                      "comparison": result["router"]["comparison"],
                      "single_tokens_per_sec":
                          result["router"]["single"]["tokens_per_sec"],
                      "multi_tokens_per_sec":
                          result["router"]["multi"]["tokens_per_sec"]},
                     indent=2))
    return 0


# ---------------------------------------------------------------------------
# The deterministic resilience benchmark (--resilience / --check,
# ISSUE 14): router core only — no controller, membership is static.
# Three stub replicas modeled as fixed-rate FIFO servers on the manual
# clock; the drills are a brownout (slow, not dead), an overload wave
# inside it, and a fail-fast flap. Every router decision (sheds,
# deadline drops, hedges, breaker transitions) is tapped via
# on_decision and fingerprinted, so the whole run replays byte-identical
# per seed.


# (start_s, end_s, arrivals_per_s) — warmup builds the latency samples
# hedging needs, then the brownout window [6, 30) holds an overload
# wave [8, 28), then the flap window [30, 36) and a cooldown tail.
RES_PHASES = ((0.0, 6.0, 8.0), (6.0, 8.0, 10.0), (8.0, 28.0, 40.0),
              (28.0, 30.0, 10.0), (30.0, 36.0, 8.0), (36.0, 44.0, 6.0))
RES_CONFIG = {
    "seed": 0,
    "tokens_lo": 32, "tokens_hi": 96,
    "replica_tokens_per_sec": 600.0,
    "replica_token_budget": 256,
    "max_queue": 24,
    "replicas": 3,
    "deadline_s": 4.0,
    # band mix: P(critical), P(critical)+P(default) thresholds on one
    # uniform draw per arrival
    "band_split": (0.2, 0.8),
    "brownout": (6.0, 30.0),          # r0 serves at rate/brownout_x here
    "brownout_x": 10.0,
    "brownout_replica": "r0",
    "flap": (30.0, 36.0),             # r1 fails fast here (breaker drill)
    "flap_replica": "r1",
    "fail_latency_s": 0.02,           # a fast error, not a timeout
}


def build_res_trace(cfg: dict, rng: random.Random) -> list[tuple]:
    """Seeded open-loop trace of (time, tokens, band) arrivals."""
    from kubeflow_tpu.serving.router import (
        BAND_CRITICAL, BAND_DEFAULT, BAND_SHEDDABLE,
    )

    p_crit, p_def = cfg["band_split"]
    out = []
    for start, end, rate in RES_PHASES:
        t = start
        while True:
            t += rng.expovariate(rate)
            if t >= end:
                break
            tokens = rng.randrange(cfg["tokens_lo"], cfg["tokens_hi"])
            u = rng.random()
            band = (BAND_CRITICAL if u < p_crit
                    else BAND_DEFAULT if u < p_def else BAND_SHEDDABLE)
            out.append((t, tokens, band))
    return out


def run_resilience_arm(arm: str, cfg: dict,
                       trace: list[tuple]) -> dict:
    """One virtual-time run over the shared trace. ``resilient`` turns
    the full layer on (deadlines reach the router, hedge checks fire,
    in-flight work is canceled at its deadline — modeling the replica-
    side slot cancel); ``control`` is the legacy router, with goodput
    still judged against the same per-request deadline."""
    import hashlib

    from kubeflow_tpu.serving.router import (
        BAND_RANK, Member, ResilienceConfig, RouterBusy, TokenRouter,
    )

    resilient = arm == "resilient"
    clock = ManualClock()
    decisions: list[dict] = []
    router = TokenRouter(
        service="bench", namespace="default", clock=clock,
        prom_sink=False, max_queue=cfg["max_queue"],
        replica_token_budget=cfg["replica_token_budget"],
        resilience=ResilienceConfig() if resilient else None,
        on_decision=decisions.append if resilient else None)
    names = [f"r{i}" for i in range(cfg["replicas"])]
    router.set_members([Member(name=n) for n in names])

    bo_start, bo_end = cfg["brownout"]
    fl_start, fl_end = cfg["flap"]

    def rate_of(name: str, at: float) -> float:
        r = cfg["replica_tokens_per_sec"]
        if name == cfg["brownout_replica"] and bo_start <= at < bo_end:
            return r / cfg["brownout_x"]
        return r

    free_at: dict[str, float] = {}
    seq: dict[int, int] = {}
    finished: set[int] = set()
    events: list[tuple] = []
    order = [0]
    # id(t) keys (arrivals/seq/finished) are only stable while the
    # ticket object is alive — hold every admitted ticket so CPython
    # never reuses an id mid-run (a recycled id would alias a new
    # ticket onto a finished one and silently drop its events)
    hold: list = []
    arrivals: dict[int, tuple] = {}   # ticket id -> (t_arr, band, tokens)
    done_at: dict[int, float] = {}
    per_band = {b: {"arrivals": 0, "rejected": 0} for b in BAND_RANK}
    hedge_wins = 0
    deadline_cancels = 0

    def push(due: float, kind: str, payload) -> None:
        order[0] += 1
        heapq.heappush(events, (due, order[0], kind, payload))

    def svc_time(name: str, tokens: int, at: float) -> float:
        return tokens / rate_of(name, at)

    def on_dispatch(t) -> None:
        """Model the dispatched leg: a flapping replica errors fast;
        everyone else serves FIFO at its current rate. The resilient arm
        also arms the deadline cancel and the hedge check."""
        name = t.member.name
        now = clock.t
        seq[id(t)] = seq.get(id(t), 0) + 1
        gen = seq[id(t)]
        if name == cfg["flap_replica"] and fl_start <= now < fl_end:
            push(now + cfg["fail_latency_s"], "fail", (t, name, gen))
            return
        svc = svc_time(name, t.tokens, now)
        due = max(now, free_at.get(name, 0.0)) + svc
        free_at[name] = due
        if resilient and t.deadline is not None and due > t.deadline:
            # the replica cancels the slot AT the deadline (frees its
            # pages); the leg never produces a completion
            push(t.deadline, "cancel", (t, name, gen, svc))
            delay = router.hedge_delay()
            if delay is not None and now + delay < t.deadline:
                push(now + delay, "hedge", (t, name, gen))
            return
        push(due, "complete", (t, name, gen, svc))
        if resilient:
            delay = router.hedge_delay()
            if delay is not None and now + delay < due \
                    and (t.deadline is None or now + delay < t.deadline):
                push(now + delay, "hedge", (t, name, gen))

    def refund(name: str, svc: float) -> None:
        """A canceled leg frees its replica early (the slot-cancel /
        hedge-loser path): pull the FIFO horizon back by its share."""
        if name in free_at:
            free_at[name] = max(clock.t, free_at[name] - svc)

    for t_arr, tokens, band in trace:
        push(t_arr, "arrive", (tokens, band))

    while events:
        due, _, kind, payload = heapq.heappop(events)
        clock.advance_to(due)
        if kind == "arrive":
            tokens, band = payload
            per_band[band]["arrivals"] += 1
            try:
                if resilient:
                    t = router.submit(
                        tokens, band=band,
                        deadline=clock.t + cfg["deadline_s"])
                else:
                    t = router.submit(tokens)
            except RouterBusy:
                per_band[band]["rejected"] += 1
                continue
            hold.append(t)
            arrivals[id(t)] = (clock.t, band, tokens)
            if t.member is not None:
                on_dispatch(t)
        elif kind == "complete":
            t, name, gen, svc = payload
            if id(t) in finished or seq.get(id(t)) != gen \
                    or t.member is None or t.member.name != name:
                continue
            finished.add(id(t))
            done_at[id(t)] = clock.t
            if t.hedge_member is not None:
                refund(t.hedge_member.name, svc_time(
                    t.hedge_member.name, t.tokens, t._hedge_at))
            for nt in router.complete(t):
                on_dispatch(nt)
        elif kind == "hcomplete":
            t, hname, svc = payload
            if id(t) in finished or t.hedge_member is None \
                    or t.hedge_member.name != hname:
                continue
            finished.add(id(t))
            done_at[id(t)] = clock.t
            hedge_wins += 1
            if t.member is not None:
                refund(t.member.name, svc_time(
                    t.member.name, t.tokens, t._dispatched_at))
            for nt in router.complete(t, winner=hname):
                on_dispatch(nt)
        elif kind == "hedge":
            t, name, gen = payload
            if id(t) in finished or seq.get(id(t)) != gen \
                    or t.member is None or t.member.name != name:
                continue
            m = router.try_hedge(t)
            if m is None:
                continue
            svc = svc_time(m.name, t.tokens, clock.t)
            hdue = max(clock.t, free_at.get(m.name, 0.0)) + svc
            free_at[m.name] = hdue
            if t.deadline is None or hdue <= t.deadline:
                push(hdue, "hcomplete", (t, m.name, svc))
            else:
                push(t.deadline, "hcancel", (t, m.name, svc))
        elif kind == "hcancel":
            t, hname, svc = payload
            if id(t) in finished or t.hedge_member is None \
                    or t.hedge_member.name != hname:
                continue
            refund(hname, svc)
        elif kind == "cancel":
            t, name, gen, svc = payload
            if id(t) in finished or seq.get(id(t)) != gen \
                    or t.member is None or t.member.name != name:
                continue
            finished.add(id(t))
            deadline_cancels += 1
            refund(name, svc)
            if t.hedge_member is not None:
                refund(t.hedge_member.name, svc_time(
                    t.hedge_member.name, t.tokens, t._hedge_at))
            # fail() sees the elapsed deadline and drops with
            # dropped_reason="deadline" (the shell's 504)
            for nt in router.fail(t, requeue=True):
                on_dispatch(nt)
        elif kind == "fail":
            t, name, gen = payload
            if id(t) in finished or seq.get(id(t)) != gen \
                    or t.member is None or t.member.name != name:
                continue
            for nt in router.fail(t, requeue=True):
                on_dispatch(nt)
            if t.member is None and t.dropped_reason is not None:
                finished.add(id(t))

    # goodput per band over the brownout-window arrivals: completed
    # within the deadline / arrived, resilience on or off
    goodput = {}
    for band in BAND_RANK:
        window = [tid for tid, (ta, b, _tok) in arrivals.items()
                  if b == band and bo_start <= ta < bo_end]
        hits = sum(1 for tid in window
                   if tid in done_at
                   and done_at[tid] - arrivals[tid][0] <= cfg["deadline_s"])
        total = sum(1 for t_arr, _tok, b in trace
                    if b == band and bo_start <= t_arr < bo_end)
        goodput[band] = round(hits / total, 4) if total else 1.0
    fingerprint = hashlib.sha256(json.dumps(
        decisions, sort_keys=True).encode()).hexdigest()
    breaker_kinds = [d for d in decisions if d["kind"] == "breaker"]
    completed = len(done_at)
    return {
        "arm": arm,
        "requests": len(trace),
        "completed": completed,
        "rejected": {b: per_band[b]["rejected"] for b in per_band},
        "arrivals": {b: per_band[b]["arrivals"] for b in per_band},
        "brownout_goodput": goodput,
        "hedge_wins": hedge_wins,
        "deadline_cancels": deadline_cancels,
        "sheds": {b: sum(1 for d in decisions
                         if d["kind"] == "shed" and d.get("band") == b)
                  for b in BAND_RANK},
        "deadline_drops": sum(
            1 for d in decisions if d["kind"] == "deadline"),
        "breaker_opened": any(d.get("state") == "open"
                              for d in breaker_kinds),
        "breaker_reclosed": any(d.get("state") == "closed"
                                for d in breaker_kinds),
        "decisions": len(decisions),
        "decision_fingerprint": fingerprint,
        "virtual_makespan_s": round(clock.t, 2),
    }


def run_kv_cancel_drill(seed: int) -> dict:
    """Host-only proof of the zero-leak contract: drive a PageAllocator
    through admit / append / mid-flight frees (the deadline-cancel and
    hedge-loser paths) and assert the refcount invariant plus a fully
    recovered freelist. No jax involved — this is the allocator the
    slot decoder's ``_cancel_slot`` calls ``free()`` on."""
    from kubeflow_tpu.runtime.kvcache import PageAllocator

    rng = random.Random(seed)
    page, slots = 8, 8
    # prefix_cache off: the LRU prefix index legitimately retains
    # prompt pages across frees, which is reuse — not the leak this
    # drill exists to catch on the cancel path
    alloc = PageAllocator(num_pages=64, page_size=page, slots=slots,
                          max_pages_per_slot=12, prefix_cache=False)
    live: dict[int, tuple[int, int]] = {}   # slot -> (position, total)
    frees = admits = 0
    for step in range(400):
        op = rng.random()
        free_slots = [s for s in range(slots) if s not in live]
        if op < 0.5 and free_slots:
            row = [rng.randrange(1, 50) for _ in range(32)]
            total = 32 + rng.randrange(8, 33)
            if alloc.can_admit(row, 0, total):
                s = free_slots[0]
                alloc.admit(s, row, 0, total)
                live[s] = (32, total)
                admits += 1
        elif op < 0.8 and live:
            s = sorted(live)[rng.randrange(len(live))]
            pos, total = live[s]
            pos = min(pos + rng.randrange(1, 9), total)
            live[s] = (pos, total)
            alloc.append(s, pos)
        elif live:
            # the cancel path: a deadline or a lost hedge frees the
            # slot MID-GENERATION, pages and all
            s = sorted(live)[rng.randrange(len(live))]
            alloc.free(s)
            live.pop(s)
            frees += 1
        alloc.check()
    for s in list(live):
        alloc.free(s)
    alloc.check()
    clean = alloc.free_pages == alloc.num_pages - 1  # page 0 is trash
    return {"admits": admits, "mid_flight_frees": frees,
            "pages_recovered": clean, "invariant_clean": True}


def run_resilience_bench(cfg: dict) -> dict:
    rng = random.Random(cfg["seed"])
    trace = build_res_trace(cfg, rng)
    resilient = run_resilience_arm("resilient", cfg, trace)
    control = run_resilience_arm("control", cfg, trace)
    replay = run_resilience_arm("resilient", cfg, trace)
    return {
        "config": dict(cfg),
        "resilient": resilient,
        "control": control,
        "kv_drill": run_kv_cancel_drill(cfg["seed"]),
        "comparison": {
            "critical_goodput_resilient":
                resilient["brownout_goodput"]["critical"],
            "critical_goodput_control":
                control["brownout_goodput"]["critical"],
            "hedge_wins": resilient["hedge_wins"],
            "critical_sheds": resilient["sheds"].get("critical", 0)
            if resilient["sheds"] else 0,
            "breaker_round_trip": resilient["breaker_opened"]
            and resilient["breaker_reclosed"],
            "replay_identical":
                resilient["decision_fingerprint"]
                == replay["decision_fingerprint"]
                and resilient["completed"] == replay["completed"],
        },
    }


def check_resilience_bench(banked_path: str) -> int:
    """CI ratchet over BENCH_SERVE_r03: rerun the banked config; fail
    when the resilience layer stops earning its keep — critical-band
    goodput through the brownout below 90% (or the control arm NOT
    degrading, which means the drill lost its teeth), zero hedge
    rescues, a critical-band shed, a broken breaker round-trip, a
    decision-fingerprint change, or a KV page leak in the cancel
    drill."""
    with open(banked_path) as fh:
        banked = json.load(fh)
    section = banked.get("resilience")
    if not section:
        print(f"check: no resilience section in {banked_path}",
              file=sys.stderr)
        return 2
    now = run_resilience_bench(dict(section["config"]))
    ok = True
    cmp_ = now["comparison"]
    if cmp_["critical_goodput_resilient"] < 0.9:
        print(f"check: resilience regression — critical goodput "
              f"{cmp_['critical_goodput_resilient']} < 0.9 through the "
              "brownout", file=sys.stderr)
        ok = False
    if cmp_["critical_goodput_control"] >= 0.7:
        print(f"check: drill regression — the control arm no longer "
              f"degrades ({cmp_['critical_goodput_control']} >= 0.7); "
              "the brownout drill lost its teeth", file=sys.stderr)
        ok = False
    if cmp_["hedge_wins"] < 1:
        print("check: resilience regression — zero hedge rescues",
              file=sys.stderr)
        ok = False
    if cmp_["critical_sheds"] != 0:
        print(f"check: resilience regression — "
              f"{cmp_['critical_sheds']} critical-band requests shed",
              file=sys.stderr)
        ok = False
    if not cmp_["breaker_round_trip"]:
        print("check: resilience regression — breaker never completed "
              "open -> half-open -> closed", file=sys.stderr)
        ok = False
    if not cmp_["replay_identical"]:
        print("check: determinism regression — same-seed replay "
              "diverged", file=sys.stderr)
        ok = False
    if now["resilient"]["decision_fingerprint"] \
            != section["resilient"]["decision_fingerprint"]:
        print("check: decision fingerprint diverged from the banked "
              "run", file=sys.stderr)
        ok = False
    drill = now["kv_drill"]
    if not (drill["pages_recovered"] and drill["invariant_clean"]
            and drill["mid_flight_frees"] > 0):
        print("check: KV cancel drill regression — pages leaked or no "
              "mid-flight frees exercised", file=sys.stderr)
        ok = False
    print(json.dumps({"check": "ok" if ok else "REGRESSED",
                      "comparison": cmp_}, indent=2))
    return 0 if ok else 1


def resilience_main(args) -> int:
    if args.check:
        return check_resilience_bench(args.resilience_out)
    cfg = dict(RES_CONFIG)
    cfg["seed"] = args.seed
    result = {"bench": "serve_bench", "round": "r03",
              "resilience": run_resilience_bench(cfg)}
    with open(args.resilience_out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps({"out": args.resilience_out,
                      "comparison": result["resilience"]["comparison"],
                      "resilient_goodput":
                          result["resilience"]["resilient"]
                          ["brownout_goodput"],
                      "control_goodput":
                          result["resilience"]["control"]
                          ["brownout_goodput"]}, indent=2))
    return 0


def main() -> int:
    p = argparse.ArgumentParser("serve_bench")
    p.add_argument("--model", default="gpt-350m")
    p.add_argument("--vocab-size", type=int, default=32000)
    p.add_argument("--prompt-len", type=int, default=512)
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--slots", type=int, default=16)
    p.add_argument("--window-ms", type=float, default=5.0,
                   help="micro-batching window for the micro mode")
    p.add_argument("--param-dtype", default="bfloat16",
                   choices=["bfloat16", "float32", "int8", "int4", ""])
    p.add_argument("--kv-pages", type=int, default=0,
                   help="paged KV cache pool size for the continuous "
                        "mode (0 = dense per-slot cache)")
    p.add_argument("--kv-page-size", type=int, default=0)
    p.add_argument("--attention-window", type=int, default=0,
                   help="sliding-window width for the served model "
                        "(0 = full causal)")
    p.add_argument("--rolling-kv-cache", action="store_true",
                   help="bound the KV cache to the window (O(window) "
                        "memory + per-step cache stream)")
    p.add_argument("--kv-cache-dtype", default="",
                   choices=["", "auto", "int8"],
                   help="int8 quantizes the decode KV cache (per-token-"
                        "head scales) — the long-context decode lever")
    p.add_argument("--mesh", default="",
                   help="axis=n[,axis=n...] to shard the served params")
    p.add_argument("--modes", default="micro,continuous")
    p.add_argument("--router", action="store_true",
                   help="run the deterministic JAXService router+"
                        "autoscaler benchmark and bank BENCH_SERVE_r01")
    p.add_argument("--decode", action="store_true",
                   help="run the deterministic per-replica decode "
                        "benchmark (dense-vs-paged KV cache, prefix "
                        "reuse, speculative lockstep) and bank "
                        "BENCH_SERVE_r02")
    p.add_argument("--resilience", action="store_true",
                   help="run the deterministic request-resilience "
                        "benchmark (brownout + overload + flap drills, "
                        "deadline/hedge/breaker/band-shed layer vs the "
                        "legacy router) and bank BENCH_SERVE_r03")
    p.add_argument("--check", action="store_true",
                   help="CI gate: rerun every banked config and fail on "
                        "drops/divergence/counter regression (with "
                        "--router or --decode: gate only that bank)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=ROUTER_OUT)
    p.add_argument("--decode-out", default=DECODE_OUT)
    p.add_argument("--resilience-out", default=RESILIENCE_OUT)
    args = p.parse_args()
    if args.check:
        if args.decode:
            return check_decode_bench(args.decode_out)
        if args.router:
            return check_router_bench(args.out)
        if args.resilience:
            return check_resilience_bench(args.resilience_out)
        rc = 0
        if os.path.exists(args.out):
            rc = max(rc, check_router_bench(args.out))
        if os.path.exists(args.decode_out):
            rc = max(rc, check_decode_bench(args.decode_out))
        if os.path.exists(args.resilience_out):
            rc = max(rc, check_resilience_bench(args.resilience_out))
        return rc
    if args.decode:
        return decode_main(args)
    if args.router:
        return router_main(args)
    if args.resilience:
        return resilience_main(args)
    if args.mesh:
        args.mesh = {k: int(v) for k, v in
                     (kv.split("=", 1) for kv in args.mesh.split(","))}
    for mode in args.modes.split(","):
        print(json.dumps(run_mode(mode.strip(), args)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
