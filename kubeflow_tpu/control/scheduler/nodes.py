"""Node/TPU-pool model for the gang scheduler.

A ``Node`` in the FakeCluster (or a real apiserver) carries the GKE TPU
pool surface the JAXJob controller already targets with nodeSelectors
(jaxjob/types.py NODESELECTOR_*): the accelerator + topology labels,
``status.allocatable["google.com/tpu"]`` chips, taints and the Ready
condition. This module reads that surface into a small value type the
admission pass computes against, and provides the constructor tests and
tpctl use to stand up TPU node pools in the fake cluster.
"""

from __future__ import annotations

import dataclasses

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.scheduler import LABEL_SPOT
from kubeflow_tpu.control.scheduler.topology import parse_topology

# Pod phases that no longer hold their node's chips.
TERMINAL_PHASES = ("Succeeded", "Failed")


def eviction_status(message: str) -> dict:
    """The kubelet-eviction status shape (phase Failed, reason Evicted,
    no containerStatuses): ONE spelling, because three call sites — the
    scheduler's priority preemption, its node-health pass, and the
    chaos engine's pod killer — must all be classified as preemption
    (never crash) by ``JAXJobReconciler._pod_preempted``."""
    return {"phase": "Failed", "reason": "Evicted", "message": message,
            "containerStatuses": []}

# GKE TPU hosts expose at most 4 chips each; larger slices span hosts.
CHIPS_PER_HOST = 4


def spot_taint() -> dict:
    """The spot-pool taint (ONE spelling, mirrored by the toleration the
    JAXJob controller adds to elastic workers): only reclaim-tolerant
    workloads may land on preemptible capacity."""
    return {"key": LABEL_SPOT, "value": "true", "effect": "NoSchedule"}


@dataclasses.dataclass(frozen=True)
class NodeView:
    """The scheduler's read of one Node."""

    name: str
    labels: dict
    allocatable_chips: int
    ready: bool
    taints: tuple
    # spot/preemptible pool membership (LABEL_SPOT): lowest-priority
    # capacity — preferred for elastic gangs, reclaimed without notice
    spot: bool = False
    # spec.unschedulable (kubectl cordon / the remediation engine's
    # cordon-and-drain): existing pods keep running, nothing new lands
    unschedulable: bool = False


def new_tpu_node(
    name: str,
    accelerator: str = "tpu-v5-lite-podslice",
    topology: str = "2x4",
    chips_per_node: int | None = None,
    ready: bool = True,
    taints: tuple = (),
    labels: dict | None = None,
    spot: bool = False,
) -> dict:
    """A Node carrying TPU pool labels (the gke node-pool analogue).

    ``chips_per_node`` defaults to the per-host share of the slice
    (min(slice chips, 4) — GKE's hightpu-4t machine shapes).

    ``spot=True`` puts the node in a spot/preemptible pool: the
    LABEL_SPOT label plus the matching NoSchedule taint, so only
    reclaim-tolerant (elastic) workers can land on it."""
    topo = parse_topology(topology)
    chips = chips_per_node if chips_per_node is not None \
        else min(topo.chips, CHIPS_PER_HOST)
    node = ob.new_object(
        "v1", "Node", name,
        labels={
            JT.NODESELECTOR_ACCEL: accelerator,
            JT.NODESELECTOR_TOPOLOGY: str(topo),
            **({LABEL_SPOT: "true"} if spot else {}),
            **(labels or {}),
        },
    )
    all_taints = tuple(taints) + ((spot_taint(),) if spot else ())
    if all_taints:
        node["spec"] = {"taints": [dict(t) for t in all_taints]}
    node["status"] = {
        "allocatable": {JT.RESOURCE_TPU: chips},
        "conditions": [
            {"type": "Ready", "status": "True" if ready else "False"}],
    }
    return node


def node_view(node: dict) -> NodeView:
    status = node.get("status") or {}
    alloc = (status.get("allocatable") or {}).get(JT.RESOURCE_TPU) or 0
    conds = status.get("conditions") or []
    ready = any(c.get("type") == "Ready" and c.get("status") == "True"
                for c in conds)
    taints = tuple((node.get("spec") or {}).get("taints") or [])
    labels = dict(ob.labels_of(node))
    return NodeView(
        name=ob.meta(node)["name"],
        labels=labels,
        allocatable_chips=int(alloc),
        ready=ready,
        taints=taints,
        spot=labels.get(LABEL_SPOT) == "true",
        unschedulable=bool((node.get("spec") or {}).get("unschedulable")),
    )


def pod_tpu_request(pod: dict) -> int:
    """Chips this pod claims: the sum of google.com/tpu limits."""
    total = 0
    for c in (pod.get("spec") or {}).get("containers") or []:
        limits = (c.get("resources") or {}).get("limits") or {}
        total += int(limits.get(JT.RESOURCE_TPU) or 0)
    return total


def selector_matches(pod: dict, view: NodeView) -> bool:
    sel = (pod.get("spec") or {}).get("nodeSelector") or {}
    return all(view.labels.get(k) == v for k, v in sel.items())


def tolerates(pod: dict, taint: dict) -> bool:
    """Kubernetes toleration semantics: effect must match (empty
    toleration effect = all effects); operator Exists matches on key
    alone (empty key = everything), operator Equal (the default) also
    requires the taint's value."""
    t_key = taint.get("key")
    t_value = taint.get("value", "")
    t_effect = taint.get("effect", "")
    for tol in (pod.get("spec") or {}).get("tolerations") or []:
        effect = tol.get("effect", "")
        if effect and effect != t_effect:
            continue
        if tol.get("operator", "Equal") == "Exists":
            if not tol.get("key") or tol.get("key") == t_key:
                return True
        elif tol.get("key") == t_key and tol.get("value", "") == t_value:
            return True
    return False


def feasible(pod: dict, view: NodeView) -> bool:
    """Can this pod land on this node at all (ignoring free capacity)?
    NotReady nodes, cordoned (spec.unschedulable) nodes, and
    untolerated NoSchedule/NoExecute taints — which include the
    impending-TPU-maintenance taint — exclude the node."""
    if not view.ready or view.unschedulable:
        return False
    if not selector_matches(pod, view):
        return False
    for t in view.taints:
        if t.get("effect") in ("NoSchedule", "NoExecute") \
                and not tolerates(pod, t):
            return False
    return True
