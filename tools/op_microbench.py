"""Per-op microbenchmark: attribute the train-step MFU gap to kernels.

Times the individual hot ops at bench shapes (gpt-350m / llama-1b,
seq 2048) and prints each op's achieved fraction of the chip's peak
bf16 FLOPs. The train-step MFU ceiling is a FLOPs-weighted mix of these
rates, so a low rate here names the kernel to fix — ablation timing the
tunnel supports, vs an xplane per-op parse that needs profiler protos
this image doesn't ship.

Usage: python tools/op_microbench.py [--model gpt-350m] [--batch 8]
Writes one JSON line per op; run with the chip otherwise idle.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def peak_flops(kind: str) -> float:
    from kubeflow_tpu.runtime.metrics import peak_flops as pf

    return pf(kind)


def _time(fn, *args, iters=20, warmup=3):
    """Chained dispatch, one readback sync (tunnel-safe timing)."""
    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.tree.leaves(out)[0].block_until_ready()
    _ = float(jax.tree.leaves(out)[0].ravel()[0])  # force a readback
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _ = float(jax.tree.leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / iters


def bench_matmul(m, k, n, peak):
    """The MXU yardstick: one big bf16 matmul at LM-layer shape."""
    a = jnp.ones((m, k), jnp.bfloat16)
    b = jnp.ones((k, n), jnp.bfloat16)
    f = jax.jit(lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(jnp.bfloat16))
    dt = _time(f, a, b)
    fl = 2.0 * m * k * n
    return {"op": f"matmul_{m}x{k}x{n}", "ms": round(dt * 1e3, 3),
            "util": round(fl / dt / peak, 4)}


def bench_flash(b, l, h, d, peak, bwd=False):
    from kubeflow_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, l, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, l, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, l, h, d), jnp.bfloat16)

    if bwd:
        f = jax.jit(jax.grad(
            lambda q, k, v: flash_attention(q, k, v, causal=True)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2)))
        dt = _time(f, q, k, v)
        # causal fwd ~2*L^2*D*B*H MACs halved; bwd ~2.5x fwd kernel work
        fl = 2.0 * 2 * b * h * l * l * d / 2 * 3.5
        tag = "flash_fwd_bwd"
    else:
        f = jax.jit(functools.partial(flash_attention, causal=True))
        dt = _time(f, q, k, v)
        fl = 2.0 * 2 * b * h * l * l * d / 2
        tag = "flash_fwd"
    return {"op": f"{tag}_b{b}h{h}_l{l}", "ms": round(dt * 1e3, 3),
            "util": round(fl / dt / peak, 4)}


def bench_chunked_head(tokens, d, v, chunks, peak):
    from kubeflow_tpu.ops.xent import chunked_lm_xent

    hidden = jax.random.normal(jax.random.PRNGKey(0), (1, tokens, d),
                               jnp.bfloat16)
    kernel = jax.random.normal(jax.random.PRNGKey(1), (d, v), jnp.float32)
    labels = jnp.zeros((1, tokens), jnp.int32)

    f = jax.jit(jax.grad(
        lambda h, w: chunked_lm_xent(h, w, labels, chunks)[0],
        argnums=(0, 1)))
    dt = _time(f, hidden, kernel)
    fl = 6.0 * tokens * d * v  # fwd + bwd + chunk re-projection
    return {"op": f"chunked_head_{tokens}x{d}x{v}", "ms": round(dt * 1e3, 3),
            "util": round(fl / dt / peak, 4)}


def bench_block_soup(b, l, d, dff, peak):
    """One transformer block minus attention kernel: the rmsnorm / rope /
    swiglu elementwise soup fused around its matmuls — how much the
    non-matmul work drags the block below the pure-matmul rate."""
    x = jax.random.normal(jax.random.PRNGKey(0), (b, l, d), jnp.bfloat16)
    wg = jnp.ones((d, dff), jnp.bfloat16)
    wu = jnp.ones((d, dff), jnp.bfloat16)
    wd = jnp.ones((dff, d), jnp.bfloat16)
    scale = jnp.ones((d,), jnp.float32)

    def block(x, wg, wu, wd, scale):
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, -1, keepdims=True) + 1e-6)
        y = (y * scale).astype(jnp.bfloat16)
        g = jax.lax.dot_general(y.reshape(-1, d), wg,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        u = jax.lax.dot_general(y.reshape(-1, d), wu,
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        h = (jax.nn.silu(g) * u).astype(jnp.bfloat16)
        o = jax.lax.dot_general(h, wd, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return x + o.reshape(b, l, d).astype(jnp.bfloat16)

    f = jax.jit(block)
    dt = _time(f, x, wg, wu, wd, scale)
    fl = 2.0 * b * l * (3 * d * dff)
    return {"op": f"mlp_block_{b}x{l}_d{d}_ff{dff}", "ms": round(dt * 1e3, 3),
            "util": round(fl / dt / peak, 4)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    args = ap.parse_args()

    devs = jax.devices()
    kind = devs[0].device_kind
    peak = peak_flops(kind)
    print(json.dumps({"device": kind, "peak_flops": peak}), flush=True)

    b, l = args.batch, args.seq
    tokens = b * l
    results = [
        # gpt-350m shapes
        bench_matmul(tokens, 1024, 4096, peak),
        bench_matmul(tokens, 4096, 1024, peak),
        bench_matmul(tokens, 1024, 32000, peak),
        bench_flash(b, l, 16, 64, peak, bwd=False),
        bench_flash(b, l, 16, 64, peak, bwd=True),
        bench_chunked_head(tokens, 1024, 32000, 8, peak),
        bench_block_soup(b, l, 1024, 4096, peak),
        # llama-1b shapes
        bench_matmul(tokens, 2048, 8192, peak),
        bench_flash(b, l, 32, 64, peak, bwd=True),
        bench_block_soup(b, l, 2048, 8192, peak),
        # llama-1b-hd128 head shape: same total head width (16x128 vs
        # 32x64) — the direct measurement of the head_dim-64 MXU
        # half-contraction penalty the r5 attribution blamed for the
        # attention utilization floor
        bench_flash(b, l, 16, 128, peak, bwd=False),
        bench_flash(b, l, 16, 128, peak, bwd=True),
    ]
    for r in results:
        print(json.dumps(r), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
