"""Promotion tooling: the unattended sweep's bank-the-best discipline.

These scripts decide what the driver's round-end bench replays, so their
invariants get their own tests: only measured points promote, windowed
points never win the LM headline, serving A/B pairs never collapse into
one table row, and non-default geometries never raise the headline
floor."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(tool, args, cwd):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", tool), *args],
        capture_output=True, text=True, cwd=cwd, timeout=120)


def _load(path):
    return json.load(open(path))


class TestPromoteServeBest:
    def _write_log(self, tmp_path, docs):
        p = tmp_path / "serve.out"
        p.write_text("\n".join(json.dumps(d) for d in docs) + "\n")
        return str(p)

    def _tool_env(self, tmp_path):
        # run the tool from a temp copy so serve_best.json lands there
        import shutil

        tooldir = tmp_path / "tools"
        tooldir.mkdir()
        for f in ("promote_serve_best.py",):
            shutil.copy(os.path.join(REPO, "tools", f), tooldir / f)
        return tooldir

    def _doc(self, **over):
        base = dict(mode="continuous", model="gpt-350m", max_new_tokens=32,
                    slots=8, param_dtype="int8", tokens_per_sec=100.0,
                    requests=16, p50_ms=10.0)
        base.update(over)
        return base

    def test_window_ab_pair_keeps_both_rows(self, tmp_path):
        tooldir = self._tool_env(tmp_path)
        log = self._write_log(tmp_path, [
            self._doc(model="llama-1b", attention_window=512,
                      rolling_kv_cache=False, tokens_per_sec=80.0),
            self._doc(model="llama-1b", attention_window=512,
                      rolling_kv_cache=True, tokens_per_sec=120.0),
        ])
        r = subprocess.run([sys.executable, str(tooldir / "promote_serve_best.py"),
                            log], capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        table = _load(tooldir / "serve_table.json")
        assert len(table) == 2, table  # the A/B must not collapse

    def test_non_default_geometry_never_wins_headline(self, tmp_path):
        tooldir = self._tool_env(tmp_path)
        log = self._write_log(tmp_path, [
            self._doc(model="llama-1b", tokens_per_sec=999.0),
            self._doc(model="gpt-350m", tokens_per_sec=50.0),
        ])
        subprocess.run([sys.executable, str(tooldir / "promote_serve_best.py"),
                        log], capture_output=True, text=True, timeout=120)
        best = _load(tooldir / "serve_best.json")
        assert best["model"] == "gpt-350m"
        assert best["tokens_per_sec"] == 50.0

    def test_micro_mode_lines_ignored(self, tmp_path):
        tooldir = self._tool_env(tmp_path)
        log = self._write_log(tmp_path, [
            self._doc(mode="micro", tokens_per_sec=500.0),
        ])
        subprocess.run([sys.executable, str(tooldir / "promote_serve_best.py"),
                        log], capture_output=True, text=True, timeout=120)
        assert not (tooldir / "serve_best.json").exists()


class TestPromoteBest:
    def test_windowed_points_never_promote(self, tmp_path):
        import shutil

        tooldir = tmp_path / "tools"
        tooldir.mkdir()
        shutil.copy(os.path.join(REPO, "tools", "promote_best.py"),
                    tooldir / "promote_best.py")
        log = tmp_path / "sweep.log"
        log.write_text(json.dumps({"lm": {
            "model": "gpt-350m", "mfu": 0.99, "window": 512,
            "optimizer": "adafactor", "tokens_per_sec": 1,
        }}) + "\n")
        subprocess.run([sys.executable, str(tooldir / "promote_best.py"),
                        str(log)], capture_output=True, text=True, timeout=120)
        assert not (tooldir / "lm_best.json").exists()

    def test_floor_from_existing_best_blocks_weaker_point(self, tmp_path):
        import shutil

        tooldir = tmp_path / "tools"
        tooldir.mkdir()
        shutil.copy(os.path.join(REPO, "tools", "promote_best.py"),
                    tooldir / "promote_best.py")
        (tooldir / "lm_best.json").write_text(json.dumps(
            {"model": "gpt-350m", "mfu": 0.4936, "optimizer": "adafactor"}))
        log = tmp_path / "sweep.log"
        log.write_text(json.dumps({"lm": {
            "model": "gpt-350m", "mfu": 0.40, "optimizer": "adafactor",
            "tokens_per_sec": 1,
        }}) + "\n")
        subprocess.run([sys.executable, str(tooldir / "promote_best.py"),
                        str(log)], capture_output=True, text=True, timeout=120)
        # the weaker measured point must NOT replace the banked best
        assert _load(tooldir / "lm_best.json")["mfu"] == 0.4936

    def test_promoted_record_drops_stale_remat_policy(self, tmp_path):
        """Ledger hygiene (VERDICT r4 weak #4): a winning point with
        remat=false must not carry a remat_policy field — the knob never
        ran, and recording it invites reading the number as
        remat-verified."""
        import shutil

        tooldir = tmp_path / "tools"
        tooldir.mkdir()
        shutil.copy(os.path.join(REPO, "tools", "promote_best.py"),
                    tooldir / "promote_best.py")
        log = tmp_path / "sweep.log"
        log.write_text(json.dumps({"lm": {
            "model": "gpt-350m", "mfu": 0.52, "optimizer": "adafactor",
            "remat": False, "remat_policy": "mlp", "tokens_per_sec": 1,
        }}) + "\n" + json.dumps({"lm": {
            "model": "gpt-350m", "mfu": 0.30, "optimizer": "adafactor",
            "remat": True, "remat_policy": "dots", "tokens_per_sec": 1,
        }}) + "\n")
        subprocess.run([sys.executable, str(tooldir / "promote_best.py"),
                        str(log)], capture_output=True, text=True,
                       timeout=120)
        best = _load(tooldir / "lm_best.json")
        assert best["mfu"] == 0.52
        assert "remat_policy" not in best

    def test_promoted_record_keeps_policy_when_remat_ran(self, tmp_path):
        import shutil

        tooldir = tmp_path / "tools"
        tooldir.mkdir()
        shutil.copy(os.path.join(REPO, "tools", "promote_best.py"),
                    tooldir / "promote_best.py")
        log = tmp_path / "sweep.log"
        log.write_text(json.dumps({"lm": {
            "model": "llama-1b", "mfu": 0.55, "optimizer": "adafactor",
            "remat": True, "remat_policy": "dots", "tokens_per_sec": 1,
        }}) + "\n")
        subprocess.run([sys.executable, str(tooldir / "promote_best.py"),
                        str(log)], capture_output=True, text=True,
                       timeout=120)
        best = _load(tooldir / "lm_best.json")
        assert best["remat_policy"] == "dots"
