"""AWS IRSA profile plugin (reference: plugin_iam.go:27-284, tests at the
fidelity of plugin_iam_test.go:1-302)."""

import json
import urllib.parse

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.profile import types as PT
from kubeflow_tpu.control.profile.controller import build_controller
from kubeflow_tpu.control.profile.plugin_irsa import (
    ANNOTATION,
    DEFAULT_AUDIENCE,
    KIND,
    ConditionExistsError,
    IrsaPlugin,
    add_service_account_in_assume_role_policy,
    issuer_url_from_provider_arn,
    make_assume_role_with_web_identity_policy_document,
    make_policy_document,
    remove_service_account_in_assume_role_policy,
    role_name_from_arn,
)
from kubeflow_tpu.control.runtime import seed_controller

ISSUER = ("oidc.beta.us-west-2.wesley.amazonaws.com/id/"
          "50D94CFC65139194EDC21891B611EF72")
PROVIDER_ARN = f"arn:aws:iam::34892524:oidc-provider/{ISSUER}"
ROLE_ARN = "arn:aws:iam::34892524:role/s3-reader"


def policy(subjects=None) -> str:
    """A trust policy like plugin_iam_test.go's fixtures."""
    equals = {f"{ISSUER}:aud": [DEFAULT_AUDIENCE]}
    if subjects is not None:
        equals[f"{ISSUER}:sub"] = subjects
    return json.dumps({
        "Version": "2012-10-17",
        "Statement": [{
            "Effect": "Allow",
            "Principal": {"Federated": PROVIDER_ARN},
            "Action": "sts:AssumeRoleWithWebIdentity",
            "Condition": {"StringEquals": equals},
        }],
    })


def subjects_of(policy_json: str) -> list:
    doc = json.loads(policy_json)
    cond = doc["Statement"][0]["Condition"]["StringEquals"]
    return cond.get(f"{ISSUER}:sub", [])


# ---- pure policy surgery (plugin_iam_test.go:67-302 analogues) -----------

class TestArnParsing:
    def test_issuer_url_from_provider_arn(self):
        # plugin_iam_test.go:52-57
        assert issuer_url_from_provider_arn(PROVIDER_ARN) == ISSUER

    def test_role_name_from_arn(self):
        # plugin_iam_test.go:59-64
        assert role_name_from_arn("arn:aws:iam::34892524:role/test-iam-role") \
            == "test-iam-role"


class TestPolicyDocumentSurgery:
    def test_add_first_subject(self):
        # plugin_iam_test.go:67-110: no :sub key yet -> created with the
        # new subject, audience preserved.
        out = add_service_account_in_assume_role_policy(policy(), "ns1", "sa1")
        doc = json.loads(out)
        stmt = doc["Statement"][0]
        assert doc["Version"] == "2012-10-17"
        assert stmt["Action"] == "sts:AssumeRoleWithWebIdentity"
        assert stmt["Principal"]["Federated"] == PROVIDER_ARN
        cond = stmt["Condition"]["StringEquals"]
        assert cond[f"{ISSUER}:aud"] == [DEFAULT_AUDIENCE]
        assert cond[f"{ISSUER}:sub"] == ["system:serviceaccount:ns1:sa1"]

    def test_add_preserves_existing_subjects(self):
        # plugin_iam_test.go second case: existing subjects stay.
        out = add_service_account_in_assume_role_policy(
            policy(["system:serviceaccount:ns0:sa0"]), "ns1", "sa1")
        assert subjects_of(out) == ["system:serviceaccount:ns0:sa0",
                                    "system:serviceaccount:ns1:sa1"]

    def test_add_duplicate_raises_condition_exists(self):
        # plugin_iam.go:154-157: present subject -> ConditionExistError,
        # caller skips the AWS update.
        with pytest.raises(ConditionExistsError):
            add_service_account_in_assume_role_policy(
                policy(["system:serviceaccount:ns1:sa1"]), "ns1", "sa1")

    def test_remove_subject(self):
        out = remove_service_account_in_assume_role_policy(
            policy(["system:serviceaccount:ns0:sa0",
                    "system:serviceaccount:ns1:sa1"]), "ns1", "sa1")
        assert subjects_of(out) == ["system:serviceaccount:ns0:sa0"]

    def test_remove_last_subject_drops_sub_key(self):
        # plugin_iam.go:213-227: empty list would serialize as null/[] and
        # break AWS policy validation -> the :sub key is omitted.
        out = remove_service_account_in_assume_role_policy(
            policy(["system:serviceaccount:ns1:sa1"]), "ns1", "sa1")
        cond = json.loads(out)["Statement"][0]["Condition"]["StringEquals"]
        assert f"{ISSUER}:sub" not in cond
        assert cond[f"{ISSUER}:aud"] == [DEFAULT_AUDIENCE]

    def test_remove_absent_subject_short_circuits(self):
        # Nothing to remove -> ConditionExistsError so the caller skips
        # the AWS write (the reference's remove path rewrites anyway).
        with pytest.raises(ConditionExistsError):
            remove_service_account_in_assume_role_policy(
                policy(["system:serviceaccount:ns0:sa0"]), "ns1", "sa1")

    def test_sibling_statements_and_conditions_preserved(self):
        """Unlike the reference's from-scratch rebuild (plugin_iam.go:
        163-175), surgery must not destroy the role's other trust
        relationships: sibling statements, non-StringEquals operators,
        extra condition keys, and custom audiences all round-trip."""
        doc = json.loads(policy())
        doc["Statement"][0]["Condition"]["StringLike"] = {
            f"{ISSUER}:sub": ["system:serviceaccount:kube-*:*"]}
        doc["Statement"][0]["Condition"]["StringEquals"][f"{ISSUER}:aud"] = \
            ["custom-audience"]
        doc["Statement"].append({
            "Effect": "Allow",
            "Principal": {"Service": "ec2.amazonaws.com"},
            "Action": "sts:AssumeRole",
        })
        out = json.loads(add_service_account_in_assume_role_policy(
            json.dumps(doc), "ns1", "sa1"))
        assert len(out["Statement"]) == 2
        assert out["Statement"][1]["Principal"] == {"Service": "ec2.amazonaws.com"}
        cond = out["Statement"][0]["Condition"]
        assert cond["StringLike"] == {f"{ISSUER}:sub":
                                      ["system:serviceaccount:kube-*:*"]}
        assert cond["StringEquals"][f"{ISSUER}:aud"] == ["custom-audience"]
        assert cond["StringEquals"][f"{ISSUER}:sub"] == \
            ["system:serviceaccount:ns1:sa1"]
        # ...and through remove as well
        out2 = json.loads(remove_service_account_in_assume_role_policy(
            json.dumps(out), "ns1", "sa1"))
        assert len(out2["Statement"]) == 2
        assert out2["Statement"][0]["Condition"]["StringLike"]
        assert f"{ISSUER}:sub" not in out2["Statement"][0]["Condition"]["StringEquals"]

    def test_policy_document_builders(self):
        # plugin_iam.go:250-267
        stmt = make_assume_role_with_web_identity_policy_document(
            PROVIDER_ARN, {"StringEquals": {}})
        doc = make_policy_document(stmt)
        assert doc["Version"] == "2012-10-17"
        assert doc["Statement"] == [stmt]

    def test_no_statements_rejected(self):
        with pytest.raises(ValueError):
            add_service_account_in_assume_role_policy(
                json.dumps({"Version": "2012-10-17", "Statement": []}),
                "ns1", "sa1")


# ---- plugin against the profile controller -------------------------------

class FakeIamBackend:
    """Stores trust policies URL-quoted, as the AWS API returns them
    (plugin_iam.go:85)."""

    def __init__(self, roles: dict[str, str]):
        self.roles = {n: urllib.parse.quote(p) for n, p in roles.items()}
        self.updates: list[tuple[str, str]] = []

    def get_assume_role_policy(self, role_name: str) -> str:
        return self.roles[role_name]

    def update_assume_role_policy(self, role_name: str, policy_json: str) -> None:
        self.roles[role_name] = urllib.parse.quote(policy_json)
        self.updates.append((role_name, policy_json))

    def decoded(self, role_name: str) -> str:
        return urllib.parse.unquote(self.roles[role_name])


def make_world(initial_policy: str):
    cluster = FakeCluster()
    iam = FakeIamBackend({"s3-reader": initial_policy})
    ctl = seed_controller(build_controller(
        cluster, plugins={KIND: IrsaPlugin(iam_backend=iam)}))
    return cluster, ctl, iam


def drain(ctl):
    for _ in range(4):
        ctl.run_until_idle(advance_delayed=True)


def irsa_profile(name="team-aws", owner="alice@example.com"):
    return PT.new_profile(name, owner, plugins=[
        {"kind": KIND, "spec": {"awsIamRole": ROLE_ARN}},
    ])


class TestIrsaPluginReconcile:
    def test_apply_annotates_sa_and_updates_trust_policy(self, ):
        cluster, ctl, iam = make_world(policy())
        cluster.create(irsa_profile())
        drain(ctl)
        sa = cluster.get("v1", "ServiceAccount", PT.SA_EDITOR, "team-aws")
        assert ob.annotations_of(sa)[ANNOTATION] == ROLE_ARN
        assert subjects_of(iam.decoded("s3-reader")) == [
            "system:serviceaccount:team-aws:default-editor"]

    def test_reapply_is_idempotent(self):
        # Second reconcile finds the subject present -> no second AWS call.
        cluster, ctl, iam = make_world(policy())
        cluster.create(irsa_profile())
        drain(ctl)
        n_updates = len(iam.updates)
        from kubeflow_tpu.control.runtime import Request
        ctl.enqueue(Request(name="team-aws", namespace=None))
        drain(ctl)
        assert len(iam.updates) == n_updates

    def test_revoke_on_delete_removes_annotation_and_subject(self):
        cluster, ctl, iam = make_world(policy())
        cluster.create(irsa_profile())
        drain(ctl)
        cluster.delete(PT.API_VERSION, PT.KIND, "team-aws")
        drain(ctl)
        # subject gone, :sub key dropped (it was the only one)
        cond = json.loads(iam.decoded("s3-reader"))[
            "Statement"][0]["Condition"]["StringEquals"]
        assert f"{ISSUER}:sub" not in cond

    def test_other_namespace_subjects_survive_revoke(self):
        cluster, ctl, iam = make_world(
            policy(["system:serviceaccount:other:default-editor"]))
        cluster.create(irsa_profile())
        drain(ctl)
        cluster.delete(PT.API_VERSION, PT.KIND, "team-aws")
        drain(ctl)
        assert subjects_of(iam.decoded("s3-reader")) == [
            "system:serviceaccount:other:default-editor"]

    def test_profile_without_irsa_plugin_never_touches_iam(self):
        cluster, ctl, iam = make_world(policy())
        cluster.create(PT.new_profile("plain", "bob@example.com"))
        drain(ctl)
        assert iam.updates == []
