"""The JAXService serving plane: controller + token router + drills.

Four layers, mirroring the subsystem's split (docs/serving.md):

1. ``TokenRouter`` semantics in isolation: least-outstanding-tokens
   dispatch, bounded admission (429), cordon draining, member-loss
   shedding with zero drops, the endpoints wire contract, and the
   ``RegistrySignals`` reader the autoscaler consumes.
2. Controller semantics against the fake cluster: validation,
   provisioning + readiness, endpoints publication, dead-replica
   re-provisioning, gang-scheduler opt-in surface.
3. The closed loop: router-exported signals driving the hysteretic
   autoscaler up and down on a manual clock, and the cordon -> drain ->
   delete state machine gated on the router's in-flight gauge.
4. Drills: the scripted replica kill mid-load (router sheds to
   survivors with ZERO dropped in-flight requests, controller
   re-provisions) — plain, and re-run under armed apiserver chaos; plus
   the chaos-parameterized rerun of the controller suite across
   CHAOS_SEEDS (the test_chaos.py convention).

The deterministic benchmark arm of the same machinery lives in
tools/serve_bench.py (banked as BENCH_SERVE_r01.json).
"""

import pytest

from conftest import CHAOS_RATE, CHAOS_SEEDS

from kubeflow_tpu.control.jaxservice import types as T
from kubeflow_tpu.control.jaxservice.controller import build_controller
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.chaos import (
    ChaosClient, ChaosPolicy, arm_controller,
)
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
from kubeflow_tpu.control.runtime import Request, seed_controller
from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.runtime.metrics import MetricsRegistry
from kubeflow_tpu.serving.router import (
    Member, RegistrySignals, RouterBusy, TokenRouter, estimate_tokens,
    parse_endpoints, render_endpoints,
)

pytestmark = pytest.mark.serving


class ManualClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture()
def world():
    cluster = FakeCluster()
    ctl = seed_controller(build_controller(cluster, record_events=True))
    kubelet = FakeKubelet(cluster)
    return cluster, ctl, kubelet


def drain(ctl, kubelet=None, rounds=6):
    for _ in range(rounds):
        ctl.run_until_idle(advance_delayed=True)
        if kubelet is not None:
            kubelet.step()


def make_service(cluster, name="chat", **kw):
    kw.setdefault("model", "gpt-125m")
    return cluster.create(T.new_jaxservice(name, **kw))


def rep(i, name="chat"):
    return T.replica_name(name, i)


# -- the token router in isolation -------------------------------------------


def _router(**kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("prom_sink", False)
    kw.setdefault("tracer", obs_trace.Tracer())
    return TokenRouter(service="svc", namespace="ns", **kw)


def _members(r, n, state=None):
    r.set_members([Member(name=f"r{i}",
                          state=state or "active") for i in range(n)])


class TestTokenRouter:
    def test_least_outstanding_tokens_wins(self):
        r = _router()
        _members(r, 3)
        t1 = r.submit(100)
        t2 = r.submit(10)
        t3 = r.submit(10)
        # r0 took 100; the two light requests spread over r1/r2
        assert t1.member.name == "r0"
        assert {t2.member.name, t3.member.name} == {"r1", "r2"}
        t4 = r.submit(5)  # r1/r2 at 10, r0 at 100 -> name-tie to r1
        assert t4.member.name == "r1"

    def test_name_breaks_ties_deterministically(self):
        r = _router()
        _members(r, 3)
        assert r.submit(1).member.name == "r0"

    def test_budget_full_replica_queues_not_dispatches(self):
        r = _router(replica_token_budget=100)
        _members(r, 1)
        t1 = r.submit(80)
        t2 = r.submit(80)  # 80+80 > 100: queue, do not overload
        assert t1.member is not None and t2.member is None
        assert r.queue_depth() == 1
        done = r.complete(t1)
        assert [d is t2 for d in done] == [True]
        assert t2.member.name == "r0"

    def test_oversized_request_still_dispatches_to_idle_replica(self):
        # budget gates only loaded replicas: a request bigger than the
        # whole budget must not queue forever in an idle fleet
        r = _router(replica_token_budget=100)
        _members(r, 1)
        assert r.submit(500).member is not None

    def test_bounded_queue_raises_busy(self):
        r = _router(max_queue=2)
        _members(r, 0)  # no capacity at all
        r.submit(1)
        r.submit(1)
        with pytest.raises(RouterBusy):
            r.submit(1)
        reg = r.registry.render()
        assert 'outcome="rejected"' in reg

    def test_cordoned_member_gets_no_new_work_but_drains(self):
        r = _router()
        _members(r, 2)
        t1 = r.submit(50)
        assert t1.member.name == "r0"
        r.cordon("r0")
        t2 = r.submit(10)
        assert t2.member.name == "r1"  # r0 is least-loaded-after-complete
        assert not r.drained("r0")
        r.complete(t1)  # in-flight work finishes on a cordoned replica
        assert r.drained("r0")
        assert r.inflight_tokens("r0") == 0

    def test_uncordon_drains_queue_back_in(self):
        r = _router()
        r.set_members([Member(name="r0", state="cordoned")])
        t = r.submit(10)
        assert t.member is None
        r.uncordon("r0")
        assert t.member is not None

    def test_member_loss_sheds_to_survivors_zero_drop(self):
        r = _router()
        _members(r, 2)
        tickets = [r.submit(10) for _ in range(4)]  # 2 on each
        on_r1 = [t for t in tickets if t.member.name == "r1"]
        assert len(on_r1) == 2
        redis = r.set_members([Member(name="r0")])  # r1 vanishes
        assert sorted(id(t) for t in redis) == sorted(id(t) for t in on_r1)
        assert all(t.member.name == "r0" for t in on_r1)
        # zero drops: every ticket is still dispatched somewhere
        assert all(t.member is not None for t in tickets)
        assert r.queue_depth() == 0
        assert 'outcome="shed"' in r.registry.render()

    def test_shed_requeues_at_front_in_original_order(self):
        r = _router(replica_token_budget=100)
        _members(r, 2)
        a = r.submit(90)   # r0
        b = r.submit(90)   # r1
        c = r.submit(90)   # queued (both full)
        assert c.member is None
        redis = r.set_members([Member(name="r1")])  # r0 dies
        # a (oldest in-flight) goes to the queue FRONT, ahead of c; r1
        # is full so nothing dispatches until b completes
        assert redis == []
        r.complete(b)
        assert a.member is not None and a.member.name == "r1"
        assert c.member is None  # behind a, still waiting

    def test_complete_after_shed_removes_queued_copy(self):
        # the symmetric race to fail()'s guard: the transport call
        # SUCCEEDS on a replica a concurrent sync just removed. The
        # handler completes the shed (queued) ticket — the queued copy
        # must go with it, or _drain_locked ghost-dispatches a request
        # whose handler already returned (permanently inflating the
        # survivor's in-flight gauge and wedging its drain gate).
        r = _router(replica_token_budget=100)
        _members(r, 2)
        a = r.submit(90)   # r0
        b = r.submit(90)   # r1
        assert a.member.name == "r0"
        r.set_members([Member(name="r1")])  # r0 vanishes mid-transport
        assert a.member is None and r.queue_depth() == 1
        r.complete(a)      # ...but r0 actually served it
        assert r.queue_depth() == 0
        redis = r.complete(b)
        assert redis == []  # nothing ghost-dispatches a onto r1
        assert r.inflight_tokens("r1") == 0
        assert r.drained("r1")

    def test_fail_requeues_for_retry(self):
        r = _router()
        _members(r, 2)
        t = r.submit(10)
        r.fail(t, requeue=True)
        assert t.member is not None   # re-dispatched immediately
        assert r.inflight_tokens() == 10  # accounted exactly once
        assert 'outcome="shed"' in r.registry.render()

    def test_retry_prefers_untried_replica(self):
        """A transport failure must NOT retry the same replica while an
        untried one exists — the (load, name) tie-break alone would
        send every retry straight back to the dead replica (found live:
        3 attempts -> 502 with a healthy survivor sitting idle)."""
        r = _router()
        _members(r, 2)
        t = r.submit(10)
        assert t.member.name == "r0"
        r.fail(t, requeue=True)
        assert t.member.name == "r1"
        # both tried: retry beats starvation, back to the least-loaded
        r.fail(t, requeue=True)
        assert t.member.name == "r0"

    def test_single_replica_retry_falls_back(self):
        r = _router()
        _members(r, 1)
        t = r.submit(10)
        r.fail(t, requeue=True)
        assert t.member is not None and t.member.name == "r0"

    def test_fail_no_requeue_drops_with_outcome(self):
        r = _router()
        _members(r, 1)
        t = r.submit(10)
        r.fail(t, requeue=False)
        assert t.member is None
        assert r.inflight_tokens("r0") == 0
        assert 'outcome="failed"' in r.registry.render()

    def test_close_rejects_queued_and_new(self):
        r = _router()
        t = r.submit(10)  # no members: queued
        orphans = r.close()
        assert orphans == [t]
        with pytest.raises(RouterBusy):
            r.submit(1)

    def test_estimate_tokens(self):
        assert estimate_tokens([{"tokens": [1, 2, 3]}], 32) == 35
        assert estimate_tokens([[1, 2], [3]], 10) == 23
        assert estimate_tokens([], 32) == 33  # empty body still costs
        assert estimate_tokens([{"x": 1}], 0) >= 1

    def test_metrics_both_sinks(self):
        import prometheus_client as prom

        reg = MetricsRegistry()
        r = TokenRouter(service="promtest", namespace="ns", registry=reg,
                        prom_sink=True, tracer=obs_trace.Tracer())
        r.set_members([Member(name="r0")])
        t = r.submit(40)
        r.complete(t)
        text = reg.render()
        assert "router_tokens_total" in text
        assert "router_request_seconds_bucket" in text  # native histogram
        assert 'replica="r0"' in text
        ptext = prom.generate_latest(prom.REGISTRY).decode()
        assert 'router_queue_depth{service="promtest"} 0.0' in ptext
        assert 'router_tokens_total{service="promtest"} 40.0' in ptext


class TestRouterSpans:
    def test_dispatch_span_parents_on_request_traceparent(self):
        tracer = obs_trace.Tracer()
        r = _router(tracer=tracer)
        _members(r, 1)
        ctx = obs_trace.SpanContext(obs_trace.new_trace_id(),
                                    obs_trace.new_span_id())
        t = r.submit(10, context=ctx)
        r.complete(t)
        spans = [s for s in tracer.collector.spans()
                 if s.name == "router.dispatch"]
        assert len(spans) == 1
        assert spans[0].trace_id == ctx.trace_id
        assert spans[0].parent_id == ctx.span_id
        assert spans[0].attrs["replica"] == "r0"

    def test_shed_dispatch_exports_error_then_fresh_span(self):
        tracer = obs_trace.Tracer()
        r = _router(tracer=tracer)
        _members(r, 2)
        ctx = obs_trace.SpanContext(obs_trace.new_trace_id(),
                                    obs_trace.new_span_id())
        t = r.submit(10, context=ctx)
        dead = t.member.name
        survivor = "r1" if dead == "r0" else "r0"
        r.set_members([Member(name=survivor)])
        r.complete(t)
        spans = [s for s in tracer.collector.spans()
                 if s.name == "router.dispatch"]
        assert [s.status for s in spans] == ["ERROR", "OK"]
        # both halves of the journey stay in the request's ONE trace
        assert {s.trace_id for s in spans} == {ctx.trace_id}


class TestEndpointsContract:
    def test_render_parse_roundtrip(self):
        eps = [{"name": "b", "addr": "http://b:1", "state": "active"},
               {"name": "a", "addr": "http://a:1", "state": "cordoned"}]
        svc = {"metadata": {"annotations": {
            T.ANNOTATION_ENDPOINTS: render_endpoints(eps)}}}
        back = parse_endpoints(svc)
        assert [e["name"] for e in back] == ["a", "b"]  # canonical order

    def test_render_is_canonical(self):
        a = [{"name": "x", "addr": "u", "state": "active"},
             {"name": "y", "addr": "v", "state": "active"}]
        assert render_endpoints(a) == render_endpoints(list(reversed(a)))

    def test_malformed_annotation_is_empty(self):
        svc = {"metadata": {"annotations": {T.ANNOTATION_ENDPOINTS: "{oops"}}}
        assert parse_endpoints(svc) == []
        assert parse_endpoints({}) == []

    def test_sync_from_object_applies_states(self):
        r = _router()
        eps = [{"name": "r0", "addr": "u0", "state": "active"},
               {"name": "r1", "addr": "u1", "state": "cordoned"}]
        svc = {"metadata": {"annotations": {
            T.ANNOTATION_ENDPOINTS: render_endpoints(eps)}}}
        r.sync_from_object(svc)
        assert r.members() == {"r0": "active", "r1": "cordoned"}
        assert r.submit(5).member.name == "r0"


class TestRegistrySignals:
    def test_reads_router_series_back_out(self):
        reg = MetricsRegistry()
        r = TokenRouter(service="svc", namespace="ns", registry=reg,
                        prom_sink=False, tracer=obs_trace.Tracer())
        sig = RegistrySignals(reg)
        _ = r  # members empty: everything queues
        r.submit(10)
        r.submit(10)
        assert sig.queue_depth("ns", "svc") == 2
        r.set_members([Member(name="r0")])
        assert sig.queue_depth("ns", "svc") == 0
        assert sig.inflight_tokens("ns", "svc", "r0") == 20
        assert not sig.replica_drained("ns", "svc", "r0")
        for t in list(r._inflight["r0"].values()):
            r.complete(t)
        assert sig.tokens_total("ns", "svc") == 20
        assert sig.replica_drained("ns", "svc", "r0")

    def test_unknown_service_reads_zero(self):
        sig = RegistrySignals(MetricsRegistry())
        assert sig.queue_depth("ns", "nope") == 0
        assert sig.replica_drained("ns", "nope", "r0")

    def test_scraped_text_source_matches_registry(self):
        # the out-of-process source: a callable returning a scraped
        # /metrics body goes through the text parser, and must read the
        # same values (labels included) as the in-process fast path
        reg = MetricsRegistry()
        r = TokenRouter(service="svc", namespace="ns", registry=reg,
                        prom_sink=False, tracer=obs_trace.Tracer())
        r.set_members([Member(name="r0")])
        r.submit(10)
        r.submit(5)
        fast = RegistrySignals(reg)
        scraped = RegistrySignals(lambda: reg.render())
        assert scraped.queue_depth("ns", "svc") \
            == fast.queue_depth("ns", "svc")
        assert scraped.inflight_tokens("ns", "svc", "r0") \
            == fast.inflight_tokens("ns", "svc", "r0") == 15
        assert not scraped.replica_drained("ns", "svc", "r0")


class TestReplicaMeter:
    def test_replica_signals_in_both_sinks(self):
        """The replica side of the signal plane (serving/server.py):
        queue depth + request-size histogram + generated-token counter
        land in the MetricsRegistry (the autoscaler's wire) AND
        prometheus_client (the scrape surface)."""
        import prometheus_client as prom

        from kubeflow_tpu.serving.server import (
            _generated_tokens, _ReplicaMeter,
        )

        reg = MetricsRegistry()
        m = _ReplicaMeter(reg)
        m.enter("m1", 3)
        text = reg.render()
        assert 'serving_queue_depth{model="m1"} 1' in text
        assert "serving_request_instances_bucket" in text
        m.exit("m1")
        m.tokens("m1", 8)
        text = reg.render()
        assert 'serving_queue_depth{model="m1"} 0' in text
        assert 'serving_tokens_generated_total{model="m1"} 8' in text
        ptext = prom.generate_latest(prom.REGISTRY).decode()
        assert 'serving_queue_depth{model="m1"} 0.0' in ptext
        assert 'serving_request_instances_count{model="m1"} 1.0' in ptext
        # only generate responses count tokens
        assert _generated_tokens([[1, 2, 3]],
                                 {"method_name": "generate"}) == 3
        assert _generated_tokens([[1, 2, 3]],
                                 {"method_name": "predict"}) == 0


# -- controller: validation ---------------------------------------------------


class TestValidation:
    def test_valid_spec_no_errors(self):
        assert T.validate(T.new_jaxservice("s", model="gpt-125m")) == []

    def test_bad_specs_report(self):
        bad = T.new_jaxservice("s", model="gpt-125m", min_replicas=3,
                               max_replicas=1)
        errs = T.validate(bad)
        assert any("min 3 > max 1" in e for e in errs)
        svc = T.new_jaxservice("s", model="gpt-125m")
        svc["spec"]["port"] = 99999
        assert any("port" in e for e in T.validate(svc))
        svc = T.new_jaxservice("s", model="gpt-125m",
                               accelerator="tpu-v5-lite-podslice",
                               topology="2xbroken")
        assert any("NxM" in e for e in T.validate(svc))
        svc = T.new_jaxservice("s", model="gpt-125m")
        del svc["spec"]["model"]["ref"]
        assert any("model.ref" in e for e in T.validate(svc))
        svc = T.new_jaxservice("s", model="gpt-125m")
        svc["spec"]["drainSeconds"] = -1
        assert any("drainSeconds" in e for e in T.validate(svc))

    def test_replicas_shorthand_int(self):
        assert T.replicas_spec({"replicas": 3}) == {"min": 3, "max": 3}

    def test_replica_index_sentinel_sorts_last(self):
        import sys

        assert T.replica_index(rep(2)) == 2
        assert T.replica_index("garbage") == sys.maxsize

    def test_invalid_spec_sets_degraded(self, world):
        cluster, ctl, _ = world
        make_service(cluster, min_replicas=2, max_replicas=1)
        drain(ctl)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert ob.cond_is_true(svc, T.COND_DEGRADED)
        assert cluster.list("v1", "Pod", namespace="default") == []


class TestResilienceSpec:
    """spec.resilience — the namespace-level request-resilience knobs
    (ISSUE 14): parsing defaults, validation, the controller threading
    maxInflight into the replica command, and the frontend adopting
    band/deadline/hedge through the endpoints watch."""

    def test_defaults_when_absent(self):
        assert T.resilience_spec({}) == {
            "defaultBand": "default", "deadlineSeconds": 0.0,
            "hedge": True, "maxInflight": 0}
        # non-dict resilience degrades to the defaults, never raises
        assert T.resilience_spec({"resilience": "nope"})["hedge"] is True

    def test_explicit_values_parse(self):
        r = T.resilience_spec({"resilience": {
            "defaultBand": "sheddable", "deadlineSeconds": 2.5,
            "hedge": False, "maxInflight": 8}})
        assert r == {"defaultBand": "sheddable", "deadlineSeconds": 2.5,
                     "hedge": False, "maxInflight": 8}

    def test_validation_rejects_bad_knobs(self):
        svc = T.new_jaxservice("s", model="gpt-125m")
        svc["spec"]["resilience"] = {"defaultBand": "platinum"}
        assert any("defaultBand" in e for e in T.validate(svc))
        svc["spec"]["resilience"] = {"deadlineSeconds": -1}
        assert any("deadlineSeconds" in e for e in T.validate(svc))
        svc["spec"]["resilience"] = {"maxInflight": -2}
        assert any("maxInflight" in e for e in T.validate(svc))
        svc["spec"]["resilience"] = {"maxInflight": True}
        assert any("maxInflight" in e for e in T.validate(svc))
        svc["spec"]["resilience"] = {
            "defaultBand": "critical", "deadlineSeconds": 30,
            "maxInflight": 4}
        assert T.validate(svc) == []

    def test_max_inflight_threaded_into_replica_command(self, world):
        cluster, ctl, kubelet = world
        svc = T.new_jaxservice("chat", model="gpt-125m")
        svc["spec"]["resilience"] = {"maxInflight": 7}
        cluster.create(svc)
        drain(ctl, kubelet)
        pod = cluster.get("v1", "Pod", rep(0), "default")
        cmd = pod["spec"]["containers"][0]["command"]
        assert cmd[cmd.index("--max-inflight") + 1] == "7"

    def test_zero_max_inflight_omits_the_flag(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster)
        drain(ctl, kubelet)
        pod = cluster.get("v1", "Pod", rep(0), "default")
        assert "--max-inflight" not in pod["spec"]["containers"][0]["command"]

    def test_frontend_adopts_spec_per_event(self):
        from kubeflow_tpu.serving.router import RouterFrontend

        fe = RouterFrontend(_router())
        fe.apply_spec({"spec": {"resilience": {
            "defaultBand": "critical", "deadlineSeconds": 3.0,
            "hedge": False}}})
        assert fe.default_band == "critical"
        assert fe.default_deadline_s == 3.0
        assert fe.hedging is False
        # a spec edit that drops the block reverts to the defaults —
        # the watch applies EVERY event, not just the first
        fe.apply_spec({"spec": {}})
        assert fe.default_band == "default"
        assert fe.default_deadline_s is None
        assert fe.hedging is True


# -- controller: provisioning + endpoints ------------------------------------


class TestProvisioning:
    def test_creates_headless_service_and_replicas(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=2)
        drain(ctl, kubelet)
        hs = cluster.get("v1", "Service", "chat", "default")
        assert hs["spec"]["clusterIP"] == "None"
        pods = cluster.list("v1", "Pod", namespace="default")
        assert {ob.meta(p)["name"] for p in pods} == {rep(0), rep(1)}
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert ob.cond_is_true(svc, T.COND_READY)
        assert svc["status"]["replicas"] == {
            "desired": 2, "ready": 2, "pending": 0, "cordoned": 0}
        assert svc["status"]["replicaStatuses"] == {
            rep(0): "Running", rep(1): "Running"}

    def test_replica_pod_surface(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=1,
                     accelerator="tpu-v5-lite-podslice", topology="2x2",
                     chips_per_replica=4)
        drain(ctl, kubelet)
        pod = cluster.get("v1", "Pod", rep(0), "default")
        c = pod["spec"]["containers"][0]
        env = {e["name"]: e["value"] for e in c["env"]}
        assert env[T.ENV_SERVICE] == "chat"
        assert env[T.ENV_REPLICA] == "0"
        assert env[T.ENV_NAMESPACE] == "default"
        assert c["command"][:3] == ["python", "-m", "kubeflow_tpu.serving"]
        assert "--continuous-batching" in c["command"]
        assert c["resources"]["limits"]["google.com/tpu"] == 4
        assert pod["spec"]["hostname"] == rep(0)
        assert pod["spec"]["subdomain"] == "chat"
        assert pod["metadata"]["labels"][T.LABEL_SERVICE_NAME] == "chat"
        # stable DNS + ownerRef GC
        assert ob.meta(pod)["ownerReferences"][0]["name"] == "chat"

    def test_endpoints_annotation_published(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=2, port=9000)
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        eps = parse_endpoints(svc)
        assert [(e["name"], e["state"]) for e in eps] == [
            (rep(0), "active"), (rep(1), "active")]
        assert eps[0]["addr"] == f"http://{rep(0)}.chat.default.svc:9000"

    def test_pending_replicas_not_in_endpoints(self, world):
        cluster, ctl, _ = world
        make_service(cluster, min_replicas=2)
        drain(ctl)  # no kubelet: pods stay Pending
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert parse_endpoints(svc) == []
        assert not ob.cond_is_true(svc, T.COND_READY)

    def test_steady_state_issues_no_writes(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=2)
        drain(ctl, kubelet)
        cluster.reset_stats()
        ctl.enqueue(Request("default", "chat"))
        drain(ctl)
        assert cluster.stats["update"] == 0, dict(cluster.stats)
        assert cluster.stats["patch"] == 0, dict(cluster.stats)
        assert cluster.stats["list_calls"] == 0, dict(cluster.stats)

    def test_events_recorded(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=1)
        drain(ctl, kubelet)
        reasons = {e["reason"]
                   for e in cluster.list("v1", "Event", namespace="default")}
        assert "JAXServiceCreated" in reasons

    def test_reconcile_span_parented_on_minted_traceparent(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=1)
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        tp = (ob.meta(svc).get("annotations") or {}).get(
            obs_trace.TRACEPARENT_ANNOTATION)
        ctx = obs_trace.parse_traceparent(tp)
        assert ctx is not None
        # the global TRACER accumulates across tests: key on OUR trace id
        spans = [s for s in obs_trace.TRACER.collector.trace(ctx.trace_id)
                 if s.name == "jaxservice.reconcile"]
        assert spans and all(s.attrs.get("service") == "chat"
                             for s in spans)
        # the traceparent also rides into replica pods for the server side
        pod = cluster.get("v1", "Pod", rep(0), "default")
        env = {e["name"]: e["value"]
               for e in pod["spec"]["containers"][0]["env"]}
        assert env[obs_trace.TRACEPARENT_ENV] == tp


class TestReplicaRestart:
    def test_dead_replica_reaped_and_reprovisioned(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=2)
        drain(ctl, kubelet)
        first_uid = ob.meta(cluster.get("v1", "Pod", rep(1), "default"))["uid"]
        kubelet.fail(rep(1))
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["restarts"] == 1
        pod = cluster.get("v1", "Pod", rep(1), "default")
        assert ob.meta(pod)["uid"] != first_uid  # a NEW incarnation
        assert (pod["status"] or {}).get("phase") == "Running"
        assert ob.cond_is_true(svc, T.COND_READY)

    def test_succeeded_replica_also_restarts(self, world):
        # a serving replica never legitimately exits: Succeeded is a
        # crash in disguise and must be replaced like a failure
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=1)
        drain(ctl, kubelet)
        kubelet.succeed(rep(0))
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["restarts"] == 1
        assert (cluster.get("v1", "Pod", rep(0), "default")["status"]
                or {}).get("phase") == "Running"

    def test_deleting_service_cascades(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=2)
        drain(ctl, kubelet)
        cluster.delete(T.API_VERSION, T.KIND, "chat", "default")
        drain(ctl, kubelet)
        assert cluster.list("v1", "Pod", namespace="default") == []


class TestGangScheduledMode:
    def test_gang_surface_per_replica(self, world):
        cluster, ctl, _ = world
        make_service(cluster, min_replicas=2, gang_schedule=True,
                     priority=7, accelerator="tpu-v5-lite-podslice",
                     topology="2x2", chips_per_replica=4)
        drain(ctl)
        from kubeflow_tpu.control.jaxjob import types as JT
        from kubeflow_tpu.control.scheduler import (
            ANNOTATION_GANG_SIZE, ANNOTATION_PRIORITY, GATE_GANG,
            SCHEDULER_NAME,
        )

        for i in range(2):
            pod = cluster.get("v1", "Pod", rep(i), "default")
            assert pod["spec"]["schedulerName"] == SCHEDULER_NAME
            gates = [g["name"] for g in pod["spec"]["schedulingGates"]]
            assert GATE_GANG in gates
            ann = ob.annotations_of(pod)
            # each replica is its own gang of ONE: independent admission
            assert ann[ANNOTATION_GANG_SIZE] == "1"
            assert ann[ANNOTATION_PRIORITY] == "7"
            assert pod["metadata"]["labels"][JT.LABEL_JOB_NAME] == rep(i)

    def test_ungated_without_gang_schedule(self, world):
        cluster, ctl, _ = world
        make_service(cluster, min_replicas=1)
        drain(ctl)
        pod = cluster.get("v1", "Pod", rep(0), "default")
        assert not pod["spec"].get("schedulingGates")
        assert not pod["spec"].get("schedulerName")


# -- the closed loop: signals -> autoscaler -> drain --------------------------


def signal_world(min_replicas=1, max_replicas=4, target_queue_depth=4,
                 up_s=1.0, down_s=2.0, tokens_per_sec=1e9):
    """Controller + router sharing one registry and one manual clock —
    the serve_bench wiring, sized for unit assertions."""
    clock = ManualClock()
    cluster = FakeCluster()
    registry = MetricsRegistry()
    signals = RegistrySignals(registry)
    ctl = seed_controller(build_controller(
        cluster, record_events=False, registry=registry, signals=signals,
        clock=clock))
    kubelet = FakeKubelet(cluster)
    cluster.create(T.new_jaxservice(
        "chat", model="gpt-125m", min_replicas=min_replicas,
        max_replicas=max_replicas, target_queue_depth=target_queue_depth,
        target_tokens_per_sec=tokens_per_sec, up_stabilization_s=up_s,
        down_stabilization_s=down_s))
    router = TokenRouter(service="chat", namespace="default", clock=clock,
                         registry=registry, prom_sink=False,
                         tracer=obs_trace.Tracer(),
                         replica_token_budget=64)
    return cluster, ctl, kubelet, router, clock


def sync(cluster, router):
    svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
    return svc, router.sync_from_object(svc)


class TestAutoscaling:
    def test_queue_pressure_scales_up_after_window(self):
        cluster, ctl, kubelet, router, clock = signal_world(up_s=1.0)
        drain(ctl, kubelet)
        sync(cluster, router)
        for _ in range(30):
            router.submit(32)  # budget 64: ~2 dispatch, ~28 queue
        assert router.queue_depth() >= 20
        drain(ctl, kubelet)  # demand seen; hysteresis pending, no move yet
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["targetReplicas"] == 1
        clock.advance(1.5)  # past the up window: demand persisted
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        # scale-up jumps straight to demand (a spike wants capacity NOW)
        assert svc["status"]["targetReplicas"] == 4
        assert svc["status"]["scales"] == 1
        pods = cluster.list("v1", "Pod", namespace="default")
        assert {ob.meta(p)["name"] for p in pods} == {rep(i)
                                                      for i in range(4)}

    def test_short_spike_does_not_scale(self):
        cluster, ctl, kubelet, router, clock = signal_world(up_s=10.0)
        drain(ctl, kubelet)
        sync(cluster, router)
        tickets = [router.submit(32) for _ in range(30)]
        drain(ctl, kubelet)  # pending-up starts
        # the spike clears before the window elapses
        for t in tickets:
            if t.member is not None:
                router.complete(t)
        while router.queue_depth() or router.inflight_tokens():
            for t in router.kick():
                pass
            for name, bucket in list(router._inflight.items()):
                for t in list(bucket.values()):
                    router.complete(t)
        clock.advance(11.0)
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["targetReplicas"] == 1
        assert svc["status"].get("scales", 0) == 0

    def test_tokens_rate_scales_up(self):
        cluster, ctl, kubelet, router, clock = signal_world(
            target_queue_depth=10**6, tokens_per_sec=100.0, up_s=1.0)
        drain(ctl, kubelet)
        sync(cluster, router)
        # complete 1000 tokens across 2 virtual seconds: rate 500/s vs a
        # 100/s per-replica target -> demand 4 (clamped)
        drain(ctl, kubelet)  # sample 0 at t0
        clock.advance(2.0)
        done = 0
        while done < 1000:
            t = router.submit(50)
            router.complete(t)
            done += 50
        drain(ctl, kubelet)  # rate observed; pending-up
        clock.advance(1.5)
        # keep the demand hot through the second sample window too —
        # the hysteresis re-confirms demand before committing
        done = 0
        while done < 600:
            t = router.submit(50)
            router.complete(t)
            done += 50
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["targetReplicas"] > 1

    def test_autoscale_deterministic_same_inputs(self):
        def run():
            cluster, ctl, kubelet, router, clock = signal_world(up_s=1.0)
            drain(ctl, kubelet)
            sync(cluster, router)
            for _ in range(30):
                router.submit(32)
            drain(ctl, kubelet)
            clock.advance(1.5)
            drain(ctl, kubelet)
            svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
            return (svc["status"]["targetReplicas"],
                    svc["status"].get("scales", 0))

        assert run() == run() == (4, 1)


class TestDrainStateMachine:
    def _three_up(self):
        cluster, ctl, kubelet, router, clock = signal_world(
            min_replicas=1, max_replicas=3, down_s=2.0)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        svc["status"] = {"targetReplicas": 3}
        cluster.update_status(svc)
        drain(ctl, kubelet)
        svc, _ = sync(cluster, router)
        assert svc["status"]["replicas"]["ready"] == 3
        return cluster, ctl, kubelet, router, clock

    def test_cordon_drain_delete_cycle(self):
        cluster, ctl, kubelet, router, clock = self._three_up()
        tickets = [router.submit(30) for _ in range(3)]  # one per replica
        assert {t.member.name for t in tickets} == {rep(i) for i in range(3)}
        drain(ctl, kubelet)          # demand=1 < 3: pending-down starts
        clock.advance(3.0)           # past the down window
        drain(ctl, kubelet)
        svc, _ = sync(cluster, router)
        # ONE step down (lulls release capacity gently), highest index
        assert svc["status"]["targetReplicas"] == 2
        pod2 = cluster.get("v1", "Pod", rep(2), "default")
        assert ob.annotations_of(pod2)[T.ANNOTATION_CORDON] == "true"
        eps = {e["name"]: e["state"] for e in parse_endpoints(svc)}
        assert eps[rep(2)] == "cordoned"
        assert router.members()[rep(2)] == "cordoned"
        # in-flight work pins the replica: NOT deleted while draining
        assert svc["status"]["replicas"]["cordoned"] == 1
        # new work avoids the cordoned replica
        extra = router.submit(5)
        assert extra.member.name != rep(2)
        router.complete(extra)
        # finish the in-flight request -> drained -> deleted
        t2 = next(t for t in tickets if t.member.name == rep(2))
        router.complete(t2)
        drain(ctl, kubelet)
        assert cluster.get_or_none("v1", "Pod", rep(2), "default") is None
        svc, _ = sync(cluster, router)
        eps = {e["name"]: e["state"] for e in parse_endpoints(svc)}
        assert rep(2) not in eps
        # the surviving in-flight work was never touched: zero drops
        for t in tickets:
            if t is not t2:
                assert t.member is not None
                router.complete(t)

    def test_unsignalled_world_holds_running_cordoned_for_drain_grace(self):
        # signals=None (the production run_controller wiring): the
        # router keeps routing whether or not the controller can read
        # its gauges, so a Running cordoned replica is held for
        # spec.drainSeconds after cordon, THEN deleted.
        clock = ManualClock()
        cluster = FakeCluster()
        ctl = seed_controller(build_controller(cluster, clock=clock))
        kubelet = FakeKubelet(cluster)
        make_service(cluster, min_replicas=2)
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        svc["spec"]["replicas"] = {"min": 1, "max": 1}
        cluster.update(svc)
        drain(ctl, kubelet)
        # cordoned but inside the grace: held, status shows draining
        pod = cluster.get("v1", "Pod", rep(1), "default")
        assert ob.annotations_of(pod)[T.ANNOTATION_CORDON] == "true"
        clock.advance(T.DEFAULT_DRAIN_SECONDS - 1.0)
        drain(ctl, kubelet)
        assert cluster.get_or_none("v1", "Pod", rep(1), "default") \
            is not None
        # past the grace: deleted
        clock.advance(2.0)
        drain(ctl, kubelet)
        assert cluster.get_or_none("v1", "Pod", rep(1), "default") is None
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["replicas"]["desired"] == 1

    def test_growback_uncordons_before_drain_completes(self):
        # the uncordon arrow: target drops (replica cordoned), then
        # grows back before the drain grace elapses — the replica must
        # return to service, not wedge the fleet below target forever
        clock = ManualClock()
        cluster = FakeCluster()
        ctl = seed_controller(build_controller(cluster, clock=clock))
        kubelet = FakeKubelet(cluster)
        make_service(cluster, min_replicas=2)
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        svc["spec"]["replicas"] = {"min": 1, "max": 1}
        cluster.update(svc)
        drain(ctl, kubelet)
        pod = cluster.get("v1", "Pod", rep(1), "default")
        assert ob.annotations_of(pod)[T.ANNOTATION_CORDON] == "true"
        # scale-down reversed inside the grace window
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        svc["spec"]["replicas"] = {"min": 2, "max": 2}
        cluster.update(svc)
        drain(ctl, kubelet)
        pod = cluster.get("v1", "Pod", rep(1), "default")
        assert ob.annotations_of(pod)[T.ANNOTATION_CORDON] != "true"
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["replicas"] == {
            "desired": 2, "ready": 2, "pending": 0, "cordoned": 0}
        eps = {e["name"]: e["state"] for e in parse_endpoints(svc)}
        assert eps[rep(1)] == "active"
        # the drain timer cleared: a LATER cordon gets a full grace
        assert ctl.reconciler._drain_started == {}

    def test_unsignalled_world_deletes_nonrunning_cordoned_immediately(
            self, world):
        # a cordoned pod that never went Running holds no connections —
        # no grace needed (world has no kubelet stepping: pods Pending)
        cluster, ctl, _ = world
        make_service(cluster, min_replicas=2)
        drain(ctl)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        svc["spec"]["replicas"] = {"min": 1, "max": 1}
        cluster.update(svc)
        drain(ctl)
        assert cluster.get_or_none("v1", "Pod", rep(1), "default") is None
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["replicas"]["desired"] == 1


# -- drills -------------------------------------------------------------------


def kill_drill(world_tuple, router=None, registry=None):
    """The scripted drill (ISSUE 8): kill one replica mid-load; the
    router must shed its in-flight requests to survivors with ZERO
    drops and the controller must re-provision the replica."""
    cluster, ctl, kubelet = world_tuple
    registry = registry if registry is not None else MetricsRegistry()
    if router is None:
        router = TokenRouter(service="chat", namespace="default",
                             registry=registry, prom_sink=False,
                             tracer=obs_trace.Tracer())
    make_service(cluster, min_replicas=2, max_replicas=2)
    drain(ctl, kubelet)
    svc, _ = sync(cluster, router)
    assert svc["status"]["replicas"]["ready"] == 2

    tickets = [router.submit(25) for _ in range(4)]  # 2 per replica
    assert all(t.member is not None for t in tickets)
    on_dead = [t for t in tickets if t.member.name == rep(1)]
    assert len(on_dead) == 2

    # the kill: replica-1's pod dies mid-load. Drain WITHOUT the kubelet
    # so the router syncs the intermediate truth — replica-1 reaped, its
    # replacement still Pending and so absent from the endpoint set
    kubelet.fail(rep(1), message="node reclaimed", exit_code=137)
    drain(ctl)
    svc, redispatched = sync(cluster, router)

    # shed to survivors: every in-flight request re-dispatched, zero lost
    assert sorted(id(t) for t in redispatched) == \
        sorted(id(t) for t in on_dead)
    assert all(t.member is not None and t.member.name == rep(0)
               for t in tickets)
    for t in tickets:
        router.complete(t)
    assert router.queue_depth() == 0 and router.inflight_tokens() == 0

    # the controller re-provisioned the replica; let the kubelet run it
    drain(ctl, kubelet)
    svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
    assert svc["status"]["restarts"] >= 1
    assert ob.cond_is_true(svc, T.COND_READY)
    pod = cluster.get("v1", "Pod", rep(1), "default")
    assert (pod["status"] or {}).get("phase") == "Running"
    svc, _ = sync(cluster, router)
    assert {e["name"] for e in parse_endpoints(svc)} == {rep(0), rep(1)}
    # all four requests completed exactly once
    assert 'outcome="completed"' in registry.render()
    sig = RegistrySignals(registry)
    assert sig.tokens_total("default", "chat") == 100.0


class TestKillDrill:
    def test_replica_kill_sheds_to_survivors_zero_drop(self, world):
        registry = MetricsRegistry()
        kill_drill(world, registry=registry)


# -- chaos: the controller suite re-run with faults armed ---------------------


def _policy(seed, **over):
    base = dict(seed=seed, rate=CHAOS_RATE, watch_drop_every=25)
    base.update(over)
    return ChaosPolicy(**base)


def _chaos_world(seed):
    """The ``world`` fixture, chaos edition (the test_chaos.py
    convention): one FakeCluster, faults armed ONLY during reconciles,
    retry delays zeroed so retries complete inside the tests' drains."""
    inner = FakeCluster()
    chaos = ChaosClient(inner, _policy(seed), always_on=False)
    ctl = arm_controller(
        seed_controller(build_controller(chaos, record_events=True)), chaos)
    ctl.CONFLICT_RETRY = (0, 0)
    ctl.RETRY_BASE = 0.0
    kubelet = FakeKubelet(inner)
    return inner, ctl, kubelet


def _methods(cls):
    return [(cls, n) for n in sorted(dir(cls))
            if n.startswith("test_")]


# Every controller-suite test that drives ONLY through the world tuple.
# (TestProvisioning.test_steady_state_issues_no_writes pins exact op
# counts — chaos retries legitimately change them, so it stays out.)
JAXSERVICE_HAPPY = [
    case for cls in (TestProvisioning, TestReplicaRestart,
                     TestGangScheduledMode, TestValidation)
    for case in _methods(cls)
    if case[1] not in ("test_steady_state_issues_no_writes",
                       "test_valid_spec_no_errors", "test_bad_specs_report",
                       "test_replicas_shorthand_int",
                       "test_replica_index_sentinel_sorts_last",
                       "test_estimate_tokens")
]


@pytest.mark.chaos
@pytest.mark.parametrize("seed", CHAOS_SEEDS)
@pytest.mark.parametrize(
    "case", JAXSERVICE_HAPPY,
    ids=[f"{cls.__name__}.{name}" for cls, name in JAXSERVICE_HAPPY])
def test_jaxservice_happy_paths_survive_chaos(case, seed):
    cls, name = case
    getattr(cls(), name)(_chaos_world(seed))


@pytest.mark.chaos
@pytest.mark.parametrize("seed", CHAOS_SEEDS[:2])
def test_kill_drill_survives_chaos(seed):
    """The scripted drill under armed apiserver faults: zero dropped
    in-flight requests and re-provisioning hold even while the control
    plane is being conflicted/errored (the PR 6 drill discipline)."""
    kill_drill(_chaos_world(seed))


# -- the banked benchmark stays meaningful -----------------------------------


@pytest.mark.usefixtures("virtual_time_guard")
class TestServeBenchContract:
    def test_banked_results_satisfy_acceptance(self):
        """BENCH_SERVE_r01.json is the PR's acceptance artifact: the
        multi-replica arm must beat single-replica at peak, both drills
        must have passed, and the banked decisions must be non-trivial
        (a real scale-up AND the scale-down half of the cycle)."""
        import json
        import os

        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(here, "BENCH_SERVE_r01.json")) as fh:
            banked = json.load(fh)
        r = banked["router"]
        cmp_ = r["comparison"]
        assert cmp_["zero_dropped"] is True
        assert cmp_["kill_drill_survived"] is True
        assert cmp_["scale_cycle_complete"] is True
        assert cmp_["decisions_replay_identical"] is True
        assert cmp_["peak_tokens_per_sec_x"] >= 2.0
        assert r["multi"]["max_target"] > 1
        assert r["multi"]["final_target"] < r["multi"]["max_target"]
        assert r["multi"]["replica_restarts"] >= 1
        assert r["single"]["dropped"] == 0 and r["multi"]["dropped"] == 0

    @staticmethod
    def _bench():
        import os
        import sys

        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        sys.path.insert(0, os.path.join(here, "tools"))
        try:
            import serve_bench as sb
        finally:
            sys.path.pop(0)
        return sb

    def test_router_bench_small_config_is_deterministic(self):
        """A miniature end-to-end run of the serve_bench harness itself
        (CI-speed): same seed, same decisions, zero drops."""
        sb = self._bench()
        cfg = dict(sb.ROUTER_CONFIG)
        cfg.update(seed=7, max_replicas=2, kill_at_s=6.0)
        old_phases = sb.PHASES
        sb.PHASES = ((2.0, 2.0), (6.0, 12.0), (6.0, 1.0))
        try:
            a = sb.run_router_arm("multi", cfg)
            b = sb.run_router_arm("multi", cfg)
        finally:
            sb.PHASES = old_phases
        assert a["dropped"] == 0
        assert a["replica_restarts"] >= 1
        assert a["decisions"] == b["decisions"]
        assert a["tokens_done"] == b["tokens_done"]

    def test_check_gate_round_trip(self, tmp_path):
        """``--check`` passes against a just-banked run of the same
        config and fails loudly (exit 1) when the banked decision
        fingerprint or throughput budget regresses — the sched_bench
        ratchet discipline."""
        import json

        sb = self._bench()
        cfg = dict(sb.ROUTER_CONFIG)
        cfg.update(seed=3, max_replicas=2, kill_at_s=4.0)
        old_phases = sb.PHASES
        sb.PHASES = ((1.0, 2.0), (4.0, 12.0), (4.0, 1.0))
        try:
            banked = {"router": sb.run_router_bench(cfg)}
            ok = tmp_path / "bank_ok.json"
            ok.write_text(json.dumps(banked))
            assert sb.check_router_bench(str(ok)) == 0
            bad = json.loads(ok.read_text())
            bad["router"]["multi"]["decisions"] = [[0.0, 99]]
            bad_path = tmp_path / "bank_bad.json"
            bad_path.write_text(json.dumps(bad))
            assert sb.check_router_bench(str(bad_path)) == 1
            slow = json.loads(ok.read_text())
            slow["router"]["multi"]["tokens_per_sec"] = \
                banked["router"]["multi"]["tokens_per_sec"] * 10
            slow_path = tmp_path / "bank_slow.json"
            slow_path.write_text(json.dumps(slow))
            assert sb.check_router_bench(str(slow_path)) == 1
        finally:
            sb.PHASES = old_phases


# -- remediation nudge + predictive autoscaling (ISSUE 13) --------------------


class TestScaleNudge:
    """The obs/remediate.py -> autoscaler handshake: a one-shot floor
    annotation, consumed (cleared) inside the normal reconcile so it
    flows through the record-first durable target move."""

    def _nudge(self, cluster, value, name="chat"):
        cluster.patch(T.API_VERSION, T.KIND, name,
                      {"metadata": {"annotations": {
                          T.ANNOTATION_SCALE_NUDGE: value}}}, "default")

    def test_nudge_raises_target_and_is_consumed(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=1, max_replicas=4)
        drain(ctl, kubelet)
        self._nudge(cluster, "3")
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["targetReplicas"] == 3
        assert svc["status"]["scales"] == 1
        # one-shot: the annotation was cleared in the same reconcile
        assert T.ANNOTATION_SCALE_NUDGE not in (
            ob.annotations_of(svc) or {})
        pods = cluster.list("v1", "Pod", namespace="default")
        assert {ob.meta(p)["name"] for p in pods} == {rep(i)
                                                      for i in range(3)}

    def test_nudge_clamps_to_max_replicas(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=1, max_replicas=4)
        drain(ctl, kubelet)
        self._nudge(cluster, "99")
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["targetReplicas"] == 4

    def test_nudge_is_a_floor_never_a_scale_down(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=3, max_replicas=4)
        drain(ctl, kubelet)
        self._nudge(cluster, "2")
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"].get("targetReplicas", 3) == 3
        assert svc["status"].get("scales", 0) == 0
        assert T.ANNOTATION_SCALE_NUDGE not in (
            ob.annotations_of(svc) or {})

    def test_malformed_nudge_is_cleared_and_ignored(self, world):
        cluster, ctl, kubelet = world
        make_service(cluster, min_replicas=1, max_replicas=4)
        drain(ctl, kubelet)
        self._nudge(cluster, "lots")
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"].get("targetReplicas", 1) == 1
        assert T.ANNOTATION_SCALE_NUDGE not in (
            ob.annotations_of(svc) or {})


def predictive_world(store, up_s=10.0):
    """signal_world wired with a fleet TSDB: the controller reads
    router_queue_depth trends from ``store`` for predictive scale-up."""
    clock = ManualClock()
    cluster = FakeCluster()
    registry = MetricsRegistry()
    signals = RegistrySignals(registry)
    ctl = seed_controller(build_controller(
        cluster, record_events=False, registry=registry,
        signals=signals, clock=clock, store=store))
    kubelet = FakeKubelet(cluster)
    cluster.create(T.new_jaxservice(
        "chat", model="gpt-125m", min_replicas=1, max_replicas=8,
        target_queue_depth=4, target_tokens_per_sec=1e9,
        up_stabilization_s=up_s, down_stabilization_s=300.0))
    return cluster, ctl, kubelet, registry, clock


class TestPredictiveAutoscaling:
    def _rising_store(self):
        from kubeflow_tpu.obs.tsdb import TimeSeriesStore

        store = TimeSeriesStore()
        # queue growing 2 items/s across the stabilization window
        for k, t in enumerate((2.0, 4.0, 6.0, 8.0, 10.0)):
            store.append("router_queue_depth",
                         {"namespace": "default", "service": "chat"},
                         4.0 * (k + 1), t)
        return store

    def test_rising_trend_raises_the_confirmed_target(self):
        store = self._rising_store()
        cluster, ctl, kubelet, reg, clock = predictive_world(store)
        reg.gauge("router_queue_depth", 8.0, namespace="default",
                  service="chat")
        drain(ctl, kubelet)  # demand seen, hysteresis pending
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        # prediction never bypasses the stabilization window
        assert svc["status"].get("targetReplicas", 1) == 1
        clock.advance(11.0)
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        # slope 2/s projected over the 10s window: queue 8 -> 28,
        # ceil(28/4) = 7 — capacity lands BEFORE the queue does
        assert svc["status"]["targetReplicas"] == 7

    def test_without_store_same_signals_scale_reactively(self):
        cluster, ctl, kubelet, reg, clock = predictive_world(None)
        reg.gauge("router_queue_depth", 8.0, namespace="default",
                  service="chat")
        drain(ctl, kubelet)
        clock.advance(11.0)
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        assert svc["status"]["targetReplicas"] == 2  # ceil(8/4) only

    def test_falling_trend_never_shrinks_the_signal(self):
        from kubeflow_tpu.obs.tsdb import TimeSeriesStore

        store = TimeSeriesStore()
        for k, t in enumerate((2.0, 4.0, 6.0, 8.0, 10.0)):
            store.append("router_queue_depth",
                         {"namespace": "default", "service": "chat"},
                         40.0 - 10.0 * k, t)
        cluster, ctl, kubelet, reg, clock = predictive_world(store)
        reg.gauge("router_queue_depth", 8.0, namespace="default",
                  service="chat")
        drain(ctl, kubelet)
        clock.advance(11.0)
        drain(ctl, kubelet)
        svc = cluster.get(T.API_VERSION, T.KIND, "chat", "default")
        # prediction accelerates scale-UP only: the negative slope is
        # ignored and the instantaneous queue drives the target
        assert svc["status"]["targetReplicas"] == 2
