"""Driver-contract dryrun at the n=16 tier (slow).

The driver itself validates dryrun_multichip(8); this covers the larger
tier the driver does not run: a 16-device virtual mesh where the composed
4-factor config G (dcn x dp x pp x tp, pp >= 2 guaranteed) exists. The
wrapper's partitioner-warning gate applies, so this also asserts every
config compiles without GSPMD involuntary rematerialization/replication
(VERDICT r3 #7). Runs in a subprocess (the wrapper re-execs with
JAX_PLATFORMS=cpu and the 16-device flag before jax initializes).
"""

import pytest

import __graft_entry__ as graft


@pytest.mark.slow
def test_dryrun_multichip_16_green_and_warning_clean():
    graft.dryrun_multichip(16)
