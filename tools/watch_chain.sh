#!/usr/bin/env bash
# Supervise the phase-1 -> phase-2 watcher handoff: exactly one watcher
# owns the queue at a time (two concurrently would contend for the one
# chip and corrupt each other's timings). Phase liveness is judged by
# the watcher's OWN pidfile (tools/watch_lib.sh writes it), not by
# pgrep substring matching — an editor with the filename open must not
# stall the handoff, and a not-yet-started phase 1 must not trigger a
# premature (concurrent) phase-2 launch.
set -u
cd "$(dirname "$0")/.."
PIDFILE=/tmp/kftpu_watch.pid

phase1_alive() {
  # the currently-running phase-1 instance predates the pidfile
  # mechanism, so fall back to an exact-cmdline match for it
  local pid
  pid=$(cat "$PIDFILE" 2>/dev/null)
  if [ -n "$pid" ] && kill -0 "$pid" 2>/dev/null; then return 0; fi
  pgrep -f "^bash tools/round5_watch.sh$" >/dev/null 2>&1
}

# give a just-starting phase 1 time to appear before concluding it is
# done (prevents the instant-passthrough double-launch)
sleep 90
while phase1_alive; do sleep 60; done
echo "$(date -u +%H:%M:%S) phase 1 exited — starting phase 2" \
  >> tools/round5_watch.log
exec bash tools/round5b_watch.sh
