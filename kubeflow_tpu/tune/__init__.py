"""Hyperparameter sweeps — the Katib StudyJob capability.

The reference's CI drives a StudyJob CRD and polls its conditions
(testing/katib_studyjob_test.py:128-194); the operator itself lived
outside the tree. Here the sweep driver is in-tree and TPU-native: each
trial is a JAXJob (gang TPU pod set), so one StudyJob fans out over
slices.
"""

from kubeflow_tpu.tune.studyjob import (  # noqa: F401
    API_VERSION,
    KIND,
    StudyJobReconciler,
    build_controller,
    new_studyjob,
)
