"""Chunked LM-head cross-entropy: loss without the [B, L, V] logits.

The LM loss is the one place the transformer step materializes a
vocab-wide tensor: full logits are [B, L, V] f32 — 2.1 GB at
B=8, L=2048, V=32k — and reverse-mode AD transiently holds the same-size
dlogits. On a 16 GB v5e that tensor (not the layer stack) is what forces
remat or caps the batch size.

This op computes the identical mean cross-entropy + argmax accuracy by
scanning over sequence chunks: each chunk projects [B, L/C, D] hidden
states through the head kernel, reduces to per-chunk loss/hit sums, and
drops the chunk logits before the next one materializes. The chunk body
is wrapped in `jax.checkpoint`, so the backward pass recomputes each
chunk's logits instead of saving them — peak vocab-wide memory falls
from O(B.L.V) to O(B.(L/C).V) in both passes, at the price of one extra
head matmul per chunk (the head is ~7% of step FLOPs on gpt-350m, so a
full re-projection costs ~3.5% FLOPs for a multi-GB memory win).

The head matmul runs bf16xbf16 -> f32 on the MXU exactly like
models.transformer.LMHead; the scan carries f32 loss / int32 hit
accumulators, and the head-kernel gradient accumulates across scan
iterations in f32 (one [D, V] buffer, 131 MB at d=1024/V=32k).

Reference analogue: the reference's workloads delegate the loss to the
opaque TF payload (tf-controller-examples/tf-cnn/launcher.py runs
tf_cnn_benchmarks unmodified); the loss design here is TPU-native work
the platform never had.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_lm_xent(hidden: jax.Array, kernel: jax.Array,
                    labels: jax.Array, n_chunks: int,
                    compute_dtype=jnp.bfloat16):
    """Mean cross-entropy + accuracy of an LM head, chunked over sequence.

    Args:
      hidden: [B, L, D] final hidden states (any float dtype; cast to
        ``compute_dtype`` for the head matmul).
      kernel: [D, V] head kernel (stored f32; cast to ``compute_dtype``).
      labels: [B, L] int targets.
      n_chunks: sequence chunks; L must divide evenly. 1 degenerates to
        the unchunked computation (still without storing logits for bwd).
      compute_dtype: matmul operand dtype (bf16 keeps the MXU fast path;
        tests use f32 to compare exactly against the unchunked oracle).

    Returns:
      (loss, accuracy): scalar f32 mean NLL over the valid positions and
      the argmax hit-rate, identical (up to dtype noise) to
      ``optax.softmax_cross_entropy_with_integer_labels`` over full
      logits followed by a masked argmax hit-rate. Negative labels are
      ignored (packed-batch padding / document boundaries).
    """
    b, l, d = hidden.shape
    if l % n_chunks:
        raise ValueError(f"seq_len {l} not divisible by n_chunks {n_chunks}")
    c = l // n_chunks

    def chunk_fn(x, y):
        # [B, c, D] @ [D, V] -> f32 [B, c, V]; dies at the end of the chunk
        logits = jnp.einsum(
            "bld,dv->blv", x.astype(compute_dtype),
            kernel.astype(compute_dtype),
            preferred_element_type=jnp.float32)
        valid = y >= 0
        y_safe = jnp.maximum(y, 0)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        correct = jnp.take_along_axis(
            logits, y_safe[..., None], axis=-1)[..., 0]
        hits = jnp.sum((logits.argmax(-1) == y) & valid)
        return (jnp.sum((lse - correct) * valid), hits,
                jnp.sum(valid.astype(jnp.int32)))

    # bwd recomputes the chunk's logits from (x, kernel) instead of saving
    # them: the whole point of the op.
    chunk_fn = jax.checkpoint(chunk_fn)

    # [C, B, c, D] scan layout; chunk index is the scanned axis.
    hc = hidden.reshape(b, n_chunks, c, d).transpose(1, 0, 2, 3)
    yc = labels.reshape(b, n_chunks, c).transpose(1, 0, 2)

    def body(carry, xy):
        loss_sum, hit_sum, n_sum = carry
        ls, h, n = chunk_fn(*xy)
        return (loss_sum + ls, hit_sum + h, n_sum + n), None

    (loss_sum, hit_sum, n_sum), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0), jnp.int32(0)), (hc, yc))
    n = jnp.maximum(n_sum, 1).astype(jnp.float32)
    return loss_sum / n, hit_sum.astype(jnp.float32) / n
