"""Promote the LM sweep's best measured operating point to the bench default.

Parses tools/lm_sweep.log (JSON lines appended by lm_sweep.py, each the
output of `bench.py --workload lm ...` whose `lm` dict is self-describing)
and writes tools/lm_best.json when a config beats BOTH the current
promotion file and the hard floor of the last hand-verified default
(gpt-350m + adafactor = 0.202 MFU, BASELINE.md round 2). bench.py's
`--lm-best auto` then runs the headline LM at that point — so a sweep
that completes unattended (the tunnel watcher fires it whenever hardware
returns) still upgrades BENCH_r03 with zero human steps. Only measured
numbers are ever promoted; a failed/partial sweep changes nothing.
"""

from __future__ import annotations

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
FLOOR_MFU = 0.202  # the hand-verified default's measured MFU


def candidates(log_path: str):
    for line in open(log_path):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        lm = doc.get("lm") or {}
        if lm.get("window"):
            # sliding-window points do LESS attention work than the MFU
            # accounting assumes — their "MFU" is inflated and must never
            # compete with full-causal points for the headline default
            continue
        if isinstance(lm.get("mfu"), (int, float)) and lm["mfu"] > 0:
            yield lm


def main() -> int:
    log_path = sys.argv[1] if len(sys.argv) > 1 else \
        os.path.join(HERE, "lm_sweep.log")
    best_path = os.path.join(HERE, "lm_best.json")
    if not os.path.exists(log_path):
        print(f"no sweep log at {log_path}; nothing to promote")
        return 0
    floor = FLOOR_MFU
    if os.path.exists(best_path):
        try:
            floor = max(floor, json.load(open(best_path)).get("mfu", 0))
        except (ValueError, OSError):
            pass
    best = None
    for lm in candidates(log_path):
        if lm["mfu"] > floor and (best is None or lm["mfu"] > best["mfu"]):
            best = lm
    if best is None:
        print(f"no sweep point beat mfu={floor:.3f}; defaults unchanged")
        return 0
    best = dict(best)
    if not best.get("remat"):
        # ledger hygiene (VERDICT r4 weak #4): record only knobs actually
        # in effect — "remat_policy" next to remat=false invites reading
        # the point as remat-verified when the policy never ran
        best.pop("remat_policy", None)
    # atomic replace: a bench.py starting concurrently (both are fired
    # by the tunnel coming back) must never read a half-written file
    tmp = best_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(best, f, indent=1)
    os.replace(tmp, best_path)
    print(f"promoted {best['model']} ({best['optimizer']}"
          f"{', remat=' + best.get('remat_policy', '') if best.get('remat') else ''}) "
          f"mfu={best['mfu']:.3f} -> {best_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
