"""TSDB durability (ISSUE 13 tentpole): segment/snapshot persistence
round-trips, the crash-recovery contract (kill mid-segment-write, no
loss beyond one flush interval, no torn reads), and the remote-write
exporter's batching/backoff/lossy-watermark semantics."""

import json
import os

import pytest

from kubeflow_tpu.obs import persist as P
from kubeflow_tpu.obs.expofmt import STALE_NAN, is_stale
from kubeflow_tpu.obs.tsdb import STALE, TimeSeriesStore
from kubeflow_tpu.runtime.metrics import MetricsRegistry

pytestmark = pytest.mark.obs


class ManualClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def fill(store, n, t0=0.0, name="m", labels=None):
    for i in range(n):
        store.append(name, labels or {"job": "x"}, float(i), t0 + i)


def persister(store, tmp_path, **kw):
    kw.setdefault("clock", ManualClock())
    return P.TsdbPersister(store, str(tmp_path / "tsdb"), **kw)


class TestSegmentsAndSnapshots:
    def test_segment_snapshot_restore_roundtrip(self, tmp_path):
        store = TimeSeriesStore()
        p = persister(store, tmp_path, snapshot_every=3)
        fill(store, 4, t0=0.0)
        assert p.flush(at=10.0)["kind"] == "segment"
        fill(store, 4, t0=100.0, name="m2")
        assert p.flush(at=20.0)["samples"] == 4
        fill(store, 2, t0=200.0)
        assert p.flush(at=30.0)["kind"] == "snapshot"  # 3rd flush
        # snapshot subsumed the segments
        assert p._segment_files() == []

        fresh = TimeSeriesStore()
        p2 = persister(fresh, tmp_path)
        counts = p2.restore()
        assert counts["snapshot_samples"] == 10
        assert fresh.dump_since(None) == store.dump_since(None)

    def test_segments_only_restore_preserves_order_and_seq(
            self, tmp_path):
        store = TimeSeriesStore()
        p = persister(store, tmp_path, snapshot_every=100)
        fill(store, 3, t0=0.0)
        p.flush(at=10.0)
        fill(store, 3, t0=50.0)
        p.flush(at=20.0)

        fresh = TimeSeriesStore()
        p2 = persister(fresh, tmp_path, snapshot_every=100)
        counts = p2.restore()
        assert counts == {"snapshot_samples": 0, "segment_samples": 6,
                          "segments": 2}
        assert fresh.dump_since(None) == store.dump_since(None)
        # the restored persister continues the sequence: a new flush
        # must not overwrite a replayed segment
        fill(fresh, 1, t0=99.0)
        p2.flush(at=30.0)
        assert len(p2._segment_files()) == 3

    def test_empty_flush_writes_no_segment(self, tmp_path):
        store = TimeSeriesStore()
        p = persister(store, tmp_path, snapshot_every=100)
        fill(store, 2)
        p.flush(at=10.0)
        out = p.flush(at=20.0)  # nothing new since the watermark
        assert out["samples"] == 0
        assert len(p._segment_files()) == 1

    def test_stale_marker_survives_the_json_roundtrip(self, tmp_path):
        store = TimeSeriesStore()
        store.append("up", {"job": "x"}, 1.0, 1.0)
        store.append("up", {"job": "x"}, STALE, 2.0)
        p = persister(store, tmp_path)
        p.flush(at=10.0)
        # on disk it is the string "stale", not a NaN the JSON round
        # trip would have destroyed
        seg = tmp_path / "tsdb" / p._segment_files()[0]
        doc = json.loads(seg.read_text())
        assert doc["series"][0][2][1][1] == "stale"

        fresh = TimeSeriesStore()
        persister(fresh, tmp_path).restore()
        (_, _, pts), = fresh.dump_since(None)
        assert pts[0] == (1.0, 1.0)
        assert pts[1][0] == 2.0 and is_stale(pts[1][1])
        assert STALE_NAN  # the marker is a real bit pattern

    def test_restore_tolerates_missing_dir_and_corrupt_docs(
            self, tmp_path):
        store = TimeSeriesStore()
        p = persister(store, tmp_path)
        assert p.restore() == {"snapshot_samples": 0,
                               "segment_samples": 0, "segments": 0}
        d = tmp_path / "tsdb"
        d.mkdir()
        (d / "segment-00000000.json").write_text("{torn")
        (d / "segment-00000001.json").write_text(
            json.dumps({"v": 99, "series": [["m", {}, [[1.0, 1.0]]]]}))
        good = {"v": 1, "seq": 2, "at": 5.0,
                "series": [["m", {"job": "x"}, [[1.0, 7.0]]]]}
        (d / "segment-00000002.json").write_text(json.dumps(good))
        counts = p.restore()
        assert counts["segments"] == 1
        assert counts["segment_samples"] == 1
        assert p._seq == 3  # continues past the replayed seq

    def test_restored_samples_counted_in_registry(self, tmp_path):
        store = TimeSeriesStore()
        p = persister(store, tmp_path)
        fill(store, 5)
        p.flush(at=10.0)
        reg = MetricsRegistry()
        persister(TimeSeriesStore(), tmp_path, registry=reg).restore()
        assert "obs_persist_restored_samples_total 5" in reg.render()

    def test_flush_gauges_published(self, tmp_path):
        reg = MetricsRegistry()
        store = TimeSeriesStore()
        p = persister(store, tmp_path, registry=reg,
                      snapshot_every=100)
        fill(store, 3)
        p.flush(at=10.0)
        text = reg.render()
        assert "obs_persist_flushes_total 1" in text
        assert "obs_persist_samples_total 3" in text
        assert "obs_persist_segments 1" in text


class TestCrashRecovery:
    """ISSUE 13 satellite (d): kill the persist loop mid-segment-write,
    restart, and verify no sample loss beyond the last flush interval
    and no torn reads."""

    def test_kill_mid_segment_write_loses_at_most_one_interval(
            self, tmp_path, monkeypatch):
        store = TimeSeriesStore()
        p = persister(store, tmp_path, snapshot_every=100)
        fill(store, 4, t0=0.0)
        p.flush(at=10.0)  # completed flush: its samples are durable

        # the kill: atomic_write_text dies after writing the temp file
        # but before the rename — exactly what SIGKILL mid-write leaves
        def dying_write(path, text):
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(text[: len(text) // 2])
            raise KeyboardInterrupt("SIGKILL mid-write")

        monkeypatch.setattr(P, "atomic_write_text", dying_write)
        fill(store, 4, t0=50.0)
        with pytest.raises(KeyboardInterrupt):
            p.flush(at=20.0)
        monkeypatch.undo()

        # restart: a fresh process restores from disk
        fresh = TimeSeriesStore()
        counts = persister(fresh, tmp_path).restore()
        # no torn read: the half-written .tmp is never even considered
        assert counts["segments"] == 1
        restored = {t for _, _, pts in fresh.dump_since(None)
                    for t, _ in pts}
        # every pre-kill-flush sample survived...
        assert restored == {0.0, 1.0, 2.0, 3.0}
        # ...and the loss is exactly the samples of the killed
        # interval, nothing older
        lost = {t for _, _, pts in store.dump_since(None)
                for t, _ in pts} - restored
        assert lost == {50.0, 51.0, 52.0, 53.0}

    def test_kill_between_snapshot_and_segment_cleanup_is_idempotent(
            self, tmp_path, monkeypatch):
        store = TimeSeriesStore()
        p = persister(store, tmp_path, snapshot_every=100)
        fill(store, 3, t0=0.0)
        p.flush(at=10.0)
        # kill AFTER the snapshot rename but BEFORE segment cleanup
        monkeypatch.setattr(os, "unlink",
                            lambda *a, **k: (_ for _ in ()).throw(
                                KeyboardInterrupt()))
        with pytest.raises(KeyboardInterrupt):
            p.snapshot_now(at=20.0)
        monkeypatch.undo()
        # both the snapshot and the now-redundant segment exist
        assert (tmp_path / "tsdb" / P.SNAPSHOT_FILE).exists()
        assert len(p._segment_files()) == 1

        fresh = TimeSeriesStore()
        persister(fresh, tmp_path).restore()
        # replaying the redundant segment is idempotent: restore skips
        # points at/below the snapshot's high-water mark
        assert fresh.dump_since(None) == store.dump_since(None)

    def test_stop_final_flush_makes_tail_durable(self, tmp_path):
        store = TimeSeriesStore()
        clock = ManualClock(10.0)
        p = persister(store, tmp_path, clock=clock, snapshot_every=100)
        fill(store, 3, t0=0.0)
        p.stop(final_flush=True)  # never started: stop still flushes
        fresh = TimeSeriesStore()
        persister(fresh, tmp_path).restore()
        assert fresh.dump_since(None) == store.dump_since(None)


class TestRemoteWrite:
    def _exporter(self, store, posts, fail_first=0, **kw):
        state = {"n": 0}

        def post(url, body):
            state["n"] += 1
            if state["n"] <= fail_first:
                raise OSError("conn refused")
            posts.append((url, body))

        kw.setdefault("clock", ManualClock())
        kw.setdefault("sleep", lambda s: None)
        kw.setdefault("rng", lambda: 1.0)
        return P.RemoteWriteExporter(store, "http://agg/write",
                                     post=post, **kw)

    def test_batched_jsonl_lines_and_watermark(self):
        store = TimeSeriesStore()
        fill(store, 5, t0=0.0)
        posts = []
        exp = self._exporter(store, posts, batch=2)
        assert exp.export_once(at=10.0) == 5
        assert len(posts) == 3  # 2 + 2 + 1
        lines = [json.loads(ln) for _, body in posts
                 for ln in body.decode().splitlines()]
        assert lines[0] == {"name": "m", "labels": {"job": "x"},
                            "t": 0.0, "v": 0.0}
        assert len(lines) == 5
        # watermark: nothing new -> nothing sent
        assert exp.export_once(at=20.0) == 0
        assert len(posts) == 3

    def test_backoff_is_capped_exponential_with_jitter(self):
        store = TimeSeriesStore()
        fill(store, 1)
        delays = []
        exp = self._exporter(store, [], fail_first=4,
                             retry_base=0.1, retry_cap=0.5,
                             max_retries=5, sleep=delays.append)
        assert exp.export_once(at=10.0) == 1
        # rng()==1.0 -> delays are the full min(cap, base*2^attempt)
        assert delays == [0.1, 0.2, 0.4, 0.5]

    def test_exhausted_batch_dropped_and_watermark_advances(self):
        store = TimeSeriesStore()
        fill(store, 3, t0=0.0)
        reg = MetricsRegistry()
        exp = self._exporter(store, [], fail_first=10 ** 6,
                             max_retries=2, registry=reg)
        assert exp.export_once(at=10.0) == 0
        assert exp.dropped == 3
        # lossy-by-design: the next pass does NOT retry the old window
        fill(store, 1, t0=100.0)
        exp.post = lambda url, body: None  # network heals
        assert exp.export_once(at=20.0) == 1
        text = reg.render()
        assert "obs_remote_write_sent_total 1" in text
        assert "obs_remote_write_dropped_total 3" in text

    def test_stale_marker_encoded_as_string(self):
        store = TimeSeriesStore()
        store.append("up", {}, STALE, 1.0)
        posts = []
        self._exporter(store, posts).export_once(at=10.0)
        (line,) = posts[0][1].decode().splitlines()
        assert json.loads(line)["v"] == "stale"
