"""tpulint regression corpus + tree gate (ISSUE 1 tentpole wiring).

Three layers:

1. Corpus: for every registered rule, a known-bad fragment asserting
   the rule fires with the right id AND line number, and a known-clean
   near-miss fragment asserting it stays silent (false-positive pin).
   The clean fragments encode the real idioms of this tree (params as
   jit arguments, scan bodies capturing within a trace, helpers called
   with the lock held) so rule tightening can't regress them.
2. Mechanics: suppression comments, reporters, CLI exit codes.
3. Tree gate: every kubeflow_tpu/ module is scanned parametrically —
   a new finding fails CI like any other test.
"""

import ast
import json
import pathlib
import textwrap

import pytest

from kubeflow_tpu.analysis import all_rules, render_json, render_text, scan_source
from kubeflow_tpu.analysis.__main__ import main as tpulint_main
from kubeflow_tpu.analysis import hygiene

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO / "kubeflow_tpu"


def _scan(src: str):
    return scan_source("<corpus>", textwrap.dedent(src))


# --------------------------------------------------------------------------
# corpus: (rule id) -> [(bad source, expected line)], [clean sources]
# line numbers are 1-based within the dedented fragment
# --------------------------------------------------------------------------

BAD = {
    "TPU101": [
        # the 700MB class: weight tree captured across the jit boundary
        ("""\
import jax


def make(model, variables):
    def fwd(x):
        return model.apply(variables, x)
    return jax.jit(fwd)
""", 6),
        # array built on host, closed over by the jitted fn
        ("""\
import jax
import jax.numpy as jnp


def build():
    table = jnp.arange(65536)
    def lookup(i):
        return table[i]
    return jax.jit(lookup)
""", 8),
    ],
    "TPU102": [
        ("""\
import jax


@jax.jit
def step(state, batch):
    loss = (state - batch).sum()
    print(loss)
    return loss
""", 7),
        ("""\
import jax


@jax.jit
def step(state, batch):
    return (state - batch).sum().item()
""", 6),
    ],
    "TPU103": [
        ("""\
import jax.numpy as jnp

NEG_MASK = jnp.full((1024,), -1e9)
""", 3),
    ],
    "TPU104": [
        ("""\
import jax


def train_step(state, batch):
    return state


step = jax.jit(train_step)
""", 8),
        ("""\
import functools

import jax


@functools.partial(jax.jit, static_argnames=("lr",))
def train_step(state, batch, lr):
    return state
""", 6),
    ],
    "LOCK201": [
        ("""\
import threading


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = {}

    def add(self, k, v):
        with self._lock:
            self.jobs[k] = v

    def drop(self, k):
        del self.jobs[k]
""", 14),
        ("""\
import threading


class Counter:
    def __init__(self):
        self._mu = threading.Lock()
        self.n = 0

    def bump(self):
        with self._mu:
            self.n += 1

    def reset(self):
        self.n = 0
""", 14),
    ],
    "LOCK202": [
        ("""\
import time


class NodeReconciler:
    def reconcile(self, client, req):
        time.sleep(5.0)
        return None
""", 6),
    ],
}

CLEAN = {
    "TPU101": [
        # params flow through jit arguments (speculative.py idiom)
        """\
import jax


def make(model):
    def fwd(params, x):
        return model.apply(params, x)
    return jax.jit(fwd)
""",
        # scan body capturing from its enclosing function with no jit
        # boundary: the capture is a tracer in the caller's trace
        # (flash_attention.py _flash_bwd_xla idiom)
        """\
import jax
import jax.numpy as jnp


def bwd(q, lse):
    positions = jnp.arange(q.shape[1])

    def kv_block(carry, jb):
        return carry + positions[jb], None

    out, _ = jax.lax.scan(kv_block, jnp.zeros(()), jnp.arange(4))
    return out
""",
        # arrays built INSIDE the jit root are part of the trace
        """\
import jax
import jax.numpy as jnp


def build(model):
    def fwd(x):
        scale = jnp.float32(2.0)

        def inner(y):
            return y * scale
        return inner(x)
    return jax.jit(fwd)
""",
    ],
    "TPU102": [
        """\
import jax
import numpy as np


@jax.jit
def step(state, batch):
    jax.debug.print("loss {l}", l=state.sum())
    return (state - batch).sum()


def host_epilogue(metrics):
    return float(np.asarray(metrics))
""",
        # float() on a static arg is concretization-safe
        """\
import functools

import jax


@functools.partial(jax.jit, static_argnames=("lr",))
def scale(x, lr):
    return x * float(lr)
""",
    ],
    "TPU103": [
        """\
import jax.numpy as jnp
import numpy as np

HOST_TABLE = np.arange(16)  # np at import is host-only: allowed


def masked(x):
    return x + jnp.full((8,), -1e9)
""",
        # the unaliased spelling gets the same host-numpy exemption
        """\
import numpy

HOST_TABLE = numpy.arange(16)
""",
    ],
    "TPU104": [
        """\
import jax


def train_step(state, batch):
    return state


def eval_step(state, batch):
    return state


step = jax.jit(train_step, donate_argnums=(0,))
evaluate = jax.jit(eval_step)
""",
    ],
    "LOCK201": [
        # private helper only called with the lock held (leases.py
        # _became idiom): no re-acquire required, no finding
        """\
import threading


class Elector:
    def __init__(self):
        self._lock = threading.Lock()
        self.held = False

    def acquire(self):
        with self._lock:
            return self._round()

    def _round(self):
        self.held = True
        return self.held
""",
        # recursive helper cycle whose every external entry holds the
        # lock (FakeCluster _delete_now <-> _gc_orphans shape): internal
        # cycle edges are lock-held, so the unlocked-looking writes are
        # fine and must not fire
        """\
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = {}

    def delete(self, k):
        with self._lock:
            self._delete_now(k)

    def _delete_now(self, k):
        self.items.pop(k, None)
        self._cascade(k)

    def _cascade(self, k):
        for child in list(self.items):
            if child.startswith(k):
                self._delete_now(child)
""",
        # mutually-recursive private helpers with NO locked entry point
        # must not vouch for each other (entry-point pass):
        # no finding because nothing here is ever mutated under the lock
        """\
import threading


class Orphans:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def _a(self, depth):
        self.n += 1
        if depth:
            self._b(depth - 1)

    def _b(self, depth):
        self._a(depth)

    def reset(self):
        self.n = 0
""",
        # .update() on an API client object is a call, not a container
        # mutation: must not make 'client' a guarded attribute
        """\
import threading


class Syncer:
    def __init__(self, client):
        self._lock = threading.Lock()
        self.client = client

    def push(self, obj):
        with self._lock:
            self.client.update(obj)

    def push_unlocked(self, obj):
        self.client.update(obj)
""",
    ],
    "LOCK202": [
        """\
import time


class NodeReconciler:
    def reconcile(self, client, req):
        return Result(requeue_after=5.0)

    def helper(self):
        time.sleep(0.1)  # not a reconcile body


class Result:
    def __init__(self, requeue_after=None):
        self.requeue_after = requeue_after
""",
    ],
}


def _bad_cases():
    return [(rule, src, line)
            for rule, cases in sorted(BAD.items())
            for src, line in cases]


def _clean_cases():
    return [(rule, src)
            for rule, cases in sorted(CLEAN.items())
            for src in cases]


@pytest.mark.parametrize("rule,src,line", _bad_cases(),
                         ids=lambda v: v if isinstance(v, str) and
                         v.startswith(("TPU", "LOCK")) else None)
def test_rule_fires_with_id_and_line(rule, src, line):
    findings = _scan(src)
    hits = [f for f in findings if f.rule == rule]
    assert hits, f"{rule} did not fire; got {[f.render() for f in findings]}"
    assert line in [f.line for f in hits], (
        f"{rule} fired at {[f.line for f in hits]}, expected line {line}")


@pytest.mark.parametrize("rule,src", _clean_cases(),
                         ids=lambda v: v if isinstance(v, str) and
                         v.startswith(("TPU", "LOCK")) else None)
def test_clean_fragment_stays_clean(rule, src):
    findings = [f for f in _scan(src) if f.rule == rule]
    assert not findings, [f.render() for f in findings]


def test_at_least_six_rules_each_with_both_cases():
    ids = {r.id for r in all_rules()}
    assert len(ids) >= 6, ids
    assert ids == set(BAD) == set(CLEAN), (
        "every registered rule needs a firing AND a non-firing corpus case")


# -- suppression mechanics ---------------------------------------------------

_SUPPRESSIBLE = """\
import time


class R:
    def reconcile(self, client, req):
        time.sleep(1.0){comment}
"""


def test_line_suppression_silences_only_named_rule():
    src = _SUPPRESSIBLE.format(
        comment="  # tpulint: disable=LOCK202  corpus justification")
    assert _scan(src) == []
    wrong = _SUPPRESSIBLE.format(comment="  # tpulint: disable=TPU101")
    assert [f.rule for f in _scan(wrong)] == ["LOCK202"]


def test_line_suppression_all():
    src = _SUPPRESSIBLE.format(comment="  # tpulint: disable=all")
    assert _scan(src) == []


def test_file_suppression():
    src = ("# tpulint: disable-file=LOCK202  corpus justification\n"
           + _SUPPRESSIBLE.format(comment=""))
    assert _scan(src) == []


def test_single_space_justification_still_suppresses():
    """A one-space separator must not swallow the justification into the
    rule list (which would silently disable the suppression)."""
    src = _SUPPRESSIBLE.format(
        comment="  # tpulint: disable=LOCK202 requeue handled by caller")
    assert _scan(src) == []


def test_parse_error_is_reported_not_raised():
    findings = scan_source("<corpus>", "def broken(:\n")
    assert [f.rule for f in findings] == ["TPU000"]


# -- reporters ---------------------------------------------------------------

def test_json_reporter_schema():
    findings = _scan(BAD["LOCK202"][0][0])
    doc = json.loads(render_json(findings))
    assert doc["version"] == 1
    assert doc["count"] == len(findings) == len(doc["findings"])
    entry = doc["findings"][0]
    assert set(entry) == {"rule", "path", "line", "col", "message"}
    assert entry["rule"] == "LOCK202"


def test_text_reporter_mentions_rule_and_location():
    f = _scan(BAD["LOCK202"][0][0])[0]
    text = render_text([f])
    assert "LOCK202" in text and f":{f.line}:" in text
    assert render_text([]) == "tpulint: clean"


# -- CLI ---------------------------------------------------------------------

def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(BAD["TPU104"][0][0]))
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert tpulint_main([str(good)]) == 0
    assert tpulint_main([str(bad)]) == 1
    assert tpulint_main(["--select", "NOPE999", str(bad)]) == 2
    assert tpulint_main(["--select", "LOCK202", str(bad)]) == 0  # filtered
    assert tpulint_main([str(tmp_path / "no_such_dir")]) == 2  # path typo
    capsys.readouterr()
    assert tpulint_main(["--json", str(bad)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"][0]["rule"] == "TPU104"


def test_cli_selecting_hygiene_rule_implies_hygiene_pass(tmp_path, capsys):
    """--select HYG002 without --hygiene must still run the hygiene
    pass (not silently scan nothing and exit 0)."""
    p = tmp_path / "hooked.py"
    p.write_text("breakpoint()\n")
    assert tpulint_main(["--select", "HYG002", str(p)]) == 1
    capsys.readouterr()


def test_cli_list_rules(capsys):
    assert tpulint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in list(BAD) + ["HYG001", "HYG002", "HYG003"]:
        assert rid in out


# -- hygiene gates -----------------------------------------------------------

def test_hygiene_catches_debugger_and_conflict_markers(tmp_path):
    (tmp_path / "hooked.py").write_text("x = 1\nbreakpoint()\n")
    (tmp_path / "torn.py").write_text("x = 1\n" + "<<" + "<<<<< HEAD\n")
    rules = {f.rule for f in hygiene.run_hygiene([str(tmp_path)])}
    # the conflict marker also breaks the parse gate, hence HYG001
    assert rules == {"HYG001", "HYG002", "HYG003"}


def test_hygiene_yaml_gate(tmp_path):
    p = tmp_path / "m.yaml"
    p.write_text("a: [1, 2\n")
    assert [f.rule for f in hygiene.run_hygiene([str(p)])] == ["HYG001"]


def test_hygiene_skips_explicit_non_gated_file(tmp_path):
    p = tmp_path / "watch.sh"
    p.write_text("#!/bin/bash\nwhile true; do date; done\n")
    assert hygiene.run_hygiene([str(p)]) == []


def test_hygiene_only_select_filters_parse_findings(tmp_path, capsys):
    """--select HYG002 must not leak TPU000 parse findings (and must not
    even run the tpulint parse pass)."""
    (tmp_path / "broken.py").write_text("def broken(:\n")
    (tmp_path / "hooked.py").write_text("breakpoint()\n")
    assert tpulint_main(["--select", "HYG002", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "HYG002" in out and "TPU000" not in out and "HYG001" not in out


# -- the tree gate: the shipped package must lint clean ----------------------

TREE_FILES = sorted(
    p for p in PACKAGE.rglob("*.py") if "__pycache__" not in p.parts)


@pytest.mark.parametrize("path", TREE_FILES,
                         ids=lambda p: str(p.relative_to(REPO)))
def test_tree_file_lints_clean(path):
    findings = scan_source(str(path), path.read_text())
    assert not findings, "\n".join(f.render() for f in findings)


def test_suppressions_in_tree_carry_justification():
    """Inline suppressions are allowed only with a why: prose must follow
    the rule list. Uses the framework's own suppression regex, so doc
    mentions of the syntax that core would not honor are not checked.
    Covers every python target tools/lint_all.sh scans, not just the
    package."""
    from kubeflow_tpu.analysis.core import _SUPPRESS_RE

    gated = TREE_FILES + sorted(
        (REPO / "tools").rglob("*.py")) + sorted(
        (REPO / "tests").rglob("*.py")) + [
        REPO / "bench.py", REPO / "__graft_entry__.py"]
    for path in gated:
        for i, line in enumerate(path.read_text().splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            justification = line[m.end():].strip().strip("#").strip()
            assert justification, (
                f"{path}:{i}: suppression without justification text")
