"""Tiny HTTP service toolkit shared by the platform's REST services.

The reference builds its services on Express (centraldashboard), Flask
(jupyter-web-app, echo-server) and net/http (gatekeeper, KFAM). Here one
stdlib-only layer covers them all: a method+path-pattern router over
ThreadingHTTPServer with JSON helpers and a Prometheus /metrics endpoint
(every reference service exports one — e.g. ksServer.go:347,
access-management/kfam/monitoring.go).

Routes are registered as ("GET", "/api/namespaces/{ns}/notebooks", fn);
``{name}`` segments capture path params passed to fn(req) via req.params.
Handlers return (status, body) | body — dicts are JSON-encoded.
"""

from __future__ import annotations

import json
import logging
import re
import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

log = logging.getLogger("kubeflow_tpu.httpd")


@dataclass
class HttpReq:
    method: str
    path: str
    params: dict[str, str]
    query: dict[str, list[str]]
    headers: dict[str, str]
    body: bytes = b""
    # set by auth middlewares (attach_user_middleware.ts analogue)
    user: str | None = None

    def json(self) -> Any:
        return json.loads(self.body or b"null")

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name.lower(), default)

    def q1(self, name: str, default: str = "") -> str:
        vals = self.query.get(name)
        return vals[0] if vals else default


@dataclass
class HttpResp:
    status: int = 200
    body: bytes = b""
    content_type: str = "application/json"
    headers: dict[str, str] = field(default_factory=dict)


Handler = Callable[[HttpReq], Any]


def _compile(pattern: str) -> re.Pattern:
    # {name} captures one path segment; {name*} captures the rest of the
    # path including slashes (catch-all routes: redirect/echo services).
    rx = re.sub(r"\{([a-zA-Z_][a-zA-Z0-9_]*)\*\}", r"(?P<\1>.+)", pattern)
    rx = re.sub(r"\{([a-zA-Z_][a-zA-Z0-9_]*)\}", r"(?P<\1>[^/]+)", rx)
    return re.compile("^" + rx + "$")


class Router:
    def __init__(self, name: str = "svc"):
        self.name = name
        self._routes: list[tuple[str, re.Pattern, Handler]] = []
        self._middlewares: list[Callable[[HttpReq], HttpResp | None]] = []

    def route(self, method: str, pattern: str, fn: Handler) -> None:
        self._routes.append((method.upper(), _compile(pattern), fn))

    def middleware(self, fn: Callable[[HttpReq], "HttpResp | None"]) -> None:
        """Runs before routing; returning an HttpResp short-circuits
        (gatekeeper-style auth gates)."""
        self._middlewares.append(fn)

    def dispatch(self, req: HttpReq) -> HttpResp:
        for mw in self._middlewares:
            resp = mw(req)
            if resp is not None:
                return resp
        for method, rx, fn in self._routes:
            if method != req.method:
                continue
            m = rx.match(req.path)
            if m:
                req.params = m.groupdict()
                try:
                    return to_resp(fn(req))
                except ApiHttpError as e:
                    return json_resp({"error": e.message}, e.status,
                                     headers=e.headers)
                except Exception as e:  # 500 with structured body
                    log.exception("%s: %s %s failed", self.name, req.method, req.path)
                    return json_resp({"error": str(e)}, 500)
        return json_resp({"error": f"no route for {req.method} {req.path}"}, 404)


class ApiHttpError(Exception):
    def __init__(self, status: int, message: str,
                 headers: dict[str, str] | None = None):
        super().__init__(message)
        self.status = status
        self.message = message
        # extra response headers (e.g. Retry-After on a 429/503)
        self.headers = headers or {}


def json_resp(obj: Any, status: int = 200,
              headers: dict[str, str] | None = None) -> HttpResp:
    return HttpResp(status=status, body=json.dumps(obj).encode(),
                    headers=headers or {})


def to_resp(out: Any) -> HttpResp:
    if isinstance(out, HttpResp):
        return out
    if isinstance(out, tuple) and len(out) == 2 and isinstance(out[0], int):
        status, body = out
        return json_resp(body, status) if not isinstance(body, HttpResp) else body
    if isinstance(out, (dict, list)):
        return json_resp(out)
    if isinstance(out, str):
        return HttpResp(body=out.encode(), content_type="text/plain; charset=utf-8")
    if out is None:
        return HttpResp(status=204)
    raise TypeError(f"handler returned unsupported type {type(out)}")


def add_metrics_route(router: Router) -> None:
    """Expose prometheus_client's default registry at /metrics."""

    def metrics(req: HttpReq) -> HttpResp:
        import prometheus_client

        data = prometheus_client.generate_latest()
        return HttpResp(body=data, content_type=prometheus_client.CONTENT_TYPE_LATEST)

    router.route("GET", "/metrics", metrics)


def add_health_routes(router: Router) -> None:
    """The liveness/readiness contract JWA exposes (base_app.py:170-175)."""
    router.route("GET", "/healthz", lambda r: {"status": "ok"})
    router.route("GET", "/readyz", lambda r: {"status": "ok"})


class HttpService:
    """ThreadingHTTPServer wrapper; serve_background() for tests/embedding.

    Pass ``tls`` (an ``ssl.SSLContext`` from ``tlscerts.server_context``)
    to serve HTTPS — required for admission webhooks, where the kube
    apiserver refuses plain-HTTP callees (admission-webhook/main.go:541-542
    serves cert/key for the same reason)."""

    def __init__(self, router: Router, host: str = "0.0.0.0", port: int = 0,
                 tls: "ssl.SSLContext | None" = None):
        self.router = router
        router_ref = router

        class _Handler(BaseHTTPRequestHandler):
            def _serve(self):
                parsed = urlparse(self.path)
                length = int(self.headers.get("Content-Length") or 0)
                req = HttpReq(
                    method=self.command,
                    path=parsed.path,
                    params={},
                    query=parse_qs(parsed.query),
                    headers={k.lower(): v for k, v in self.headers.items()},
                    body=self.rfile.read(length) if length else b"",
                )
                resp = router_ref.dispatch(req)
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Content-Length", str(len(resp.body)))
                for k, v in resp.headers.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(resp.body)

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _serve

            def log_message(self, fmt, *args):  # route through logging
                log.debug("%s %s", self.address_string(), fmt % args)

        self._server = ThreadingHTTPServer((host, port), _Handler)
        if tls is not None:
            self._server.socket = tls.wrap_socket(
                self._server.socket, server_side=True)
        self.tls = tls is not None
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def serve_background(self) -> "HttpService":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name=f"http-{self.router.name}"
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
