"""Kubernetes object-name validation shared by every CR-creating endpoint.

Browser-side checks (dashboard NS_RGX, spawner form) are advisory; a real
apiserver rejects non-RFC1123 metadata.name with an opaque 422, so the
backends validate up front and answer a clean 400. One validator, used by
JWA, the dashboard workgroup API, and the tensorboards CRUD app.
"""

from __future__ import annotations

import re

_DNS1123 = re.compile(r"[a-z0-9]([-a-z0-9]*[a-z0-9])?")


def is_dns1123(name: object) -> bool:
    return (isinstance(name, str) and 0 < len(name) <= 63
            and _DNS1123.fullmatch(name) is not None)


def require_dns1123(name: object, what: str = "name") -> str:
    from kubeflow_tpu.utils.httpd import ApiHttpError

    if not is_dns1123(name):
        raise ApiHttpError(
            400, f"invalid {what} {name!r}: must be lowercase RFC-1123 "
                 "(letters, digits, '-'; max 63 chars)")
    return name  # type: ignore[return-value]


def sanitize_dns1123(raw: str, fallback: str = "user") -> str:
    """Best-effort conversion of free-form text (e.g. an email localpart)
    into a valid name — for server-derived defaults, never user input."""
    s = re.sub(r"[^a-z0-9-]", "-", raw.lower()).strip("-")[:63].strip("-")
    return s if is_dns1123(s) else fallback
