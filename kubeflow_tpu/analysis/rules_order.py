"""tpulint deadlock & atomicity rules (LOCK203/LOCK204) — whole-program.

LOCK203 builds the program-wide lock-acquisition-order graph: every
``with <recv>.<lock>:`` acquisition is an edge from each lock that may
already be held at that point (lexically nested withs, plus the any-path
``may_held`` call-graph context, so an acquisition reached through a
call made under a lock still orders after that lock). A cycle in that
graph — ``_cv`` then ``_lock`` on one path, ``_lock`` then ``_cv`` on
another, across classes or modules — is the classic ABBA deadlock the
control plane's threaded mode (watch + worker threads + elector) could
only hit probabilistically at runtime.

LOCK204 is the check-then-act (TOCTOU) atomicity rule: a guarded
attribute read *outside* any lock in an ``if``/``while`` test, followed
by a locked write of that same attribute inside the branch. Between the
unlocked check and the locked act another thread may have changed the
state, so the decision is stale. The accepted idiom — re-checking the
condition once the lock is held (double-checked locking) — is
recognized and stays quiet.
"""

from __future__ import annotations

import ast
from typing import Iterator

from kubeflow_tpu.analysis.callgraph import Program, Token, receiver_attr
from kubeflow_tpu.analysis.core import Finding, ProgramRule, register


def _token_str(t: Token) -> str:
    return f"{t[0].split(':')[-1]}.{t[1]}"


def _own_walk(node: ast.AST) -> Iterator[ast.AST]:
    """Walk without descending into nested function/lambda defs: their
    bodies run at call time, not in this branch (mirrors the lock-
    context rule in Program.lex_tokens)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)) and cur is not node:
            continue
        stack.extend(ast.iter_child_nodes(cur))


def _sccs(adj: dict[Token, set[Token]]) -> list[list[Token]]:
    """Tarjan's strongly-connected components, iterative (no recursion
    limit risk on long chains). Returns components of size >= 2."""
    index: dict[Token, int] = {}
    low: dict[Token, int] = {}
    on_stack: set[Token] = set()
    stack: list[Token] = []
    out: list[list[Token]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work: list[tuple[Token, list[Token], int]] = [
            (root, sorted(adj.get(root, ())), 0)]
        while work:
            node, succs, i = work.pop()
            if i == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            while i < len(succs):
                s = succs[i]
                i += 1
                if s not in index:
                    work.append((node, succs, i))
                    work.append((s, sorted(adj.get(s, ())), 0))
                    recurse = True
                    break
                if s in on_stack:
                    low[node] = min(low[node], index[s])
            if recurse:
                continue
            if low[node] == index[node]:
                comp: list[Token] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) >= 2:
                    out.append(sorted(comp))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return out


@register
class LockOrderCycle(ProgramRule):
    """LOCK203: two locks acquired in opposite orders on different
    program paths — a potential ABBA deadlock under the threaded
    controller mode."""

    id = "LOCK203"
    name = "lock-order-cycle"
    short = "locks acquired in opposite orders on different paths"

    def check_program(self, program: Program) -> Iterator[Finding]:
        edges = program.lock_order_edges()
        adj: dict[Token, set[Token]] = {}
        for held, acquired, _node, _module in edges:
            adj.setdefault(held, set()).add(acquired)
            adj.setdefault(acquired, set())
        for comp in _sccs(adj):
            members = set(comp)
            cycle = " -> ".join(_token_str(t) for t in comp)
            for held, acquired, node, module in edges:
                if held in members and acquired in members:
                    yield Finding(
                        self.id, module.path, node.lineno, node.col_offset,
                        f"'{_token_str(acquired)}' acquired while holding "
                        f"'{_token_str(held)}', but another path acquires "
                        f"them in the opposite order (cycle: {cycle}) — "
                        "potential deadlock; pick one global order")


@register
class CheckThenAct(ProgramRule):
    """LOCK204: unlocked read of a guarded attribute deciding a locked
    write of that attribute — the decision is stale by the time the
    lock arrives. Re-check under the lock (double-checked locking)."""

    id = "LOCK204"
    name = "check-then-act"
    short = "guarded attribute checked without the lock, then written under it"

    def check_program(self, program: Program) -> Iterator[Finding]:
        guarded = program.guarded_map()
        entry = program.locked_entry()
        for fi in program.functions.values():
            if not fi.param_classes:
                continue
            ctx = entry.get(fi.qual, frozenset())
            for node in ast.walk(fi.node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                yield from self._check_branch(program, fi, ctx, node, guarded)

    def _check_branch(self, program: Program, fi, ctx, node,
                      guarded) -> Iterator[Finding]:
        # reads of guarded attrs in the test, per receiver class
        read: list[tuple[str, str, str]] = []  # (recv, class_qual, attr)
        for sub in ast.walk(node.test):
            if not (isinstance(sub, ast.Attribute)
                    and isinstance(sub.ctx, ast.Load)):
                continue
            if not isinstance(sub.value, ast.Name):
                continue
            recv = sub.value.id
            cqual = fi.param_classes.get(recv)
            if cqual is None:
                continue
            if sub.attr in guarded.get(cqual, ()):
                read.append((recv, cqual, sub.attr))
        if not read:
            return
        # the check itself must be unlocked (lexically and by entry
        # context) for the class whose attr it reads
        held = program.lex_tokens(node, fi) | ctx
        for recv, cqual, attr in read:
            if any(cq == cqual for cq, _ in held):
                continue
            for w in _own_walk(node):
                if not (isinstance(w, ast.With)
                        and self._acquires(program, fi, w, cqual)):
                    continue
                if self._rechecks(w, recv, attr):
                    continue  # double-checked locking: the real idiom
                if self._writes_attr(program, w, recv, attr):
                    yield Finding(
                        self.id, fi.module.path, node.test.lineno,
                        node.test.col_offset,
                        f"'{recv}.{attr}' is read here without its lock, "
                        "then written under the lock inside this branch — "
                        "the check is stale by the time the lock is held; "
                        "re-check under the lock (double-checked locking) "
                        "or widen the locked region")
                    break

    @staticmethod
    def _acquires(program: Program, fi, with_node: ast.With,
                  cqual: str) -> bool:
        return any((tok := program._with_token(item.context_expr, fi))
                   is not None and tok[0] == cqual
                   for item in with_node.items)

    @staticmethod
    def _rechecks(with_node: ast.With, recv: str, attr: str) -> bool:
        """A re-read of recv.attr in a test/assert inside the locked
        region means the decision is re-made under the lock."""
        for sub in _own_walk(with_node):
            if isinstance(sub, (ast.If, ast.While, ast.IfExp, ast.Assert)):
                test = sub.test
                for n in ast.walk(test):
                    if (receiver_attr(n, recv) == attr
                            and isinstance(getattr(n, "ctx", None), ast.Load)):
                        return True
        return False

    @staticmethod
    def _writes_attr(program: Program, with_node: ast.With, recv: str,
                     attr: str) -> bool:
        roots = {recv: ""}
        for sub in _own_walk(with_node):
            for r, a, _loc in Program._write_targets(sub, roots):
                if r == recv and a == attr:
                    return True
        return False
