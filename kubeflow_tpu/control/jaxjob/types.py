"""JAXJob CRD: API types, defaults, validation.

The reference's TFJob spec shape (replicaSpecs with per-replica pod
templates — tf-controller-examples/tf-cnn/create_job_specs.py:125-191)
collapses on TPU: parameter servers disappear (synchronous in-XLA
allreduce replaces them) and MASTER/WORKER distinction reduces to
process_id 0. A JAXJob is therefore one homogeneous worker set plus TPU
slice topology.

Condition types follow the Katib/TFJob contract that E2E tests poll
(testing/katib_studyjob_test.py:128-194 waits on
status.conditions[].type == Running): Created, Running, Restarting,
Succeeded, Failed.
"""

from __future__ import annotations

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.scheduler import SCHEDULER_NAME
from kubeflow_tpu.control.scheduler.topology import parse_topology

GROUP = "kubeflow.org"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "JAXJob"

# Condition types (katib/tf-operator contract)
COND_CREATED = "Created"
COND_RUNNING = "Running"
COND_RESTARTING = "Restarting"
COND_SUCCEEDED = "Succeeded"
COND_FAILED = "Failed"
# Elastic addition (not part of the katib polling contract, which only
# reads the five above): True while the gang runs below its full size —
# set on shrink, cleared when grow-back restores every worker.
COND_RESIZING = "Resizing"

# Pod labels (the `notebook-name` analogue, notebook_controller.go:541-563)
LABEL_JOB_NAME = "jaxjob.kubeflow.org/job-name"
LABEL_REPLICA_INDEX = "jaxjob.kubeflow.org/replica-index"
LABEL_SLICE_INDEX = "jaxjob.kubeflow.org/slice-index"

# Pod incarnation marker: the gang epoch (status.restarts +
# status.preemptions at creation time). A pod whose epoch is older than
# the job's current epoch belongs to a TORN-DOWN incarnation — the
# controller condemns it (deletes, excludes from status derivation)
# instead of re-reading its phase as a fresh failure. This is what
# makes gang restart resumable across transient apiserver errors
# without double-counting the restart budget.
ANNOTATION_EPOCH = "jaxjob.kubeflow.org/epoch"

# Elastic world stamp: the serialized parallel.dist.WorldSpec naming the
# CURRENT world's ordered members (rank = position, coordinator =
# members[0]). The controller re-stamps every live pod on a resize; the
# downward API projects the annotation into the pod (generate_pod mounts
# it at WORLD_FILE_PATH) so the worker-side elastic coordinator
# (runtime/elastic.py) sees shrink/grow without a kube client.
ANNOTATION_WORLD = "jaxjob.kubeflow.org/world"
WORLD_FILE_PATH = "/etc/jaxjob/world"

# Elastic resize policies (spec.elastic.resizePolicy)
RESIZE_RESIZE = "Resize"
RESIZE_RESTART = "Restart"
# Slice-failure policies (spec.elastic.slicePolicy, multislice jobs):
# worker-granular Resize cannot shrink a sliceCount > 1 gang (the dcn
# mesh axis moves in whole slices), so slice elasticity is its own
# knob. Shrink: losing any worker of a slice condemns exactly that
# slice's pods and the world shrinks to the surviving slices (gen bump,
# dcn axis shrinks, batchPolicy applies); below minSlices the normal
# restart path takes over. Restart (default): any loss restarts the
# whole gang — the pre-slice semantics.
SLICE_SHRINK = "Shrink"
SLICE_RESTART = "Restart"
# Global-batch policies across a resize (spec.elastic.batchPolicy):
# Preserve keeps the global batch (the loss curve is continuous);
# Scale shrinks/grows the global batch with the world. Values are the
# ENV_BATCH_POLICY wire contract, re-exported below from dist (the ONE
# spelling the worker-side coordinator compares against).
# Resizes never burn maxRestarts/maxPreemptions, but a generous ceiling
# bounds a pathological shrink/grow flap the way maxPreemptions bounds
# an always-75 loop; beyond it the controller falls back to the normal
# preemption-restart path (whose own budget then applies).
DEFAULT_MAX_RESIZES = 100

# Env contract consumed by kubeflow_tpu.parallel.dist.initialize_from_env.
# Re-exported from dist (ONE authoritative spelling of the wire contract);
# the import is jax-free — parallel/__init__ is lazy exactly so the
# control plane can import dist, and test_dist.py pins that property.
from kubeflow_tpu.parallel.dist import (  # noqa: E402
    BATCH_PRESERVE,
    BATCH_SCALE,
    ENV_BATCH_POLICY,
    ENV_COORD,
    ENV_NAME,
    ENV_NAMESPACE,
    ENV_NPROC,
    ENV_NUM_SLICES,
    ENV_PID,
    ENV_SLICE_ID,
    ENV_WORLD_FILE,
)

# GKE TPU scheduling surface (the nvidia.com/gpu swap point —
# create_job_specs.py:165-170 sets resources.limits["nvidia.com/gpu"])
RESOURCE_TPU = "google.com/tpu"
NODESELECTOR_ACCEL = "cloud.google.com/gke-tpu-accelerator"
NODESELECTOR_TOPOLOGY = "cloud.google.com/gke-tpu-topology"

DEFAULT_COORDINATOR_PORT = 8476
RESTART_GANG = "GangOnFailure"
RESTART_NEVER = "Never"

# The launcher's graceful-preemption exit status (runtime/preemption.py
# EX_TEMPFAIL): the worker checkpointed and asked for a gang restart.
# Preemptions are counted in status.preemptions and do NOT consume the
# maxRestarts crash budget — TPU maintenance can evict a slice many
# times without the job being at fault.
EXIT_PREEMPTED = 75
# GKE taints nodes ahead of TPU maintenance/preemption; treat as unhealthy
TAINT_IMPENDING_TERMINATION = "cloud.google.com/impending-node-termination"


def gang_size(spec: dict) -> int:
    """Total worker pods = replicas-per-slice x sliceCount. The whole
    multislice set is ONE gang and ONE jax.distributed world; the mesh's
    `dcn` axis spans the slice boundary (parallel/mesh.py)."""
    return spec.get("replicas", 1) * spec.get("sliceCount", 1)


def elastic_spec(spec: dict) -> dict | None:
    """spec.elastic with defaults applied, or None when absent."""
    el = spec.get("elastic")
    if not isinstance(el, dict):
        return None
    return {
        "minReplicas": el.get("minReplicas", 1),
        "maxReplicas": el.get("maxReplicas", gang_size(spec)),
        "resizePolicy": el.get("resizePolicy", RESIZE_RESIZE),
        "batchPolicy": el.get("batchPolicy", BATCH_PRESERVE),
        "maxResizes": el.get("maxResizes", DEFAULT_MAX_RESIZES),
        "slicePolicy": el.get("slicePolicy", SLICE_RESTART),
        "minSlices": el.get("minSlices", 1),
    }


def is_slice_elastic(spec: dict) -> bool:
    """True when slice loss shrinks the world instead of restarting the
    gang: a multislice job with spec.elastic.slicePolicy Shrink."""
    el = elastic_spec(spec)
    return bool(el and spec.get("sliceCount", 1) > 1
                and el["slicePolicy"] == SLICE_SHRINK)


def is_elastic(spec: dict) -> bool:
    """True when the controller should resize instead of restart.
    Single-slice jobs: spec.elastic with resizePolicy Resize (Restart
    keeps restart semantics while still opting into spot-pool
    scheduling). Multislice jobs resize at SLICE granularity only —
    slicePolicy Shrink (worker-granular Resize cannot move the dcn
    axis)."""
    el = elastic_spec(spec)
    if not el:
        return False
    if spec.get("sliceCount", 1) > 1:
        return el["slicePolicy"] == SLICE_SHRINK
    return el["resizePolicy"] == RESIZE_RESIZE


def elastic_floor(spec: dict) -> int:
    """The smallest world (in WORKERS) a shrink may reach: minReplicas
    for worker-granular elasticity, minSlices x replicas for
    slice-granular (slices shrink whole)."""
    el = elastic_spec(spec)
    if el is None:
        return gang_size(spec)
    if is_slice_elastic(spec):
        return el["minSlices"] * spec.get("replicas", 1)
    return el["minReplicas"]


def new_jaxjob(
    name: str,
    namespace: str = "default",
    *,
    replicas: int = 1,
    slice_count: int = 1,
    image: str = "kubeflow-tpu/jaxrt:latest",
    command: list[str] | None = None,
    accelerator: str | None = None,
    topology: str | None = None,
    chips_per_worker: int = 4,
    restart_policy: str = RESTART_GANG,
    max_restarts: int = 3,
    priority: int = 0,
    gang_schedule: bool = False,
    elastic_min: int | None = None,
    resize_policy: str = RESIZE_RESIZE,
    batch_policy: str = BATCH_PRESERVE,
    slice_policy: str | None = None,
    min_slices: int | None = None,
) -> dict:
    """Convenience constructor (the create_job_specs.py analogue).

    ``replicas`` is the worker count PER SLICE; ``slice_count`` > 1 asks
    for a multislice deployment (the reference's closest analogue is the
    multi-replica TFJob topology, create_job_specs.py:125-191 — but DCN
    replaces the PS/gRPC fabric).

    ``gang_schedule=True`` opts the job into the TPU gang scheduler
    (control/scheduler): generated pods get spec.schedulerName plus a
    scheduling gate, and are only run once the whole gang is bound
    all-or-nothing. ``priority`` orders admission; a higher-priority
    gang may preempt a running lower-priority one.

    ``elastic_min`` makes the job ELASTIC (docs/elastic.md): on node
    loss/preemption the gang shrinks to the survivors (down to this
    floor) instead of restarting, and grows back when capacity returns;
    with gang_schedule, the scheduler may also admit the gang partially
    (>= elastic_min) and prefers spot-pool nodes for its workers."""
    spec: dict = {
        "replicas": replicas,
        "template": {
            "metadata": {"labels": {}},
            "spec": {
                "containers": [
                    {
                        "name": "jax",
                        "image": image,
                        "command": command
                        or ["python", "-m", "kubeflow_tpu.runtime.launcher"],
                    }
                ],
                "restartPolicy": "Never",
            },
        },
        "coordinatorPort": DEFAULT_COORDINATOR_PORT,
        "restartPolicy": restart_policy,
        "maxRestarts": max_restarts,
    }
    if slice_count > 1:
        spec["sliceCount"] = slice_count
    if priority:
        spec["priority"] = priority
    if elastic_min is not None or slice_policy is not None:
        el: dict = {}
        if elastic_min is not None:
            el["minReplicas"] = elastic_min
            el["resizePolicy"] = resize_policy
        el["batchPolicy"] = batch_policy
        if slice_policy is not None:
            el["slicePolicy"] = slice_policy
        if min_slices is not None:
            el["minSlices"] = min_slices
        spec["elastic"] = el
    if gang_schedule:
        spec["schedulerName"] = SCHEDULER_NAME
    if accelerator:
        spec["tpu"] = {
            "accelerator": accelerator,
            "topology": topology or "",
            "chipsPerWorker": chips_per_worker,
        }
    return ob.new_object(API_VERSION, KIND, name, namespace, spec=spec)


def validate(job: dict) -> list[str]:
    """Spec validation; returned problems become Failed-condition reasons."""
    errs = []
    spec = job.get("spec") or {}
    replicas = spec.get("replicas", 1)
    if not isinstance(replicas, int) or replicas < 1:
        errs.append(f"spec.replicas must be a positive int, got {replicas!r}")
    slices = spec.get("sliceCount", 1)
    if not isinstance(slices, int) or slices < 1:
        errs.append(f"spec.sliceCount must be a positive int, got {slices!r}")
    tmpl = spec.get("template") or {}
    containers = (tmpl.get("spec") or {}).get("containers") or []
    if not containers:
        errs.append("spec.template.spec.containers must have at least one container")
    rp = spec.get("restartPolicy", RESTART_GANG)
    if rp not in (RESTART_GANG, RESTART_NEVER):
        errs.append(f"spec.restartPolicy must be {RESTART_GANG} or {RESTART_NEVER}")
    port = spec.get("coordinatorPort", DEFAULT_COORDINATOR_PORT)
    if not isinstance(port, int) or not (0 < port < 65536):
        errs.append(f"spec.coordinatorPort invalid: {port!r}")
    prio = spec.get("priority", 0)
    if not isinstance(prio, int) or isinstance(prio, bool):
        errs.append(f"spec.priority must be an int, got {prio!r}")
    errs += _validate_elastic(spec)
    errs += _validate_tpu_topology(spec)
    return errs


def _validate_elastic(spec: dict) -> list[str]:
    raw = spec.get("elastic")
    if raw is None:
        return []
    if not isinstance(raw, dict):
        return [f"spec.elastic must be an object, got {raw!r}"]
    errs = []
    el = elastic_spec(spec)
    total = gang_size(spec)
    mn, mx = el["minReplicas"], el["maxReplicas"]

    def _posint(v) -> bool:
        return isinstance(v, int) and not isinstance(v, bool) and v >= 1

    if not _posint(mn):
        errs.append(f"spec.elastic.minReplicas must be a positive int, "
                    f"got {mn!r}")
    if not _posint(mx):
        errs.append(f"spec.elastic.maxReplicas must be a positive int, "
                    f"got {mx!r}")
    if _posint(mn) and _posint(mx):
        if mn > mx:
            errs.append(f"spec.elastic.minReplicas {mn} > maxReplicas {mx}")
        if mx != total:
            # the controller provisions the full gang and shrinks within
            # it; a maxReplicas above the pod set could never be reached
            # and one below it would strand provisioned workers
            errs.append(
                f"spec.elastic.maxReplicas {mx} must equal replicas x "
                f"sliceCount = {total} (the provisioned gang)")
    if el["resizePolicy"] not in (RESIZE_RESIZE, RESIZE_RESTART):
        errs.append(f"spec.elastic.resizePolicy must be {RESIZE_RESIZE} "
                    f"or {RESIZE_RESTART}")
    if el["batchPolicy"] not in (BATCH_PRESERVE, BATCH_SCALE):
        errs.append(f"spec.elastic.batchPolicy must be {BATCH_PRESERVE} "
                    f"or {BATCH_SCALE}")
    if not _posint(el["maxResizes"]):
        errs.append(f"spec.elastic.maxResizes must be a positive int, "
                    f"got {el['maxResizes']!r}")
    multislice = spec.get("sliceCount", 1) > 1
    if el["slicePolicy"] not in (SLICE_SHRINK, SLICE_RESTART):
        errs.append(f"spec.elastic.slicePolicy must be {SLICE_SHRINK} "
                    f"or {SLICE_RESTART}")
    if not _posint(el["minSlices"]):
        errs.append(f"spec.elastic.minSlices must be a positive int, "
                    f"got {el['minSlices']!r}")
    elif el["minSlices"] > spec.get("sliceCount", 1):
        errs.append(f"spec.elastic.minSlices {el['minSlices']} > "
                    f"sliceCount {spec.get('sliceCount', 1)}")
    if (multislice and el["resizePolicy"] == RESIZE_RESIZE
            and "slicePolicy" not in raw):
        # the pre-slicePolicy shape: worker-granular Resize cannot
        # shrink a multislice gang (the dcn axis moves in whole
        # slices). Point at the migration instead of silently changing
        # what the old spelling meant.
        errs.append(
            "spec.elastic on a multislice job resizes at SLICE "
            f"granularity: add elastic.slicePolicy: {SLICE_SHRINK} to "
            "shrink to surviving slices on slice loss (or "
            f"{SLICE_RESTART} to keep whole-gang restarts); "
            "worker-granular resizePolicy Resize alone is not "
            "supported with sliceCount > 1")
    if is_elastic(spec):
        argv = _worker_argv(spec)
        if "--" in argv and "--config" not in argv:
            # only the launcher's built-in-trainer path wires the
            # ElasticCoordinator; a user payload after "--" would never
            # see a resize — its world file updates unread while the
            # controller shrinks the gang around it
            errs.append(
                "spec.elastic with in-place resize (resizePolicy "
                f"{RESIZE_RESIZE} / slicePolicy {SLICE_SHRINK}) "
                "requires the built-in trainer (launcher --config): a "
                "user command after '--' cannot follow a resize (use "
                f"{RESIZE_RESTART} for spot tolerance without in-place "
                "resize)")
    return errs


def _worker_argv(spec: dict) -> list:
    """The worker container's effective argv (command + args)."""
    tmpl_spec = (spec.get("template") or {}).get("spec") or {}
    c = (tmpl_spec.get("containers") or [{}])[0]
    if not isinstance(c, dict):
        return []
    return list(c.get("command") or []) + list(c.get("args") or [])


def _validate_tpu_topology(spec: dict) -> list[str]:
    """Slice-geometry consistency: the topology's chip count must equal
    replicas x chipsPerWorker, or the gang can never be placed on one
    slice — catching it at admission beats a forever-Pending pod set."""
    tpu = spec.get("tpu") or {}
    topology = tpu.get("topology") or ""
    chips = tpu.get("chipsPerWorker")
    if not topology or not chips:
        return []
    try:
        # the ONE topology parser (control/scheduler/topology.py);
        # AST-pinned against reimplementation in tests/test_scheduler.py
        slice_chips = parse_topology(topology).chips
    except ValueError:
        return [f"spec.tpu.topology {topology!r} is not NxM[xK]"]
    replicas = spec.get("replicas", 1)
    if isinstance(replicas, int) and replicas >= 1 \
            and slice_chips != replicas * chips:
        return [f"spec.tpu.topology {topology} has {slice_chips} chips but "
                f"replicas x chipsPerWorker = {replicas} x {chips} = "
                f"{replicas * chips}; the gang cannot tile the slice"]
    return []


def crd_manifest() -> dict:
    """The CustomResourceDefinition applied by tpctl."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"jaxjobs.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "listKind": "JAXJobList",
                "plural": "jaxjobs",
                "singular": "jaxjob",
                "shortNames": ["jj"],
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "x-kubernetes-preserve-unknown-fields": True,
                        }
                    },
                }
            ],
        },
    }
