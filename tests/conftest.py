"""Test harness: force an 8-device virtual CPU mesh.

The reference has no fake backend for distributed tests — its distributed
behavior is only exercised on real per-CI-run GKE clusters (SURVEY.md §4).
This conftest is the fake backend: every test sees 8 XLA host devices, so
dp/fsdp/tp/sp/ep shardings compile and run hermetically.

Must run before jax initializes a backend, hence env mutation at import
time (pytest imports conftest before test modules).
"""

import os

# Unconditional: the image pins JAX_PLATFORMS=axon (real TPU tunnel);
# tests are hermetic CPU by design.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep XLA/CPU from oversubscribing the test machine.
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The image's sitecustomize imports jax at interpreter start (TPU tunnel
# plugin), so jax's config has already captured JAX_PLATFORMS=axon; the
# env var alone is too late. Override the live config before any backend
# initializes (backends init lazily at first jax.devices()).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent XLA compilation cache: many tests build Trainers over the
# same tiny models, and each new jit closure recompiles identical HLO.
# The disk cache turns those (and repeat suite runs) into ~ms loads.
# Pure speedup: never a hard dependency (read-only HOME just skips it).
try:
    _cache_dir = os.environ.get("KFTPU_TEST_JAX_CACHE",
                                os.path.expanduser("~/.cache/kftpu-test-jax"))
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except OSError:
    pass

import pytest  # noqa: E402

# Chaos tier knobs (the TPU_RACE_* convention, threaded here so every
# chaos test agrees on one seed set): TPU_CHAOS_RATE scales the per-call
# fault probability of the chaos-parameterized reruns and the soak;
# TPU_CHAOS_SEED re-bases the seed sweep so CI can explore fresh fault
# schedules without editing tests. Defaults are the committed, verified
# schedule — every seed in CHAOS_SEEDS converges deterministically.
CHAOS_RATE = float(os.environ.get("TPU_CHAOS_RATE") or 0.05)
CHAOS_SEED_BASE = int(os.environ.get("TPU_CHAOS_SEED") or 1)
CHAOS_SEEDS = tuple(CHAOS_SEED_BASE + i for i in range(5))


@pytest.fixture(scope="session")
def devices8():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-process e2e tests (gang worlds, real subprocesses); "
        "run explicitly or via the full suite",
    )
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection tier (tests/test_chaos.py); knobs: "
        "TPU_CHAOS_SEED / TPU_CHAOS_RATE; the full-platform soak is also "
        "marked slow",
    )


def pytest_collection_modifyitems(config, items):
    """Default tier: deselect `slow` tests — but never when the user
    passed an explicit -m expression, or named the test's file directly
    (pytest tests/test_gang_e2e.py must run its tests)."""
    if config.option.markexpr:
        return
    explicit = {
        os.path.abspath(a.split("::")[0])
        for a in config.args
        if a.split("::")[0].endswith(".py")
    }
    deselected = [
        it for it in items
        if "slow" in it.keywords and str(it.fspath) not in explicit
    ]
    if deselected:
        config.hook.pytest_deselected(items=deselected)
        dropped = set(deselected)
        items[:] = [it for it in items if it not in dropped]


def make_segments(b, l, n_docs, seed=7):
    """Random monotone sequence-packing ids [b, l] (1-based spans) —
    shared by the flash/ring/ulysses segment-masking tests."""
    import numpy as np

    rng = np.random.RandomState(seed)
    seg = np.zeros((b, l), np.int32)
    for r in range(b):
        cuts = np.sort(rng.choice(np.arange(1, l), n_docs - 1, replace=False))
        seg[r] = np.searchsorted(cuts, np.arange(l), side="right")
    import jax.numpy as jnp

    return jnp.asarray(seg)


# -- virtual-time determinism guard (ISSUE 16) -------------------------------
#
# The bench-contract tests assert byte-stable decision fingerprints, which
# only holds if the modules under test never read the wall clock during a
# replay. tpulint's DET6xx family proves that statically; this fixture is
# the dynamic twin: it snapshots time.time() call counts per calling
# module across the test and fails on any read attributed to a
# replay-critical module (the docs/scale.md "Determinism contract" list).

REPLAY_CRITICAL_MODULES = (
    "kubeflow_tpu.control.scheduler",
    "kubeflow_tpu.control.cache",
    "kubeflow_tpu.serving.router",
    "kubeflow_tpu.serving.continuous",
    "kubeflow_tpu.obs",
    "kubeflow_tpu.control.jaxservice",
    "kubeflow_tpu.control.jaxjob",
)


@pytest.fixture
def virtual_time_guard(monkeypatch):
    """Fail the test if a replay-critical module reads time.time().

    Yields the live {caller module -> call count} snapshot so a test can
    also assert on reads it *expects* (e.g. from the bench harness
    itself, which owns the virtual clock and may read real time freely).
    """
    import sys
    import time as _time

    real_time = _time.time
    reads: dict = {}

    def guarded_time():
        mod = sys._getframe(1).f_globals.get("__name__", "<unknown>")
        reads[mod] = reads.get(mod, 0) + 1
        return real_time()

    monkeypatch.setattr(_time, "time", guarded_time)
    yield reads
    offenders = {m: n for m, n in sorted(reads.items())
                 if m.startswith(REPLAY_CRITICAL_MODULES)}
    assert not offenders, (
        "wall-clock time.time() read from replay-critical module(s) "
        f"during a bench-contract test: {offenders} — inject a clock "
        "(see docs/scale.md 'Determinism contract')")
