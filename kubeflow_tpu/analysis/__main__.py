"""tpulint CLI: ``python -m kubeflow_tpu.analysis [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage error. ``--hygiene`` adds the
stdlib hygiene gates (parse/debugger/conflict-marker, yaml manifests)
on top of the tpulint rules, so tools/lint_all.sh is one process.
``--format sarif`` emits a code-scanning artifact; ``--sarif-file``
writes one alongside whatever stdout format is selected (so a CI pass
gets an uploadable artifact without a second scan); ``--write-baseline``
/ ``--baseline`` implement the ratchet (fail only on NEW findings).
``--select``/``--rules`` accept FAMILY prefixes (``RES``, ``WIRE``,
``DET``…): an all-caps token expands to every registered id spelled
``<token><digits>``. Multi-path scans run the whole-program rules
(cross-module call graph) over all paths as one program.
"""

from __future__ import annotations

import argparse
import pathlib
import re
import sys

from kubeflow_tpu.analysis import core, hygiene, report


def _parse_rules(text: str | None) -> set[str] | None:
    if not text:
        return None
    return {r.strip() for r in text.split(",") if r.strip()}


def _expand_families(wanted: set[str] | None,
                     known: set[str]) -> set[str] | None:
    """Expand family prefixes: ``RES`` -> RES701..RES705. A token that
    is already a known id, or matches no family, passes through (the
    unknown-id check still rejects typos)."""
    if not wanted:
        return wanted
    out: set[str] = set()
    for token in wanted:
        if token in known:
            out.add(token)
            continue
        family = {k for k in known
                  if re.fullmatch(re.escape(token) + r"\d+", k)}
        out |= family or {token}
    return out


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.analysis",
        description="JAX/TPU-aware static analysis (tpulint)")
    parser.add_argument("paths", nargs="*", default=["kubeflow_tpu"],
                        help="files or directories to scan "
                             "(default: kubeflow_tpu)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout "
                             "(alias for --format json)")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text",
                        help="report format (sarif for CI code-scanning "
                             "uploads)")
    parser.add_argument("--select", "--rules", dest="select",
                        metavar="RULES",
                        help="comma-separated rule ids to run exclusively")
    parser.add_argument("--ignore", metavar="RULES",
                        help="comma-separated rule ids to skip")
    parser.add_argument("--baseline", metavar="FILE",
                        help="ratchet mode: fail only on findings not in "
                             "this baseline file")
    parser.add_argument("--write-baseline", metavar="FILE",
                        help="write current findings as the baseline and "
                             "exit 0")
    parser.add_argument("--sarif-file", metavar="FILE",
                        help="also write a SARIF artifact to FILE "
                             "(independent of the stdout format)")
    parser.add_argument("--hygiene", action="store_true",
                        help="also run the stdlib hygiene gates "
                             "(parse/debugger/conflict markers, yaml)")
    parser.add_argument("--jobs", type=int, default=1, metavar="N",
                        help="shard the scan across N worker processes "
                             "(fork pool; output is byte-identical to "
                             "the serial run)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rules and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in core.all_rules():
            print(f"{rule.id}  {rule.name}: {rule.short}")
        for rid, short in sorted(hygiene.HYGIENE_RULES.items()):
            print(f"{rid}  hygiene: {short}")
        return 0

    for raw in args.paths:
        if not pathlib.Path(raw).exists():
            # a typo'd path must not exit 0 "clean" while scanning nothing
            print(f"no such path: {raw}", file=sys.stderr)
            return 2

    select, ignore = _parse_rules(args.select), _parse_rules(args.ignore)
    known = {r.id for r in core.all_rules()} | {core.PARSE_RULE}
    known |= set(hygiene.HYGIENE_RULES)
    select = _expand_families(select, known)
    ignore = _expand_families(ignore, known)
    for wanted in (select or set()) | (ignore or set()):
        if wanted not in known:
            print(f"unknown rule id: {wanted}", file=sys.stderr)
            return 2
    if select and select & set(hygiene.HYGIENE_RULES):
        # selecting a HYG id implies the hygiene pass — otherwise the
        # selection would silently scan nothing and exit 0
        args.hygiene = True

    if args.jobs < 0:
        print(f"--jobs must be >= 0, got {args.jobs}", file=sys.stderr)
        return 2
    findings = core.scan_paths(args.paths, select=select, ignore=ignore,
                               jobs=args.jobs)
    if args.hygiene:
        hyg = hygiene.run_hygiene(args.paths)
        if select:
            hyg = [f for f in hyg if f.rule in select]
        if ignore:
            hyg = [f for f in hyg if f.rule not in ignore]
        findings = sorted(findings + hyg, key=core._sort_key)

    if args.sarif_file:
        pathlib.Path(args.sarif_file).write_text(
            report.render_sarif(findings))

    if args.write_baseline:
        pathlib.Path(args.write_baseline).write_text(
            report.render_baseline(findings))
        print(f"tpulint: baseline written to {args.write_baseline} "
              f"({len(findings)} finding"
              f"{'s' if len(findings) != 1 else ''})")
        return 0
    if args.baseline:
        try:
            baseline = report.load_baseline(
                pathlib.Path(args.baseline).read_text())
        except FileNotFoundError:
            print(f"no such baseline: {args.baseline}", file=sys.stderr)
            return 2
        findings = report.new_findings(findings, baseline)

    fmt = "json" if args.json else args.format
    print(report.render_sarif(findings) if fmt == "sarif"
          else report.render_json(findings) if fmt == "json"
          else report.render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
