"""HTTP apiserver speaking Kubernetes JSON, backed by FakeCluster.

This is the bridge that lets RestClient (control/k8s/rest.py) — the
client-go analogue the controllers use against a live cluster — be
exercised hermetically: the full HTTP surface (CRUD, PUT /status,
merge/json PATCH, label/field selectors, 404/409 status codes, chunked
watch streams) is served by a real ThreadingHTTPServer in front of the
same in-memory store the unit tests use. A controller runs identically
on FakeCluster (direct) and RestClient->ApiServer->FakeCluster (HTTP);
tests/test_rest_apiserver.py asserts exactly that.

The reference had nothing like this: its controllers are only integration
-tested against per-CI GKE clusters (SURVEY.md §4 tier 4).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.rest import _KINDS

log = logging.getLogger("kubeflow_tpu.apiserver")

# plural -> (Kind, cluster_scoped), inverted from the client's table so
# both sides of the HTTP boundary share one source of truth.
_BY_PLURAL: dict[str, tuple[str, bool]] = {
    plural: (kind, cluster_scoped)
    for kind, (plural, cluster_scoped) in _KINDS.items()
}


def _status(code: int, message: str, reason: str = "") -> dict:
    return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
            "code": code, "reason": reason, "message": message}


class _Parsed:
    def __init__(self, api_version: str, kind: str, namespace: str | None,
                 name: str | None, subresource: str | None):
        self.api_version = api_version
        self.kind = kind
        self.namespace = namespace
        self.name = name
        self.subresource = subresource


def parse_api_path(path: str) -> _Parsed:
    """/api/v1/... or /apis/{group}/{version}/... ->
    (api_version, Kind, namespace, name, subresource)."""
    parts = [p for p in path.split("/") if p]
    if not parts:
        raise ValueError("empty path")
    if parts[0] == "api":
        if len(parts) < 2 or parts[1] != "v1":
            raise ValueError(f"unknown core version {path}")
        api_version, rest = "v1", parts[2:]
    elif parts[0] == "apis":
        if len(parts) < 3:
            raise ValueError(f"bad group path {path}")
        api_version, rest = f"{parts[1]}/{parts[2]}", parts[3:]
    else:
        raise ValueError(f"not an api path: {path}")

    namespace = None
    # "namespaces" is a scope prefix only when a resource segment follows
    # (/api/v1/namespaces/{ns}/{plural}...); /api/v1/namespaces[/{name}]
    # addresses the Namespace resource itself.
    if rest and rest[0] == "namespaces" and len(rest) >= 3:
        namespace, rest = rest[1], rest[2:]
    if not rest:
        raise ValueError(f"no resource in path {path}")
    plural, rest = rest[0], rest[1:]
    if plural not in _BY_PLURAL:
        raise LookupError(f"unknown resource {plural!r}")
    kind, cluster_scoped = _BY_PLURAL[plural]
    if cluster_scoped:
        namespace = None
    name = rest[0] if rest else None
    subresource = rest[1] if len(rest) > 1 else None
    return _Parsed(api_version, kind, namespace, name, subresource)


class ApiServer:
    """Serves a FakeCluster over the Kubernetes REST wire format."""

    def __init__(self, cluster: FakeCluster | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.cluster = cluster if cluster is not None else FakeCluster()
        self._shutting_down = False
        self._drop_generation = 0  # bumped by drop_watches()
        self.bookmark_interval = 1.0  # seconds of idle between BOOKMARKs
        server_ref = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                log.debug("%s %s", self.address_string(), fmt % args)

            def _body(self):
                length = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(length) if length else b""

            def _send_json(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _fail(self, e: Exception) -> None:
                if isinstance(e, ob.NotFound):
                    self._send_json(404, _status(404, str(e), "NotFound"))
                elif isinstance(e, ob.Conflict):
                    self._send_json(409, _status(409, str(e), "Conflict"))
                elif isinstance(e, ob.Expired):
                    self._send_json(410, _status(410, str(e), "Expired"))
                elif isinstance(e, ob.Invalid):
                    # 422 round-trips to ob.Invalid in RestClient._req:
                    # a controller catching Invalid behaves identically
                    # on FakeCluster and over HTTP
                    self._send_json(422, _status(422, str(e), "Invalid"))
                elif isinstance(e, (ValueError, LookupError)):
                    self._send_json(400, _status(400, str(e), "BadRequest"))
                else:
                    log.exception("apiserver internal error")
                    self._send_json(500, _status(500, str(e), "InternalError"))

            def _handle(self, verb: str) -> None:
                try:
                    url = urlparse(self.path)
                    q = parse_qs(url.query)
                    p = parse_api_path(url.path)
                    server_ref._dispatch(self, verb, p, q)
                except Exception as e:  # noqa: BLE001 — maps to Status codes
                    try:
                        self._fail(e)
                    except (BrokenPipeError, ConnectionResetError):
                        pass

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_PATCH(self):
                self._handle("PATCH")

            def do_DELETE(self):
                self._handle("DELETE")

        class _Server(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                # watch clients disconnect routinely (reconnect loops,
                # process exit); a reset mid-request-read is not an error
                import sys as _sys

                etype = _sys.exc_info()[0]
                if etype in (BrokenPipeError, ConnectionResetError):
                    return
                super().handle_error(request, client_address)

        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread: threading.Thread | None = None

    # -- request dispatch ---------------------------------------------------

    def _dispatch(self, h, verb: str, p: _Parsed, q: dict) -> None:
        c = self.cluster
        if verb == "GET" and p.name is None and q.get("watch", ["0"])[0] in ("1", "true"):
            self._serve_watch(h, p, q)
            return
        if verb == "GET" and p.name is None:
            label = (q.get("labelSelector") or [None])[0]
            fields = None
            fsel = (q.get("fieldSelector") or [None])[0]
            if fsel:
                fields = dict(kv.split("=", 1) for kv in fsel.split(","))
            limit = (q.get("limit") or [None])[0]
            cont = (q.get("continue") or [None])[0]
            items, next_cont, rv = c.list_page(
                p.api_version, p.kind, p.namespace,
                label_selector=label, field_selector=fields,
                limit=int(limit) if limit else None, continue_token=cont)
            meta: dict = {"resourceVersion": rv}
            if next_cont:
                meta["continue"] = next_cont
            h._send_json(200, {"apiVersion": p.api_version,
                               "kind": f"{p.kind}List", "metadata": meta,
                               "items": items})
            return
        if verb == "GET":
            h._send_json(200, c.get(p.api_version, p.kind, p.name, p.namespace))
            return
        if verb == "POST":
            obj = json.loads(h._body())
            obj.setdefault("apiVersion", p.api_version)
            obj.setdefault("kind", p.kind)
            if p.namespace:
                ob.meta(obj).setdefault("namespace", p.namespace)
            h._send_json(201, c.create(obj))
            return
        if verb == "PUT":
            obj = json.loads(h._body())
            if p.subresource == "status":
                h._send_json(200, c.update_status(obj))
            else:
                h._send_json(200, c.update(obj))
            return
        if verb == "PATCH":
            patch = json.loads(h._body())
            ctype = h.headers.get("Content-Type") or ""
            if "apply-patch" in ctype:
                # server-side apply: PATCH with apply-patch content type
                # (the body is the manager's full intent; JSON is a YAML
                # subset, so kubectl-style +yaml bodies parse fine)
                fm = (q.get("fieldManager") or [""])[0]
                force = (q.get("force") or ["false"])[0] in ("1", "true")
                patch.setdefault("apiVersion", p.api_version)
                patch.setdefault("kind", p.kind)
                ob.meta(patch).setdefault("name", p.name)
                if p.namespace:
                    ob.meta(patch).setdefault("namespace", p.namespace)
                # a body naming a DIFFERENT object than the URL must be
                # a 400, never a silent apply elsewhere (apiserver
                # semantics: the URL is authoritative)
                got = (patch["apiVersion"], patch["kind"],
                       ob.meta(patch).get("name"),
                       ob.meta(patch).get("namespace") or None)
                want = (p.api_version, p.kind, p.name, p.namespace)
                if got != want:
                    raise ValueError(
                        f"apply body addresses {got}, URL addresses "
                        f"{want}")
                h._send_json(200, c.apply(patch, field_manager=fm,
                                          force=force))
                return
            h._send_json(200, c.patch(p.api_version, p.kind, p.name, patch,
                                      p.namespace))
            return
        if verb == "DELETE":
            c.delete(p.api_version, p.kind, p.name, p.namespace)
            h._send_json(200, {"kind": "Status", "status": "Success"})
            return
        h._send_json(405, _status(405, f"verb {verb} not supported"))

    def _serve_watch(self, h, p: _Parsed, q: dict | None = None) -> None:
        """Chunked stream of {"type", "object"} JSON lines — the
        watch wire format RestClient._RestWatchStream consumes.

        Honors ``resourceVersion`` (resume: replay missed events, or 410
        Gone past the retained window), and ``allowWatchBookmarks``
        (periodic BOOKMARK events carrying the latest RV so a resumed
        watch never rewinds further than its last heartbeat)."""
        q = q or {}
        since_rv = (q.get("resourceVersion") or [None])[0]
        bookmarks = (q.get("allowWatchBookmarks") or ["false"])[0] in (
            "1", "true")
        try:
            stream = self.cluster.watch(p.api_version, p.kind, p.namespace,
                                        since_rv=since_rv)
        except ob.Expired as e:
            h._send_json(410, _status(410, str(e), "Expired"))
            return
        gen = self._drop_generation
        try:
            h.send_response(200)
            h.send_header("Content-Type", "application/json")
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()

            def chunk(data: bytes) -> None:
                h.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                h.wfile.flush()

            idle = 0.0
            while not self._shutting_down:
                if gen != self._drop_generation:
                    break  # test hook: forcibly drop active watch streams
                # snapshot BEFORE polling: an event that lands during the
                # poll window is delivered by it; one landing after
                # postdates this rv — either way the bookmark never
                # advertises an rv covering an undelivered event
                rv_snapshot = self.cluster.current_rv
                ev = stream.poll(timeout=0.1)
                if ev is None:
                    idle += 0.1
                    if bookmarks and idle >= self.bookmark_interval:
                        idle = 0.0
                        bm = {"type": "BOOKMARK",
                              "object": {"apiVersion": p.api_version,
                                         "kind": p.kind,
                                         "metadata": {"resourceVersion":
                                                      rv_snapshot}}}
                        chunk(json.dumps(bm).encode() + b"\n")
                    continue
                idle = 0.0
                line = json.dumps({"type": ev.type, "object": ev.object})
                chunk(line.encode() + b"\n")
            chunk(b"")  # terminating chunk on clean shutdown / drop
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away: normal watch teardown
        finally:
            stream.stop()

    def drop_watches(self) -> None:
        """Failure injection: terminate every active watch stream (the
        mid-stream disconnect a real apiserver/LB produces on timeouts);
        clients must resume from their last seen resourceVersion."""
        self._drop_generation += 1

    # -- lifecycle ----------------------------------------------------------

    def serve_background(self) -> "ApiServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="fake-apiserver")
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._shutting_down = True
        self._server.shutdown()
        self._server.server_close()


def client_for(server: ApiServer):
    """A RestClient wired to this apiserver (plain HTTP, no auth)."""
    from kubeflow_tpu.control.k8s.rest import RestClient

    return RestClient(base_url=server.url, token="test-token", ca_cert=False)
