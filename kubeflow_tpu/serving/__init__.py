"""Model serving with the TF-Serving REST contract.

The reference deploys TF-Serving and its E2E asserts the REST surface
POST /v1/models/<m>:predict with {"instances": [...]} and a numeric-
tolerance golden compare (testing/test_tf_serving.py:105-133). This
package serves jit-compiled JAX models behind the same contract, so
those test paths run unmodified against the TPU backend.
"""

from kubeflow_tpu.serving.server import ModelServer, ServedModel  # noqa: F401
