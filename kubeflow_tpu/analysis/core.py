"""tpulint core: the module model, rule registry, and suppressions.

The framework is deliberately small — every rule gets a parsed
``Module`` (source + AST + parent links) and yields ``Finding``s; the
registry maps rule ids to singleton rule instances; suppression is a
per-line ``# tpulint: disable=RULE[,RULE...]  <justification>`` comment
(or ``disable-file=`` for a whole module). Nothing here imports jax or
touches devices: tpulint must run in CI images with no accelerator and
must never execute the code it scans.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re
from typing import Iterable, Iterator

PARSE_RULE = "TPU000"  # reserved: file does not parse

# the rule list is strictly comma-separated ids (no spaces inside ids),
# so a justification after a SINGLE space still leaves the rules intact
# instead of being swallowed into the rule list as a silent no-op
_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: rule id + location + human message."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """A parsed source file handed to every rule.

    Carries the AST with parent back-links (``parents``) so rules can
    walk *up* — "is this node inside a ``with self._lock`` block?" —
    which ``ast`` alone cannot answer.
    """

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self._line_suppress, self._file_suppress = _parse_suppressions(
            self.lines)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing FunctionDef/AsyncFunctionDef, or None."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def suppressed(self, finding: Finding) -> bool:
        if {"all"} & self._file_suppress or finding.rule in self._file_suppress:
            return True
        rules = self._line_suppress.get(finding.line, set())
        return "all" in rules or finding.rule in rules


def _parse_suppressions(lines: list[str]):
    """Collect ``# tpulint: disable=...`` comments.

    Line suppressions apply to findings reported on that physical line;
    file suppressions (``disable-file=``) apply module-wide. Rule lists
    are comma-separated; ``all`` matches every rule. Text after two
    spaces (or a second ``#``) is the justification and is ignored.
    """
    line_map: dict[int, set[str]] = {}
    file_set: set[str] = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        kind, rules_text = m.group(1), m.group(2)
        rules = {r.strip() for r in rules_text.split(",") if r.strip()}
        if kind == "disable-file":
            file_set |= rules
        else:
            line_map.setdefault(i, set()).update(rules)
    return line_map, file_set


# -- rule registry -----------------------------------------------------------

class Rule:
    """Base class: subclass, set id/name/short, implement check()."""

    id: str = ""
    name: str = ""
    short: str = ""

    def check(self, module: Module) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module: Module, node: ast.AST, message: str) -> Finding:
        return Finding(self.id, module.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    """Class decorator: instantiate the rule and add it to REGISTRY."""
    rule = cls()
    assert rule.id and rule.id not in REGISTRY, f"bad rule id {rule.id!r}"
    REGISTRY[rule.id] = rule
    return cls


def all_rules() -> list[Rule]:
    _load_builtin_rules()
    return [REGISTRY[k] for k in sorted(REGISTRY)]


def _load_builtin_rules() -> None:
    # import for the @register side effect; lazy so core stays importable
    # from rule modules without a cycle
    from kubeflow_tpu.analysis import rules_jax, rules_lockset  # noqa: F401


# -- scanning ----------------------------------------------------------------

def iter_py_files(paths: Iterable[str]) -> Iterator[pathlib.Path]:
    """Expand files/directories into .py files, skipping caches."""
    for raw in paths:
        p = pathlib.Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" not in f.parts:
                    yield f
        elif p.suffix == ".py":
            yield p


def scan_source(path: str, source: str,
                rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Run rules over one in-memory source (also the test-corpus entry
    point). Returns unsuppressed findings sorted by position."""
    if rules is None:
        rules = all_rules()
    try:
        module = Module(path, source)
    except SyntaxError as e:
        return [Finding(PARSE_RULE, path, e.lineno or 1, e.offset or 0,
                        f"file does not parse: {e.msg}")]
    out: list[Finding] = []
    for rule in rules:
        for f in rule.check(module):
            if not module.suppressed(f):
                out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.col, f.rule))


def scan_paths(paths: Iterable[str], select: set[str] | None = None,
               ignore: set[str] | None = None) -> list[Finding]:
    rules = all_rules()
    if select:
        rules = [r for r in rules if r.id in select]
    if ignore:
        rules = [r for r in rules if r.id not in ignore]
    if not rules and (not select or PARSE_RULE not in select):
        # nothing to run (e.g. a hygiene-only --select): skip the parse
        # pass entirely instead of AST-ing the tree for zero rules
        return []
    findings: list[Finding] = []
    for f in iter_py_files(paths):
        findings.extend(scan_source(str(f), f.read_text(), rules))
    # select/ignore also apply to TPU000 parse findings, which
    # scan_source emits outside the rules list
    if select:
        findings = [f for f in findings if f.rule in select]
    if ignore:
        findings = [f for f in findings if f.rule not in ignore]
    return findings


# -- shared AST helpers ------------------------------------------------------

def dotted(node: ast.AST) -> str | None:
    """Render Name/Attribute chains as 'a.b.c' (None for anything else)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted(call.func)
