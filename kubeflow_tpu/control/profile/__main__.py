from kubeflow_tpu.control.mains import run_controller
from kubeflow_tpu.control.profile.controller import WorkloadIdentityPlugin, build_controller
from kubeflow_tpu.control.profile.plugin_irsa import IrsaPlugin

run_controller(
    "profile-controller",
    lambda client, args: build_controller(
        client,
        plugins={
            "WorkloadIdentity": WorkloadIdentityPlugin(),
            IrsaPlugin.KIND: IrsaPlugin(),
        },
    ),
)
