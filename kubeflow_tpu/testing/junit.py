"""junit XML artifacts — the Gubernator/testgrid contract.

Every reference E2E emits junit XML (test_tf_serving.py:139-143; katib
via kubeflow.testing's test_helper). Same schema here: a <testsuite>
of <testcase> elements with failure text and timing, written atomically
so a killed run never leaves a truncated artifact.
"""

from __future__ import annotations

import dataclasses
import os
import time
from xml.sax.saxutils import escape, quoteattr


@dataclasses.dataclass
class TestCase:
    __test__ = False  # not a pytest class

    name: str
    class_name: str = ""
    time_s: float = 0.0
    failure: str | None = None
    skipped: str | None = None

    @property
    def ok(self) -> bool:
        return self.failure is None


@dataclasses.dataclass
class TestSuite:
    __test__ = False  # not a pytest class

    name: str
    cases: list[TestCase] = dataclasses.field(default_factory=list)

    def case(self, name: str, class_name: str = "") -> "_CaseTimer":
        return _CaseTimer(self, name, class_name)

    @property
    def failures(self) -> int:
        return sum(1 for c in self.cases if c.failure is not None)

    def to_xml(self) -> str:
        total_t = sum(c.time_s for c in self.cases)
        skipped = sum(1 for c in self.cases if c.skipped is not None)
        out = [
            '<?xml version="1.0" encoding="utf-8"?>',
            f'<testsuite name={quoteattr(self.name)} tests="{len(self.cases)}" '
            f'failures="{self.failures}" skipped="{skipped}" '
            f'time="{total_t:.3f}">',
        ]
        for c in self.cases:
            attrs = f'name={quoteattr(c.name)} time="{c.time_s:.3f}"'
            if c.class_name:
                attrs += f" classname={quoteattr(c.class_name)}"
            if c.failure is None and c.skipped is None:
                out.append(f"  <testcase {attrs}/>")
            else:
                out.append(f"  <testcase {attrs}>")
                if c.failure is not None:
                    out.append(f"    <failure>{escape(c.failure)}</failure>")
                if c.skipped is not None:
                    out.append(f"    <skipped>{escape(c.skipped)}</skipped>")
                out.append("  </testcase>")
        out.append("</testsuite>")
        return "\n".join(out)

    def write(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_xml())
        os.replace(tmp, path)
        return path


class _CaseTimer:
    """`with suite.case("deploy"):` — records timing and failure text."""

    def __init__(self, suite: TestSuite, name: str, class_name: str):
        self.suite = suite
        self.tc = TestCase(name=name, class_name=class_name)

    def __enter__(self):
        self._t0 = time.monotonic()
        return self.tc

    def __exit__(self, etype, e, tb):
        self.tc.time_s = time.monotonic() - self._t0
        if e is not None:
            self.tc.failure = f"{etype.__name__}: {e}"
        self.suite.cases.append(self.tc)
        return False  # propagate
