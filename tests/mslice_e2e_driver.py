"""Subprocess driver for the hermetic multi-slice shrink/grow e2e.

Run by test_mslice_e2e.py in a FRESH interpreter (the
elastic_e2e_driver.py pattern): this image's jaxlib corrupts its heap
when a long-lived process mixes many prior compilations with meshes
over device SUBSETS, and a slice shrink is exactly a subset mesh
(devices[:4] -> devices[:2]). The verdict is one JSON line:

    MSLICE_E2E {"worlds": [[4, 2], [2, 1], [4, 2]], "losses": [...], ...}

Scenario (deterministic under the fake scheduler clock): a 2-slice x
2-worker slice-elastic JAXJob (slicePolicy Shrink, minSlices 1) admits
across TWO pools (slice 0 -> pool a, slice 1 -> pool b — the
slice-affinity pin from test_scheduler.py), forms its world on the
LoopbackBackend (in-process slices: the dcn mesh axis falls on the
slice partition), and trains. Pool b dies mid-run: the controller
condemns slice 1 whole, the world shrinks to the surviving slice
(dcn=1 over devices[:2]) WITHOUT burning restarts/preemptions, and
training resumes from the checkpointed step. Pool b heals: slice 1
readmits whole, the world grows back (dcn=2 over devices[:4]), and the
run completes with a loss curve matching an uninterrupted 2-slice
reference step for step.
"""

from __future__ import annotations

import json
import os
import sys


def main(ckpt_root: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import test_elastic as TE

    import prometheus_client as prom

    from kubeflow_tpu.control.jaxjob import types as T
    from kubeflow_tpu.control.jaxjob.controller import (
        job_world, worker_name,
    )
    from kubeflow_tpu.control.k8s import objects as ob
    from kubeflow_tpu.control.scheduler.nodes import new_tpu_node
    from kubeflow_tpu.parallel import backends as B
    from kubeflow_tpu.parallel import dist as D
    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime import elastic
    from kubeflow_tpu.runtime.trainer import Trainer

    fc = TE.S.FakeClock()
    cluster, jax_ctl, sched_ctl, kubelet, _reg = TE.sched_world(fc)
    # two pools of the same accelerator: a slice fits exactly one pool
    for i in range(2):
        cluster.create(new_tpu_node(f"a{i}", topology="2x4"))
        cluster.create(new_tpu_node(f"b{i}", topology="4x4"))
    cluster.create(T.new_jaxjob(
        "ms", replicas=2, slice_count=2,
        accelerator="tpu-v5-lite-podslice", topology="2x4",
        chips_per_worker=4, gang_schedule=True, elastic_min=4,
        slice_policy=T.SLICE_SHRINK, min_slices=1))
    def job():
        return cluster.get(T.API_VERSION, T.KIND, "ms", "default")

    def status():
        return job().get("status") or {}

    def bound():
        return {k: v for k, v in TE.bindings(cluster).items() if v}

    def pump_until(pred, limit=60):
        for _ in range(limit):
            if pred():
                return
            TE.pump([jax_ctl, sched_ctl], fc, kubelet, rounds=1)
        raise RuntimeError("control plane never converged")

    pump_until(lambda: ob.cond_is_true(job(), T.COND_RUNNING)
               and len(bound()) == 4)
    bind0 = bound()
    # which pool did slice 1 (workers 2,3) land in? that pool is the
    # victim — a slice is reclaimed as a unit — and the coordinator
    # rides a worker from the SURVIVING slice 0
    victim = "b" if bind0[worker_name("ms", 2)].startswith("b") else "a"

    def set_victim_pool(ready: bool) -> None:
        for name in (f"{victim}0", f"{victim}1"):
            node = cluster.get("v1", "Node", name)
            node["status"]["conditions"] = [
                {"type": "Ready", "status": "True" if ready else "False"}]
            cluster.update_status(node)

    losses: list[float] = []

    def callback(i, m):
        losses.append(float(m["loss"]))
        if len(losses) == 5:
            set_victim_pool(False)   # slice 1's pool dies mid-step-6
            pump_until(lambda: status().get("activeSlices") == 1)
        if len(losses) == 8:
            set_victim_pool(True)    # the pool heals mid-step-9
            pump_until(lambda: status().get("activeSlices") == 2)

    def source():
        return job_world(job())

    worlds_formed: list[tuple[int, int]] = []

    def form_world(w):
        # ONE process simulates the gang: form the loopback backend's
        # in-process slice world at the stamp's surviving slice count
        # (real formation + teardown through dist on every resize)
        ns = w.num_slices
        worlds_formed.append((w.size, ns))
        D.initialize_from_env({
            B.ENV_BACKEND: B.BACKEND_LOOPBACK,
            D.ENV_NPROC: "1", D.ENV_PID: "0",
            D.ENV_NUM_SLICES: str(ns), D.ENV_SLICE_ID: "0"})

    def mesh_fn(cfg, wsize):
        w = D.active_world()
        ns = w.num_slices if w is not None else 1
        return B.get_backend(B.BACKEND_LOOPBACK).mesh(
            MeshSpec(dcn=ns, data=wsize // ns), jax.devices()[:wsize])

    def sample(direction):
        return prom.REGISTRY.get_sample_value(
            "jaxjob_slice_resizes_total", {"direction": direction}) or 0.0

    coord = elastic.ElasticCoordinator(
        source, my_name=worker_name("ms", 0 if victim == "b" else 2),
        form_world=form_world, mesh_fn=mesh_fn)
    state, summary = coord.run(
        TE._train_cfg(os.path.join(ckpt_root, "mslice")),
        full_world=4, callback=callback)

    # uninterrupted 2-slice reference on the SAME loopback mesh shape
    ref_losses: list[float] = []
    ref_mesh = B.get_backend(B.BACKEND_LOOPBACK).mesh(
        MeshSpec(dcn=2, data=2), jax.devices()[:4])
    ref = Trainer(TE._train_cfg(os.path.join(ckpt_root, "ref")),
                  mesh=ref_mesh)
    ref.fit(callback=lambda i, m: ref_losses.append(float(m["loss"])))

    st = status()
    world = st.get("world") or {}
    print("MSLICE_E2E " + json.dumps({
        "elastic": summary["elastic"],
        "step": int(state.step),
        "losses": losses,
        "ref_losses": ref_losses,
        "worlds_formed": worlds_formed,
        "slice0_bindings": sorted(
            bind0[worker_name("ms", i)] for i in (0, 1)),
        "slice1_bindings": sorted(
            bind0[worker_name("ms", i)] for i in (2, 3)),
        "restarts": st.get("restarts", 0),
        "preemptions": st.get("preemptions", 0),
        "resizes": st.get("resizes", 0),
        "active_replicas": st.get("activeReplicas", 0),
        "active_slices": st.get("activeSlices", 0),
        "world_slices": world.get("slices"),
        "resizing": (ob.cond_get(job(), T.COND_RESIZING) or {}).get(
            "status"),
        "running": ob.cond_is_true(job(), T.COND_RUNNING),
        "slice_resizes_metric": {"shrink": sample("shrink"),
                                 "grow": sample("grow")},
    }), flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
