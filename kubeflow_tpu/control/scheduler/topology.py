"""Canonical TPU slice-topology parsing — ONE spelling for the tree.

``"2x4"`` / ``"4x4x4"`` strings name the physical chip grid of a TPU
slice (the value of the GKE ``cloud.google.com/gke-tpu-topology`` node
label). Three places used to parse them independently — tpctl's
node-pool sizing, JAXJob admission validation, and now the gang
scheduler's node model — which is exactly how a "2x4" and a "2X4"
drift apart. This module is the single parser; every other module
imports it, and tests/test_scheduler.py AST-pins the spelling the way
parallel/mesh.py pins AXIS_NAMES for tpulint: no other module in the
package may split on the separator itself.
"""

from __future__ import annotations

import dataclasses

# The one spelling of the dimension separator (AST-pinned in tests).
TOPOLOGY_SEPARATOR = "x"


@dataclasses.dataclass(frozen=True)
class Topology:
    """A parsed slice shape: dimension extents, outermost first."""

    dims: tuple[int, ...]

    @property
    def chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def rank(self) -> int:
        return len(self.dims)

    def __str__(self) -> str:
        return TOPOLOGY_SEPARATOR.join(str(d) for d in self.dims)


def parse_topology(s: str) -> Topology:
    """Parse ``"2x4"``-style strings; raises ValueError on anything that
    is not positive-int extents joined by the separator."""
    parts = (s or "").strip().lower().split(TOPOLOGY_SEPARATOR)
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(f"topology {s!r} is not NxM[xK]") from None
    if not dims or any(d < 1 for d in dims):
        raise ValueError(f"topology {s!r} is not NxM[xK]")
    return Topology(dims)


def chip_count(s: str) -> int:
    """Total chips in a slice topology string."""
    return parse_topology(s).chips
