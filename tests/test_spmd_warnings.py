"""Partitioner-warning gate: sharded train steps must compile without
GSPMD falling back to replicate-then-repartition.

An "[SPMD] Involuntary full rematerialization" warning means the
partitioner could not bridge two shardings and inserted a full all-gather
+ reslice — invisible at test shapes, a per-step full-tensor broadcast on
real meshes. Round 3 shipped exactly this on every fsdp mesh: the
embedding table was sharded [vocab@model, d@fsdp], and bridging the
batch-sharded dx cotangent to the d-over-fsdp gradient scatter has no
efficient lowering in the pre-Shardy partitioner (fixed by vocab-sharding
the table — models/transformer.py). The reference has no analogue (its
placement policy is "the PS owns all variables", launcher.py:74-80); this
is the TPU-native regression class.

XLA emits the warning from C++ at compile time, so it must be captured at
the process level: the check runs tools/repro_accum_warn.py (a dcn=2 x
data=2 x fsdp=2 train step with grad accumulation + chunked xent — the
config that warned in round 3) in a subprocess and greps its stderr.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_repro(overrides_json: str | None = None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # the repro script sets JAX_PLATFORMS=cpu and the 8-device flag itself
    env.pop("_KFTPU_DRYRUN_INNER", None)
    cmd = [sys.executable, os.path.join(REPO, "tools", "repro_accum_warn.py")]
    if overrides_json:
        cmd.append(overrides_json)
    proc = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "loss" in proc.stdout, proc.stdout
    bad = [ln for ln in proc.stderr.splitlines()
           if "Involuntary full rematerialization" in ln
           or "SPMD will replicate the tensor" in ln]
    assert not bad, "GSPMD involuntary remat in sharded step:\n" + "\n".join(bad[:4])


def test_fsdp_accum_step_has_no_involuntary_remat():
    _run_repro()


def test_dense_moe_on_fsdp_expert_mesh_has_no_involuntary_remat():
    """`expert` is a batch axis; the dense dispatch path (the fallback
    whenever fsdp/model/seq are sharded) must pull tokens off the expert
    axis with its explicit reshard ladder rather than leave the
    partitioner to replicate-then-repartition (ops/moe.py _dense)."""
    _run_repro('{"model": "moe-test", '
               '"model_kwargs": {"moe_impl": "dense"}, '
               '"mesh": {"fsdp": 2, "expert": 4}, "global_batch": 16}')
