"""Sharding inference for parameter pytrees.

Two sources of truth, merged:

1. Explicit annotations — models that care about tensor parallelism wrap
   weights in `nn.with_partitioning(init, (axis, axis))`, so leaves carry
   `nn.Partitioned` metadata naming mesh axes directly.
2. FSDP heuristic — unannotated leaves get their largest dimension sharded
   over the `fsdp` axis when divisible (ZeRO-3-style parameter sharding);
   otherwise replicated.

The reference has no analogue: its parameter placement policy is "the PS
owns all variables" (launcher.py:74-80). On TPU, placement is a compiler
input, so it lives here as data, not in a server topology.
"""

from __future__ import annotations

from typing import Any

import jax
from flax import linen as nn
from flax.core import meta
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import AXIS_FSDP


def _fsdp_spec(shape: tuple[int, ...], fsdp_size: int, min_size: int = 2**14) -> P:
    """Shard the largest divisible dim over fsdp; tiny tensors replicate
    (sharding a 64-element bias buys nothing and costs an all-gather)."""
    if fsdp_size <= 1 or not shape:
        return P()
    total = 1
    for d in shape:
        total *= d
    if total < min_size:
        return P()
    # Largest dim first; ties go to the later (usually output-feature) dim.
    order = sorted(range(len(shape)), key=lambda i: (shape[i], i), reverse=True)
    for i in order:
        if shape[i] % fsdp_size == 0:
            spec = [None] * len(shape)
            spec[i] = AXIS_FSDP
            return P(*spec)
    return P()


def partition_specs(abstract_vars: Any, mesh: Mesh, *, fsdp: bool = True) -> Any:
    """PartitionSpec pytree for a variables pytree (from jax.eval_shape of
    model.init). Honors nn.Partitioned metadata; falls back to the FSDP
    heuristic for bare leaves."""
    fsdp_size = mesh.shape.get(AXIS_FSDP, 1) if fsdp else 1

    def axis_size(name) -> int:
        if name is None:
            return 1
        names = name if isinstance(name, (tuple, list)) else (name,)
        sz = 1
        for n in names:
            sz *= mesh.shape.get(n, 1)
        return sz

    def leaf_spec(leaf):
        if isinstance(leaf, meta.Partitioned):
            shape = tuple(leaf.value.shape)
            # Drop annotated axes that don't divide the dim (e.g. 2 KV
            # heads under model=4 → replicate KV heads across TP ranks).
            names = [
                n if (n is not None and shape[i] % axis_size(n) == 0) else None
                for i, n in enumerate(leaf.names)
            ]
            return P(*names)
        shape = getattr(leaf, "shape", ())
        return _fsdp_spec(tuple(shape), fsdp_size)

    return jax.tree.map(
        leaf_spec, abstract_vars, is_leaf=lambda x: isinstance(x, meta.Partitioned)
    )


def shardings_from_specs(specs: Any, mesh: Mesh) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )


def unbox(variables: Any) -> Any:
    """Strip nn.Partitioned boxes (keep raw arrays) — we carry shardings
    separately as NamedShardings, the jit-native representation."""
    return meta.unbox(variables)


def infer_shardings(abstract_vars: Any, mesh: Mesh, *, fsdp: bool = True) -> Any:
    return shardings_from_specs(partition_specs(abstract_vars, mesh, fsdp=fsdp), mesh)
