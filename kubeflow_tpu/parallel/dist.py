"""Multi-host bootstrap: the TPU-native TF_CONFIG.

The reference clusters TF1 processes by having the (external) TFJob
operator inject a `TF_CONFIG` JSON env var which an in-pod launcher decodes
into `--job_name/--ps_hosts/--worker_hosts/--task_index` flags
(tf-controller-examples/tf-cnn/launcher.py:68-80). Parameter servers and
gRPC disappear on TPU: every process joins one `jax.distributed` cluster
and gradient reduction happens inside the compiled step over ICI.

The JAXJob controller (kubeflow_tpu.control.jaxjob) injects:

    JAXJOB_COORDINATOR_ADDRESS   host:port of process 0
    JAXJOB_NUM_PROCESSES         world size
    JAXJOB_PROCESS_ID            this pod's rank (from the pod index)
    JAXJOB_NAME / JAXJOB_NAMESPACE  (identification / logging only)

`initialize_from_env()` is the single call a training container makes
before touching jax; it also honors the standard JAX / Cloud-TPU env vars
so images run unmodified on GKE TPU node pools (where the device plugin
injects TPU_WORKER_HOSTNAMES etc.) and under bare `jax.distributed`.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import socket
import threading
import time

log = logging.getLogger("kubeflow_tpu.dist")

ENV_COORD = "JAXJOB_COORDINATOR_ADDRESS"
ENV_NPROC = "JAXJOB_NUM_PROCESSES"
ENV_PID = "JAXJOB_PROCESS_ID"
ENV_NAME = "JAXJOB_NAME"
ENV_NAMESPACE = "JAXJOB_NAMESPACE"
# Elastic resize contract (runtime/elastic.py): the JAXJob controller
# projects its world annotation into the pod via the downward API and
# points this env var at the projected file; the worker-side elastic
# coordinator re-reads it to learn resizes. ENV_BATCH_POLICY carries
# spec.elastic.batchPolicy (Preserve|Scale) to the worker.
ENV_WORLD_FILE = "JAXJOB_WORLD_FILE"
ENV_BATCH_POLICY = "JAXJOB_BATCH_POLICY"
# The values ENV_BATCH_POLICY carries (ONE spelling of the wire value;
# jaxjob types and runtime/elastic re-export): Preserve keeps the
# global batch across a resize, Scale scales it with the world.
BATCH_PRESERVE = "Preserve"
BATCH_SCALE = "Scale"
# Multislice (one jax.distributed world spanning several ICI slices wired
# by DCN). The JAXJob controller injects these alongside the libtpu
# MEGASCALE_* vars; the mesh's `dcn` axis maps onto the slice boundary.
ENV_NUM_SLICES = "JAXJOB_NUM_SLICES"
ENV_SLICE_ID = "JAXJOB_SLICE_ID"
DEFAULT_COORD_PORT = 8476
MEGASCALE_PORT = 8080


@dataclasses.dataclass(frozen=True)
class DistConfig:
    coordinator_address: str | None
    num_processes: int
    process_id: int
    job_name: str = ""
    namespace: str = ""
    # multislice topology: this process's slice and the slice count; the
    # `dcn` mesh axis spans slices (slice_id = process_id // procs-per-slice
    # under the controller's contiguous-rank assignment)
    num_slices: int = 1
    slice_id: int = 0

    @property
    def distributed(self) -> bool:
        return self.num_processes > 1

    @property
    def multislice(self) -> bool:
        return self.num_slices > 1

    @classmethod
    def from_env(cls, env: dict[str, str] | None = None) -> "DistConfig":
        env = dict(os.environ) if env is None else env
        coord = env.get(ENV_COORD)
        nproc = int(env.get(ENV_NPROC, "1"))
        pid = int(env.get(ENV_PID, "0"))
        if coord is not None and ":" not in coord:
            coord = f"{coord}:{DEFAULT_COORD_PORT}"
        return cls(
            coordinator_address=coord,
            num_processes=nproc,
            process_id=pid,
            job_name=env.get(ENV_NAME, ""),
            namespace=env.get(ENV_NAMESPACE, ""),
            num_slices=int(env.get(ENV_NUM_SLICES, "1")),
            slice_id=int(env.get(ENV_SLICE_ID, "0")),
        )

    def to_env(self) -> dict[str, str]:
        """The env block the JAXJob controller injects into each worker pod."""
        env = {
            ENV_NPROC: str(self.num_processes),
            ENV_PID: str(self.process_id),
        }
        if self.coordinator_address:
            env[ENV_COORD] = self.coordinator_address
        if self.job_name:
            env[ENV_NAME] = self.job_name
        if self.namespace:
            env[ENV_NAMESPACE] = self.namespace
        if self.num_slices > 1:
            env.update(slice_env(self.num_slices, self.slice_id,
                                 self.coordinator_address))
        return env


def slice_env(num_slices: int, slice_id: int,
              coordinator_address: str | None) -> dict[str, str]:
    """Multislice env block: the JAXJOB_* contract plus the MEGASCALE_*
    vars libtpu's DCN transport reads at backend init. The spelling
    lives in parallel/backends.py (the ONE module allowed to name the
    MEGASCALE keys — tpulint COLL401); this delegator keeps the
    jax-free import surface the controller relies on."""
    from kubeflow_tpu.parallel import backends as B

    return B.slice_env(num_slices, slice_id, coordinator_address)


@dataclasses.dataclass(frozen=True)
class WorldSpec:
    """One elastic-world incarnation — the value of the JAXJob
    controller's world annotation (jaxjob/types.py ANNOTATION_WORLD),
    projected into each pod via the downward API.

    ``members`` is the ordered worker-pod-name list of the CURRENT
    world: a member's rank is its position, and the coordinator is
    members[0]'s stable DNS address. ``gen`` increments with every
    resize, so a worker distinguishes 4→2→4 from never having resized.
    ``slices`` (multi-slice jobs only) is each member's slice id,
    aligned with ``members`` — slice identity survives a shrink, so a
    2-slice world that lost slice 0 reads slices=(1, 1), not a
    renumbered (0, 0). This is the ONE spelling of the resize wire
    contract — the controller writes it, runtime/elastic.py reads it."""

    gen: int
    size: int
    members: tuple[str, ...]
    coordinator: str | None = None
    slices: tuple[int, ...] | None = None

    def rank_of(self, name: str) -> int | None:
        """This worker's rank in the current world; None = not a member
        (a replacement pod waiting out the join barrier)."""
        try:
            return self.members.index(name)
        except ValueError:
            return None

    @property
    def num_slices(self) -> int:
        """Distinct surviving slices (1 when the world is single-slice
        or predates slice stamping)."""
        return len(set(self.slices)) if self.slices else 1

    def slice_of(self, name: str) -> int | None:
        """The member's ORIGINAL slice id (None when untracked)."""
        rank = self.rank_of(name)
        if rank is None or not self.slices:
            return None
        return self.slices[rank]

    def to_json(self) -> str:
        return json.dumps({
            "gen": self.gen, "size": self.size,
            "members": list(self.members),
            **({"coordinator": self.coordinator} if self.coordinator
               else {}),
            **({"slices": list(self.slices)} if self.slices is not None
               else {}),
        }, sort_keys=True)

    @classmethod
    def from_json(cls, text: str | None) -> "WorldSpec | None":
        """None on missing/malformed input — the downward-API file can
        be mid-write or absent before the kubelet first syncs it, and a
        worker must keep its current world rather than crash."""
        if not text:
            return None
        try:
            d = json.loads(text)
            members = tuple(str(m) for m in d["members"])
            slices = d.get("slices")
            if slices is not None:
                slices = tuple(int(s) for s in slices)
            spec = cls(gen=int(d["gen"]), size=int(d["size"]),
                       members=members,
                       coordinator=d.get("coordinator") or None,
                       slices=slices)
        except (ValueError, TypeError, KeyError):
            return None
        if spec.size != len(members) or spec.gen < 0:
            return None
        if spec.slices is not None and len(spec.slices) != spec.size:
            return None
        return spec


def wait_for_coordinator(address: str, timeout_s: float = 300.0) -> None:
    """Readiness gate: block until the coordinator's port accepts TCP.

    Replaces the reference's two hacks around bootstrap ordering: the
    openmpi sidecar's SIGCONT file handshake (openmpi-controller/
    controller/controller.py:53-57) and launcher.py's sleep-forever guard.
    """
    host, _, port = address.partition(":")
    deadline = time.monotonic() + timeout_s
    delay = 0.25
    while True:
        try:
            with socket.create_connection((host, int(port or DEFAULT_COORD_PORT)), timeout=2.0):
                return
        except OSError:
            if time.monotonic() > deadline:
                raise TimeoutError(f"coordinator {address} not reachable after {timeout_s}s")
            time.sleep(delay)
            delay = min(delay * 2, 5.0)


# -- world lifecycle (elastic re-formation) ---------------------------------
#
# Module state: the world this process currently belongs to. Elastic
# resize re-enters initialize_from_env with a CHANGED world (new size /
# rank / coordinator after a shrink or grow); before this state existed
# a second call silently kept the stale jax.distributed config while
# returning a fresh-looking DistConfig. Now a re-entry either no-ops
# (same world — idempotent) or tears the prior state down first.
_WORLD_LOCK = threading.RLock()
_ACTIVE: DistConfig | None = None
_DIST_LIVE = False  # the active backend holds live world state
_BACKEND = None     # the CollectivesBackend that formed the active world


class WorldTeardownError(RuntimeError):
    """Prior distributed state could not be torn down for re-formation.

    The elastic coordinator (runtime/elastic.py) handles this by exiting
    EX_TEMPFAIL instead of resizing in place: the gang restart rebuilds
    the world from scratch, which is always safe."""


def _world_key(cfg: DistConfig) -> tuple:
    """The fields that define a distributed world's identity; metadata
    (job name/namespace) may change without re-forming anything."""
    return (cfg.coordinator_address, cfg.num_processes, cfg.process_id,
            cfg.num_slices, cfg.slice_id)


def active_world() -> DistConfig | None:
    """The world this process last initialized (None before the first
    initialize_from_env)."""
    with _WORLD_LOCK:
        return _ACTIVE


def _jax_initialize(cfg: DistConfig) -> None:
    """Monkeypatchable seam (tests fake world formation here). The real
    jax.distributed call lives in parallel/backends.py — the ONE module
    allowed to spell it (tpulint COLL401)."""
    from kubeflow_tpu.parallel import backends as B

    B._raw_jax_initialize(cfg)


def _jax_shutdown() -> None:
    from kubeflow_tpu.parallel import backends as B

    B._raw_jax_shutdown()


def active_backend():
    """The CollectivesBackend that formed the active world (None before
    the first initialize_from_env)."""
    with _WORLD_LOCK:
        return _BACKEND


def _teardown_locked() -> None:
    global _ACTIVE, _DIST_LIVE, _BACKEND
    if _DIST_LIVE:
        try:
            if _BACKEND is not None:
                _BACKEND.leave()
            else:
                _jax_shutdown()
        except Exception as e:
            raise WorldTeardownError(
                f"could not shut down the previous distributed world "
                f"({_ACTIVE}): {type(e).__name__}: {e}") from e
        _DIST_LIVE = False
    _ACTIVE = None
    _BACKEND = None


def shutdown() -> None:
    """Tear down this process's distributed state (no-op when none).
    The elastic coordinator calls this between worlds; raising
    WorldTeardownError means in-place re-formation is off the table."""
    with _WORLD_LOCK:
        _teardown_locked()


def initialize_from_env(env: dict[str, str] | None = None, *, wait: bool = True) -> DistConfig:
    """Join the jax.distributed cluster described by JAXJOB_* env vars.

    No-op for single-process jobs, so the same image runs on one chip or a
    multi-host slice without code changes (num_processes==1 ⇒ no
    coordinator needed, exactly like running the reference's tf-cnn with
    an empty TF_CONFIG, launcher.py:64-66).

    Re-entrant: calling again with the SAME world (coordinator, size,
    rank, slices) is an idempotent no-op; a CHANGED world first tears
    down the prior distributed state (raising WorldTeardownError if that
    fails) and then forms the new one — the elastic resize path.

    Formation is delegated to the selected CollectivesBackend
    (env JAXJOB_COLLECTIVES_BACKEND ∈ {single, loopback, tpu};
    parallel/backends.py). The default (single) is byte-compatible with
    the pre-backend behavior.
    """
    from kubeflow_tpu.parallel import backends as B

    cfg = DistConfig.from_env(env)
    if cfg.distributed and cfg.coordinator_address is None:
        # validate before touching world state: a bad env must not tear
        # down a healthy world
        raise ValueError(f"{ENV_NPROC}>1 but {ENV_COORD} unset")
    backend = B.get_backend(env=env)
    with _WORLD_LOCK:
        global _ACTIVE, _DIST_LIVE, _BACKEND
        if _ACTIVE is not None:
            if _world_key(cfg) == _world_key(_ACTIVE):
                _ACTIVE = cfg  # refresh metadata (job name etc.)
                return cfg
            log.info("world changed (%s -> %s): tearing down prior state",
                     _world_key(_ACTIVE), _world_key(cfg))
            _teardown_locked()
        _DIST_LIVE = backend.join(cfg, wait=wait)
        _BACKEND = backend if _DIST_LIVE else None
        _ACTIVE = cfg
    return cfg


def is_coordinator(cfg: DistConfig | None = None) -> bool:
    cfg = cfg or DistConfig.from_env()
    return cfg.process_id == 0
