"""jsdom conformance: the semantics the harness GUARANTEES, pinned.

The web UIs are tested by executing their real <script> payloads in
kubeflow_tpu/testing/jsdom.py — a second implementation of JS semantics
(the reference uses Selenium against real browsers,
testing/test_jwa.py:17-24; this container has no browser). A divergence
between this harness and a real engine is invisible to every UI test, so
this file is the contract (VERDICT r3 #8): each test pins a spec edge
case the UIs rely on, and each KNOWN DEVIATION from real-engine behavior
is asserted AS the deviant behavior — if the harness's semantics drift,
these tests fail loudly instead of the UI tests silently meaning
something else.

Guaranteed (spec-conformant):
  - event bubbling order target -> ancestors; stopPropagation halts
    before the next ancestor but not the current node's listeners;
    removeEventListener detaches.
  - FormData(form): unchecked checkboxes omitted, checked included;
    <select> contributes the selected option's value.
  - async/await: awaited rejections route to catch; an async function's
    return value resolves the caller's promise; Promise chaining maps
    values through .then.
  - Promise.all resolves with ordered results.
  - microtask queue (round 5, VERDICT r4 #7): .then callbacks defer to
    the microtask checkpoint ('sync,then' order, as real engines);
    fetch settles on the macrotask queue in request order.

Known deviations (asserted as such):
  - setTimeout/setInterval NEVER auto-fire: callbacks queue until the
    test driver calls Browser.fire_timers() (jest-fake-timer model);
    one-shots drain, intervals refire per call.
  - an async function runs to completion before its CALLER resumes
    (`await` drains the loop cooperatively instead of suspending a
    continuation) — caller-vs-continuation interleavings are the one
    ordering class still unobservable.
  - addEventListener's capture argument is ignored (no capture phase).
"""

from kubeflow_tpu.testing.jsdom import Browser


def run(html, script):
    b = Browser()
    b.load(html + '<div id="out"></div>', run_scripts=False)
    b.run(script)
    return b


OUT = "document.getElementById('out').textContent = log.join(',');"


class TestEventBubbling:
    def test_bubbles_target_then_ancestors(self):
        b = run('<div id="o"><p id="m"><button id="i">x</button></p></div>', """
          let log = [];
          for (const id of ['o', 'm', 'i'])
            document.getElementById(id).addEventListener('click', () => log.push(id));
          document.getElementById('i').click();
        """ + OUT)
        assert b.text("out") == "i,m,o"

    def test_stop_propagation_halts_ancestors_not_siblings(self):
        b = run('<div id="o"><button id="i">x</button></div>', """
          let log = [];
          document.getElementById('o').addEventListener('click', () => log.push('outer'));
          const el = document.getElementById('i');
          el.addEventListener('click', (e) => { log.push('a'); e.stopPropagation(); });
          el.addEventListener('click', () => log.push('b'));
          el.click();
        """ + OUT)
        assert b.text("out") == "a,b"

    def test_remove_event_listener(self):
        b = run('<button id="i">x</button>', """
          let log = [];
          const el = document.getElementById('i');
          const h = () => log.push('h');
          el.addEventListener('click', h);
          el.click();
          el.removeEventListener('click', h);
          el.click();
        """ + OUT)
        assert b.text("out") == "h"


class TestFormData:
    def test_checkbox_and_select_semantics(self):
        b = run("""
          <form id="f">
            <input name="a" value="1">
            <input type="checkbox" name="unchecked" value="u">
            <input type="checkbox" name="checked" value="c" checked>
            <select name="s"><option value="x">x</option>
              <option value="y" selected>y</option></select>
          </form>""", """
          let log = [];
          for (const [k, v] of new FormData(document.getElementById('f')).entries())
            log.push(k + '=' + v);
        """ + OUT)
        assert b.text("out") == "a=1,checked=c,s=y"


class TestAsync:
    def test_await_rejection_routes_to_catch(self):
        b = run("", """
          let log = [];
          const api = () => Promise.reject(new Error('down'));
          async function go() {
            try { await api(); log.push('unreachable'); }
            catch (e) { log.push('caught:' + e.message); }
            return 'done';
          }
          go().then(v => { log.push(v); """ + OUT + """ });
        """)
        assert b.text("out") == "caught:down,done"

    def test_then_chaining_maps_values(self):
        b = run("", """
          let log = [];
          Promise.resolve(2).then(v => v * 3).then(v => log.push('v' + v));
        """ + OUT)
        # real-engine order: OUT runs at script end, BEFORE the deferred
        # then callbacks; after the drain the chain has mapped 2*3
        assert b.text("out") == ""
        assert b.eval("log.join(',')") == "v6"

    def test_promise_all_ordered(self):
        b = run("", """
          let log = [];
          Promise.all([Promise.resolve('a'), Promise.resolve('b')])
            .then(vs => log.push(vs.join('+')));
        """ + OUT)
        assert b.eval("log.join(',')") == "a+b"

    def test_microtask_queue_defers_then(self):
        """The regression VERDICT r4 #7 asked for: under round-4's EAGER
        resolution this ordered 'then,sync' and the real-engine order
        was untestable by construction; the event loop restores
        'sync,then' (script to completion, then microtask checkpoint)."""
        b = run("", """
          let log = [];
          Promise.resolve(1).then(() => log.push('then'));
          log.push('sync');
        """ + OUT)
        assert b.text("out") == "sync"  # script-end snapshot
        assert b.eval("log.join(',')") == "sync,then"

    def test_fetch_handlers_run_after_sync_code_in_request_order(self):
        """The fetch-then-render interleaving class Selenium catches in
        the reference (test_jwa.py state waits): two back-to-back
        fetches settle on the macrotask queue — after ALL sync code, in
        request order."""
        from kubeflow_tpu.utils.httpd import Router, json_resp

        r = Router()
        r.route("GET", "/slow", lambda req: json_resp({"v": "slow"}))
        r.route("GET", "/fast", lambda req: json_resp({"v": "fast"}))
        b = Browser(r)
        b.load('<div id="out"></div>', run_scripts=False)
        b.run("""
          window.log = [];
          fetch('/slow').then(r => r.json()).then(d => window.log.push(d.v));
          fetch('/fast').then(r => r.json()).then(d => window.log.push(d.v));
          window.log.push('sync');
        """)
        assert b.eval("window.log.join(',')") == "sync,slow,fast"


class TestKnownDeviations:
    """Real engines behave differently HERE. These tests pin the
    harness's actual model so drift is loud; UI scripts must not depend
    on the real-engine order for these."""

    def test_timers_fire_only_via_fire_timers(self):
        b = Browser()
        b.load('<div id="out"></div>', run_scripts=False)
        flush = ("document.getElementById('out').textContent = "
                 "window.log.join(',');")
        b.run("""
          window.log = [];
          setTimeout(() => window.log.push('once'), 0);
          setInterval(() => window.log.push('tick'), 1000);
          window.log.push('sync');
        """)
        b.run(flush)
        assert b.text("out") == "sync"          # nothing auto-fired
        b.fire_timers()
        b.run(flush)
        assert b.text("out") == "sync,tick,once"
        b.fire_timers()                          # one-shot drained
        b.run(flush)
        assert b.text("out") == "sync,tick,once,tick"


class TestRejectionIsolation:
    def test_orphaned_rejection_fails_the_same_browser_not_the_next(self):
        """A rejection created during an eval expression (after the
        pre-drain) must surface in THIS browser's eval — and must never
        leak into an unrelated Browser created afterwards."""
        import pytest

        from kubeflow_tpu.testing.jsdom import JSThrow

        b1 = Browser()
        b1.load("<div></div>", run_scripts=False)
        with pytest.raises(JSThrow):
            b1.eval("[Promise.reject('boom'), 2][1]")
        b2 = Browser()
        b2.load("<div></div>", run_scripts=False)
        b2.run("let y = 1;")  # must not re-raise b1's rejection
        assert b2.eval("y") == 1
