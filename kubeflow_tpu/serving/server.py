"""JAX model server: TF-Serving REST surface, jit-compiled predict path.

Endpoints (the contract test_tf_serving.py:105-133 exercises, plus the
status surface its readiness poll uses):

- GET  /v1/models/{model}                     -> model version status
- GET  /v1/models/{model}/metadata            -> signature metadata
- POST /v1/models/{model}:predict             -> {"predictions": [...]}
- POST /v1/models/{model}/versions/{v}:predict

TPU serving notes: predict functions are jit-compiled once per input
shape; batches are padded up to the next power of two so XLA reuses a
small set of compiled programs instead of recompiling per request size
(static shapes are an XLA requirement, SURVEY.md north-star notes).
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from kubeflow_tpu.runtime.metrics import REGISTRY as METRICS_REGISTRY
from kubeflow_tpu.serving.router import (DeadlineExceeded, HEADER_DEADLINE,
                                         _retry_after_headers)
from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import ApiHttpError, HttpReq, Router

log = logging.getLogger("kubeflow_tpu.serving")

# the request deadline (ABSOLUTE time.monotonic value), set by the HTTP
# handler from the x-request-deadline-s header and read by predict
# closures on the SAME thread (the direct / continuous-batching path;
# the micro-batch worker thread intentionally doesn't see it — there the
# deadline is enforced at admission, see docs/robustness.md)
_REQUEST_DEADLINE: contextvars.ContextVar[float | None] = \
    contextvars.ContextVar("request_deadline", default=None)


def request_deadline() -> float | None:
    """Absolute monotonic deadline of the request being handled on this
    thread, or None."""
    return _REQUEST_DEADLINE.get()

def _metric(name, kind, doc, **kw):
    from kubeflow_tpu.runtime.metrics import prom_metric

    return prom_metric(name, kind, doc, **kw)


def predict_latency():
    import prometheus_client as prom

    return _metric("serving_predict_seconds", prom.Histogram,
                   "end-to-end predict handler latency",
                   labelnames=("model",),
                   buckets=(.005, .01, .025, .05, .1, .25, .5, 1, 2.5, 5, 10))


def device_batch_size():
    import prometheus_client as prom

    return _metric("serving_device_batch_size", prom.Histogram,
                   "instances per device call after micro-batch coalescing",
                   labelnames=("model",),
                   buckets=(1, 2, 4, 8, 16, 32, 64, 128))


def predict_errors():
    import prometheus_client as prom

    return _metric("serving_predict_errors_total", prom.Counter,
                   "failed predict requests", labelnames=("model",))


def speculative_counters():
    import prometheus_client as prom

    return (_metric("serving_speculative_drafted_total", prom.Counter,
                    "draft tokens proposed", labelnames=("model",)),
            _metric("serving_speculative_accepted_total", prom.Counter,
                    "draft tokens accepted by the target "
                    "(accepted/drafted = acceptance rate; low rates mean "
                    "the draft is wasting rounds)", labelnames=("model",)))


class _ReplicaMeter:
    """Replica-side serving signals, exported to BOTH sinks (the PR 4
    convention): the MetricsRegistry text a JAXService control plane
    scrapes for autoscaling (``serving_queue_depth``,
    ``serving_tokens_generated_total``, ``serving_request_instances``) and
    prometheus_client for dashboards. Queue depth counts requests that
    have entered ``predict`` and not yet returned — the micro-batch
    window plus the decode itself — which is exactly the congestion a
    router should not add to."""

    def __init__(self, registry=METRICS_REGISTRY):
        import collections

        self.registry = registry
        self._lock = threading.Lock()
        self._inflight: dict[str, int] = {}
        # completion timestamps (perf_counter) per model: the drain-rate
        # window behind Retry-After on the overload 429
        self._done: dict[str, Any] = {}
        self._deque = collections.deque

    def _publish_locked(self, model: str) -> None:
        import prometheus_client as prom

        depth = self._inflight.get(model, 0)
        self.registry.gauge(
            "serving_queue_depth", depth,
            help_="requests inside predict (queued + decoding)",
            model=model)
        _metric("serving_queue_depth", prom.Gauge,
                "requests inside predict (queued + decoding)",
                labelnames=("model",)).labels(model).set(depth)

    def enter(self, model: str, n_requests: int) -> None:
        import prometheus_client as prom

        with self._lock:
            self._inflight[model] = self._inflight.get(model, 0) + 1
            self._publish_locked(model)
        self.registry.histogram(
            "serving_request_instances", n_requests,
            help_="instances per predict call",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128), model=model)
        _metric("serving_request_instances", prom.Histogram,
                "instances per predict call", labelnames=("model",),
                buckets=(1, 2, 4, 8, 16, 32, 64, 128)) \
            .labels(model).observe(n_requests)

    def exit(self, model: str) -> None:
        with self._lock:
            self._inflight[model] = max(0, self._inflight.get(model, 0) - 1)
            if model not in self._done:
                self._done[model] = self._deque(maxlen=64)
            self._done[model].append(time.perf_counter())
            self._publish_locked(model)

    def depth(self, model: str) -> int:
        with self._lock:
            return self._inflight.get(model, 0)

    def retry_after(self, model: str) -> float:
        """Seconds until the current queue should have drained, from the
        observed completion rate (the Retry-After a 429 carries; the
        router's backoff floor honors it). Conservative default of 1s
        before any completion history exists."""
        with self._lock:
            done = self._done.get(model)
            depth = self._inflight.get(model, 0)
            if not done or len(done) < 2:
                return 1.0
            span = done[-1] - done[0]
            if span <= 0:
                return 1.0
            rate = (len(done) - 1) / span
            return float(min(max(math.ceil((depth + 1) / rate), 1.0), 120.0))

    def tokens(self, model: str, n: int) -> None:
        if n <= 0:
            return
        self.registry.counter_inc(
            "serving_tokens_generated_total", by=float(n),
            help_="new tokens generated (rate = this replica's "
                  "tokens/sec, the autoscaler signal)",
            model=model)
        import prometheus_client as prom

        _metric("serving_tokens_generated_total", prom.Counter,
                "new tokens generated", labelnames=("model",)) \
            .labels(model).inc(n)


REPLICA_METER = _ReplicaMeter()


def _generated_tokens(result: list, signature: dict) -> int:
    """New-token count of a generate response (lists of token ids per
    row after _unstack); non-generate signatures contribute none."""
    if signature.get("method_name") != "generate":
        return 0
    total = 0
    for row in result or []:
        if hasattr(row, "__len__"):
            total += len(row)
    return total


@dataclass
class ServedModel:
    """One versioned model: predict_fn maps a batched np array / dict of
    arrays to predictions. batch_window_ms > 0 turns on cross-request
    micro-batching: concurrent /predict calls within the window coalesce
    into ONE padded device call (each jit dispatch has fixed overhead and
    the MXU wants large batches; serving traffic is many small
    requests — the TPU-native answer is coalescing, not more threads)."""

    name: str
    predict_fn: Callable[[Any], Any]
    version: int = 1
    signature: dict = field(default_factory=dict)
    pad_batches: bool = True
    batch_window_ms: float = 0.0
    max_batch: int = 64
    # minimum padded batch (power of two): mesh-sharded models need the
    # batch divisible by the product of data-parallel axis sizes
    pad_multiple: int = 1
    # replica-side overload gate: >0 caps concurrent predict calls; the
    # excess gets 429 + Retry-After (queue-drain estimate) instead of
    # stacking unbounded latency the router can't see
    max_inflight: int = 0
    _batcher: "MicroBatcher | None" = field(default=None, repr=False)

    def _predict_now(self, instances: list) -> list:
        batch = _stack(instances)
        n = _batch_size(batch)
        device_batch_size().labels(self.name).observe(n)
        if self.pad_batches:
            padded = _pad_batch(batch, _next_pow2(max(n, self.pad_multiple)))
        else:
            padded = batch
        out = self.predict_fn(padded)
        return _unstack(out, n)

    def __post_init__(self):
        # constructed eagerly (not lazily) so concurrent first requests
        # can't race a lazy init
        if self.batch_window_ms > 0:
            self._batcher = MicroBatcher(
                self._predict_now, max_batch=self.max_batch,
                max_wait_ms=self.batch_window_ms)

    def predict(self, instances: list) -> list:
        if not instances:
            raise ApiHttpError(400, "instances must be non-empty")
        if self.max_inflight > 0 \
                and REPLICA_METER.depth(self.name) >= self.max_inflight:
            ra = REPLICA_METER.retry_after(self.name)
            raise ApiHttpError(
                429, f"replica overloaded ({self.max_inflight} in flight)",
                headers=_retry_after_headers(ra))
        REPLICA_METER.enter(self.name, len(instances))
        try:
            if self._batcher is not None:
                result = self._batcher.submit(instances)
            else:
                result = self._predict_now(instances)
        finally:
            REPLICA_METER.exit(self.name)
        REPLICA_METER.tokens(
            self.name, _generated_tokens(result, self.signature))
        return result

    def close(self) -> None:
        if self._batcher is not None:
            self._batcher.close()


class _Pending:
    __slots__ = ("instances", "event", "result", "error")

    def __init__(self, instances: list):
        self.instances = instances
        self.event = threading.Event()
        self.result: list | None = None
        self.error: BaseException | None = None


class MicroBatcher:
    """Coalesces concurrent predict calls into single batched calls.

    A worker thread blocks for the first pending request, then keeps
    collecting arrivals until max_wait_ms elapses or max_batch instances
    are queued, concatenates all instance lists into one call of
    `fn(instances) -> results`, and scatters the per-request slices
    back. Errors from fn propagate to every caller in that batch."""

    def __init__(self, fn: Callable[[list], list], max_batch: int = 64,
                 max_wait_ms: float = 5.0):
        import queue as _queue

        self.fn = fn
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1e3
        self._q: "_queue.Queue[_Pending | None]" = _queue.Queue()
        self._lock = threading.Lock()
        self._closed = False
        self._carry: _Pending | None = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-microbatch")
        self._thread.start()

    def submit(self, instances: list) -> list:
        p = _Pending(instances)
        # enqueue under the same lock close() takes to set _closed, so
        # every pending lands strictly before the shutdown sentinel (a
        # request behind the sentinel would block its caller forever)
        with self._lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._q.put(p)
        # the worker sets the event on every dispatch outcome (result,
        # error, shutdown drain), so the park cannot leak
        p.event.wait()  # tpulint: disable=NET501  worker guarantees set
        if p.error is not None:
            raise p.error
        return p.result  # type: ignore[return-value]

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(None)
        self._thread.join(timeout=5)

    def _run(self) -> None:
        import queue as _queue
        import time as _time

        while True:
            head = self._carry or self._q.get()
            self._carry = None
            if head is None:
                return
            group = [head]
            total = len(head.instances)
            deadline = _time.monotonic() + self.max_wait
            stop = False
            while total < self.max_batch:
                timeout = deadline - _time.monotonic()
                if timeout <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=timeout)
                except _queue.Empty:
                    break
                if nxt is None:
                    stop = True
                    break
                if total + len(nxt.instances) > self.max_batch:
                    # would overshoot the device-batch cap (and pow2
                    # padding would amplify it) — start the next group
                    self._carry = nxt
                    break
                group.append(nxt)
                total += len(nxt.instances)
            self._dispatch(group)
            if stop:
                if self._carry is not None:
                    self._dispatch([self._carry])
                    self._carry = None
                return

    def _dispatch(self, group: list[_Pending]) -> None:
        flat = [inst for p in group for inst in p.instances]
        try:
            results = self.fn(flat)
        except BaseException as e:  # noqa: BLE001 - propagate to callers
            for p in group:
                p.error = e
                p.event.set()
            return
        off = 0
        for p in group:
            p.result = results[off:off + len(p.instances)]
            off += len(p.instances)
            p.event.set()


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _ragged_ok_asarray(rows: list) -> np.ndarray:
    """np.asarray, falling back to an object array for ragged rows
    (e.g. variable-length token prompts — padded later by the model's
    own host-side handling)."""
    try:
        return np.asarray(rows)
    except ValueError:
        arr = np.empty(len(rows), dtype=object)
        for i, r in enumerate(rows):
            arr[i] = r
        return arr


def _stack(instances: list) -> Any:
    if not instances:
        raise ApiHttpError(400, "instances must be non-empty")
    first = instances[0]
    if isinstance(first, dict):
        return {k: _ragged_ok_asarray([inst[k] for inst in instances])
                for k in first}
    return _ragged_ok_asarray(instances)


def _batch_size(batch: Any) -> int:
    if isinstance(batch, dict):
        return len(next(iter(batch.values())))
    return len(batch)


def _pad_batch(batch: Any, to: int) -> Any:
    def pad(a: np.ndarray) -> np.ndarray:
        if len(a) == to:
            return a
        reps = np.repeat(a[-1:], to - len(a), axis=0)
        return np.concatenate([a, reps], axis=0)

    if isinstance(batch, dict):
        return {k: pad(v) for k, v in batch.items()}
    return pad(batch)


def _unstack(out: Any, n: int) -> list:
    if isinstance(out, dict):
        arrs = {k: np.asarray(v)[:n] for k, v in out.items()}
        return [{k: arrs[k][i].tolist() for k in arrs} for i in range(n)]
    if isinstance(out, list):
        # ragged rows (per-request max_new_tokens budgets differ)
        return [list(r) for r in out[:n]]
    return np.asarray(out)[:n].tolist()


class ModelServer:
    def __init__(self):
        self._models: dict[str, dict[int, ServedModel]] = {}
        self._lock = threading.Lock()

    def register(self, model: ServedModel) -> None:
        with self._lock:
            versions = self._models.setdefault(model.name, {})
            old = versions.get(model.version)
            versions[model.version] = model
        if old is not None:
            # hot-swap: release the replaced model's micro-batch worker
            # (and with it the old predict closure) instead of leaking
            # one thread per reload
            old.close()

    def close(self) -> None:
        """Shut down every model's micro-batch worker (service exit)."""
        with self._lock:
            models = [m for vs in self._models.values() for m in vs.values()]
        for m in models:
            m.close()

    def _get(self, name: str, version: int | None = None) -> ServedModel:
        versions = self._models.get(name)
        if not versions:
            raise ApiHttpError(404, f"model {name!r} not found")
        if version is None:
            return versions[max(versions)]
        if version not in versions:
            raise ApiHttpError(404, f"model {name!r} version {version} not found")
        return versions[version]

    # -- handlers -----------------------------------------------------------

    def list_models(self, req: HttpReq):
        """Inventory endpoint: every served model with versions and the
        signature method (classify vs generate) — what a router or the
        dashboard needs to enumerate the serving surface."""
        with self._lock:
            out = []
            for name, versions in sorted(self._models.items()):
                latest = versions[max(versions)]
                out.append({
                    "name": name,
                    "versions": sorted(versions),
                    "method": latest.signature.get("method_name", "predict"),
                    "micro_batching": latest.batch_window_ms > 0,
                })
        return {"models": out}

    def status(self, req: HttpReq):
        name = req.params["model"]
        versions = self._models.get(name)
        if not versions:
            raise ApiHttpError(404, f"model {name!r} not found")
        return {"model_version_status": [
            {"version": str(v), "state": "AVAILABLE",
             "status": {"error_code": "OK", "error_message": ""}}
            for v in sorted(versions)
        ]}

    def metadata(self, req: HttpReq):
        m = self._get(req.params["model"])
        return {"model_spec": {"name": m.name, "version": str(m.version)},
                "metadata": {"signature_def": m.signature}}

    def predict(self, req: HttpReq):
        name = req.params["model"]
        version = int(req.params["version"]) if "version" in req.params else None
        body = req.json() or {}
        instances = body.get("instances")
        if instances is None:
            raise ApiHttpError(400, 'request body must contain "instances"')
        model = self._get(name, version)
        import time as _time

        # deadline propagation, replica hop: the header carries REMAINING
        # seconds (the router re-derives it per attempt); expose the
        # absolute monotonic deadline to same-thread predict closures
        deadline = None
        raw = req.headers.get(HEADER_DEADLINE)
        if raw:  # missing OR empty ("" is the shell's missing-header)
            try:
                remaining = float(raw)
            except ValueError:
                raise ApiHttpError(
                    400, f"bad {HEADER_DEADLINE} header: {raw!r}")
            if remaining <= 0:
                raise ApiHttpError(504, "deadline exceeded")
            deadline = _time.monotonic() + remaining
        token = _REQUEST_DEADLINE.set(deadline)
        t0 = _time.perf_counter()
        try:
            preds = model.predict(instances)
        except ApiHttpError:
            predict_errors().labels(name).inc()
            raise
        except DeadlineExceeded as e:
            predict_errors().labels(name).inc()
            raise ApiHttpError(504, f"deadline exceeded: {e}")
        except Exception as e:
            predict_errors().labels(name).inc()
            log.exception("predict failed for %s", name)
            raise ApiHttpError(400, f"prediction failed: {e}")
        finally:
            _REQUEST_DEADLINE.reset(token)
        predict_latency().labels(name).observe(_time.perf_counter() - t0)
        return {"predictions": preds}

    def router(self) -> Router:
        r = Router("serving")
        r.route("POST", "/v1/models/{model}:predict", self.predict)
        r.route("POST", "/v1/models/{model}/versions/{version}:predict", self.predict)
        r.route("GET", "/v1/models/{model}/metadata", self.metadata)
        r.route("GET", "/v1/models/{model}", self.status)
        r.route("GET", "/v1/models", self.list_models)
        httpd.add_health_routes(r)
        httpd.add_metrics_route(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 8500) -> httpd.HttpService:
        return httpd.HttpService(self.router(), host, port)


# ---------------------------------------------------------------------------
# model builders


class _ServingMesh:
    """Mesh-sharded parameter holder for serving (SURVEY north-star: a
    model too big for one chip's HBM — e.g. llama-1b f32 on v5e — is
    served by sharding parameters over the mesh: tensor-parallel leaves
    follow their nn.with_partitioning annotations, the rest fall to the
    fsdp heuristic in parallel/shardings.py, and GSPMD inserts the
    activation collectives into one compiled program per shape).

    Variables materialize on the FIRST predict (shardings are inferred
    from eval_shape of the real input), either restored from orbax and
    device_put onto their shards, or initialized directly sharded via
    jit out_shardings — the full replicated tree never exists on any
    single device.
    """

    def __init__(self, mesh_spec, seed: int, checkpoint_dir: str | None,
                 param_dtype: str | None = None):
        from kubeflow_tpu.parallel.mesh import BATCH_AXES, build_mesh

        self.mesh = build_mesh(mesh_spec)
        self.seed = seed
        self.checkpoint_dir = checkpoint_dir
        self.param_dtype = param_dtype
        if checkpoint_dir:
            # a missing/empty checkpoint must fail AT REGISTRATION
            # (crashloop + readiness gate), not as a 500 on the first
            # routed request. A cheap latest_step probe only — NOT a full
            # restore: pinning every registered model's unsharded host
            # tree until its first request would multiply host RSS.
            # Builders that know their input shape (the LM generator)
            # materialize eagerly right after construction, catching
            # corrupt/shape-mismatched checkpoints at registration too.
            from kubeflow_tpu.runtime.checkpoint import Checkpointer

            ck = Checkpointer(checkpoint_dir, async_save=False)
            try:
                if ck.latest_step() is None:
                    raise FileNotFoundError(
                        f"no checkpoint found in {checkpoint_dir}")
            finally:
                ck.close()
        self.variables = None
        self._lock = threading.Lock()
        # every batch axis, INCLUDING expert (BATCH_AXES widened in round
        # 4): padding to a multiple the batch sharding doesn't divide
        # would fail device_put at request time on MoE serving meshes
        dp = 1
        for a in BATCH_AXES:
            dp *= self.mesh.shape[a]
        if dp & (dp - 1):
            raise ValueError(
                f"serving mesh data axes product {dp} must be a power of "
                "two (batches are padded to powers of two)")
        self.pad_multiple = dp

    def get_variables(self, model, example):
        import jax

        from kubeflow_tpu.parallel import shardings as S

        with self._lock:
            if self.variables is not None:
                return self.variables
            rng = jax.random.PRNGKey(self.seed)
            abstract = jax.eval_shape(
                lambda: model.init(rng, example, train=False))
            shardings = S.infer_shardings(abstract, self.mesh)
            if self.checkpoint_dir:
                from kubeflow_tpu.runtime.checkpoint import restore_variables

                host_vars, step = restore_variables(self.checkpoint_dir)
                log.info("restored variables from %s step %d (sharded %s)",
                         self.checkpoint_dir, step, dict(self.mesh.shape))
                if self.param_dtype:
                    host_vars = cast_params(host_vars, self.param_dtype)
                self.variables = jax.device_put(S.unbox(host_vars), shardings)
            else:
                with self.mesh:
                    def init_fn(r):
                        v = S.unbox(model.init(r, example, train=False))
                        return (cast_params(v, self.param_dtype)
                                if self.param_dtype else v)

                    self.variables = jax.jit(
                        init_fn, out_shardings=shardings)(rng)
            return self.variables


def serve_flax_classifier(name: str, model_name: str, input_key: str | None = None,
                          seed: int = 0, checkpoint_dir: str | None = None,
                          mesh: "Any | None" = None,
                          **model_kwargs) -> ServedModel:
    """Wrap a zoo model into a ServedModel with a jitted softmax head.
    With `checkpoint_dir`, weights come from the latest orbax training
    checkpoint (runtime.checkpoint.restore_variables) — the analogue of
    TF-Serving pointing at an exported SavedModel; otherwise they are
    randomly initialized and the serving contract is shape/latency-
    exercised, matching the reference's mnist golden-compare approach.

    With `mesh` (a MeshSpec/dict), parameters are sharded over the device
    mesh (tensor parallelism + fsdp heuristic) and every predict runs as
    one GSPMD program across it."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.registry import get_model

    model = get_model(model_name, **model_kwargs)
    sm = _ServingMesh(mesh, seed, checkpoint_dir) if mesh is not None else None
    params = None
    if sm is None and checkpoint_dir:
        from kubeflow_tpu.runtime.checkpoint import restore_variables

        params, step = restore_variables(checkpoint_dir)
        log.info("model %s: restored variables from %s step %d", name,
                 checkpoint_dir, step)

    @jax.jit
    def fwd(params, x):
        logits = model.apply(params, x, train=False)
        return jax.nn.softmax(logits, axis=-1)

    state = {}

    def predict(batch):
        nonlocal params
        x = batch[input_key] if input_key and isinstance(batch, dict) else batch
        x = jnp.asarray(x, jnp.float32)
        if sm is not None:
            use_params = sm.get_variables(model, x)
        else:
            if params is None:
                state["rng"] = jax.random.PRNGKey(seed)
                params = model.init(state["rng"], x, train=False)
            use_params = params
        with (sm.mesh if sm is not None else contextlib.nullcontext()):
            return np.asarray(fwd(use_params, x))

    return ServedModel(name=name, predict_fn=predict,
                       pad_multiple=sm.pad_multiple if sm else 1,
                       signature={"inputs": input_key or "array",
                                  "method_name": "predict"})


def _prepare_serving_params(variables, param_dtype):
    """Serving-time weight preparation: 'int8'/'int4' quantize
    (weight-only, serving/quant.py), any other dtype casts, None
    passes through."""
    if param_dtype in ("int8", "int4"):
        from kubeflow_tpu.serving.quant import quantize_params

        return quantize_params(variables,
                               bits=4 if param_dtype == "int4" else 8)
    return cast_params(variables, param_dtype) if param_dtype else variables


def cast_params(variables, dtype):
    """Inference-time parameter cast (f32 training checkpoints -> bf16
    serving): KV-cache decode is HBM-bandwidth-bound on WEIGHT reads, so
    halving weight bytes is the single biggest single-chip decode lever.
    Floating leaves only; integer leaves pass
    through untouched."""
    import jax
    import jax.numpy as jnp

    if not jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        # astype(int8) would silently truncate weights to garbage; int8
        # serving goes through _prepare_serving_params -> quantize_params
        raise ValueError(
            f"cast_params target must be floating, got {dtype!r} "
            "(use param_dtype='int8' via _prepare_serving_params)")

    def leaf(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(leaf, variables)


def serve_lm_generator(name: str, model_name: str, *, prompt_len: int = 128,
                       max_new_tokens: int = 32, temperature: float = 0.0,
                       top_k: int = 0, seed: int = 0,
                       checkpoint_dir: str | None = None,
                       batch_window_ms: float = 0.0, max_batch: int = 64,
                       mesh: "Any | None" = None,
                       continuous_batching: bool = False,
                       decode_slots: int = 8,
                       kv_pages: int = 0, kv_page_size: int = 0,
                       prefix_cache: bool = True,
                       param_dtype: str | None = None,
                       draft_model: str | None = None,
                       draft_checkpoint_dir: str | None = None,
                       draft_k: int = 4,
                       max_inflight: int = 0,
                       **model_kwargs) -> ServedModel:
    """Wrap a zoo LM into a generative ServedModel (the transformer-era
    analogue of the TF-Serving classifier path).

    Request instances are `{"tokens": [int, ...]}` (pre-tokenized
    prompts); each is left-padded/truncated host-side to the fixed
    `prompt_len` and decoded with the KV-cache loop
    (runtime/generate.py) for exactly `max_new_tokens` steps — one
    compiled program per batch bucket, never per request shape (static
    shapes are an XLA requirement). Responses carry the new tokens only.
    """
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.runtime.generate import generate

    # speculative decoding needs k positions of verify-chunk headroom
    seq_budget = prompt_len + max_new_tokens + (draft_k if draft_model else 0)
    if kv_pages and not continuous_batching:
        raise ValueError("kv_pages (the paged KV cache) requires "
                         "continuous_batching — the page pool is shared "
                         "across decode slots")
    if kv_pages and mesh is not None:
        raise ValueError("the paged KV cache is single-chip for now "
                         "(no mesh)")
    if kv_pages and not kv_page_size:
        raise ValueError("kv_pages requires kv_page_size > 0")
    if kv_pages:
        model_kwargs = dict(model_kwargs,
                            kv_pages=kv_pages, kv_page_size=kv_page_size)
    model = get_model(model_name, max_seq_len=seq_budget, **model_kwargs)
    if draft_model:
        if temperature > 0:
            raise ValueError("speculative decoding is greedy-only "
                             "(temperature must be 0)")
        if mesh is not None:
            raise ValueError("speculative decoding is single-chip for "
                             "now (no mesh)")
        if getattr(model.cfg, "rolling_kv_cache", False):
            # fail at REGISTRATION like the other exclusions — the
            # per-request guard in runtime/speculative.py would otherwise
            # 500 every decode on a server that reported healthy
            raise ValueError("speculative decoding requires the full KV "
                             "cache (rolling_kv_cache evicts positions a "
                             "rejected draft must rewind over)")
    if kv_pages and getattr(model.cfg, "rolling_kv_cache", False):
        raise ValueError("the paged KV cache is exclusive with "
                         "rolling_kv_cache")
    quantized = param_dtype in ("int8", "int4")
    if quantized and mesh is not None:
        raise ValueError(f"param_dtype={param_dtype!r} serving is "
                         "single-chip for now (mesh-sharded weights "
                         "stay bf16)")
    if quantized:
        # weight-only int8/int4 (serving/quant.py): HBM streams the
        # narrow ints, the (unpack+)dequant fuses into the decode
        # matmuls inside jit
        from kubeflow_tpu.serving.quant import QuantizedModel

        model = QuantizedModel(model)
    sm = (_ServingMesh(mesh, seed, checkpoint_dir, param_dtype=param_dtype)
          if mesh is not None else None)
    if sm is not None and checkpoint_dir:
        # input shape is known here: materialize now so a shape-mismatched
        # checkpoint (wrong model/vocab) crashes registration, not the
        # first routed request
        sm.get_variables(model, jnp.zeros((1, 1), jnp.int32))
    variables = None
    if sm is None and checkpoint_dir:
        from kubeflow_tpu.runtime.checkpoint import restore_variables

        variables, step = restore_variables(checkpoint_dir)
        variables = _prepare_serving_params(variables, param_dtype)
        log.info("model %s: restored variables from %s step %d", name,
                 checkpoint_dir, step)

    def _materialize(prompt_col):
        """Non-mesh variables: lazy init + serving cast/quantize — the
        ONE place uncast f32 weights could otherwise leak from."""
        v = model.init(jax.random.PRNGKey(seed), prompt_col, train=False)
        return _prepare_serving_params(v, param_dtype)

    draft_box: list = []

    def _draft():
        """Lazy draft model + variables (same cast/quantize treatment
        as the target)."""
        if not draft_box:
            dm = get_model(draft_model, max_seq_len=seq_budget)
            if quantized:
                from kubeflow_tpu.serving.quant import QuantizedModel

                dm = QuantizedModel(dm)
            if draft_checkpoint_dir:
                from kubeflow_tpu.runtime.checkpoint import restore_variables

                dvars, _ = restore_variables(draft_checkpoint_dir)
            else:
                dvars = dm.init(jax.random.PRNGKey(seed + 1),
                                jnp.zeros((1, 1), jnp.int32), train=False)
            draft_box.extend([dm, _prepare_serving_params(dvars, param_dtype)])
        return draft_box[0], draft_box[1]

    import itertools

    # temperature>0: each request gets a fresh seed (generate() takes it
    # as a traced scalar, so this does NOT recompile per request);
    # temperature==0 stays at the fixed seed — greedy is deterministic.
    request_seed = itertools.count(seed).__next__

    decoder_box: list = []  # lazy SlotDecoder (needs materialized vars)
    _decoder_lock = threading.Lock()

    def _validated_rows(toks):
        # host-side ragged handling: LEFT-pad / keep the LAST prompt_len
        # tokens so the most recent context survives a trim; pad_lens
        # mask the pad positions out of decode attention (generate.py)
        vocab = model.cfg.vocab_size
        rows, pad_lens = [], []
        for row in np.asarray(toks, dtype=object):
            row = [int(t) for t in (row if hasattr(row, "__len__") else [row])]
            bad = [t for t in row if not 0 <= t < vocab]
            if bad:
                # JAX gather clamps out-of-range indices silently; a
                # tokenizer/vocab mismatch must be a 400, not garbage
                raise ApiHttpError(
                    400, f"token ids out of range [0, {vocab}): {bad[:5]}")
            row = row[-prompt_len:]
            pad_lens.append(prompt_len - len(row))
            rows.append([0] * (prompt_len - len(row)) + row)
        return rows, pad_lens

    def _validated_max_news(batch, n):
        """Optional per-instance "max_new_tokens" cap (every instance
        must carry the key or none): a paged decoder reserves pages for
        the REQUEST's budget, not the server-wide ceiling."""
        caps = (batch.get("max_new_tokens")
                if isinstance(batch, dict) else None)
        if caps is None:
            return [None] * n
        flat = np.asarray(caps, dtype=object).reshape(-1)
        if len(flat) != n:
            # a short list would silently zip-truncate the batch,
            # dropping requests and misaligning instance -> prediction
            raise ApiHttpError(
                400, f"max_new_tokens must be one value per instance "
                     f"(got {len(flat)} for {n} instances)")
        out = []
        for c in flat:
            c = int(c)
            if not 1 <= c <= max_new_tokens:
                raise ApiHttpError(
                    400, f"max_new_tokens must be in 1..{max_new_tokens}, "
                         f"got {c}")
            out.append(c)
        return out

    def _capped_rows(out_rows, maxnews):
        """Apply per-instance budgets to whole-batch decode output:
        every path honors the documented cap, not just the slot
        decoder (ragged results when budgets differ)."""
        if all(c is None for c in maxnews):
            return out_rows
        return [list(np.asarray(row)[:c if c is not None else len(row)])
                for row, c in zip(out_rows, maxnews)]

    def predict(batch):
        nonlocal variables
        toks = batch["tokens"] if isinstance(batch, dict) else batch
        rows, pad_lens = _validated_rows(toks)
        maxnews = _validated_max_news(batch, len(rows))
        if continuous_batching:
            # slot-based lockstep decode: rows join the shared decoder at
            # step boundaries and finish independently — a long
            # generation never blocks a short one (serving/continuous.py)
            from kubeflow_tpu.serving.continuous import SlotDecoder

            with _decoder_lock:  # concurrent first requests: one decoder
                if not decoder_box:
                    if sm is not None:
                        use_vars = sm.get_variables(
                            model, jnp.zeros((1, 1), jnp.int32))
                    else:
                        use_vars = variables or _materialize(
                            jnp.zeros((1, 1), jnp.int32))
                    dm = dv = None
                    if draft_model:
                        dm, dv = _draft()
                    decoder_box.append(SlotDecoder(
                        model, use_vars, slots=decode_slots,
                        prompt_len=prompt_len,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature, top_k=top_k, seed=seed,
                        mesh=sm.mesh if sm is not None else None,
                        prefix_cache=prefix_cache,
                        draft_model=dm, draft_variables=dv,
                        draft_k=draft_k, metrics_name=name))
            dec = decoder_box[0]
            # capture the handler thread's deadline HERE: pool.map runs
            # submit_padded on worker threads that don't inherit the
            # contextvar (micro-batched callers see None — admission-time
            # enforcement only, docs/robustness.md)
            dl = request_deadline()
            if len(rows) == 1:  # hot path: no thread churn per request
                outs = [dec.submit_padded(rows[0], pad_lens[0],
                                          maxnews[0], dl)]
            else:
                import concurrent.futures as cf

                with cf.ThreadPoolExecutor(max_workers=len(rows)) as pool:
                    outs = list(pool.map(dec.submit_padded, rows,
                                         pad_lens, maxnews,
                                         [dl] * len(rows)))
            # per-request budgets produce ragged rows; pad the response
            # rows only when a caller actually mixed budgets
            if len({len(o) for o in outs}) > 1:
                return [list(o) for o in outs]
            return np.asarray(outs, dtype=np.int64)
        prompt = jnp.asarray(rows, jnp.int32)
        if sm is not None:
            use_vars = sm.get_variables(model, prompt[:, :1])
        else:
            if variables is None:
                variables = _materialize(prompt[:, :1])
            use_vars = variables
        if draft_model:
            # speculative: batch-1 rounds per row (accept lengths are
            # data-dependent); concurrency comes from the micro-batcher
            from kubeflow_tpu.runtime.speculative import speculative_generate

            dm, dv = _draft()
            drafted_c, accepted_c = speculative_counters()
            outs = []
            for r in range(prompt.shape[0]):
                toks, stats = speculative_generate(
                    model, use_vars, dm, dv, prompt[r:r + 1],
                    max_new_tokens=max_new_tokens, k=draft_k,
                    pad_len=jnp.asarray(pad_lens[r:r + 1], jnp.int32))
                drafted_c.labels(model=name).inc(stats["drafted"])
                accepted_c.labels(model=name).inc(stats["accepted"])
                outs.append(np.asarray(toks)[0])
            return _capped_rows(np.stack(outs)[:, prompt_len:], maxnews)
        with (sm.mesh if sm is not None else contextlib.nullcontext()):
            out = np.asarray(generate(
                model, use_vars, prompt, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k,
                seed=request_seed() if temperature > 0 else seed,
                pad_len=jnp.asarray(pad_lens, jnp.int32)))
        return _capped_rows(out[:, prompt_len:], maxnews)  # new tokens only

    served = ServedModel(
        name=name, predict_fn=predict,
        # the slot decoder handles raggedness natively, and the
        # speculative path is sequential batch-1 rounds; pow2 padding
        # would just decode phantom rows in both
        pad_batches=not (continuous_batching or draft_model),
        batch_window_ms=batch_window_ms, max_batch=max_batch,
        pad_multiple=sm.pad_multiple if sm else 1,
        max_inflight=max_inflight,
        signature={"inputs": "tokens", "method_name": "generate",
                   "prompt_len": prompt_len,
                   "max_new_tokens": max_new_tokens,
                   **({"continuous_batching": True,
                       "decode_slots": decode_slots}
                      if continuous_batching else {}),
                   **({"kv_pages": kv_pages,
                       "kv_page_size": kv_page_size,
                       "prefix_cache": prefix_cache}
                      if kv_pages else {}),
                   **({"param_dtype": param_dtype} if param_dtype else {}),
                   **({"draft_model": draft_model, "draft_k": draft_k}
                      if draft_model else {}),
                   **({"mesh": {k: v for k, v in sm.mesh.shape.items()
                                if v > 1}} if sm else {})})
    if continuous_batching:
        orig_close = served.close

        def _close():
            if decoder_box:
                decoder_box[0].close()
            orig_close()

        served.close = _close  # type: ignore[method-assign]
    return served


def main() -> None:  # pragma: no cover - container entry
    import argparse

    p = argparse.ArgumentParser("kubeflow-tpu-serving")
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--model", action="append", default=[],
                   help="name=zoo_model, e.g. mnist=resnet18")
    p.add_argument("--checkpoint-dir", default=None,
                   help="orbax checkpoint dir to restore model weights from "
                        "(single --model only; use name=zoo@dir per model)")
    p.add_argument("--lm", action="append", default=[],
                   help="generative LM entry: name=zoo_model[@ckpt_dir], "
                        "e.g. chat=gpt-125m")
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--max-new-tokens", type=int, default=32)
    p.add_argument("--param-dtype", default=None,
                   choices=["bfloat16", "float32", "int8", "int4"],
                   help="cast served LM parameters (bfloat16 halves the "
                        "weight HBM reads that dominate decode; int8 is "
                        "weight-only quantization, halving them again; "
                        "int4 packs two nibbles per byte for one more "
                        "halving at a looser error bound)")
    p.add_argument("--attention-window", type=int, default=0,
                   help="sliding-window attention width for served LMs "
                        "(0 = full causal)")
    p.add_argument("--rolling-kv-cache", action="store_true",
                   help="bound the decode KV cache to the attention "
                        "window (slot = position %% window): serving "
                        "memory and per-step cache bandwidth become "
                        "O(window) instead of O(max_seq); requires "
                        "--attention-window")
    p.add_argument("--kv-cache-dtype", default=None,
                   choices=["auto", "int8"],
                   help="int8 quantizes the decode KV cache (per-token-"
                        "head scales): the long-context decode lever")
    p.add_argument("--draft-model", default=None,
                   help="zoo model that drafts k tokens per round for "
                        "speculative decoding (greedy-exact; e.g. "
                        "gpt-125m drafting for llama-1b)")
    p.add_argument("--draft-k", type=int, default=4)
    p.add_argument("--draft-checkpoint-dir", default=None,
                   help="orbax checkpoint for the draft model — a "
                        "randomly initialized draft accepts ~nothing "
                        "and makes speculative serving SLOWER")
    p.add_argument("--max-inflight", type=int, default=0,
                   help="replica overload gate: cap concurrent predict "
                        "calls per LM; excess gets 429 + Retry-After "
                        "(0 = uncapped)")
    p.add_argument("--continuous-batching", action="store_true",
                   help="slot-based lockstep decode: requests join at any "
                        "step boundary and finish independently")
    p.add_argument("--decode-slots", type=int, default=8)
    p.add_argument("--kv-pages", type=int, default=0,
                   help="paged KV cache: total pool pages shared across "
                        "decode slots (page 0 is trash); admission is "
                        "gated on page availability and shared prompt "
                        "prefixes reuse pages. Requires "
                        "--continuous-batching and --kv-page-size")
    p.add_argument("--kv-page-size", type=int, default=0,
                   help="positions per KV-cache page")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable prompt-prefix page sharing (A/B lever; "
                        "pages are still pooled)")
    p.add_argument("--mesh", default=None,
                   help="shard served params over a mesh, e.g. "
                        "'model=4,fsdp=2' — required for models whose "
                        "state exceeds one chip's HBM")
    args = p.parse_args()
    mesh_spec = None
    if args.mesh:
        try:
            mesh_spec = {k: int(v) for k, v in
                         (kv.split("=", 1) for kv in args.mesh.split(","))}
        except ValueError:
            p.error(f"--mesh must be axis=int[,axis=int...], got {args.mesh!r}")
    # default classifier only when nothing at all was requested
    models = args.model or ([] if args.lm else ["mnist=resnet18"])
    if args.checkpoint_dir and len(models) > 1:
        p.error("--checkpoint-dir applies to exactly one --model; "
                "use name=zoo@ckpt_dir syntax for multiple models")
    server = ModelServer()
    for spec in models:
        name, _, zoo = spec.partition("=")
        zoo, _, ckpt = zoo.partition("@")
        server.register(serve_flax_classifier(name, zoo or "resnet18",
                                              num_classes=10, mesh=mesh_spec,
                                              checkpoint_dir=ckpt or args.checkpoint_dir))
    for spec in args.lm:
        name, _, zoo = spec.partition("=")
        zoo, _, ckpt = zoo.partition("@")
        server.register(serve_lm_generator(
            name, zoo or "gpt-125m", prompt_len=args.prompt_len,
            max_new_tokens=args.max_new_tokens, mesh=mesh_spec,
            continuous_batching=args.continuous_batching,
            decode_slots=args.decode_slots,
            kv_pages=args.kv_pages, kv_page_size=args.kv_page_size,
            prefix_cache=not args.no_prefix_cache,
            param_dtype=args.param_dtype,
            max_inflight=args.max_inflight,
            checkpoint_dir=ckpt or None,
            draft_model=args.draft_model, draft_k=args.draft_k,
            draft_checkpoint_dir=args.draft_checkpoint_dir,
            **({"kv_cache_dtype": args.kv_cache_dtype}
               if args.kv_cache_dtype else {}),
            **({"attention_window": args.attention_window}
               if args.attention_window else {}),
            **({"rolling_kv_cache": True}
               if args.rolling_kv_cache else {})))
    svc = server.serve(port=args.port)
    log.info("serving on :%d", svc.port)
    try:
        svc.serve_forever()
    finally:
        server.close()


if __name__ == "__main__":  # pragma: no cover
    main()
