"""Tensorboards CRUD web app — the first crud_backend consumer.

The reference factors next-gen CRUD apps onto the shared
crud-web-apps/common backend (SURVEY.md §2.3); the Tensorboard CRD
(tensorboard-controller, SURVEY.md §2.2) had no web app in the
snapshot. This closes that gap the crud_backend way: standard resource
routes (namespaces/PVCs/events) from the shared package plus
Tensorboard-specific CRUD, serving the listing the dashboard's
Tensorboards tab embeds.

Routes (crud_backend envelope {success, status, ...}):
  GET    /api/namespaces/{ns}/tensorboards
  POST   /api/namespaces/{ns}/tensorboards        {name, logspath}
  DELETE /api/namespaces/{ns}/tensorboards/{name}
Connect URLs follow the controller's VirtualService prefix
(/tensorboard/<ns>/<name>/, tensorboard_controller.go:228 analogue).
"""

from __future__ import annotations

import logging
import re

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.tensorboard import API_VERSION, KIND, new_tensorboard
from kubeflow_tpu.control.tensorboard.controller import is_cloud_path
from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import ApiHttpError, HttpReq, Router
from kubeflow_tpu.webapps.crud_backend import Authorizer, CrudBackend, success

log = logging.getLogger("kubeflow_tpu.tensorboards")

NAME_RGX = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


class TensorboardsApp:
    """CRUD app = shared backend + Tensorboard-specific routes + UI."""

    def __init__(self, client, authz: Authorizer | None = None):
        self.client = client
        self.crud = CrudBackend(client, authz)

    # -- handlers -----------------------------------------------------------

    def _phase(self, tb: dict) -> str:
        for c in (tb.get("status") or {}).get("conditions", []):
            if c.get("type") == "Ready":
                return "ready" if c.get("status") == "True" else "waiting"
        return "waiting"

    def list_tensorboards(self, req: HttpReq):
        ns = req.params["namespace"]
        self.crud._auth(req, "list", ns)
        rows = []
        for tb in self.client.list(API_VERSION, KIND, namespace=ns):
            m = ob.meta(tb)
            logspath = (tb.get("spec") or {}).get("logspath", "")
            rows.append({
                "name": m["name"],
                "namespace": ns,
                "logspath": logspath,
                "storage": "cloud" if is_cloud_path(logspath) else "pvc",
                "phase": self._phase(tb),
                "connect": f"/tensorboard/{ns}/{m['name']}/",
            })
        return success(tensorboards=sorted(rows, key=lambda r: r["name"]))

    def create_tensorboard(self, req: HttpReq):
        ns = req.params["namespace"]
        self.crud._auth(req, "create", ns)
        body = req.json() or {}
        if not isinstance(body, dict):
            raise ApiHttpError(400, "request body must be a JSON object")
        name = body.get("name") or ""
        logspath = body.get("logspath") or ""
        if not isinstance(name, str) or not NAME_RGX.match(name) \
                or len(name) > 63:
            raise ApiHttpError(400, f"invalid tensorboard name {name!r}")
        # non-cloud paths become a volumeMount mountPath, which the
        # apiserver requires to be absolute
        if not isinstance(logspath, str) or not logspath or not (
                is_cloud_path(logspath) or logspath.startswith("/")):
            raise ApiHttpError(400, "logspath must be gs://, s3:// or an "
                                    "absolute PVC-backed path")
        try:
            self.client.create(new_tensorboard(name, ns, logspath=logspath))
        except ob.Conflict:
            raise ApiHttpError(409, f"tensorboard {name} already exists")
        log.info("created tensorboard %s/%s logspath=%s", ns, name, logspath)
        return success(name=name)

    def delete_tensorboard(self, req: HttpReq):
        ns, name = req.params["namespace"], req.params["name"]
        self.crud._auth(req, "delete", ns)
        try:
            self.client.delete(API_VERSION, KIND, name, ns)
        except ob.NotFound:
            raise ApiHttpError(404, f"tensorboard {name} not found")
        return success(name=name)

    # -- wiring -------------------------------------------------------------

    def router(self) -> Router:
        r = Router("tensorboards")
        self.crud.add_routes(r)
        r.route("GET", "/api/namespaces/{namespace}/tensorboards",
                self.list_tensorboards)
        r.route("POST", "/api/namespaces/{namespace}/tensorboards",
                self.create_tensorboard)
        r.route("DELETE", "/api/namespaces/{namespace}/tensorboards/{name}",
                self.delete_tensorboard)
        r.route("GET", "/", self.page)
        httpd.add_health_routes(r)
        httpd.add_metrics_route(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 5005) -> httpd.HttpService:
        return httpd.HttpService(self.router(), host, port)

    # -- UI -----------------------------------------------------------------

    def page(self, req: HttpReq):
        return httpd.HttpResp(200, PAGE.encode(), "text/html")


PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>Tensorboards</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 0; background: #f5f6f8; }
  main { max-width: 760px; margin: 24px auto; padding: 0 16px; }
  .card { background: #fff; border-radius: 8px; padding: 16px;
          box-shadow: 0 1px 3px rgba(0,0,0,.15); margin-bottom: 16px; }
  h2 { margin: 0 0 10px; font-size: 15px; color: #333; }
  input, button { font-size: 14px; padding: 6px 10px; border-radius: 4px;
                  border: 1px solid #ccc; }
  button.primary { background: #1a73e8; color: #fff; border-color: #1a73e8;
                   cursor: pointer; }
  table { width: 100%; border-collapse: collapse; font-size: 13px; }
  td, th { text-align: left; padding: 5px 6px; border-bottom: 1px solid #eee; }
  .badge { display: inline-block; border-radius: 3px; padding: 0 6px;
           font-size: 11px; color: #fff; background: #e37400; }
  .badge.ready { background: #188038; }
  .muted { color: #777; font-size: 12px; }
  .error { color: #c5221f; font-size: 12px; }
</style>
</head>
<body>
<main>
  <div class="card">
    <h2>New tensorboard</h2>
    <input id="name" placeholder="name">
    <input id="logspath" placeholder="gs://bucket/logs or /pvc/path" size="34">
    <button class="primary" id="create">Create</button>
    <p class="error" id="err"></p>
    <p class="muted">Cloud paths (gs://, s3://) stream directly; other
      paths mount the namespace PVC.</p>
  </div>
  <div class="card">
    <h2>Tensorboards</h2>
    <table><tbody id="rows"><tr><td class="muted">loading…</td></tr></tbody>
    </table>
  </div>
</main>
<script>
const $ = (id) => document.getElementById(id);
const ns = new URLSearchParams(location.search).get('ns') || 'default';
const api = (p, opt) => fetch(p, opt).then(async r => {
  const j = await r.json().catch(() => ({}));
  if (!r.ok) throw new Error(j.error || r.status);
  return j;
});
async function load() {
  const out = await api('api/namespaces/' + ns + '/tensorboards')
    .catch(() => ({tensorboards: []}));
  const tb = $('rows');
  tb.innerHTML = '';
  for (const t of out.tensorboards || []) {
    // DOM-built rows: names/paths are user data, never raw HTML
    const tr = document.createElement('tr');
    const name = document.createElement('td');
    name.textContent = t.name;
    const path = document.createElement('td');
    path.textContent = t.logspath;
    path.className = 'muted';
    const phase = document.createElement('td');
    const badge = document.createElement('span');
    badge.className = 'badge ' + t.phase;
    badge.textContent = t.phase;
    phase.appendChild(badge);
    const act = document.createElement('td');
    const open = document.createElement('a');
    open.href = t.connect; open.textContent = 'Open';
    const del = document.createElement('button');
    del.textContent = 'Delete';
    del.addEventListener('click', async () => {
      await api('api/namespaces/' + ns + '/tensorboards/' + t.name,
                {method: 'DELETE'}).catch(e => { $('err').textContent = e.message; });
      load();
    });
    act.append(open, document.createTextNode(' '), del);
    tr.append(name, path, phase, act);
    tb.appendChild(tr);
  }
  if (!tb.children.length)
    tb.innerHTML = '<tr><td class="muted">none yet</td></tr>';
}
$('create').addEventListener('click', async () => {
  $('err').textContent = '';
  try {
    await api('api/namespaces/' + ns + '/tensorboards', {
      method: 'POST', headers: {'Content-Type': 'application/json'},
      body: JSON.stringify({name: $('name').value.trim(),
                            logspath: $('logspath').value.trim()}),
    });
    $('name').value = ''; $('logspath').value = '';
    load();
  } catch (e) { $('err').textContent = e.message; }
});
load();
setInterval(load, 15000);
</script>
</body>
</html>
"""
