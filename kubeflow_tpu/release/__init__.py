"""Release tooling: image build/push workflows and version stamping.

Mirrors components/image-releaser + releasing/releaser (SURVEY.md §2.4):
Argo/ksonnet workflows that build each component image, tag it with the
git SHA + semver, push, and cut a release. Here the DAG is expressed on
kubeflow_tpu.testing.workflow (the same runner the E2E harness uses) and
the container tool is pluggable (docker/podman/`gcloud builds submit`).
"""

from kubeflow_tpu.release.releaser import (  # noqa: F401
    IMAGES,
    ImageSpec,
    build_commands,
    release_workflow,
)
