"""Property-based tests (hypothesis) for the numerical primitives added
this round: invariants that hold for ALL inputs, not just the worked
examples — the cheap way to catch edge shapes the unit tests miss."""

import jax.numpy as jnp
import numpy as np
from hypothesis import given, settings, strategies as st

from kubeflow_tpu.ops.quantize import symmetric_int8
from kubeflow_tpu.runtime.records import pack_documents

# JAX tracing dominates runtime: few, derandomized examples keep the
# tier fast and CI-stable while still sweeping the structure space.
FAST = settings(max_examples=20, deadline=None, derandomize=True)


@FAST
@given(st.lists(st.integers(min_value=0, max_value=40), min_size=0,
                max_size=12),
       st.integers(min_value=3, max_value=16))
def test_pack_documents_invariants(doc_lens, seq_len):
    docs = [np.arange(1, n + 1, dtype=np.int32) + 100 * i
            for i, n in enumerate(doc_lens)]
    tokens, seg = pack_documents(docs, seq_len=seq_len)
    cap = seq_len + 1
    assert tokens.shape == seg.shape
    assert tokens.shape[0] == 0 or tokens.shape[1] == cap
    # every input token survives exactly once (pad_id=0 never collides:
    # doc tokens are >= 1)
    want = sorted(int(t) for d in docs for t in d)
    got = sorted(int(t) for t in tokens[seg > 0])
    assert got == want
    # padding is exactly the seg==0 positions and tokens there are 0
    assert (tokens[seg == 0] == 0).all()
    for r in range(seg.shape[0]):
        row = seg[r]
        # per-row segment ids are contiguous 1..k spans with padding
        # only at the tail
        nz = row[row > 0]
        assert len(nz) > 0  # no empty rows are emitted
        k = nz.max()
        assert sorted(set(nz.tolist())) == list(range(1, k + 1))
        # spans are contiguous (a segment never restarts)
        changes = np.flatnonzero(np.diff(row) != 0)
        assert len(changes) <= k  # k-1 span boundaries + optional pad edge


@FAST
@given(st.integers(min_value=1, max_value=5),
       st.integers(min_value=1, max_value=9),
       st.floats(min_value=0.01, max_value=1000.0))
def test_symmetric_int8_error_bound(rows, cols, scale_mag):
    rng = np.random.RandomState(rows * 31 + cols)
    x = (rng.randn(rows, cols) * scale_mag).astype(np.float32)
    q, s = symmetric_int8(x, -1)
    back = np.asarray(q, np.float32) * np.asarray(s)
    # per-element error <= half a quantization step of that row
    assert (np.abs(back - x) <= np.asarray(s)[..., 0:1] / 2 + 1e-6).all()
    assert np.asarray(q).min() >= -127 and np.asarray(q).max() <= 127


@FAST
@given(st.integers(min_value=1, max_value=3),
       st.sampled_from([1, 2, 4, 8]),
       st.integers(min_value=0, max_value=3))
def test_chunked_xent_matches_oracle_any_shape(batch, n_chunks, n_masked):
    import optax

    from kubeflow_tpu.ops.xent import chunked_lm_xent

    l, d, v = 8, 4, 11
    rng = np.random.RandomState(batch * 7 + n_chunks + n_masked)
    hidden = jnp.asarray(rng.randn(batch, l, d), jnp.float32)
    kernel = jnp.asarray(rng.randn(d, v), jnp.float32)
    labels = rng.randint(0, v, size=(batch, l))
    if n_masked:
        labels[:, :n_masked] = -1
    labels = jnp.asarray(labels)

    loss, acc = chunked_lm_xent(hidden, kernel, labels, n_chunks,
                                compute_dtype=jnp.float32)
    logits = jnp.einsum("bld,dv->blv", hidden, kernel)
    valid = labels >= 0
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.maximum(labels, 0))
    want_loss = jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1)
    want_acc = (jnp.sum((logits.argmax(-1) == labels) & valid)
                / jnp.maximum(jnp.sum(valid), 1))
    np.testing.assert_allclose(loss, want_loss, rtol=1e-5)
    np.testing.assert_allclose(acc, want_acc, rtol=1e-6)
