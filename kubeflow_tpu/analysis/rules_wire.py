"""tpulint wire-contract one-spelling rules (WIRE8xx).

A wire contract is a string two processes must agree on: a
``*.kubeflow.org/*`` annotation/label key, a ``JAXJOB_*`` /
``MEGASCALE_*`` / ``TPU_*`` env name, an ``x-request-*`` HTTP header.
Each has bitten us with two-spellings drift before (the endpoints
annotation, the MEGASCALE env block, the topology parse all needed AST
pins to stay single-sourced). The WIRE family makes single-sourcing
structural: every contract string is a constant defined in exactly one
OWNING module and imported everywhere else. The ownership map lives
here, in the rule — so a new contract (the coming ``role`` field, the
prefix-affinity page-hash header) gets an owner on day one by adding
one map entry, and any literal spelled outside its owner is flagged at
the drifting site with the constant to import.

- **WIRE801** annotation/label keys. Exact-key overrides beat domain
  prefixes (``jaxservice.kubeflow.org/endpoints`` belongs to the
  router, the rest of the jaxservice domain to its types module).
  ``apiVersion``-shaped strings (``.../v1alpha1``) are group/version
  coordinates, not keys, and are exempt. A key in a domain with no
  declared owner is flagged too: claim it in the map.
- **WIRE802** env names, full-string matches only (a log template
  mentioning ``TPU_CHAOS_SEED=%s`` is prose, not a contract site).
  Prefixes too generic to blanket-own (bare ``TPU_*``) are opt-in:
  only mapped prefixes are enforced, so an unrelated ALL-CAPS string
  cannot false-positive.
- **WIRE803** ``x-request-*`` headers, owned by the serving router.

Inside the owning module the ONE spelling is the module-level constant
assignment; a second definition, or an inline literal in a function
body (even in the owner), is flagged — hoist it. Docstrings and bare
string statements are prose and never flagged. ``kubeflow_tpu/
analysis/`` itself is exempt: the linter (and the rule tables below)
must be able to spell the contracts it polices.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from kubeflow_tpu.analysis.core import Finding, Module, Rule, register

# -- the ownership map -------------------------------------------------------
# suffix-matched module paths; exact keys beat prefixes, longest
# prefix wins otherwise.

ANNOTATION_KEY_OWNERS = {
    "jaxservice.kubeflow.org/endpoints": "kubeflow_tpu/serving/router.py",
    # the rollout revision label: routers, benches and operators match
    # on it, so its spelling is a wire contract pinned to ONE owner
    "jaxservice.kubeflow.org/revision":
        "kubeflow_tpu/control/jaxservice/types.py",
}
ANNOTATION_PREFIX_OWNERS = {
    "jaxjob.kubeflow.org/": "kubeflow_tpu/control/jaxjob/types.py",
    "jaxservice.kubeflow.org/": "kubeflow_tpu/control/jaxservice/types.py",
    "scheduler.kubeflow.org/": "kubeflow_tpu/control/scheduler/__init__.py",
    "obs.kubeflow.org/": "kubeflow_tpu/obs/trace.py",
    "studyjob.kubeflow.org/": "kubeflow_tpu/tune/studyjob.py",
    "notebooks.kubeflow.org/": "kubeflow_tpu/webapps/jwa_flavors.py",
    "poddefault.admission.kubeflow.org/":
        "kubeflow_tpu/control/poddefault/webhook.py",
}

ENV_KEY_OWNERS = {
    # JAXJOB_-prefixed keys owned away from dist.py: the collectives
    # backend contract and the preemption grace knob
    "JAXJOB_COLLECTIVES_BACKEND": "kubeflow_tpu/parallel/backends.py",
    "JAXJOB_MESH_DCN_AXES": "kubeflow_tpu/parallel/backends.py",
    "JAXJOB_LOOPBACK_JOIN_TIMEOUT_S": "kubeflow_tpu/parallel/backends.py",
    "JAXJOB_TERMINATION_GRACE_S": "kubeflow_tpu/runtime/preemption.py",
}
ENV_PREFIX_OWNERS = {
    "JAXJOB_": "kubeflow_tpu/parallel/dist.py",
    "MEGASCALE_": "kubeflow_tpu/parallel/backends.py",
    "TPU_CHAOS_": "kubeflow_tpu/control/k8s/chaos.py",
    "TPU_GOODPUT_": "kubeflow_tpu/obs/goodput.py",
    "TPU_RACE_": "kubeflow_tpu/analysis/dyntrace.py",
}

HEADER_KEY_OWNERS = {
    # the chargeback attribution header (HEADER_TENANT): spelled once,
    # next to the TENANT_RE validator that gates it
    "x-request-tenant": "kubeflow_tpu/serving/router.py",
}
HEADER_PREFIX_OWNERS = {
    "x-request-": "kubeflow_tpu/serving/router.py",
}

_ANN_RE = re.compile(
    r"^[a-z0-9-]+(?:\.[a-z0-9-]+)*\.kubeflow\.org/[A-Za-z0-9._/-]+$")
_APIVERSION_RE = re.compile(r"/v\d[a-z0-9]*$")  # group/version, not a key
_ENV_RE = re.compile(r"^(JAXJOB|MEGASCALE|TPU)_[A-Z0-9_]+$")
_HDR_RE = re.compile(r"^x-request-[a-z0-9-]+$")

_EXEMPT_DIR = "kubeflow_tpu/analysis/"


def _norm(path: str) -> str:
    return path.replace("\\", "/")


def _owner_for(value: str, exact: dict[str, str],
               prefixes: dict[str, str]) -> str | None:
    got = exact.get(value)
    if got is not None:
        return got
    best = None
    for prefix, owner in prefixes.items():
        if value.startswith(prefix) and (best is None
                                         or len(prefix) > len(best[0])):
            best = (prefix, owner)
    return best[1] if best else None


def _is_prose(module: Module, node: ast.Constant) -> bool:
    """Docstrings and bare string statements are prose, not code."""
    parent = module.parents.get(node)
    return isinstance(parent, ast.Expr)


def _is_module_level_def(module: Module, node: ast.Constant) -> bool:
    """True when the literal is the RHS of a module-level constant
    assignment (``KEY = "..."``) — the one allowed definition site."""
    parent = module.parents.get(node)
    if not isinstance(parent, ast.Assign) or parent.value is not node:
        return False
    if not all(isinstance(t, ast.Name) for t in parent.targets):
        return False
    return isinstance(module.parents.get(parent), ast.Module)


class _WireRule(Rule):
    """Shared engine: subclass sets the matcher + ownership maps."""

    exact: dict[str, str] = {}
    prefixes: dict[str, str] = {}
    flag_unmapped = False          # no owner declared -> still flag?
    what = "wire-contract string"

    def matches(self, value: str) -> bool:  # pragma: no cover
        raise NotImplementedError

    def check(self, module: Module) -> Iterator[Finding]:
        path = _norm(module.path)
        if _EXEMPT_DIR in path:
            return  # the linter may spell the contracts it polices
        defs: dict[str, int] = {}  # value -> first definition line
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            value = node.value
            if not self.matches(value) or _is_prose(module, node):
                continue
            owner = _owner_for(value, self.exact, self.prefixes)
            if owner is None:
                if self.flag_unmapped:
                    yield self.finding(
                        module, node,
                        f"{self.what} \"{value}\" has no declared "
                        "owner: add its domain to the ownership map "
                        f"in rules_wire.py ({self.id})")
                continue
            if path.endswith(owner):
                if _is_module_level_def(module, node):
                    first = defs.setdefault(value, node.lineno)
                    if first != node.lineno:
                        yield self.finding(
                            module, node,
                            f"duplicate definition of {self.what} "
                            f"\"{value}\" (first defined at line "
                            f"{first}): one spelling, one constant")
                else:
                    yield self.finding(
                        module, node,
                        f"inline {self.what} \"{value}\" in its own "
                        "owning module: hoist to the module-level "
                        "constant and use that")
            else:
                yield self.finding(
                    module, node,
                    f"re-spelled {self.what} \"{value}\": it is owned "
                    f"by {owner} — import the constant from there "
                    "(one wire contract, one spelling)")


@register
class AnnotationKeySpelling(_WireRule):
    """WIRE801: ``*.kubeflow.org/*`` annotation/label keys must be
    constants in their owning module (see ANNOTATION_*_OWNERS)."""

    id = "WIRE801"
    name = "annotation-key-respelled"
    short = "kubeflow.org annotation/label key spelled outside its owner"
    exact = ANNOTATION_KEY_OWNERS
    prefixes = ANNOTATION_PREFIX_OWNERS
    flag_unmapped = True
    what = "annotation/label key"

    def matches(self, value: str) -> bool:
        return bool(_ANN_RE.match(value)
                    and not _APIVERSION_RE.search(value))


@register
class EnvNameSpelling(_WireRule):
    """WIRE802: JAXJOB_/MEGASCALE_/TPU_ env names must be constants in
    their owning module (see ENV_*_OWNERS); unmapped prefixes are
    exempt so generic ALL-CAPS strings cannot false-positive."""

    id = "WIRE802"
    name = "env-name-respelled"
    short = "wire env name spelled outside its owning module"
    exact = ENV_KEY_OWNERS
    prefixes = ENV_PREFIX_OWNERS
    what = "env name"

    def matches(self, value: str) -> bool:
        return bool(_ENV_RE.match(value))


@register
class RequestHeaderSpelling(_WireRule):
    """WIRE803: ``x-request-*`` headers are the serving router's
    contract; every other module imports the HEADER_* constants."""

    id = "WIRE803"
    name = "request-header-respelled"
    short = "x-request-* header spelled outside serving/router.py"
    exact = HEADER_KEY_OWNERS
    prefixes = HEADER_PREFIX_OWNERS
    what = "request header"

    def matches(self, value: str) -> bool:
        return bool(_HDR_RE.match(value))
