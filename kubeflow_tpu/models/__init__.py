"""Model zoo: the training/serving payloads the reference platform ships
as opaque container images (tf_cnn_benchmarks ResNet-50, TF-Serving BERT)
rebuilt as first-class JAX models with sharding annotations.
"""

from kubeflow_tpu.models.registry import get_model, list_models, register_model

__all__ = ["get_model", "list_models", "register_model"]
