"""Indexed free-capacity model: sorted per-pool buckets + bisect best-fit.

The admission pass used to best-fit each worker with a linear scan over
EVERY node (``scheduler.py`` pre-ISSUE 7): O(nodes) feasibility checks
per worker, O(nodes x workers) per gang, quadratic death at fleet scale.
This module is the kube-scheduler NodeInfo-snapshot analogue rebuilt for
chips: nodes live in per-``(accelerator, topology)`` buckets — the label
pair every gang worker's nodeSelector names — each bucket a list of
``(free_chips, name)`` kept in sorted order, so best-fit is a
``bisect_left`` to the first node with enough room followed by a short
walk to the first FEASIBLE one (readiness/taints/extra selector keys
still checked per node; the bucket only pre-filters the label pair).

The ordering IS the old semantics: the legacy scan picked the minimum
remaining-chips node, ties broken by lexicographically-first name, and
``(free, name)`` tuples sort exactly that way — the 34 admission-
semantics tests pin the equivalence.

``Capacity`` is an immutable snapshot (built by ``ClusterCache`` from
its incremental indexes, or from a one-shot relist on the legacy path);
``CapacityTxn`` overlays what-if placement on it copy-on-write, so
all-or-nothing trial assignments and preemption what-ifs never disturb
the snapshot they simulate against.
"""

from __future__ import annotations

import bisect

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.scheduler import nodes as N

# Bucket key for nodes whose labels (or pods whose selectors) don't pin
# the (accelerator, topology) pair — they fall into the catch-all bucket
# holding every node, so placement stays correct, just unbucketed.
ALL_NODES = None

# best_fit(bucket_key=...) sentinel: "derive the bucket from the pod's
# selector" (ALL_NODES/None is itself a meaningful key, so the default
# can't be None).
AUTO_BUCKET = object()


def node_bucket_key(labels: dict) -> tuple | None:
    """The (accelerator, topology) pool a node belongs to, or None."""
    accel = labels.get(JT.NODESELECTOR_ACCEL)
    topo = labels.get(JT.NODESELECTOR_TOPOLOGY)
    if accel is None or topo is None:
        return ALL_NODES
    return (accel, topo)


def pod_bucket_key(pod: dict) -> tuple | None:
    """The bucket a pod's placement search may be confined to: only when
    its selector names BOTH pool labels is the bucket a superset of the
    feasible set — any other selector shape searches the catch-all."""
    sel = (pod.get("spec") or {}).get("nodeSelector") or {}
    accel = sel.get(JT.NODESELECTOR_ACCEL)
    topo = sel.get(JT.NODESELECTOR_TOPOLOGY)
    if accel is None or topo is None:
        return ALL_NODES
    return (accel, topo)


class Bucket:
    """One pool's nodes as parallel sorted ``(free, name)`` lists: every
    node, and the spot-pool subset (elastic gangs best-fit spot FIRST so
    reclaim-tolerant work burns reclaimable capacity)."""

    __slots__ = ("items", "spot")

    def __init__(self):
        self.items: list[tuple[int, str]] = []
        self.spot: list[tuple[int, str]] = []

    def clone(self) -> "Bucket":
        b = Bucket()
        b.items = list(self.items)
        b.spot = list(self.spot)
        return b

    def add(self, free: int, name: str, is_spot: bool) -> None:
        bisect.insort(self.items, (free, name))
        if is_spot:
            bisect.insort(self.spot, (free, name))

    def remove(self, free: int, name: str, is_spot: bool) -> None:
        _discard(self.items, (free, name))
        if is_spot:
            _discard(self.spot, (free, name))

    def adjust(self, old_free: int, new_free: int, name: str,
               is_spot: bool) -> None:
        self.remove(old_free, name, is_spot)
        self.add(new_free, name, is_spot)


def _discard(items: list, entry: tuple) -> None:
    i = bisect.bisect_left(items, entry)
    if i < len(items) and items[i] == entry:
        del items[i]


class Capacity:
    """A placement snapshot: node views, per-node free chips, and the
    sorted buckets. Immutable by contract — trials go through txn()."""

    __slots__ = ("views", "free", "buckets", "scanned")

    def __init__(self, views: dict[str, N.NodeView], free: dict[str, int],
                 buckets: dict[tuple | None, Bucket]):
        self.views = views
        self.free = free
        self.buckets = buckets
        # nodes examined by best-fit walks across every txn on this
        # snapshot — the scheduler publishes it per admission attempt
        # (scheduler_nodes_scanned_total)
        self.scanned = 0

    @classmethod
    def from_views(cls, views: dict[str, N.NodeView],
                   free: dict[str, int]) -> "Capacity":
        """Build the bucket index from a one-shot (view, free) read —
        the legacy relist path and small tests share this constructor;
        ClusterCache maintains the same shape incrementally."""
        buckets: dict[tuple | None, Bucket] = {ALL_NODES: Bucket()}
        for name, v in views.items():
            f = free.get(name, 0)
            buckets[ALL_NODES].add(f, name, v.spot)
            key = node_bucket_key(v.labels)
            if key is not ALL_NODES:
                buckets.setdefault(key, Bucket()).add(f, name, v.spot)
        return cls(views, free, buckets)

    def txn(self) -> "CapacityTxn":
        return CapacityTxn(self)


class CapacityTxn:
    """Copy-on-write what-if placement over a Capacity snapshot.

    Forks form a lifecycle (tpulint RES703): every ``fork()`` must end
    in exactly one ``commit()`` (replay this trial's net takes onto the
    parent) or ``rollback()`` (drop them) — a fork abandoned on an
    exceptional path silently diverges the caller's ledger from what
    was actually placed, which is precisely the bug shape the
    exception-edge dataflow rule exists to catch."""

    __slots__ = ("cap", "_delta", "_over", "_parent", "_base", "_closed")

    def __init__(self, cap: Capacity, _delta=None, _over=None,
                 _parent: "CapacityTxn | None" = None):
        self.cap = cap
        self._delta: dict[str, int] = dict(_delta) if _delta else {}
        self._over: dict[tuple | None, Bucket] = \
            {k: b.clone() for k, b in _over.items()} if _over else {}
        self._parent = _parent
        # the fork point: commit() replays only shifts made AFTER this
        self._base: dict[str, int] = dict(self._delta)
        self._closed = False

    def fork(self) -> "CapacityTxn":
        """An independent trial continuing from this txn's state (the
        preemption loop forks once per what-if assignment so cumulative
        victim credits persist while each trial's takes do not)."""
        return CapacityTxn(self.cap, self._delta, self._over,
                           _parent=self)

    @property
    def closed(self) -> bool:
        return self._closed

    def commit(self) -> None:
        """Replay this fork's net per-node shifts onto its parent and
        close the fork. The replay goes through the parent's own
        ``_shift`` so its bucket overlays stay sorted-correct."""
        if self._parent is None:
            raise ValueError("commit() on a root txn: root transactions "
                             "are scratch overlays with nothing to "
                             "merge into")
        if self._closed:
            raise ValueError("commit() on a closed txn")
        self._closed = True
        for name, total in self._delta.items():
            rel = total - self._base.get(name, 0)
            if rel:
                self._parent._shift(name, rel)

    def rollback(self) -> None:
        """Close the fork, discarding its shifts. Idempotence is NOT
        offered on purpose — a double close is a lifecycle bug."""
        if self._closed:
            raise ValueError("rollback() on a closed txn")
        self._closed = True

    def free_of(self, name: str) -> int:
        return self.cap.free.get(name, 0) + self._delta.get(name, 0)

    def _bucket(self, key: tuple | None) -> Bucket | None:
        b = self._over.get(key)
        if b is not None:
            return b
        return self.cap.buckets.get(key)

    def _bucket_for_write(self, key: tuple | None) -> Bucket:
        b = self._over.get(key)
        if b is None:
            base = self.cap.buckets.get(key)
            b = base.clone() if base is not None else Bucket()
            self._over[key] = b
        return b

    def _shift(self, name: str, by: int) -> None:
        view = self.cap.views.get(name)
        if view is None:
            return
        old = self.free_of(name)
        self._delta[name] = self._delta.get(name, 0) + by
        new = old + by
        keys: list[tuple | None] = [ALL_NODES]
        nk = node_bucket_key(view.labels)
        if nk is not ALL_NODES:
            keys.append(nk)
        for key in keys:
            self._bucket_for_write(key).adjust(old, new, name, view.spot)

    def take(self, name: str, chips: int) -> None:
        self._shift(name, -chips)

    def credit(self, name: str, chips: int) -> None:
        """Return chips to a node (preemption what-if: a victim gang's
        chips free the moment its eviction status lands)."""
        self._shift(name, chips)

    def bucket_keys(self) -> list[tuple]:
        """The REAL (accelerator, topology) pool keys of the underlying
        snapshot (never ALL_NODES). Pool membership is label-static, so
        a txn's overlays can only re-sort nodes within these keys."""
        return [k for k in self.cap.buckets if k is not ALL_NODES]

    def bucket_free(self, key: tuple | None) -> int:
        """Total free chips in one pool AS THIS TXN SEES IT — the
        pool-level best-fit ordering key for slice-aware admission."""
        b = self._bucket(key)
        if b is None:
            return 0
        return sum(f for f, _ in b.items)

    def best_fit(self, pod: dict, need: int, prefer_spot: bool = False,
                 bucket_key=AUTO_BUCKET) -> str | None:
        """The node this pod best-fits onto, or None. Spot preference is
        a preference: when no feasible spot node has room, placement
        falls back to the whole bucket (legacy semantics, pinned).

        ``bucket_key`` confines the search to ONE explicit pool instead
        of the pod's selector-derived bucket — slice-aware admission
        places every worker of a slice in a single (accelerator,
        topology) pool even when the pod's selector names no topology."""
        key = pod_bucket_key(pod) if bucket_key is AUTO_BUCKET \
            else bucket_key
        bucket = self._bucket(key)
        if bucket is None:
            return None
        if prefer_spot:
            name = self._walk(bucket.spot, pod, need)
            if name is not None:
                return name
        return self._walk(bucket.items, pod, need)

    def _walk(self, items: list[tuple[int, str]], pod: dict,
              need: int) -> str | None:
        i = bisect.bisect_left(items, (need, ""))
        while i < len(items):
            _free, name = items[i]
            self.cap.scanned += 1
            if N.feasible(pod, self.cap.views[name]):
                return name
            i += 1
        return None
