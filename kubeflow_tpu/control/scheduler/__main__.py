from kubeflow_tpu.control.mains import run_controller
from kubeflow_tpu.control.scheduler.scheduler import build_scheduler

run_controller("gang-scheduler",
               lambda client, args: build_scheduler(client))
