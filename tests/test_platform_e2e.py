"""Whole-platform integration: the hermetic kf_is_ready_test.py.

The reference's tier-4 E2E deploys kubeflow to a real GKE cluster, then
asserts every component is ready and drives user journeys against the
live APIs (testing/kfctl/kf_is_ready_test.py; katib_studyjob_test.py;
test_jwa.py). This is the same shape against the in-memory apiserver:
tpctl applies the full platform, every controller reconciles the SAME
cluster, and a user registers a workspace, spawns a notebook, creates a
tensorboard, runs a training job, and adds a contributor — all through
the web-app REST surfaces, ending with the dashboard reflecting it all.
"""

import json

import pytest
import yaml

from kubeflow_tpu.control.jaxjob import types as JT
from kubeflow_tpu.control.jaxjob.controller import build_controller as build_jaxjob
from kubeflow_tpu.control.jaxjob.controller import worker_name
from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet
from kubeflow_tpu.control.kfam.service import KfamService
from kubeflow_tpu.control.notebook import types as NT
from kubeflow_tpu.control.notebook.controller import (
    build_controller as build_notebook,
)
from kubeflow_tpu.control.profile import types as PT
from kubeflow_tpu.control.profile.controller import (
    build_controller as build_profile,
)
from kubeflow_tpu.control.runtime import seed_controller
from kubeflow_tpu.control.tensorboard import (
    API_VERSION as TB_API,
    KIND as TB_KIND,
)
from kubeflow_tpu.control.tensorboard.controller import (
    build_controller as build_tensorboard,
)
from kubeflow_tpu.tpctl.apply import Coordinator
from kubeflow_tpu.tpctl.tpudef import COND_AVAILABLE, TpuDef, example_yaml
from kubeflow_tpu.utils.httpd import HttpReq
from kubeflow_tpu.webapps.crud_backend import Authorizer
from kubeflow_tpu.webapps.dashboard import Dashboard
from kubeflow_tpu.webapps.jwa import JupyterWebApp
from kubeflow_tpu.webapps.tensorboards import TensorboardsApp

USER = "alice@example.com"


def req(method, path, user=USER, body=None):
    h = {"kubeflow-userid": user} if user else {}
    b = json.dumps(body).encode() if body is not None else b""
    return HttpReq(method=method, path=path, params={}, query={},
                   headers=h, body=b)


def J(resp):
    assert resp.status < 300, resp.body
    return json.loads(resp.body)


@pytest.fixture()
def platform():
    """tpctl-deployed platform + all controllers on one cluster."""
    cluster = FakeCluster()
    cfg = TpuDef.from_dict(yaml.safe_load(example_yaml()))
    stored = Coordinator(cluster).apply(cfg)
    assert ob.cond_is_true(stored, COND_AVAILABLE)

    ctls = [seed_controller(c) for c in (
        build_jaxjob(cluster, record_events=True),
        build_notebook(cluster),
        build_profile(cluster),
        build_tensorboard(cluster),
    )]
    kubelet = FakeKubelet(cluster)

    def drain():
        for _ in range(8):
            for c in ctls:
                c.run_until_idle(advance_delayed=True)

    return cluster, drain, kubelet


def test_platform_is_ready_after_apply(platform):
    """kf_is_ready contract: every component Deployment + CRD + RBAC
    object from the manifest set exists on the cluster."""
    cluster, drain, _ = platform
    deployments = {ob.meta(d)["name"]
                   for d in cluster.list("apps/v1", "Deployment",
                                         namespace="kubeflow")}
    for component in ("jaxjob-controller", "notebook-controller",
                      "profile-controller", "tensorboard-controller",
                      "centraldashboard", "jupyter-web-app",
                      "tensorboards-web-app", "kfam", "serving",
                      "metric-collector"):
        assert component in deployments, component
    crds = {ob.meta(c)["name"] for c in cluster.list(
        "apiextensions.k8s.io/v1", "CustomResourceDefinition")}
    assert {"jaxjobs.kubeflow.org", "notebooks.kubeflow.org",
            "profiles.kubeflow.org", "studyjobs.kubeflow.org"} <= crds
    assert cluster.get("rbac.authorization.k8s.io/v1", "ClusterRole",
                       "kubeflow-admin")


def test_user_journey_end_to_end(platform):
    cluster, drain, kubelet = platform
    kfam = KfamService(cluster)
    dash = Dashboard(cluster, kfam=kfam).router()
    jwa = JupyterWebApp(cluster).router()
    tb_app = TensorboardsApp(cluster, Authorizer(cluster)).router()

    # -- 1. registration: no workspace -> create -> profile reconciles --
    assert J(dash.dispatch(req("GET", "/api/workgroup/exists")))[
        "hasWorkgroup"] is False
    J(dash.dispatch(req("POST", "/api/workgroup/create",
                        body={"namespace": "alice"})))
    drain()
    ns = cluster.get("v1", "Namespace", "alice")
    assert ob.labels_of(ns).get("istio-injection")
    sas = {ob.meta(s)["name"] for s in cluster.list(
        "v1", "ServiceAccount", namespace="alice")}
    assert {"default-editor", "default-viewer"} <= sas
    info = J(dash.dispatch(req("GET", "/api/workgroup/env-info")))
    assert {"namespace": "alice", "role": "owner"} in info["namespaces"]

    # -- 2. notebook: spawn via JWA -> controller -> dashboard card --
    J(jwa.dispatch(req("POST", "/api/namespaces/alice/notebooks",
                       body={"name": "my-nb", "tpu": {"count": 4}})))
    drain()
    sts = cluster.get("apps/v1", "StatefulSet", "my-nb", "alice")
    assert sts["spec"]["replicas"] == 1
    nb = cluster.get(NT.API_VERSION, NT.KIND, "my-nb", "alice")
    nb.setdefault("status", {})["containerState"] = {"running": {}}
    cluster.update(nb)
    rows = J(dash.dispatch(req(
        "GET", "/api/namespaces/alice/notebooks")))["notebooks"]
    assert rows[0]["name"] == "my-nb" and rows[0]["status"] == "running"

    # -- 3. tensorboard via the CRUD app -> controller deployment --
    J(tb_app.dispatch(req("POST", "/api/namespaces/alice/tensorboards",
                          body={"name": "tb", "logspath": "gs://b/logs"})))
    drain()
    assert cluster.get("apps/v1", "Deployment", "tb", "alice")
    tbs = J(tb_app.dispatch(req(
        "GET", "/api/namespaces/alice/tensorboards")))["tensorboards"]
    assert tbs[0]["connect"] == "/tensorboard/alice/tb/"

    # -- 4. training job: gang runs to completion -> dashboard card --
    cluster.create(JT.new_jaxjob("train", namespace="alice", replicas=2))
    drain()
    kubelet.step()
    drain()
    for i in range(2):
        kubelet.succeed(worker_name("train", i), namespace="alice")
    drain()
    job = cluster.get(JT.API_VERSION, JT.KIND, "train", "alice")
    assert ob.cond_is_true(job, JT.COND_SUCCEEDED)
    jj = J(dash.dispatch(req(
        "GET", "/api/namespaces/alice/jaxjobs")))["jaxjobs"]
    assert jj[0]["phase"] == "succeeded"

    # -- 5. contributor management through the dashboard --
    out = J(dash.dispatch(req(
        "POST", "/api/workgroup/add-contributor/alice",
        body={"contributor": "bob@example.com"})))
    assert out["contributors"] == ["bob@example.com"]
    # bob can now read the namespace through authz-gated apps
    bob_sees = J(tb_app.dispatch(req(
        "GET", "/api/namespaces/alice/tensorboards",
        user="bob@example.com")))
    assert bob_sees["tensorboards"]
    # a stranger cannot
    assert tb_app.dispatch(req(
        "GET", "/api/namespaces/alice/tensorboards",
        user="mallory@example.com")).status == 403

    # -- 6. the activity feed saw the journey --
    acts = J(dash.dispatch(req("GET", "/api/activities/alice")))
    assert isinstance(acts["events"], list)
