"""tpctl REST plane: create/get deployments + worker pool + GC.

The router/kfctlServer/gcServer triple of the reference
(bootstrap/cmd/bootstrap/app/{router,kfctlServer,gcServer}.go) collapsed
into one process:

- POST /tpctl/apps/v1/create  — enqueue a deployment (router.go:407;
  per-deployment serialization through a channel, kfctlServer.go:87)
- POST /tpctl/apps/v1/get     — poll status (kfctlServer.go:373-384)
- one worker thread per deployment name (the per-deployment StatefulSet
  pod of router.go:275-357 becomes a thread; same isMatch conflict
  rejection, kfctlServer.go:531)
- GC loop deleting deployments idle past TTL (gcServer.go:56-86,
  LastRequestTime annotation)
"""

from __future__ import annotations

import logging
import queue
import threading
import time

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.tpctl.apply import Coordinator
from kubeflow_tpu.tpctl.tpudef import TpuDef
from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import ApiHttpError, HttpReq, Router

log = logging.getLogger("kubeflow_tpu.tpctl.server")

DEFAULT_TTL_S = 3600.0


class _Worker:
    """Per-deployment worker: owns a queue (cap 10, kfctlServer.go:87)."""

    def __init__(self, name: str, coordinator: Coordinator | None):
        self.name = name
        self.coordinator = coordinator
        self.q: "queue.Queue[TpuDef]" = queue.Queue(maxsize=10)
        self.last_request = time.monotonic()
        self.current_spec: dict | None = None
        self.error: str | None = None
        # applies submitted but not yet finished (queued + in flight):
        # incremented under the server lock in submit(), decremented by
        # the worker thread when an apply completes. GC must never reap
        # a worker with pending > 0 — a reaped-but-still-applying worker
        # plus a fresh one for the same name would break the
        # per-deployment serialization this class exists to provide.
        self.pending = 0
        self._plock = threading.Lock()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name=f"tpctl-worker-{name}")
        self.thread.start()

    def _apply(self, cfg: TpuDef) -> None:
        self.coordinator.apply(cfg)

    def _run(self):
        while True:
            cfg = self.q.get()
            if cfg is None:
                return
            try:
                self._apply(cfg)
                self.error = None
            except Exception as e:
                log.exception("deployment %s failed", self.name)
                self.error = str(e)
            finally:
                with self._plock:
                    self.pending -= 1

    def submit(self, cfg: TpuDef) -> None:
        spec = cfg.to_object()["spec"]
        if self.current_spec is not None and self.current_spec != spec:
            # isMatch guard (kfctlServer.go:531): same name, different spec
            # is a conflict, not a silent overwrite
            raise ApiHttpError(409, f"deployment {self.name} exists with a "
                               "different spec; delete it first")
        self.current_spec = spec
        self.last_request = time.monotonic()
        try:
            # never block: submit() runs under the server-wide lock, and a
            # blocking put on a full queue would freeze the whole REST
            # plane for up to one apply duration (30 min in subprocess
            # mode). A full queue is backpressure — tell the client.
            self.q.put_nowait(cfg)
        except queue.Full:
            raise ApiHttpError(
                429, f"deployment {self.name} has {self.q.maxsize} applies "
                     "queued; retry later")
        with self._plock:
            self.pending += 1

    @property
    def busy(self) -> bool:
        with self._plock:
            return self.pending > 0


class _SubprocessWorker(_Worker):
    """Per-deployment OS-process isolation: the apply runs in a child
    `tpctl apply` process against the apiserver, so a poisoned apply —
    segfault in a native dep, OOM kill, runaway memory — takes down one
    deployment's worker, never the REST plane. This is the
    StatefulSet-pod-per-deployment isolation of router.go:275-357 with a
    subprocess standing in for the pod; the thread mode keeps the
    capability without the isolation for hermetic/dry-run servers."""

    APPLY_TIMEOUT_S = 1800.0

    def __init__(self, name: str, apiserver_url: str):
        self.apiserver_url = apiserver_url
        self.last_pid: int | None = None
        super().__init__(name, coordinator=None)

    def _apply(self, cfg: TpuDef) -> None:
        import subprocess
        import sys
        import tempfile

        with tempfile.NamedTemporaryFile(
                "w", suffix=".yaml", prefix=f"tpudef-{self.name}-",
                delete=False) as f:
            f.write(cfg.dump())
            path = f.name
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "kubeflow_tpu.tpctl.cli", "apply",
                 "-f", path, "--server", self.apiserver_url],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            self.last_pid = proc.pid
            try:
                out, _ = proc.communicate(timeout=self.APPLY_TIMEOUT_S)
            except subprocess.TimeoutExpired:
                # communicate() does NOT kill on timeout: an orphaned
                # child would keep mutating the cluster while the next
                # queue item spawns a concurrent apply for the same
                # deployment — kill and reap before surfacing the error
                proc.kill()
                proc.communicate()
                raise RuntimeError(
                    f"apply subprocess killed after "
                    f"{self.APPLY_TIMEOUT_S:.0f}s timeout")
            if proc.returncode != 0:
                raise RuntimeError(
                    f"apply subprocess exited {proc.returncode}: "
                    f"{(out or '').strip()[-500:]}")
        finally:
            import os

            os.unlink(path)


class TpctlServer:
    # Request-path access checks get a short retry budget: a create
    # handler must not pin a server thread for the offline-job default
    # of 60s (cloudauth.check_project_access) during a CRM outage.
    ACCESS_CHECK_BUDGET_S = 8.0

    def __init__(self, client, ttl_s: float = DEFAULT_TTL_S,
                 crm_backend=None, coordinator_factory=None,
                 isolation: str = "thread", apiserver_url: str = ""):
        if isolation not in ("thread", "subprocess"):
            raise ValueError(f"isolation must be thread|subprocess, "
                             f"got {isolation!r}")
        if isolation == "subprocess" and not apiserver_url:
            raise ValueError("subprocess isolation needs apiserver_url "
                             "(the child tpctl process dials it)")
        self.client = client
        self.ttl_s = ttl_s
        self.isolation = isolation
        self.apiserver_url = apiserver_url
        self.workers: dict[str, _Worker] = {}
        self._lock = threading.Lock()
        self._coordinator = coordinator_factory or (lambda: Coordinator(self.client))
        # Cloud-credential validity gate (kfctlServer.go:519/:545): when a
        # cloudauth.CrmBackend is provided, cloud-platform deployments
        # must carry a bearer token that grants setIamPolicy on the
        # project, and the per-project RefreshableTokenSource is kept
        # fresh for later platform calls.
        self.crm = crm_backend
        self._token_sources: dict[str, object] = {}

    def _check_cloud_access(self, req: HttpReq, cfg: TpuDef) -> None:
        if self.crm is None or cfg.platform == "existing":
            return
        import functools

        from kubeflow_tpu.tpctl import cloudauth

        if not cfg.project:
            raise ApiHttpError(400, "cloud platform deployments require "
                               "spec.platform.project")
        auth = req.header("authorization") or ""
        token = auth.split(" ", 1)[1] if auth.lower().startswith("bearer ") else ""
        if not token:
            raise ApiHttpError(401, "cloud platform deployments require a "
                               "bearer token")
        checker = functools.partial(cloudauth.check_project_access,
                                    max_elapsed=self.ACCESS_CHECK_BUDGET_S)
        with self._lock:
            ts = self._token_sources.get(cfg.project)
            if ts is None:
                ts = cloudauth.RefreshableTokenSource(
                    cfg.project, self.crm, checker=checker)
                self._token_sources[cfg.project] = ts
        try:
            ts.refresh(token)  # validates via CheckProjectAccess
        except (PermissionError, ValueError) as e:
            raise ApiHttpError(403, str(e))
        except Exception as e:  # CRM outage is not a credentials verdict
            raise ApiHttpError(
                503, f"cloud access check unavailable: {e}")

    # -- endpoints ----------------------------------------------------------

    def create(self, req: HttpReq):
        try:
            body = req.json() or {}
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            cfg = TpuDef.from_dict(body)
        except (ValueError, TypeError) as e:  # malformed JSON / bad TpuDef
            raise ApiHttpError(400, f"invalid TpuDef: {e}")
        self._check_cloud_access(req, cfg)
        with self._lock:
            w = self.workers.get(cfg.name)
            if w is None:
                if self.isolation == "subprocess":
                    w = _SubprocessWorker(cfg.name, self.apiserver_url)
                else:
                    w = _Worker(cfg.name, self._coordinator())
                self.workers[cfg.name] = w
            w.submit(cfg)
        return 200, {"name": cfg.name, "status": "enqueued"}

    def get(self, req: HttpReq):
        body = req.json() or {}
        name = body.get("name") or req.q1("name")
        if not name:
            raise ApiHttpError(400, "name required")
        with self._lock:
            w = self.workers.get(name)
            if w:
                w.last_request = time.monotonic()
        obj = self._coordinator().status(name)
        if obj is None and (w is None or w.error is None):
            raise ApiHttpError(404, f"deployment {name} not found")
        return {
            "name": name,
            "conditions": (obj or {}).get("status", {}).get("conditions", []),
            "error": w.error if w else None,
        }

    def openapi(self, req: HttpReq):
        from kubeflow_tpu.tpctl.apispec import openapi

        return openapi()

    def router(self) -> Router:
        r = Router("tpctl")
        r.route("POST", "/tpctl/apps/v1/create", self.create)
        r.route("POST", "/tpctl/apps/v1/get", self.get)
        r.route("GET", "/tpctl/apps/v1/get", self.get)
        # machine-readable contract (bootstrap/api/swagger.yaml analogue)
        r.route("GET", "/tpctl/apps/v1/openapi.json", self.openapi)
        httpd.add_health_routes(r)
        httpd.add_metrics_route(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 0) -> httpd.HttpService:
        self.start_gc()
        return httpd.HttpService(self.router(), host, port)

    # -- GC (gcServer.go:56-86) ---------------------------------------------

    def gc_once(self, now: float | None = None) -> list[str]:
        now = time.monotonic() if now is None else now
        reaped = []
        with self._lock:
            for name, w in list(self.workers.items()):
                if now - w.last_request > self.ttl_s:
                    # idle means NOTHING pending: a worker with queued or
                    # in-flight applies must keep its identity (reaping
                    # it would let a re-submit start a SECOND concurrent
                    # apply for the same deployment). submit() holds the
                    # same lock, so pending can't grow under us.
                    if w.busy:
                        continue
                    try:
                        w.q.put_nowait(None)
                    except queue.Full:  # defensive; empty when not busy
                        continue
                    del self.workers[name]
                    reaped.append(name)
        return reaped

    def start_gc(self, period_s: float = 60.0) -> None:
        def loop():
            while True:
                time.sleep(period_s)
                reaped = self.gc_once()
                if reaped:
                    log.info("gc reaped idle deployments: %s", reaped)

        threading.Thread(target=loop, daemon=True, name="tpctl-gc").start()
