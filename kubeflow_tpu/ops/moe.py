"""Mixture-of-experts with expert parallelism.

Two dispatch implementations behind one module:

- **dense** (Switch/GShard one-hot einsums): dispatch/combine are einsums
  against one-hot [b,s,e,c] tensors. Correct on any mesh, runs the whole
  block on the MXU — and materializes capacity-padded tensors whose
  dispatch/combine einsums cost O(s*e*c*d) MACs regardless of how many
  slots are filled. Kept as the oracle and as the fallback for meshes the
  sparse path doesn't cover.

- **sparse** (sort + scatter + explicit all-to-all under shard_map): per
  token-shard, routed (token, slot) pairs are sorted by expert id,
  scattered into per-expert capacity buffers (no one-hot tensors — the
  dispatch is a gather/scatter, not a matmul), exchanged over the
  `expert` mesh axis with jax.lax.all_to_all, run through the local
  experts as one batched GEMM, and returned by the reverse all-to-all.
  This is SURVEY.md §2.5's "all-to-all dispatch over ICI" made explicit
  instead of hoping GSPMD derives it from the einsum. Enabled
  automatically on meshes where tokens are sharded over (dcn, data,
  expert) only (fsdp/model/seq all 1 — the canonical EP regime);
  anything else falls back to dense.

Tokens are BATCH-sharded over the `expert` axis outside this block
(parallel/mesh.py BATCH_AXES): the expert axis would otherwise duplicate
every dense layer's compute ep-fold.

Per-step diagnostics are sowed into the "diagnostics" collection:
  moe_fill — filled fraction of expert capacity slots (1 - padding);
  moe_drop — fraction of routed (token, slot) pairs dropped to overflow.

Reference framework has no MoE (SURVEY.md §2.5 "Expert parallelism:
Absent"); this is TPU-native net-new capability.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kubeflow_tpu.parallel.mesh import (
    AXIS_DCN,
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_PIPELINE,
    AXIS_SEQ,
    current_mesh,
)


def _router(cfg, x, init):
    """Top-k routing (f32 softmax). Returns (probs [b,s,e],
    gate_vals [b,s,k] renormalized, gate_idx [b,s,k])."""
    router = nn.DenseGeneral(
        cfg.n_experts, use_bias=False, dtype=jnp.float32,
        kernel_init=nn.with_partitioning(init, (AXIS_FSDP, None)),
        name="router",
    )
    probs = jax.nn.softmax(router(x.astype(jnp.float32)), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, cfg.expert_top_k)
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)
    return probs, gate_vals, gate_idx


def _expert_mlp(cfg, xin, w_gate, w_up, w_down):
    """Batched SwiGLU over experts: xin [e, t, d] -> [e, t, d]."""
    h = nn.silu(jnp.einsum("etd,edf->etf", xin, w_gate.astype(cfg.dtype))) * \
        jnp.einsum("etd,edf->etf", xin, w_up.astype(cfg.dtype))
    return jnp.einsum("etf,efd->etd", h, w_down.astype(cfg.dtype))


def sparse_dispatch_mlp(cfg, x_local, gate_vals, gate_idx, w_gate, w_up,
                        w_down, capacity_factor, ep_axis=None):
    """Per-shard sort-based dispatch + expert MLP + combine.

    All arrays are LOCAL (this runs inside shard_map, or directly when
    there is no mesh): x_local [t, d] flattened tokens, gate_* [t, k],
    weights [e_local, ...]. When ep_axis is set, buffers are exchanged
    across it (global experts e = e_local * ep). Returns (y [t, d],
    fill_count, routed_count, slot_count) — slot_count is THIS shard's
    allocated capacity slots (e * cap), the denominator for the fill
    diagnostic (per-shard capacity rounds differently from the dense
    per-row formula, so callers must not recompute it).
    """
    t, d = x_local.shape
    k = gate_idx.shape[-1]
    ep = 1 if ep_axis is None else jax.lax.axis_size(ep_axis)
    e_local = w_gate.shape[0]
    e = e_local * ep
    # per-shard per-expert capacity (same invariant as the dense path's
    # per-row capacity: cf * tokens * k / e)
    cap = max(1, int(capacity_factor * t * k / e))

    # sort routed (token, slot) pairs by expert id -> contiguous groups
    eidx = gate_idx.reshape(-1)                      # [t*k]
    order = jnp.argsort(eidx)                        # stable
    sorted_e = eidx[order]
    sorted_tok = order // k
    # position within each expert's group: running index minus the
    # group's start (exclusive cumsum of per-expert counts)
    counts = jnp.bincount(eidx, length=e)            # [e]
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(t * k) - starts[sorted_e]
    keep = pos < cap
    slot = jnp.where(keep, sorted_e * cap + pos, e * cap)  # overflow -> OOB

    # scatter tokens into capacity buffers [e*cap, d] (OOB rows drop)
    buf = jnp.zeros((e * cap, d), cfg.dtype).at[slot].set(
        x_local[sorted_tok].astype(cfg.dtype), mode="drop")

    if ep_axis is not None and ep > 1:
        # [e, cap, d] -> exchange expert groups so every shard holds ALL
        # shards' buffers for ITS local experts: [ep, e_local, cap, d]
        buf = buf.reshape(ep, e_local * cap, d)
        buf = jax.lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=0,
                                 tiled=False)        # [ep, e_local*cap, d]
        xin = buf.reshape(ep, e_local, cap, d).transpose(1, 0, 2, 3) \
                 .reshape(e_local, ep * cap, d)
    else:
        xin = buf.reshape(e_local, cap, d)

    out = _expert_mlp(cfg, xin, w_gate, w_up, w_down)

    if ep_axis is not None and ep > 1:
        out = out.reshape(e_local, ep, cap, d).transpose(1, 0, 2, 3) \
                 .reshape(ep, e_local * cap, d)
        out = jax.lax.all_to_all(out, ep_axis, split_axis=0, concat_axis=0,
                                 tiled=False)
    flat_out = out.reshape(e * cap, d)

    # combine: gather each kept (token, slot) row, weight by its gate
    contrib = flat_out.at[slot].get(mode="fill", fill_value=0)  # [t*k, d]
    w = jnp.where(keep, gate_vals.reshape(-1)[order], 0.0)
    y = jnp.zeros((t, d), jnp.float32).at[sorted_tok].add(
        contrib.astype(jnp.float32) * w[:, None])
    return (y.astype(cfg.dtype), jnp.sum(keep), jnp.asarray(t * k),
            jnp.asarray(e * cap))


class MoEBlock(nn.Module):
    """Drop-in replacement for the dense SwiGLU MLP."""

    cfg: "TransformerConfig"  # noqa: F821 — structural typing, avoids cycle
    capacity_factor: float = 1.25

    def _sparse_ok(self, mesh) -> bool:
        impl = getattr(self.cfg, "moe_impl", "auto")
        if impl == "dense":
            return False
        if mesh is None:
            # No mesh context -> dense, even when sparse is forced:
            # init-time traces (jax.eval_shape of model.init) legitimately
            # run outside the mesh context, so raising here would break
            # every forced-sparse config before its first step. Trainer
            # steps always carry the mesh; a truly meshless forced-sparse
            # run therefore measures the DENSE path — single-chip A/Bs
            # must go through the trainer/bench (which always build a
            # mesh) for the label to mean what it says.
            return False
        ep = mesh.shape.get(AXIS_EXPERT, 1)
        # preconditions of the shard_map formulation: tokens sharded over
        # dcn/data/expert only (d and seq unsharded) and experts evenly
        # divisible across the expert axis
        ok = all(mesh.shape.get(a, 1) == 1
                 for a in (AXIS_FSDP, AXIS_MODEL, AXIS_SEQ, AXIS_PIPELINE)) \
            and self.cfg.n_experts % ep == 0
        if impl == "sparse" and not ok:
            # forced sparse on an uncovered mesh would die deep inside
            # shard_map tracing; fail with the config error instead
            raise ValueError(
                f"moe_impl='sparse' requires fsdp/model/seq/pipe mesh axes "
                f"of size 1 and n_experts % expert_axis == 0; got mesh "
                f"{dict(mesh.shape)} with n_experts={self.cfg.n_experts}")
        return ok

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        b, s, d = x.shape
        e, k = cfg.n_experts, cfg.expert_top_k
        init = nn.initializers.normal(0.02)

        probs, gate_vals, gate_idx = _router(cfg, x, init)

        w_gate = self.param(
            "w_gate", nn.with_partitioning(init, (AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL)),
            (e, d, cfg.d_ff), jnp.float32)
        w_up = self.param(
            "w_up", nn.with_partitioning(init, (AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL)),
            (e, d, cfg.d_ff), jnp.float32)
        w_down = self.param(
            "w_down", nn.with_partitioning(init, (AXIS_EXPERT, AXIS_MODEL, AXIS_FSDP)),
            (e, cfg.d_ff, d), jnp.float32)

        mesh = current_mesh()
        use_sparse = self._sparse_ok(mesh)
        if use_sparse:
            y, kept, routed, slots = self._sparse(
                x, gate_vals, gate_idx, w_gate, w_up, w_down, mesh)
        else:
            y, kept, routed, slots = self._dense(
                x, gate_vals, gate_idx, w_gate, w_up, w_down)
        # Ground truth for which dispatch path actually ran (ADVICE r4):
        # _sparse_ok silently falls back to dense on a meshless trace, so
        # a run labeled 'sparse' could measure dense with nothing in the
        # record saying so. 1.0 = sparse all-to-all, 0.0 = dense oracle.
        self.sow("diagnostics", "moe_sparse_dispatch",
                 jnp.float32(1.0 if use_sparse else 0.0))

        # aux load-balancing loss: mean_e (dispatch fraction * prob mass),
        # with the dispatch fraction taken from the router's PRE-capacity
        # top-k assignment — the Switch/T5X convention, and identical in
        # both dispatch paths by construction (it depends only on
        # gate_idx). NOTE round 3's dense path used the post-capacity
        # fraction; the conventions differ only when experts overflow.
        me = probs.mean(axis=(0, 1))                   # [e]
        assign_pre = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)
        ce = assign_pre.sum(axis=2).mean(axis=(0, 1))
        aux = e * jnp.sum(me * ce)
        self.sow("losses", "moe_aux", aux)
        # dispatch diagnostics (VERDICT r3 #5): how much of the capacity
        # buffer is padding, and how much routing overflowed. `slots` is
        # reported by the path that allocated them — the sparse path's
        # per-shard capacity (cf*t_local*k/e) rounds differently from the
        # dense per-row formula, so recomputing it here would let
        # moe_fill exceed 1.
        self.sow("diagnostics", "moe_fill",
                 kept.astype(jnp.float32)
                 / jnp.maximum(slots.astype(jnp.float32), 1.0))
        self.sow("diagnostics", "moe_drop",
                 1.0 - kept.astype(jnp.float32)
                 / jnp.maximum(routed.astype(jnp.float32), 1.0))
        return y.astype(cfg.dtype)

    # ---- dense (oracle) path --------------------------------------------

    def _dense(self, x, gate_vals, gate_idx, w_gate, w_up, w_down):
        cfg = self.cfg
        b, s, d = x.shape
        e, k = cfg.n_experts, cfg.expert_top_k
        capacity = int(self.capacity_factor * s * k / e) or 1

        # Tokens arrive sharded over BATCH_AXES, which includes `expert`.
        # The dense dispatch/combine einsums regroup tokens by expert —
        # a transition the pre-Shardy partitioner can only bridge with
        # its replicate-then-repartition fallback ("Involuntary full
        # rematerialization"). Pull the batch off the expert axis
        # explicitly first (one all-gather over expert), and push the
        # output back at the end.
        from kubeflow_tpu.parallel.mesh import shard_constraint

        noexp = (AXIS_DCN, AXIS_DATA, AXIS_FSDP)
        mesh = current_mesh()
        resharded = mesh is not None and mesh.shape.get(AXIS_EXPERT, 1) > 1
        if resharded:
            x = shard_constraint(x, P(noexp, None, None))
            gate_vals = shard_constraint(gate_vals, P(noexp, None, None))
            gate_idx = shard_constraint(gate_idx, P(noexp, None, None))

        assign = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)  # [b,s,k,e]
        flat = assign.reshape(b, s * k, e)
        pos = jnp.cumsum(flat, axis=1) - flat          # arrival order
        pos = pos.reshape(b, s, k, e)
        within_cap = pos < capacity
        assign = assign * within_cap                   # drop overflow
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=jnp.float32)
        dispatch = jnp.einsum("bske,bskec->bsec", assign, pos_oh)
        combine = jnp.einsum("bsk,bske,bskec->bsec",
                             gate_vals.astype(jnp.float32), assign, pos_oh)

        xin = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(cfg.dtype), x)
        h = nn.silu(jnp.einsum("ebcd,edf->ebcf", xin, w_gate.astype(cfg.dtype))) * \
            jnp.einsum("ebcd,edf->ebcf", xin, w_up.astype(cfg.dtype))
        out = jnp.einsum("ebcf,efd->ebcd", h, w_down.astype(cfg.dtype))
        y = jnp.einsum("bsec,ebcd->bsd", combine.astype(cfg.dtype), out)
        if resharded:
            from kubeflow_tpu.parallel.mesh import BATCH_AXES

            # two-step ladder: pin the einsum output (and, transposed,
            # its backward cotangent) to the expert-free layout FIRST so
            # the only transition at the einsum is an all-gather over
            # `expert`; then restore the full batch sharding for the
            # residual stream
            y = shard_constraint(y, P(noexp, None, None))
            y = shard_constraint(y, P(BATCH_AXES, None, None))
        kept = jnp.sum(assign)
        return (y, kept, jnp.asarray(b * s * k, jnp.float32),
                jnp.asarray(b * e * capacity, jnp.float32))

    # ---- sparse (all-to-all) path ---------------------------------------

    def _sparse(self, x, gate_vals, gate_idx, w_gate, w_up, w_down, mesh):
        from jax import shard_map

        cfg = self.cfg
        b, s, d = x.shape
        tok_axes = (AXIS_DCN, AXIS_DATA, AXIS_EXPERT)
        cf = self.capacity_factor

        def body(xl, gvl, gil, wg, wu, wd):
            bl = xl.shape[0]
            y, fill, routed, slots = sparse_dispatch_mlp(
                cfg, xl.reshape(bl * s, d), gvl.reshape(bl * s, -1),
                gil.reshape(bl * s, -1), wg, wu, wd, cf,
                ep_axis=AXIS_EXPERT)
            # diagnostics are global sums: reduce over the token shards
            fill = jax.lax.psum(fill, tok_axes)
            routed = jax.lax.psum(routed, tok_axes)
            slots = jax.lax.psum(slots, tok_axes)
            return y.reshape(bl, s, d), fill, routed, slots

        tok_spec = P(tok_axes, None, None)
        gate_spec = P(tok_axes, None, None)
        y, fill, routed, slots = shard_map(
            body, mesh=mesh,
            in_specs=(tok_spec, gate_spec, gate_spec,
                      P(AXIS_EXPERT, None, None), P(AXIS_EXPERT, None, None),
                      P(AXIS_EXPERT, None, None)),
            out_specs=(tok_spec, P(), P(), P()),
        )(x, gate_vals, gate_idx, w_gate, w_up, w_down)
        return y, fill, routed, slots
