#!/usr/bin/env python
"""chargeback_bench — deterministic 8-tenant contention drill.

Builds the REAL serving + control + observability stack on one virtual
clock — a resilience-mode TokenRouter, the gang scheduler + JAXJob
controller over a FakeCluster, and a FleetPlane scraping all of them
with the default AND tenant rule packs — then runs eight tenants
against it for a fixed number of 15 s cycles:

- every tenant trains (synthetic ``train.step``/``train.checkpoint``
  spans stamped with its tenant attr) and serves steady traffic;
- ONE noisy tenant (``tenant-3``) runs a retry storm for a window:
  each of its requests burns retry-budget tokens twice before
  completing, plus one outright failure per cycle;
- ONE tenant (``tenant-6``) burns its latency SLO: its requests
  complete above the 0.5 s target for a window;
- mid-run a high-priority burst gang lands in ``tenant-7`` and
  preempts two running victims (scheduler attribution under
  contention).

The bench then pulls the bill the plane renders — the
``FleetPlane.chargeback`` ledger (conservation checked: per-tenant
chip-second buckets must sum EXACTLY to the fleet ledger or the run
raises), per-tenant retry/hedge spend, request outcomes, and scheduler
admission/requeue/preemption counts — and fingerprints the decision
log (alert transitions + the invoice). Correct attribution is asserted,
not eyeballed: the storm must bill to the storm tenant, the burn to
the burn tenant, and nobody else.

    python tools/chargeback_bench.py          # full + smoke, write JSON
    python tools/chargeback_bench.py --check  # CI gate: rerun the
        # banked smoke config; fail when the decision fingerprint,
        # invoice, attribution or op counts drift, or p99 regresses
        # past 3x budget
"""

from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.control.jaxjob import types as JJ  # noqa: E402
from kubeflow_tpu.control.jaxjob.controller import (  # noqa: E402
    build_controller as build_jaxjob_controller,
)
from kubeflow_tpu.control.k8s.fake import FakeCluster  # noqa: E402
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet  # noqa: E402
from kubeflow_tpu.control.runtime import seed_controller  # noqa: E402
from kubeflow_tpu.control.scheduler.nodes import new_tpu_node  # noqa: E402
from kubeflow_tpu.control.scheduler.scheduler import build_scheduler  # noqa: E402
from kubeflow_tpu.obs import expofmt  # noqa: E402
from kubeflow_tpu.obs.plane import FleetPlane  # noqa: E402
from kubeflow_tpu.obs.rules import (  # noqa: E402
    default_rule_pack, tenant_rule_pack,
)
from kubeflow_tpu.obs.trace import Span, TraceCollector, Tracer  # noqa: E402
from kubeflow_tpu.obs.tsdb import RegistryTarget  # noqa: E402
from kubeflow_tpu.runtime.metrics import MetricsRegistry  # noqa: E402
from kubeflow_tpu.serving.router import (  # noqa: E402
    Member, ResilienceConfig, TokenRouter,
)

DEFAULT_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_TENANT_r01.json")

SCRAPE_INTERVAL_S = 15.0
TENANTS = tuple(f"tenant-{i}" for i in range(8))
STORM_TENANT = "tenant-3"   # retry storm (noisy neighbor)
BURN_TENANT = "tenant-6"    # latency SLO burn
BURST_TENANT = "tenant-7"   # lands the preempting burst gang
# chip weights per tenant (the chargeback denominators): even tenants
# hold 4 chips, odd tenants 8 — asymmetric on purpose so the fleet
# conservation check multiplies through unequal weights
CHIPS_BY_TENANT = {t: 4 if i % 2 == 0 else 8
                   for i, t in enumerate(TENANTS)}
NODES = tuple(f"tpu-{i}" for i in range(8))
TENANT_ALERT_RULES = ("TenantSLOBurn", "TenantRetryStorm",
                      "TenantRequestFailures")


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _percentile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(math.ceil(q * len(xs))) - 1)]


def build_world(clock: ManualClock, seed: int) -> dict:
    cluster = FakeCluster()
    for name in NODES:
        cluster.create(new_tpu_node(name, topology="2x4"))
    sched_reg = MetricsRegistry()
    sched_ctl = seed_controller(build_scheduler(
        cluster, registry=sched_reg, record_events=False, clock=clock))
    job_reg = MetricsRegistry()
    job_ctl = seed_controller(build_jaxjob_controller(
        cluster, record_events=False, registry=job_reg))
    router_reg = MetricsRegistry()
    # the router's dispatch spans go to a private collector: the
    # plane's ledger cut must account ONLY the deterministic synthetic
    # training spans staged below (dispatch spans carry wall-clock
    # stamps and would not replay)
    router = TokenRouter(
        service="chat", namespace="default", clock=clock,
        registry=router_reg, tracer=Tracer(TraceCollector()),
        prom_sink=False,
        resilience=ResilienceConfig(
            # the storm must spend the budget, not exhaust it — and a
            # breaker trip would turn the synchronous driver's
            # redispatch into a queue park
            breaker_failures=10 ** 6,
            retry_budget_ratio=0.5, retry_budget_cap=200.0))
    router.set_members([Member(name="replica-0", transport=None),
                        Member(name="replica-1", transport=None)])
    train = TraceCollector()
    plane = FleetPlane(
        registry=MetricsRegistry(),
        targets=[
            RegistryTarget("router", router_reg, labels={"job": "router"}),
            RegistryTarget("sched", sched_reg, labels={"job": "control"}),
            RegistryTarget("jaxjob", job_reg, labels={"job": "control"}),
        ],
        rules=default_rule_pack() + tenant_rule_pack(),
        interval_s=SCRAPE_INTERVAL_S, clock=clock, collector=train,
        max_points=256, max_series=20000)
    kubelet = FakeKubelet(cluster)
    # one 2-worker gang per tenant (2x4 tiles as 2 x 4-chip hosts):
    # 8 single-host nodes hold four gangs, so four tenants requeue
    # every pass — admission contention is the point, not an accident
    for i, tenant in enumerate(TENANTS):
        cluster.create(JJ.new_jaxjob(
            f"train-{i}", namespace=tenant, replicas=2,
            accelerator="tpu-v5-lite-podslice", topology="2x4",
            chips_per_worker=4, gang_schedule=True))
    return {"cluster": cluster, "router": router, "plane": plane,
            "train": train, "sched_ctl": sched_ctl, "job_ctl": job_ctl,
            "kubelet": kubelet, "router_reg": router_reg,
            "sched_reg": sched_reg, "job_reg": job_reg}


def control_tick(world: dict, rounds: int = 3) -> None:
    for _ in range(rounds):
        for ctl in (world["sched_ctl"], world["job_ctl"]):
            ctl.run_until_idle(advance_delayed=True)
        world["kubelet"].step()


def _stage_training(train: TraceCollector, cycle: int,
                    cycle_start: float) -> None:
    """One cycle of synthetic per-tenant training spans on the virtual
    clock. Every tenant steps; each checkpoints on its own staggered
    cadence; the storm tenant's shorter step leaves visible ``other``
    time — eight DIFFERENT goodput profiles, so the invoice has
    something to attribute."""
    for i, tenant in enumerate(TENANTS):
        step_start = cycle_start + (6.0 if tenant == STORM_TENANT
                                    else 3.0)
        step_end = cycle_start + (12.0 if tenant == STORM_TENANT
                                  else 14.0)
        if cycle % 8 == i:
            train.add(Span(
                name="train.checkpoint", trace_id=f"trace-{tenant}",
                span_id=f"{tenant}-c{cycle}-ckpt",
                start=cycle_start + 1.0, end=cycle_start + 3.0,
                attrs={"tenant": tenant, "namespace": tenant},
                pid=0, tid=0))
        train.add(Span(
            name="train.step", trace_id=f"trace-{tenant}",
            span_id=f"{tenant}-c{cycle}-step",
            start=step_start, end=step_end,
            attrs={"tenant": tenant, "namespace": tenant, "step": cycle},
            pid=0, tid=0))


def _stage_serving(world: dict, clock: ManualClock, rng: random.Random,
                   cycle: int, cfg: dict) -> None:
    """One cycle of synchronous router traffic. Tickets are completed
    in latency order by advancing the shared clock — completion latency
    is ``clock - submit``, so the histogram sees exactly the staged
    distribution."""
    router: TokenRouter = world["router"]
    storm = cfg["storm_at"] <= cycle < cfg["storm_until"]
    burn = cfg["burn_at"] <= cycle < cfg["burn_until"]
    plan: list[tuple[float, int, object]] = []
    seq = 0
    for tenant in TENANTS:
        for _ in range(3):
            slow = burn and tenant == BURN_TENANT
            lat = rng.uniform(0.9, 1.8) if slow \
                else rng.uniform(0.03, 0.3)
            plan.append((lat, seq, router.submit(40, tenant=tenant)))
            seq += 1
    if storm:
        for _ in range(6):
            t = router.submit(40, tenant=STORM_TENANT)
            # two transport failures -> two retry-budget tokens billed
            # to the storm tenant; capacity is free so each requeue
            # redispatches synchronously
            router.fail(t)
            router.fail(t)
            plan.append((rng.uniform(0.05, 0.3), seq, t))
            seq += 1
        dead = router.submit(40, tenant=STORM_TENANT)
        router.fail(dead, requeue=False)  # outcome=failed, storm-billed
    elapsed = 0.0
    for lat, _seq, ticket in sorted(plan, key=lambda p: (p[0], p[1])):
        clock.advance(lat - elapsed)
        elapsed = lat
        router.complete(ticket)


def _by_tenant(registry: MetricsRegistry, name: str,
               extra_key: str | None = None) -> dict:
    """Sum a tenant-labeled family from a registry's exposition:
    tenant -> value, or tenant -> {extra_label: value}."""
    out: dict = {}
    for s in expofmt.parse(registry.render()):
        if s.name != name:
            continue
        labels = s.labels_dict()
        tenant = labels.get("tenant")
        if not tenant:
            continue
        if extra_key is None:
            out[tenant] = out.get(tenant, 0.0) + s.value
        else:
            sub = out.setdefault(tenant, {})
            k = labels.get(extra_key, "")
            sub[k] = sub.get(k, 0.0) + s.value
    return out


def _invoice(world: dict, at: float, window_s: float) -> dict:
    """The per-tenant bill: the plane's conservation-checked chargeback
    ledger joined with retry spend, request outcomes and scheduler
    contention counts — the JSON an operator would hand to billing."""
    cb = world["plane"].chargeback(
        window_s=window_s, at=at, chips_by_tenant=dict(CHIPS_BY_TENANT))
    retry = _by_tenant(world["router_reg"],
                       "router_tenant_retry_tokens_total",
                       extra_key="kind")
    outcomes = _by_tenant(world["router_reg"], "router_requests_total",
                          extra_key="outcome")
    admitted = _by_tenant(world["sched_reg"],
                          "scheduler_gangs_admitted_total")
    requeues = _by_tenant(world["sched_reg"], "scheduler_requeues_total")
    preempted = _by_tenant(world["sched_reg"],
                           "scheduler_preemptions_total")
    out: dict = {}
    for tenant in sorted(set(cb["tenants"]) | set(TENANTS)):
        entry = cb["tenants"].get(tenant) or {}
        good = entry.get("goodput")
        slo = (entry.get("slo") or [{}])[0]
        out[tenant] = {
            "chips": CHIPS_BY_TENANT.get(tenant, 0),
            "goodput_pct": (good or {}).get("goodput_pct"),
            "chip_seconds_lost": (good or {}).get("chip_seconds_lost"),
            "slo_attainment": slo.get("attainment"),
            "slo_met": slo.get("met"),
            "remediations": entry.get("remediations", 0),
            "retry_tokens": {k: round(v, 6) for k, v in
                             sorted(retry.get(tenant, {}).items())},
            "requests": {k: round(v, 6) for k, v in
                         sorted(outcomes.get(tenant, {}).items())
                         if v > 0},
            "sched": {
                "admitted": round(admitted.get(tenant, 0.0), 6),
                "requeues": round(requeues.get(tenant, 0.0), 6),
                "preemptions": round(preempted.get(tenant, 0.0), 6),
            },
        }
    return out


def _assert_attribution(invoice: dict, tenant_alerts: dict) -> None:
    """The bench's reason to exist: the storm bills to the storm
    tenant, the burn to the burn tenant, and to NOBODY else. Raised,
    not reported — a chargeback plane that misattributes is worse than
    none."""
    for tenant, bill in invoice.items():
        spent = sum(bill["retry_tokens"].values())
        if tenant == STORM_TENANT:
            assert spent > 0, "storm tenant billed zero retry tokens"
            assert bill["requests"].get("failed", 0) > 0, \
                "storm tenant shows no failed requests"
        else:
            assert spent == 0, \
                f"retry spend misattributed to {tenant}: {spent}"
            assert bill["requests"].get("failed", 0) == 0, \
                f"failures misattributed to {tenant}"
        if tenant == BURN_TENANT:
            assert bill["slo_met"] is False, \
                "burn tenant's SLO reads as met"
        elif tenant in CHIPS_BY_TENANT:
            assert bill["slo_met"] is not False, \
                f"SLO burn misattributed to {tenant}"
    storm_alerts = tenant_alerts.get("TenantRetryStorm", [])
    assert storm_alerts == [STORM_TENANT], \
        f"TenantRetryStorm fired for {storm_alerts}"
    burn_alerts = tenant_alerts.get("TenantSLOBurn", [])
    assert burn_alerts == [BURN_TENANT], \
        f"TenantSLOBurn fired for {burn_alerts}"
    fail_alerts = tenant_alerts.get("TenantRequestFailures", [])
    assert fail_alerts == [STORM_TENANT], \
        f"TenantRequestFailures fired for {fail_alerts}"


def run_bench(cycles: int, seed: int = 0, storm_at: int = 8,
              storm_until: int = 22, burn_at: int = 5,
              burn_until: int = 30, burst_at: int = 12) -> dict:
    clock = ManualClock()
    rng = random.Random(seed)
    world = build_world(clock, seed)
    cfg = {"storm_at": storm_at, "storm_until": storm_until,
           "burn_at": burn_at, "burn_until": burn_until,
           "burst_at": burst_at}
    control_tick(world, rounds=4)  # settle: admit the first five gangs

    plane: FleetPlane = world["plane"]
    plane_ms: list[float] = []
    control_ms: list[float] = []
    transitions: list[dict] = []
    samples_total = 0
    for cycle in range(cycles):
        cycle_start = clock.t
        if cycle == burst_at:
            # the contention event: a high-priority 2-worker gang in
            # the burst tenant preempts two running victims
            world["cluster"].create(JJ.new_jaxjob(
                "burst", namespace=BURST_TENANT, replicas=2,
                accelerator="tpu-v5-lite-podslice", topology="2x4",
                chips_per_worker=4, gang_schedule=True, priority=100))
        _stage_training(world["train"], cycle, cycle_start)
        _stage_serving(world, clock, rng, cycle, cfg)
        t0 = time.perf_counter()
        control_tick(world)
        t1 = time.perf_counter()
        res = plane.tick(at=clock.t)
        t2 = time.perf_counter()
        control_ms.append((t1 - t0) * 1e3)
        plane_ms.append((t2 - t1) * 1e3)
        samples_total += res["scrape"]["samples"]
        for tr in res["transitions"]:
            transitions.append({"cycle": cycle, **tr})
        clock.advance(SCRAPE_INTERVAL_S - (clock.t - cycle_start))

    window_s = cycles * SCRAPE_INTERVAL_S
    invoice = _invoice(world, at=clock.t, window_s=window_s)
    # the chargeback call above already conservation-checked the
    # ledger; re-prove it independently against the raw span stream so
    # the banked "ok" is a second computation, not a copied flag
    from kubeflow_tpu.obs import goodput as gp

    gp.tenant_report(world["train"].spans(), clock.t - window_s, clock.t,
                     chips_by_tenant=dict(CHIPS_BY_TENANT)).check()
    tenant_alerts = {
        rule: sorted({t["labels"].get("tenant") for t in transitions
                      if t["alert"] == rule and t["to"] == "firing"
                      and t["labels"].get("tenant")})
        for rule in TENANT_ALERT_RULES}
    _assert_attribution(invoice, tenant_alerts)
    store_stats = plane.store.stats()
    decision_log = json.dumps(
        {"transitions": transitions, "invoice": invoice},
        sort_keys=True)
    return {
        "config": {"cycles": cycles, "seed": seed, **cfg},
        "series": store_stats["series"],
        "points": store_stats["points"],
        "appends": store_stats["appends"],
        "samples_total": samples_total,
        "alerts_fired": sorted({t["alert"] for t in transitions
                                if t["to"] == "firing"}),
        "tenant_alerts": tenant_alerts,
        "transitions": len(transitions),
        "invoice": invoice,
        "conservation": "ok",
        "decision_fingerprint": hashlib.sha256(
            decision_log.encode()).hexdigest(),
        # wall-clock timings live apart from the deterministic body so
        # a double-run byte-compares everything else
        "machine": {
            "plane_p50_ms": round(_percentile(plane_ms, 0.50), 3),
            "plane_p99_ms": round(_percentile(plane_ms, 0.99), 3),
            "control_p50_ms": round(_percentile(control_ms, 0.50), 3),
            "control_p99_ms": round(_percentile(control_ms, 0.99), 3),
        },
    }


# FULL: storm and burn both open AND close (their alerts fire and
# resolve as the rate windows slide the bad samples out). SMOKE: the
# CI-gate config — shorter, but every attribution assert still holds.
FULL_CONFIG = {"cycles": 48, "seed": 0, "storm_at": 8,
               "storm_until": 22, "burn_at": 5, "burn_until": 30,
               "burst_at": 12}
SMOKE_CONFIG = {"cycles": 28, "seed": 0, "storm_at": 4,
                "storm_until": 14, "burn_at": 3, "burn_until": 18,
                "burst_at": 6}


def check_against(banked_path: str) -> int:
    """CI ratchet: rerun the banked smoke config. Fail (1) when the
    decision fingerprint, the invoice, the tenant-alert attribution or
    the op counts drift (the plane BILLED differently on identical
    input), or when plane/control p99 regresses past 3x the committed
    budget (floored at 250 ms so CI contention cannot flake the
    gate)."""
    with open(banked_path) as fh:
        banked = json.load(fh)
    smoke = banked.get("smoke")
    if not smoke:
        print(f"check: no smoke section in {banked_path}",
              file=sys.stderr)
        return 2
    now = run_bench(**smoke["config"])
    ok = True
    if now["decision_fingerprint"] != smoke["decision_fingerprint"]:
        print("check: decision fingerprint drifted "
              f"({now['decision_fingerprint'][:12]} != banked "
              f"{smoke['decision_fingerprint'][:12]}) — alerting or "
              "the invoice decided differently on identical input",
              file=sys.stderr)
        ok = False
    for key in ("appends", "series", "samples_total", "invoice",
                "tenant_alerts", "conservation"):
        if now[key] != smoke[key]:
            print(f"check: {key} {now[key]!r} != banked {smoke[key]!r} "
                  "(the bill must replay exactly)", file=sys.stderr)
            ok = False
    for key in ("plane_p99_ms", "control_p99_ms"):
        budget = max(smoke["machine"][key] * 3.0, 250.0)
        if now["machine"][key] > budget:
            print(f"check: {key} {now['machine'][key]} exceeds budget "
                  f"{budget:.3f} (banked {smoke['machine'][key]})",
                  file=sys.stderr)
            ok = False
    print(json.dumps({"check": "ok" if ok else "REGRESSED",
                      "plane_p99_ms": now["machine"]["plane_p99_ms"],
                      "control_p99_ms": now["machine"]["control_p99_ms"],
                      "fingerprint": now["decision_fingerprint"][:12]},
                     indent=2))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cycles", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--no-smoke", action="store_true")
    ap.add_argument("--check", action="store_true",
                    help="rerun the banked smoke config and gate on "
                         "fingerprint/invoice/attribution drift or a "
                         ">3x p99 budget regression")
    args = ap.parse_args(argv)
    if args.check:
        return check_against(args.out)

    config = dict(FULL_CONFIG, seed=args.seed)
    if args.cycles:
        config["cycles"] = args.cycles
    full = run_bench(**config)
    result = {"bench": "chargeback_bench", "round": "r01", "full": full}
    if not args.no_smoke:
        result["smoke"] = run_bench(**SMOKE_CONFIG)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps({
        "out": args.out,
        "tenant_alerts": full["tenant_alerts"],
        "storm_bill": full["invoice"][STORM_TENANT]["retry_tokens"],
        "burn_slo": full["invoice"][BURN_TENANT]["slo_attainment"],
        "plane_p99_ms": full["machine"]["plane_p99_ms"]}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
