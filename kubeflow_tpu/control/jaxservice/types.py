"""JAXService CRD: API types, defaults, validation.

The serving analogue of JAXJob (ROADMAP #2): where a JAXJob is one gang
that runs to completion, a JAXService is N interchangeable model-server
replicas that run forever behind the token-aware router
(``serving/router.py``), scaled between ``replicas.min`` and
``replicas.max`` on router queue depth and tokens/sec. Each replica is
its own gang of ONE for the gang scheduler — replicas admit
independently (a serving fleet wants every replica it can get, not
all-or-nothing), but still get slice-topology placement, spot-pool
preference and priority from the same scheduler the training plane
uses.

Status contract: ``status.targetReplicas`` is the autoscaler's durable
decision (level-triggered provisioning reconciles toward it across
controller restarts); per-replica phases land in
``status.replicaStatuses``; the READY endpoint set is published on the
``ANNOTATION_ENDPOINTS`` metadata annotation — the downward-style feed
the router consumes (docs/serving.md).
"""

from __future__ import annotations

import hashlib
import json

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.scheduler import SCHEDULER_NAME
from kubeflow_tpu.control.scheduler.topology import parse_topology

# The ONE spelling of the controller -> router endpoints wire contract
# lives with its consumer (serving/router.py, the dist.py pattern);
# re-exported here for the control plane.
from kubeflow_tpu.serving.router import (  # noqa: F401
    ANNOTATION_ENDPOINTS,
    BAND_DEFAULT,
    BAND_RANK,
    STATE_ACTIVE,
    STATE_CORDONED,
)

GROUP = "kubeflow.org"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "JAXService"

# Condition types (the JAXJob Created/Running/Failed shape, serving
# vocabulary: a service is Ready, never Succeeded)
COND_CREATED = "Created"
COND_READY = "Ready"
COND_DEGRADED = "Degraded"

# Pod labels (the jaxjob.kubeflow.org/job-name analogue)
LABEL_SERVICE_NAME = "jaxservice.kubeflow.org/service-name"
LABEL_REPLICA_INDEX = "jaxservice.kubeflow.org/replica-index"

# Revision identity on replica PODS: the content-addressed hash of the
# pod-shaping spec fields (``revision_hash``). The rollout state machine
# keys every decision off this label — which replicas are old, which are
# the surge canary — and the router stamps it on request metrics so
# canary-vs-baseline burn is measurable (docs/serving.md, "Safe
# rollouts").
LABEL_REVISION = "jaxservice.kubeflow.org/revision"

# Scale-down drain marker on replica PODS: a cordoned replica is
# published to the router as state=cordoned (no new work), the
# controller deletes it only once the router reports zero in-flight
# tokens for it — the drain state machine in docs/serving.md.
ANNOTATION_CORDON = "jaxservice.kubeflow.org/cordon"

# Durable drain deadline on cordoned replica PODS: the absolute
# controller-clock time after which a signal-less drain may delete the
# pod. Persisted so a controller restart RESUMES the countdown instead
# of restarting it (the in-memory timer of PR 8 only ever drained
# longer; this makes the grace exact across restarts).
ANNOTATION_DRAIN_DEADLINE = "jaxservice.kubeflow.org/drain-deadline"

# One-shot replica floor on the JAXSERVICE, written by the alert-driven
# remediation engine (obs/remediate.py, KVPagesExhausted -> scale up).
# The autoscaler consumes and CLEARS it inside its normal reconcile, so
# the move flows through the record-first durable target write and the
# max-replica clamp like any other scale decision.
ANNOTATION_SCALE_NUDGE = "jaxservice.kubeflow.org/scale-nudge"

# Env injected into replica containers
ENV_SERVICE = "JAXSERVICE_NAME"
ENV_REPLICA = "JAXSERVICE_REPLICA"
ENV_NAMESPACE = "JAXSERVICE_NAMESPACE"

DEFAULT_PORT = 8500

# Autoscaling defaults: targets are PER-REPLICA capacities; the
# stabilization windows are the hysteresis (a demand spike shorter than
# the up window scales nothing, a lull shorter than the down window
# keeps every replica — docs/serving.md).
DEFAULT_TARGET_QUEUE_DEPTH = 8
DEFAULT_TARGET_TOKENS_PER_SEC = 2000.0
DEFAULT_UP_STABILIZATION_S = 5.0
DEFAULT_DOWN_STABILIZATION_S = 30.0

# Scale-down drain grace when NO signal plane is wired to the
# controller (the production default): a Running cordoned replica may
# still hold multi-minute decodes the controller cannot observe, so it
# is held this long after cordon before deletion. With signals wired,
# the router's per-replica in-flight gauge gates the delete instead.
DEFAULT_DRAIN_SECONDS = 60.0

# Rollout defaults: one surge replica at a time, never dip below the
# target (maxUnavailable=0), a 10% -> 50% -> 100% canary ladder, and a
# 60 s analysis window per step with automatic rollback armed.
DEFAULT_MAX_SURGE = 1
DEFAULT_MAX_UNAVAILABLE = 0
DEFAULT_CANARY_STEPS = (0.1, 0.5, 1.0)
DEFAULT_ANALYSIS_WINDOW_S = 60.0

# Rollout phases recorded in status.revisions.phase — the rollout state
# machine (docs/serving.md, "Safe rollouts"). Idle means current ==
# target (no rollout in flight).
PHASE_IDLE = "Idle"
PHASE_SURGE = "Surge"
PHASE_ANALYZE = "Analyze"
PHASE_PROMOTE = "Promote"
PHASE_ROLLBACK = "Rollback"
ROLLOUT_PHASES = (PHASE_IDLE, PHASE_SURGE, PHASE_ANALYZE,
                  PHASE_PROMOTE, PHASE_ROLLBACK)

# jaxservice_rollouts_total outcomes, pre-registered at 0 on first
# sight (the first-failure tripwire discipline): rate()/increase() must
# see a zero sample before the first aborted rollout.
ROLLOUT_OUTCOMES = ("promoted", "rolled_back", "aborted")


def drain_seconds(spec: dict) -> float:
    return spec.get("drainSeconds", DEFAULT_DRAIN_SECONDS)


def replica_name(service_name: str, index: int) -> str:
    return f"{service_name}-replica-{index}"


def replica_index(pod_name: str) -> int:
    """Replica slot from a pod name; unparseable names sort AFTER every
    real replica (the jaxjob worker_index discipline — a malformed
    leftover must never alias slot 0)."""
    import sys

    try:
        return int(pod_name.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return sys.maxsize


def replicas_spec(spec: dict) -> dict:
    """spec.replicas with defaults: {min, max}."""
    r = spec.get("replicas")
    if isinstance(r, int):  # shorthand: fixed size, autoscaler clamped
        return {"min": r, "max": r}
    r = r if isinstance(r, dict) else {}
    mn = r.get("min", 1)
    return {"min": mn, "max": r.get("max", mn)}


def autoscaling_spec(spec: dict) -> dict:
    a = spec.get("autoscaling")
    a = a if isinstance(a, dict) else {}
    return {
        "targetQueueDepth": a.get("targetQueueDepth",
                                  DEFAULT_TARGET_QUEUE_DEPTH),
        "targetTokensPerSec": a.get("targetTokensPerSec",
                                    DEFAULT_TARGET_TOKENS_PER_SEC),
        "scaleUpStabilizationSeconds": a.get(
            "scaleUpStabilizationSeconds", DEFAULT_UP_STABILIZATION_S),
        "scaleDownStabilizationSeconds": a.get(
            "scaleDownStabilizationSeconds", DEFAULT_DOWN_STABILIZATION_S),
    }


def resilience_spec(spec: dict) -> dict:
    """spec.resilience with defaults — the namespace-level request
    resilience knobs the router frontend adopts through the endpoints
    watch (``RouterFrontend.apply_spec``):

    - ``defaultBand``: criticality band for requests without an
      x-request-band header (the ROADMAP #3 multi-tenancy bridge —
      a tenant's JAXService declares how sheddable its traffic is);
    - ``deadlineSeconds``: deadline for requests without an
      x-request-deadline-s header (0 = no default deadline);
    - ``hedge``: whether the frontend may race a second replica leg;
    - ``maxInflight``: per-REPLICA concurrent-request admission cap
      (0 = unbounded), threaded into the model-server command line so
      an overloaded replica 429s with Retry-After instead of queueing
      unboundedly.
    """
    r = spec.get("resilience")
    r = r if isinstance(r, dict) else {}
    return {
        "defaultBand": r.get("defaultBand", BAND_DEFAULT),
        "deadlineSeconds": r.get("deadlineSeconds", 0.0),
        "hedge": bool(r.get("hedge", True)),
        "maxInflight": r.get("maxInflight", 0),
    }


def rollout_spec(spec: dict) -> dict:
    """spec.rollout with defaults — the staged-replacement knobs:

    - ``maxSurge``: extra replicas (above target) the rollout may run
      while old and new revisions coexist;
    - ``maxUnavailable``: how far below target the fleet may dip while
      old replicas drain (0 = surge-only, capacity never drops);
    - ``canarySteps``: the canary weight ladder — the fraction of
      traffic the router sends to the NEW revision at each step,
      strictly increasing, ending at full weight;
    - ``analysisWindowSeconds``: how long each step must look healthy
      (canary error rate and latency quantile vs baseline) before the
      rollout advances;
    - ``autoRollback``: whether a failed analysis rolls the fleet back
      to the previous revision automatically.
    """
    r = spec.get("rollout")
    r = r if isinstance(r, dict) else {}
    steps = r.get("canarySteps")
    if not isinstance(steps, (list, tuple)) or not steps:
        steps = list(DEFAULT_CANARY_STEPS)
    return {
        "maxSurge": r.get("maxSurge", DEFAULT_MAX_SURGE),
        "maxUnavailable": r.get("maxUnavailable", DEFAULT_MAX_UNAVAILABLE),
        "canarySteps": list(steps),
        "analysisWindowSeconds": r.get("analysisWindowSeconds",
                                       DEFAULT_ANALYSIS_WINDOW_S),
        "autoRollback": bool(r.get("autoRollback", True)),
    }


def revision_hash(spec: dict) -> str:
    """Content-addressed revision of the POD-SHAPING spec fields.

    Two specs that generate byte-identical replica pods hash the same
    (editing ``spec.replicas`` or the autoscaling windows is NOT a
    rollout); any change that alters the pod — model flags, port, TPU
    shape, scheduler opt-in, the inflight cap threaded into the server
    command line, a custom template — mints a new revision. The hash is
    a valid k8s label value (``v`` + 10 hex chars).
    """
    shaping = {
        "model": model_spec(spec),
        "image": spec.get("image", ""),
        "port": spec.get("port", DEFAULT_PORT),
        "tpu": spec.get("tpu") or {},
        "priority": spec.get("priority", 0),
        "schedulerName": spec.get("schedulerName", ""),
        "maxInflight": resilience_spec(spec)["maxInflight"],
        "template": spec.get("template") or {},
    }
    blob = json.dumps(shaping, sort_keys=True, separators=(",", ":"))
    return "v" + hashlib.sha256(blob.encode("utf-8")).hexdigest()[:10]


def revisions_status(svc: dict) -> dict:
    """status.revisions with defaults: the durable rollout record.

    ``current`` is the revision the stable fleet runs, ``target`` the
    revision a rollout is moving toward (== current when idle),
    ``previous`` the rollback destination, ``phase`` the state-machine
    position, ``step`` the canary-ladder index, and ``stepStartedAt``
    the controller-clock time the step's analysis window opened. The
    record lands in status BEFORE any pod is touched (record-FIRST), so
    an interrupted rollout re-enters idempotently.

    ``snapshots`` maps revision -> the spec that minted it, so rollback
    can regenerate previous-revision pods after the live spec has moved
    on. ``aborted`` pins the revision a failed analysis rolled back
    from: the controller will not re-attempt it until the spec changes
    again (sticky abort). ``held`` marks a failed analysis frozen in
    place because ``autoRollback`` is off.
    """
    rev = (svc.get("status") or {}).get("revisions")
    rev = rev if isinstance(rev, dict) else {}
    snaps = rev.get("snapshots")
    return {
        "current": rev.get("current", ""),
        "target": rev.get("target", ""),
        "previous": rev.get("previous", ""),
        "phase": rev.get("phase", PHASE_IDLE),
        "step": rev.get("step", 0),
        "stepStartedAt": rev.get("stepStartedAt", 0.0),
        "snapshots": snaps if isinstance(snaps, dict) else {},
        "aborted": rev.get("aborted", ""),
        "held": bool(rev.get("held", False)),
    }


def model_spec(spec: dict) -> dict:
    m = spec.get("model")
    m = m if isinstance(m, dict) else {}
    return {
        "name": m.get("name", "model"),
        "ref": m.get("ref", ""),           # zoo model[@checkpoint_dir]
        "promptLen": m.get("promptLen", 128),
        "maxNewTokens": m.get("maxNewTokens", 32),
        "decodeSlots": m.get("decodeSlots", 8),
        "continuousBatching": bool(m.get("continuousBatching", True)),
        "paramDtype": m.get("paramDtype", ""),
    }


def new_jaxservice(
    name: str,
    namespace: str = "default",
    *,
    model: str = "gpt-125m",
    model_name: str = "chat",
    min_replicas: int = 1,
    max_replicas: int | None = None,
    port: int = DEFAULT_PORT,
    accelerator: str | None = None,
    topology: str | None = None,
    chips_per_replica: int = 4,
    priority: int = 0,
    gang_schedule: bool = False,
    target_queue_depth: int = DEFAULT_TARGET_QUEUE_DEPTH,
    target_tokens_per_sec: float = DEFAULT_TARGET_TOKENS_PER_SEC,
    up_stabilization_s: float = DEFAULT_UP_STABILIZATION_S,
    down_stabilization_s: float = DEFAULT_DOWN_STABILIZATION_S,
) -> dict:
    """Convenience constructor (the new_jaxjob analogue)."""
    spec: dict = {
        "model": {"name": model_name, "ref": model},
        "replicas": {"min": min_replicas,
                     "max": max_replicas if max_replicas is not None
                     else min_replicas},
        "port": port,
        "autoscaling": {
            "targetQueueDepth": target_queue_depth,
            "targetTokensPerSec": target_tokens_per_sec,
            "scaleUpStabilizationSeconds": up_stabilization_s,
            "scaleDownStabilizationSeconds": down_stabilization_s,
        },
    }
    if priority:
        spec["priority"] = priority
    if gang_schedule:
        spec["schedulerName"] = SCHEDULER_NAME
    if accelerator:
        spec["tpu"] = {
            "accelerator": accelerator,
            "topology": topology or "",
            "chipsPerWorker": chips_per_replica,
        }
    return ob.new_object(API_VERSION, KIND, name, namespace, spec=spec)


def _posint(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and v >= 1


def _posnum(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and v > 0)


def validate(svc: dict) -> list[str]:
    """Spec validation; problems become Degraded-condition reasons."""
    errs: list[str] = []
    spec = svc.get("spec") or {}
    model = model_spec(spec)
    if not model["ref"] or not isinstance(model["ref"], str):
        errs.append("spec.model.ref must name a zoo model "
                    "(e.g. 'gpt-125m' or 'gpt-125m@/ckpt/dir')")
    for k in ("promptLen", "maxNewTokens", "decodeSlots"):
        if not _posint(model[k]):
            errs.append(f"spec.model.{k} must be a positive int, "
                        f"got {model[k]!r}")
    reps = replicas_spec(spec)
    mn, mx = reps["min"], reps["max"]
    if not _posint(mn):
        errs.append(f"spec.replicas.min must be a positive int, got {mn!r}")
    if not _posint(mx):
        errs.append(f"spec.replicas.max must be a positive int, got {mx!r}")
    if _posint(mn) and _posint(mx) and mn > mx:
        errs.append(f"spec.replicas.min {mn} > max {mx}")
    port = spec.get("port", DEFAULT_PORT)
    if not isinstance(port, int) or not (0 < port < 65536):
        errs.append(f"spec.port invalid: {port!r}")
    prio = spec.get("priority", 0)
    if not isinstance(prio, int) or isinstance(prio, bool):
        errs.append(f"spec.priority must be an int, got {prio!r}")
    auto = autoscaling_spec(spec)
    if not _posint(auto["targetQueueDepth"]):
        errs.append("spec.autoscaling.targetQueueDepth must be a "
                    f"positive int, got {auto['targetQueueDepth']!r}")
    if not _posnum(auto["targetTokensPerSec"]):
        errs.append("spec.autoscaling.targetTokensPerSec must be a "
                    f"positive number, got {auto['targetTokensPerSec']!r}")
    for k in ("scaleUpStabilizationSeconds",
              "scaleDownStabilizationSeconds"):
        v = auto[k]
        if not (isinstance(v, (int, float)) and not isinstance(v, bool)
                and v >= 0):
            errs.append(f"spec.autoscaling.{k} must be a non-negative "
                        f"number, got {v!r}")
    drain = drain_seconds(spec)
    if not (isinstance(drain, (int, float)) and not isinstance(drain, bool)
            and drain >= 0):
        errs.append("spec.drainSeconds must be a non-negative number, "
                    f"got {drain!r}")
    res = resilience_spec(spec)
    if res["defaultBand"] not in BAND_RANK:
        errs.append("spec.resilience.defaultBand must be one of "
                    f"{sorted(BAND_RANK)}, got {res['defaultBand']!r}")
    dl = res["deadlineSeconds"]
    if not (isinstance(dl, (int, float)) and not isinstance(dl, bool)
            and dl >= 0):
        errs.append("spec.resilience.deadlineSeconds must be a "
                    f"non-negative number, got {dl!r}")
    mi = res["maxInflight"]
    if not (isinstance(mi, int) and not isinstance(mi, bool) and mi >= 0):
        errs.append("spec.resilience.maxInflight must be a non-negative "
                    f"int, got {mi!r}")
    roll = rollout_spec(spec)
    if not _posint(roll["maxSurge"]):
        errs.append("spec.rollout.maxSurge must be a positive int, "
                    f"got {roll['maxSurge']!r}")
    mu = roll["maxUnavailable"]
    if not (isinstance(mu, int) and not isinstance(mu, bool) and mu >= 0):
        errs.append("spec.rollout.maxUnavailable must be a non-negative "
                    f"int, got {mu!r}")
    steps = roll["canarySteps"]
    bad_step = any(
        not (isinstance(s, (int, float)) and not isinstance(s, bool)
             and 0 < s <= 1)
        for s in steps)
    if bad_step:
        errs.append("spec.rollout.canarySteps must be fractions in "
                    f"(0, 1], got {steps!r}")
    elif list(steps) != sorted(set(steps)):
        errs.append("spec.rollout.canarySteps must be strictly "
                    f"increasing, got {steps!r}")
    elif steps[-1] != 1:
        errs.append("spec.rollout.canarySteps must end at 1.0 (full "
                    f"weight), got {steps!r}")
    if not _posnum(roll["analysisWindowSeconds"]):
        errs.append("spec.rollout.analysisWindowSeconds must be a "
                    "positive number, got "
                    f"{roll['analysisWindowSeconds']!r}")
    tpu = spec.get("tpu") or {}
    topology = tpu.get("topology") or ""
    if topology:
        try:
            parse_topology(topology)
        except ValueError:
            errs.append(f"spec.tpu.topology {topology!r} is not NxM[xK]")
    return errs


def crd_manifest() -> dict:
    """The CustomResourceDefinition applied by tpctl."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"jaxservices.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "listKind": "JAXServiceList",
                "plural": "jaxservices",
                "singular": "jaxservice",
                "shortNames": ["jsvc"],
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "x-kubernetes-preserve-unknown-fields": True,
                        }
                    },
                }
            ],
        },
    }
