"""jaxrt — the in-pod training runtime.

The reference delegates all model math to opaque payload images and only
ships the pod-side glue (launcher.py env decoding, openmpi sidecar
lifecycle). Here the runtime is in-scope: launcher, trainer loop, MFU
meter, checkpointing, and metrics are part of the framework.
"""
