from kubeflow_tpu.control.mains import run_controller
from kubeflow_tpu.control.tensorboard.controller import build_controller

run_controller("tensorboard-controller", lambda client, args: build_controller(client))
