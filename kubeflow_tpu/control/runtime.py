"""Controller engine — the controller-runtime Manager/Controller analogue.

Every reference operator follows the same kubebuilder shape: a Reconcile
function driven by watches on the primary CRD plus Owns() on generated
children, with mapped watches for side objects (e.g. the notebook
controller watches Pods via the `notebook-name` label and Events via
involvedObject — notebook_controller.go:519-613). This module provides
that machinery once:

- ``Controller``: a named workqueue of reconcile keys, fed by watches;
  dedup, rate-limited retry on error, RequeueAfter support.
- ``watches(kind)``, ``owns(kind)``, ``maps(kind, fn)`` registration.
- Two drive modes: ``run()`` (threads + watch streams, production) and
  ``run_until_idle()`` (synchronous drain for hermetic tests — processes
  events deterministically without sleeping, the fast path envtest never
  gave the reference).
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Callable

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.obs import trace as obs_trace
from kubeflow_tpu.runtime.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("kubeflow_tpu.control")


@dataclasses.dataclass(frozen=True)
class Request:
    namespace: str
    name: str


@dataclasses.dataclass
class Result:
    requeue_after: float | None = None  # seconds


class Reconciler:
    """Interface: reconcile(client, req) -> Result | None."""

    def reconcile(self, client, req: Request) -> Result | None:  # pragma: no cover
        raise NotImplementedError


@dataclasses.dataclass
class _Source:
    api_version: str
    kind: str
    mapper: Callable[[dict], list[Request]] | None  # None → identity (primary)


def _owner_mapper(owner_kind: str) -> Callable[[dict], list[Request]]:
    def fn(obj: dict) -> list[Request]:
        ref = ob.controller_owner(obj)
        if ref and ref.get("kind") == owner_kind:
            return [Request(ob.meta(obj).get("namespace") or "", ref["name"])]
        return []

    return fn


class Controller:
    MAX_RETRIES = 8
    # Error-retry backoff: min(RETRY_BASE * 2^attempt, RETRY_CAP).
    # Class attrs so harnesses can pin the schedule (a chaos replay sets
    # RETRY_BASE=0 to make retry timing wall-clock-free).
    RETRY_BASE = 0.01
    RETRY_CAP = 5.0
    # Conflict (409) retry window: two writers ping-ponging conflicts
    # with IMMEDIATE re-enqueue spin at CPU speed against the apiserver;
    # a small jittered delay desynchronizes them while staying far below
    # human-visible latency. (0, 0) re-enables immediate retry for
    # deterministic harnesses.
    CONFLICT_RETRY = (0.01, 0.05)

    def __init__(self, name: str, client, reconciler: Reconciler,
                 registry: MetricsRegistry | None = None, tracer=None):
        self.name = name
        self.client = client
        self.reconciler = reconciler
        self.registry = registry if registry is not None else REGISTRY
        self.tracer = tracer if tracer is not None else obs_trace.TRACER
        self._sources: list[_Source] = []
        self._primary: tuple[str, str] | None = None
        self._queue: dict[Request, None] = {}  # ordered set
        self._delayed: list[tuple[float, Request]] = []
        self._failures: dict[Request, int] = {}
        # first-enqueue time per key, for the workqueue-wait histogram
        # and the reconcile span's queue_wait_s attribute (the answer to
        # "why did my job take 40s to start" when the queue was deep)
        self._enqueued_at: dict[Request, float] = {}
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._streams: list = []
        self._resources: list = []  # lifecycle-coupled (see uses())
        self._elector = None  # set by with_leader_election
        # Pre-register the outcome counter at 0 for every result label:
        # rate()/increase() need two samples to see a delta, so a series
        # born AT its first error (value 1, then flat) never shows an
        # increase — the fleet plane's ReconcileErrorRate alert would be
        # blind to a controller's first failure window.
        for result in ("success", "requeue", "error", "conflict"):
            self.registry.counter_inc(
                "controller_reconcile_total", help_="reconciles by outcome",
                by=0.0, controller=self.name, result=result)

    # -- registration (kubebuilder For/Owns/Watches analogues) -------------

    def watches_primary(self, api_version: str, kind: str) -> "Controller":
        self._primary = (api_version, kind)
        self._sources.append(_Source(api_version, kind, None))
        return self

    def owns(self, api_version: str, kind: str) -> "Controller":
        assert self._primary, "call watches_primary first"
        self._sources.append(_Source(api_version, kind, _owner_mapper(self._primary[1])))
        return self

    def maps(
        self, api_version: str, kind: str, fn: Callable[[dict], list[Request]]
    ) -> "Controller":
        self._sources.append(_Source(api_version, kind, fn))
        return self

    def uses(self, resource) -> "Controller":
        """Attach a lifecycle-coupled resource — e.g. a ``ClusterCache``
        whose watch pumps must run exactly as long as this controller's
        threads do. ``run()`` calls each resource's ``start()``,
        ``stop()`` its ``stop()``. Hermetic ``run_until_idle`` drives
        such resources synchronously instead (the reconciler calls
        ``refresh()``), so no threads are started for them there."""
        self._resources.append(resource)
        return self

    # -- queue --------------------------------------------------------------

    def enqueue(self, req: Request) -> None:
        with self._cv:
            self._queue[req] = None
            self._enqueued_at.setdefault(req, time.monotonic())
            self._report_depth_locked()
            self._cv.notify_all()

    def enqueue_after(self, req: Request, delay: float) -> None:
        with self._cv:
            self._delayed.append((time.monotonic() + delay, req))
            self._cv.notify_all()

    def _report_depth_locked(self) -> None:
        """Publish the depth gauge WHILE holding _cv: read+report must be
        atomic against other reporters, or a stale depth published late
        overwrites a newer one and the gauge sticks wrong on an idle
        queue. (_cv -> registry lock only, never the reverse.)"""
        self.registry.gauge(
            "workqueue_depth", len(self._queue),
            help_="reconcile keys queued, per controller",
            controller=self.name)

    def _dispatch(self, src: _Source, obj: dict) -> None:
        if src.mapper is None:
            m = ob.meta(obj)
            self.enqueue(Request(m.get("namespace") or "", m["name"]))
        else:
            for req in src.mapper(obj):
                self.enqueue(req)

    def _pump_delayed(self) -> float | None:
        """Move due delayed items into the queue; return next due in secs."""
        now = time.monotonic()
        due = [r for t, r in self._delayed if t <= now]
        self._delayed = [(t, r) for t, r in self._delayed if t > now]
        for r in due:
            self._queue[r] = None
            # queue wait counts from (re)entry into the hot queue, not
            # from when the requeue-after timer was armed
            self._enqueued_at.setdefault(r, now)
        if self._delayed:
            return max(0.0, min(t for t, _ in self._delayed) - now)
        return None

    def _process_one(self, req: Request) -> None:
        now = time.monotonic()
        with self._cv:
            t_enq = self._enqueued_at.pop(req, None)
            attempt = self._failures.get(req, 0) + 1
            self._report_depth_locked()
        wait = max(now - t_enq, 0.0) if t_enq is not None else 0.0
        self.registry.histogram(
            "workqueue_wait_seconds", wait,
            help_="time a reconcile key spent queued before processing",
            controller=self.name)
        result = "success"
        t0 = time.perf_counter()
        # nothing may sit between begin() and the try whose finally
        # finishes the span — a raise in that window orphans it (RES704)
        span = self.tracer.begin(
            "reconcile", controller=self.name, namespace=req.namespace,
            object=req.name, attempt=attempt, queue_wait_s=round(wait, 6))
        try:
            res = self.reconciler.reconcile(self.client, req)
            with self._cv:
                self._failures.pop(req, None)
            if res and res.requeue_after:
                result = "requeue"
                self.enqueue_after(req, res.requeue_after)
        except ob.Conflict:
            # optimistic-concurrency loser: benign retry after a small
            # jittered delay (immediate re-enqueue lets two writers
            # ping-pong 409s at CPU speed — a conflict hot-spin)
            result = "conflict"
            lo, hi = self.CONFLICT_RETRY
            delay = random.uniform(lo, hi) if hi > 0 else 0.0
            if delay > 0:
                self.enqueue_after(req, delay)
            else:
                self.enqueue(req)
        except Exception as e:
            result = "error"
            span.status = "ERROR"
            span.error = f"{type(e).__name__}: {e}"
            with self._cv:
                n = self._failures.get(req, 0) + 1
                self._failures[req] = n
            self.registry.counter_inc(
                "controller_reconcile_retries_total",
                help_="reconciles retried after an error",
                controller=self.name)
            if n <= self.MAX_RETRIES:
                log.exception("%s: reconcile %s failed (attempt %d)", self.name, req, n)
                self.enqueue_after(
                    req, min(self.RETRY_BASE * (2 ** n), self.RETRY_CAP))
            else:
                log.error("%s: reconcile %s dropped after %d attempts", self.name, req, n)
                # dropping ends this failure streak: a later event-driven
                # reconcile of the same key starts from attempt 1 with a
                # full retry budget (and a truthful span attribute)
                with self._cv:
                    self._failures.pop(req, None)
        finally:
            span.attrs["result"] = result
            self.tracer.finish(span)
            self.registry.counter_inc(
                "controller_reconcile_total",
                help_="reconciles by outcome",
                controller=self.name, result=result)
            self.registry.histogram(
                "controller_reconcile_seconds", time.perf_counter() - t0,
                help_="reconcile latency", controller=self.name)

    # -- production mode ----------------------------------------------------

    def run(self, workers: int = 1) -> "Controller":
        """Start watch threads + worker threads; returns immediately."""
        for resource in self._resources:
            resource.start()
        for src in self._sources:
            stream = self.client.watch(src.api_version, src.kind)
            with self._cv:
                self._streams.append(stream)
            t = threading.Thread(
                target=self._watch_loop, args=(src, stream), daemon=True,
                name=f"{self.name}-watch-{src.kind}",
            )
            t.start()
        # seed with existing objects (informer initial list)
        for src in self._sources:
            for obj in self.client.list(src.api_version, src.kind):
                self._dispatch(src, obj)
        for i in range(workers):
            threading.Thread(
                target=self._worker, daemon=True, name=f"{self.name}-worker-{i}"
            ).start()
        return self

    def _watch_loop(self, src: _Source, stream) -> None:
        """Pump one watch stream into the queue — and OUTLIVE it. A
        stream that raises (or ends while we are still running) would
        otherwise silently kill this thread and the controller would
        never see another {kind} event; instead resubscribe and relist
        (the level-triggered resync) after a short pause."""
        while not self._stop.is_set():
            try:
                for ev in stream:
                    if self._stop.is_set():
                        return
                    self._dispatch(src, ev.object)
            except Exception:
                log.exception("%s: watch stream for %s failed; resubscribing",
                              self.name, src.kind)
            if self._stop.is_set():
                return
            self._stop.wait(0.2)
            try:
                new_stream = self.client.watch(src.api_version, src.kind)
            except Exception:
                log.exception("%s: watch resubscribe for %s failed; will "
                              "retry", self.name, src.kind)
                continue
            # REPLACE the dead stream's slot (never append): a
            # long-lived controller resubscribing across apiserver
            # restarts must not grow _streams — or leak the dead
            # stream's socket — forever
            with self._cv:
                try:
                    self._streams.remove(stream)
                except ValueError:
                    pass
                self._streams.append(new_stream)
            try:
                stream.stop()
            except Exception:
                pass
            stream = new_stream
            try:
                for obj in self.client.list(src.api_version, src.kind):
                    self._dispatch(src, obj)
            except Exception:
                log.exception("%s: post-resubscribe relist for %s failed; "
                              "stream is live, next events resync",
                              self.name, src.kind)

    def _worker(self) -> None:
        while not self._stop.is_set():
            if not self._may_lead():
                # standby: watches stay subscribed, work queues up, but
                # nothing reconciles until the lease is ours
                self._stop.wait(0.5)
                continue
            req = None
            with self._cv:
                timeout = self._pump_delayed()
                if not self._queue:
                    # bounded wait, then fall through to the outer loop:
                    # an IDLE leader must keep renewing its lease (the
                    # elector caches, so this is a local check most
                    # rounds, one apiserver renew per lease_seconds/3)
                    self._cv.wait(timeout=min(timeout, 0.2) if timeout else 0.2)
                    self._pump_delayed()
                if self._queue:
                    req = next(iter(self._queue))
                    del self._queue[req]
            if req is None:
                continue
            if not self._may_lead():
                # lost the lease between pop and process: hand the item
                # back rather than reconcile as a deposed leader
                self.enqueue(req)
                continue
            self._process_one(req)

    def stop(self) -> None:
        self._stop.set()
        for s in self._streams:
            s.stop()
        for resource in self._resources:
            resource.stop()
        with self._cv:
            self._cv.notify_all()

    # -- hermetic test mode -------------------------------------------------

    def _drain_streams(self) -> None:
        """Pull pending watch events synchronously (test mode)."""
        for stream in self._streams:
            if not hasattr(stream, "poll"):
                continue
            while True:
                ev = stream.poll()
                if ev is None:
                    break
                for src in self._sources:
                    if (src.api_version, src.kind) == (
                        ev.object.get("apiVersion"),
                        ev.object.get("kind"),
                    ):
                        self._dispatch(src, ev.object)

    def with_leader_election(self, elector) -> "Controller":
        """Only reconcile while holding the lease (the reference's
        --enable-leader-election manager capability): watches keep
        accumulating work so a standby is current the moment it takes
        over, but _process_one runs only on the leader."""
        self._elector = elector
        return self

    def _may_lead(self) -> bool:
        return self._elector is None or self._elector.try_acquire()

    def run_until_idle(self, max_rounds: int = 200, advance_delayed: bool = False) -> int:
        """Synchronously drain the queue (and watch events) until no work
        remains. Returns the number of reconciles performed. With
        advance_delayed, due-in-the-future requeues fire immediately once
        per drain (so culling/requeue paths are testable without sleeping).
        """
        done = 0
        if not self._may_lead():
            self._drain_streams()  # stay current on standby
            return 0
        for _ in range(max_rounds):
            self._drain_streams()
            # queue surgery under the condition lock: the drain is
            # single-threaded by contract, but nothing stops a caller
            # from draining while run() workers are live, and unlocked
            # dict/list mutation here would tear their state
            with self._cv:
                self._pump_delayed()
                if not self._queue and advance_delayed and self._delayed:
                    now = time.monotonic()
                    for _, r in self._delayed:
                        self._queue[r] = None
                        self._enqueued_at.setdefault(r, now)
                    self._delayed = []
                    advance_delayed = False  # one synthetic advance per call
                if not self._queue:
                    break
                req = next(iter(self._queue))
                del self._queue[req]
            # reconcile outside the lock: holding _cv through a reconcile
            # would serialize this drain against every run() worker
            self._process_one(req)
            done += 1
        return done


class Manager:
    """Holds controllers sharing one client; mirrors ctrl.Manager."""

    def __init__(self, client):
        self.client = client
        self.controllers: list[Controller] = []

    def add(self, controller: Controller) -> Controller:
        self.controllers.append(controller)
        return controller

    def start(self, workers: int = 1) -> None:
        for c in self.controllers:
            c.run(workers=workers)

    def stop(self) -> None:
        for c in self.controllers:
            c.stop()

    def run_until_idle(self, rounds: int = 10) -> int:
        """Drain all controllers to a fixpoint (cross-controller cascades:
        e.g. Profile creates a Namespace that another controller watches)."""
        total = 0
        for _ in range(rounds):
            did = 0
            for c in self.controllers:
                did += c.run_until_idle()
            total += did
            if did == 0:
                break
        return total


def seed_controller(c: Controller) -> Controller:
    """Test-mode wiring: subscribe watches (poll-driven) + initial list,
    without starting threads. Use with run_until_idle()."""
    for src in c._sources:
        stream = c.client.watch(src.api_version, src.kind)
        with c._cv:
            c._streams.append(stream)
    for src in c._sources:
        for obj in c.client.list(src.api_version, src.kind):
            c._dispatch(src, obj)
    return c
