"""Jupyter-web-app frontend: the notebook spawner UI.

The reference JWA ships an Angular/JS frontend (jupyter-web-app/frontend)
over its Flask backend; this is the same spawner as one dependency-free
page served by the backend itself: notebook list with status/connect/
delete, and a create form (name/image/cpu/memory/TPU chips) that POSTs
the form shape `webapps/jwa.py` expects (`notebook_from_form`). TPU
resources replace the reference's GPU dropdown (the utils.py:262 swap
point, surfaced in the UI).
"""

from __future__ import annotations

from kubeflow_tpu.utils.httpd import HttpReq, HttpResp

PAGE = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>Notebooks — kubeflow-tpu</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 0; background: #f5f6f8; }
  header { background: #1a73e8; color: #fff; padding: 10px 20px;
           display: flex; gap: 16px; align-items: center; }
  header h1 { font-size: 18px; margin: 0; flex: 1; }
  main { max-width: 950px; margin: 20px auto; display: grid; gap: 16px; }
  .card { background: #fff; border-radius: 8px; padding: 16px;
          box-shadow: 0 1px 3px rgba(0,0,0,.15); }
  table { width: 100%; border-collapse: collapse; font-size: 14px; }
  th, td { text-align: left; padding: 6px 8px; border-bottom: 1px solid #eee; }
  select, input, button { font-size: 14px; padding: 6px 8px; margin: 2px 0;
                          border: 1px solid #ccc; border-radius: 4px; }
  button { cursor: pointer; background: #fff; }
  .primary { background: #1a73e8; color: #fff; border: none; }
  .muted { color: #777; font-size: 12px; }
  form { display: grid; grid-template-columns: repeat(3, 1fr); gap: 8px; }
  form label { display: flex; flex-direction: column; font-size: 12px;
               color: #555; }
</style>
</head>
<body>
<header>
  <h1>Notebooks</h1>
  <select id="ns"></select>
</header>
<main>
  <div class="card">
    <h2>New notebook</h2>
    <form id="spawn">
      <label>Name <input name="name" required></label>
      <label>Image <select name="image" id="images"></select></label>
      <label>CPU <input name="cpu" value="0.5"></label>
      <label>Memory <input name="memory" value="1Gi"></label>
      <label>TPU chips <select name="tpu" id="tpus"></select></label>
      <label>&nbsp;<button class="primary" type="submit">Launch</button></label>
    </form>
    <p class="muted" id="msg"></p>
  </div>
  <div class="card">
    <h2>Running</h2>
    <table>
      <thead><tr><th>Name</th><th>Status</th><th>Image</th><th></th></tr></thead>
      <tbody id="list"><tr><td class="muted" colspan="4">loading</td></tr></tbody>
    </table>
  </div>
</main>
<script>
const $ = (id) => document.getElementById(id);
const api = (p, opt) => fetch(p, opt).then(r => {
  if (!r.ok) throw new Error('HTTP ' + r.status);
  return r.json();
});

let config = {};

async function init() {
  config = (await api('/api/config')).config || {};
  for (const img of (config.image?.options || [])) {
    const o = document.createElement('option');
    o.value = o.textContent = img;
    $('images').appendChild(o);
  }
  for (const n of (config.tpu?.options || [0])) {
    const o = document.createElement('option');
    o.value = o.textContent = n;
    $('tpus').appendChild(o);
  }
  const nss = (await api('/api/namespaces')).namespaces || [];
  for (const ns of nss) {
    const o = document.createElement('option');
    o.value = o.textContent = ns;
    $('ns').appendChild(o);
  }
  if (nss.length) await refresh();
}

async function refresh() {
  const ns = $('ns').value;
  const out = await api('/api/namespaces/' + ns + '/notebooks');
  const tb = $('list');
  tb.innerHTML = '';
  for (const nb of out.notebooks || []) {
    // DOM-built rows: names/images are never interpolated into HTML
    const tr = document.createElement('tr');
    for (const text of [nb.name, (nb.status && nb.status.phase) || 'unknown',
                        nb.image || '']) {
      const td = document.createElement('td');
      td.textContent = text;
      tr.appendChild(td);
    }
    const td = document.createElement('td');
    const a = document.createElement('a');
    a.href = '/notebook/' + encodeURIComponent(ns) + '/' +
             encodeURIComponent(nb.name) + '/';
    a.textContent = 'connect';
    const del = document.createElement('button');
    del.textContent = 'delete';
    del.addEventListener('click', async () => {
      await fetch('/api/namespaces/' + encodeURIComponent(ns) +
                  '/notebooks/' + encodeURIComponent(nb.name),
                  {method: 'DELETE'});
      refresh();
    });
    td.append(a, ' ', del);
    tr.appendChild(td);
    tb.appendChild(tr);
  }
  if (!tb.children.length)
    tb.innerHTML = '<tr><td class="muted" colspan="4">none</td></tr>';
}

$('ns').addEventListener('change', refresh);
$('spawn').addEventListener('submit', async (e) => {
  e.preventDefault();
  const ns = $('ns').value;
  const form = Object.fromEntries(new FormData(e.target).entries());
  form.tpu = parseInt(form.tpu || '0', 10);
  const r = await fetch('/api/namespaces/' + ns + '/notebooks', {
    method: 'POST',
    headers: {'Content-Type': 'application/json'},
    body: JSON.stringify(form),
  });
  $('msg').textContent = r.ok ? 'created' : 'failed: HTTP ' + r.status;
  if (r.ok) refresh();
});

init().catch(e => { $('msg').textContent = String(e); });
setInterval(() => refresh().catch(() => {}), 10000);
</script>
</body>
</html>
"""


def page(req: HttpReq) -> HttpResp:
    return HttpResp(200, PAGE.encode(), "text/html")


def add_ui_routes(router) -> None:
    router.route("GET", "/", page)
    router.route("GET", "/spawner", page)
