"""MoE dispatch: sparse (sort + all-to-all) vs dense (one-hot einsum)
oracle, dispatch diagnostics, and the expert-as-batch-axis regime.

The dense path is the correctness oracle (SURVEY.md §2.5: the TPU-native
EP design is "all-to-all dispatch over ICI"; the dense einsum is the
GShard formulation GSPMD can partition on any mesh). The sparse path must
produce the same module output whenever no token overflows capacity —
the two differ only in WHICH overflow tokens drop (per-row vs per-shard
arrival order), so tests pin ample capacity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.parallel.mesh import MeshSpec, build_mesh
from kubeflow_tpu.runtime.trainer import TrainConfig, Trainer


def lm_cfg(**kw):
    base = dict(
        model="moe-test",
        task="lm",
        global_batch=8,
        seq_len=16,
        vocab_size=256,
        optimizer="adamw",
        learning_rate=1e-3,
        total_steps=2,
        warmup_steps=1,
    )
    base.update(kw)
    return TrainConfig.from_dict(base)


@pytest.fixture
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]


def _one_step_loss(cfg, devs):
    mesh = build_mesh(cfg.mesh, devices=devs)
    trainer = Trainer(cfg, mesh=mesh)
    state = trainer.init_state()
    state, m = trainer.train_step(state, next(trainer.data_iter()))
    return float(m["loss"]), m


def test_sparse_matches_dense_on_ep_mesh(devices8):
    """Same seed, same tokens, ample capacity: the sparse all-to-all
    path must reproduce the dense oracle's loss on a dp x ep mesh."""
    mesh = MeshSpec(data=2, expert=4)
    dense_cfg = lm_cfg(model_kwargs={"moe_impl": "dense"}, mesh=mesh)
    sparse_cfg = lm_cfg(model_kwargs={"moe_impl": "sparse"}, mesh=mesh)
    loss_d, _ = _one_step_loss(dense_cfg, devices8)
    loss_s, m_s = _one_step_loss(sparse_cfg, devices8)
    # bf16 forward, different contraction orders: small tolerance
    assert abs(loss_d - loss_s) < 5e-2, (loss_d, loss_s)
    assert np.isfinite(loss_s)


def test_sparse_reports_dispatch_diagnostics(devices8):
    cfg = lm_cfg(model_kwargs={"moe_impl": "sparse"},
                 mesh=MeshSpec(data=2, expert=4))
    _, m = _one_step_loss(cfg, devices8)
    assert 0.0 < float(m["moe_fill"]) <= 1.0, m
    assert 0.0 <= float(m["moe_drop"]) < 1.0, m


def test_dense_reports_dispatch_diagnostics(devices8):
    cfg = lm_cfg(model_kwargs={"moe_impl": "dense"},
                 mesh=MeshSpec(data=2, expert=4))
    _, m = _one_step_loss(cfg, devices8)
    assert 0.0 < float(m["moe_fill"]) <= 1.0, m


def test_auto_uses_sparse_on_pure_ep_mesh(devices8):
    """moe_impl=auto on dcn/data/expert-only meshes takes the sparse
    path (observable: sparse + dense diverge once tokens overflow, but
    both must train finitely either way — here just assert it runs and
    the diagnostics exist, which only the instrumented paths emit)."""
    cfg = lm_cfg(mesh=MeshSpec(data=4, expert=2))
    loss, m = _one_step_loss(cfg, devices8)
    assert np.isfinite(loss)
    assert "moe_fill" in m


def test_sparse_single_device_no_mesh_matches_dense():
    """ep=1, no mesh: sparse degenerates to local sort+scatter and must
    match the dense oracle closely (same tokens kept at high capacity)."""
    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.ops import moe as moe_mod

    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (2, 16), 0, 256)

    outs = {}
    for impl in ("dense", "sparse"):
        model = get_model("moe-test", moe_impl=impl)
        # force the sparse branch decision even without a mesh by
        # monkeypatching the gate: no mesh means _sparse_ok is False for
        # "auto"/"sparse" (shard_map needs a mesh), so call the kernel
        # directly below instead for the no-mesh case.
        variables = model.init(jax.random.PRNGKey(1), tokens, train=True)
        out = model.apply(variables, tokens, train=True)
        outs[impl] = np.asarray(out, np.float32)
    # no mesh -> both configs ran the dense path; sanity equality
    np.testing.assert_allclose(outs["dense"], outs["sparse"], rtol=0, atol=0)

    # now the sparse kernel itself vs the dense math on one shard
    cfg = get_model("moe-test").cfg
    d, e, k = cfg.d_model, cfg.n_experts, cfg.expert_top_k
    t = 32
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (t, d), jnp.float32).astype(cfg.dtype)
    gate_idx = jax.random.randint(key, (t, k), 0, e)
    gate_vals = jax.nn.softmax(jax.random.normal(key, (t, k)), axis=-1)
    wg = jax.random.normal(key, (e, d, cfg.d_ff), jnp.float32) * 0.02
    wu = jax.random.normal(key, (e, d, cfg.d_ff), jnp.float32) * 0.02
    wd = jax.random.normal(key, (e, cfg.d_ff, d), jnp.float32) * 0.02

    y, fill, routed, slots = moe_mod.sparse_dispatch_mlp(
        cfg, x, gate_vals, gate_idx, wg, wu, wd, capacity_factor=8.0)
    assert int(routed) == t * k
    assert int(fill) == t * k  # ample capacity: nothing drops
    assert int(slots) >= int(fill)

    # dense reference: run each (token, slot) through its expert
    xin = x.astype(jnp.float32)
    y_ref = np.zeros((t, d), np.float32)
    for ti in range(t):
        for ki in range(k):
            ei = int(gate_idx[ti, ki])
            g = jax.nn.silu(xin[ti] @ wg[ei]) * (xin[ti] @ wu[ei])
            y_ref[ti] += float(gate_vals[ti, ki]) * np.asarray(
                (g @ wd[ei]), np.float32)
    np.testing.assert_allclose(np.asarray(y, np.float32), y_ref,
                               rtol=0.1, atol=0.05)


def test_capacity_factor_flows_from_config(devices8):
    """moe_capacity_factor is the tuning knob against the fill/drop
    diagnostics: a tight factor must visibly raise fill and drop."""
    loose = lm_cfg(model_kwargs={"moe_capacity_factor": 8.0},
                   mesh=MeshSpec(data=4, expert=2))
    tight = lm_cfg(model_kwargs={"moe_capacity_factor": 0.5},
                   mesh=MeshSpec(data=4, expert=2))
    _, m_loose = _one_step_loss(loose, devices8)
    _, m_tight = _one_step_loss(tight, devices8)
    assert float(m_loose["moe_drop"]) == 0.0
    assert float(m_tight["moe_fill"]) > float(m_loose["moe_fill"])
    assert float(m_tight["moe_drop"]) > 0.0
