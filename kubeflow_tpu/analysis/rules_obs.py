"""tpulint observability rules: OBS301 wall-clock duration math,
OBS302 metrics-catalog drift.

``time.time()`` is wall clock: NTP slew/step can make consecutive
readings go backwards or jump, so a latency computed as
``time.time() - t0`` can be negative or wildly wrong — and those are
exactly the numbers the span pipeline and the Prometheus histograms
publish. Duration math must use ``time.perf_counter()`` (monotonic,
high resolution); ``obs/trace.py`` converts perf_counter readings to
epoch timestamps through a single module-level wall anchor.

What fires: a subtraction whose operand is a ``time.time()`` call, or a
name bound to one in the same scope. What stays silent (FP pins in
tests/test_tpulint.py): deadline arithmetic (``time.time() + ttl``),
expiry comparisons (``exp < time.time()``), plain timestamping, and all
``perf_counter``/``monotonic`` math.
"""

from __future__ import annotations

import ast
import fnmatch
import pathlib
import re
from typing import Iterator

from kubeflow_tpu.analysis.core import (
    Finding, Module, ProgramRule, Rule, dotted, register,
)


def _time_time_aliases(module: Module) -> set[str]:
    """Dotted spellings that resolve to time.time in this module."""
    aliases = {"time.time"}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    aliases.add(a.asname or "time")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time" and a.asname:
                    aliases.add(f"{a.asname}.time")
    return aliases


@register
class WallClockDuration(Rule):
    id = "OBS301"
    name = "wall-clock-duration"
    short = "time.time() used to measure a duration; use time.perf_counter()"

    def check(self, module: Module) -> Iterator[Finding]:
        aliases = _time_time_aliases(module)

        def is_time_time(node: ast.AST) -> bool:
            return (isinstance(node, ast.Call)
                    and dotted(node.func) in aliases)

        # names bound to a time.time() reading, keyed by enclosing
        # function (None = module level) so an unrelated local called
        # `t0` in another function never taints this one
        tainted: dict[ast.AST | None, set[str]] = {}
        for node in ast.walk(module.tree):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign) and is_time_time(node.value):
                targets = node.targets
            elif (isinstance(node, ast.AnnAssign) and node.value is not None
                    and is_time_time(node.value)):
                targets = [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    scope = module.enclosing_function(node)
                    tainted.setdefault(scope, set()).add(tgt.id)

        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            scope = module.enclosing_function(node)
            names = tainted.get(scope, set()) | tainted.get(None, set())

            def wallish(operand: ast.AST) -> bool:
                return is_time_time(operand) or (
                    isinstance(operand, ast.Name) and operand.id in names)

            if wallish(node.left) or wallish(node.right):
                yield self.finding(
                    module, node,
                    "duration computed from time.time(); wall clock can "
                    "step/slew under NTP — use time.perf_counter()")


# -- OBS302: metrics-catalog drift -------------------------------------------

# A catalog row is a markdown TABLE row inside the "## Metrics catalog"
# section whose first cell is a backtick-quoted series name:
# "| `metric_name` | ...". Wildcards (`*`) cover dynamic families
# (f-string names like jaxrt_eval_{k}). Tables in OTHER sections
# (events, alert pack, goodput buckets) are not catalog rows.
_CATALOG_ROW_RE = re.compile(
    r"^\|\s*`(?P<name>[a-zA-Z_:][a-zA-Z0-9_*:]*)`")
_CATALOG_HEADING_RE = re.compile(r"^##\s+Metrics catalog\b")
_HEADING_RE = re.compile(r"^##\s")
CATALOG_DOC = "docs/observability.md"

# Registration spellings this repo uses — MetricsRegistry methods, the
# memoized prometheus_client helpers, and direct prom.<Kind> ctors.
_REG_METHODS = frozenset({"gauge", "counter_inc", "histogram"})
_REG_HELPERS = frozenset({"prom_metric", "_prom_metric", "_metric",
                          "_counter"})
_PROM_KINDS = frozenset({"Gauge", "Counter", "Histogram", "Summary"})

# Doc-side (stale row) findings are only provable when the whole tree
# was scanned: the sentinel module must be present AND the scan must
# cover a real slice of the package (a single-file scan of metrics.py
# itself must not declare every other catalog row stale). Corpus tests
# inject catalog_override, which waives the size floor.
_FULL_SCAN_SENTINEL = "kubeflow_tpu.runtime.metrics"
_MIN_FULL_SCAN_MODULES = 10


def _name_pattern(node: ast.AST) -> str | None:
    """First-arg metric name as a literal or an f-string glob
    (``f"jaxrt_eval_{k}"`` -> ``jaxrt_eval_*``). Non-string args
    (helper passthrough params) return None — unknowable statically."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            parts.append(v.value if isinstance(v, ast.Constant) else "*")
        pat = "".join(parts)
        return pat if pat.strip("*") else None
    return None


def _registrations(module: Module) -> Iterator[tuple[ast.AST, str]]:
    """(node, name-or-glob) for every metric registration site."""
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        hit = False
        if isinstance(fn, ast.Attribute):
            if fn.attr in _REG_METHODS or fn.attr in _REG_HELPERS:
                hit = True
            elif (fn.attr in _PROM_KINDS and isinstance(fn.value, ast.Name)
                    and fn.value.id == "prom"):
                hit = True
        elif isinstance(fn, ast.Name) and fn.id in _REG_HELPERS:
            hit = True
        if not hit:
            continue
        pat = _name_pattern(node.args[0])
        if pat:
            yield node, pat


def _patterns_match(a: str, b: str) -> bool:
    """Either glob covering the other counts as a match (a doc family
    row matches a dynamic code name and vice versa)."""
    return fnmatch.fnmatchcase(a, b) or fnmatch.fnmatchcase(b, a)


@register
class MetricsCatalogDrift(ProgramRule):
    """OBS302: every metric registered under ``kubeflow_tpu/`` must
    have a row in the docs/observability.md catalog tables, and every
    catalog row must correspond to a live registration (stale rows are
    drift in the other direction — an operator paging through the
    catalog must be able to trust it)."""

    id = "OBS302"
    name = "metrics-catalog-drift"
    short = ("metric registration and the docs/observability.md catalog "
             "must agree")

    # tests inject catalog text here (the committed doc is the default)
    catalog_override: str | None = None

    def _catalog(self, program) -> tuple[list[tuple[int, str]], str]:
        """-> ([(line, name-or-glob), ...], doc_path)."""
        if self.catalog_override is not None:
            text, path = self.catalog_override, CATALOG_DOC
        else:
            path = self._find_doc(program)
            if path is None:
                return [], CATALOG_DOC
            try:
                text = pathlib.Path(path).read_text()
            except OSError:
                return [], str(path)
        rows = []
        in_catalog = False
        for i, line in enumerate(text.splitlines(), start=1):
            if _CATALOG_HEADING_RE.match(line):
                in_catalog = True
                continue
            if in_catalog and _HEADING_RE.match(line):
                in_catalog = False
            if not in_catalog:
                continue
            m = _CATALOG_ROW_RE.match(line)
            if m:
                rows.append((i, m.group("name")))
        return rows, str(path)

    @staticmethod
    def _find_doc(program) -> str | None:
        """Walk up from any scanned module to the repo's docs/ dir;
        falls back to the installed package's parent."""
        candidates = []
        for module in program.modules.values():
            candidates.append(pathlib.Path(module.path).resolve().parent)
        try:
            import kubeflow_tpu

            candidates.append(
                pathlib.Path(kubeflow_tpu.__file__).resolve().parent.parent)
        except ImportError:  # pragma: no cover - always importable here
            pass
        seen = set()
        for base in candidates:
            for parent in (base, *base.parents):
                if parent in seen:
                    continue
                seen.add(parent)
                doc = parent / CATALOG_DOC
                if doc.is_file():
                    return str(doc)
        return None

    def check_program(self, program) -> Iterator[Finding]:
        regs: list[tuple[Module, ast.AST, str]] = []
        pkg_modules = 0
        for modname, module in sorted(program.modules.items()):
            in_pkg = modname.startswith("kubeflow_tpu.") \
                or modname == "kubeflow_tpu" \
                or "kubeflow_tpu/" in module.path.replace("\\", "/")
            if not in_pkg:
                continue  # tools/bench registrations are not platform API
            pkg_modules += 1
            for node, pat in _registrations(module):
                regs.append((module, node, pat))
        full_scan = _FULL_SCAN_SENTINEL in program.modules and (
            self.catalog_override is not None
            or pkg_modules >= _MIN_FULL_SCAN_MODULES)
        if not regs and not full_scan:
            return
        rows, doc_path = self._catalog(program)
        row_names = [name for _, name in rows]
        for module, node, pat in regs:
            if not any(_patterns_match(pat, row) for row in row_names):
                yield self.finding(
                    module, node,
                    f"metric '{pat}' is registered here but has no row "
                    f"in the {CATALOG_DOC} catalog — document it (or it "
                    "is invisible to operators)")
        # stale doc rows are only provable on a full-package scan
        if not full_scan:
            return
        code_pats = {pat for _, _, pat in regs}
        for line, row in rows:
            if not any(_patterns_match(row, pat) for pat in code_pats):
                yield Finding(
                    self.id, doc_path, line, 0,
                    f"catalog row '{row}' matches no metric registration "
                    "in kubeflow_tpu/ — stale doc row, delete or fix it")
