"""User-facing web app backends (SURVEY.md §2.3).

- ``jwa``       — the Jupyter web app REST backend (reference:
  components/jupyter-web-app/backend): notebook spawner APIs.
- ``dashboard`` — the central dashboard API (reference:
  components/centraldashboard/app): workgroup/env-info/contributor
  endpoints + activity feed + cluster metrics interface.

Frontends are out of scope for parity of *capability*: both reference
UIs talk to exactly these REST surfaces, which is what the E2E tier
exercises programmatically (testing/test_jwa.py drives notebook state
transitions through the same endpoints Selenium clicks through).
"""
