"""Weight-only int8 serving quantization (serving/quant.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.serving.quant import (
    QuantizedModel,
    dequantize_params,
    quantize_params,
)


def test_roundtrip_error_bounded_per_channel():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32) * 3.0
    q = quantize_params({"k": w}, min_size=1)
    assert q["k"]["int8"].dtype == jnp.int8
    assert q["k"]["scale"].shape == (1, 128)
    back = dequantize_params(q, dtype=jnp.float32)["k"]
    # symmetric per-channel: |err| <= scale/2 per element
    err = np.abs(np.asarray(back) - np.asarray(w))
    bound = np.asarray(q["k"]["scale"])[0] / 2 + 1e-6
    assert (err <= bound[None, :]).all()


def test_small_and_1d_leaves_stay_exact():
    tree = {"scale": jnp.ones((16,)), "tiny": jnp.ones((2, 2)),
            "big": jnp.ones((128, 64)), "ints": jnp.zeros((8, 8), jnp.int32)}
    q = quantize_params(tree, min_size=1024)
    assert isinstance(q["big"], dict)          # quantized
    assert q["scale"] is tree["scale"]         # 1-D untouched
    assert q["tiny"] is tree["tiny"]           # below min_size
    assert q["ints"] is tree["ints"]           # integer untouched
    back = dequantize_params(q)
    assert back["scale"] is tree["scale"]


def test_zero_channel_does_not_nan():
    w = jnp.zeros((32, 4096), jnp.float32)
    q = quantize_params({"k": w}, min_size=1)
    back = dequantize_params(q, dtype=jnp.float32)["k"]
    np.testing.assert_array_equal(np.asarray(back), 0.0)


def test_quantized_model_logits_close_to_full_precision():
    from kubeflow_tpu.models.registry import get_model

    model = get_model("transformer-test", dtype=jnp.float32)
    tokens = jnp.arange(32, dtype=jnp.int32).reshape(1, 32) % 250
    variables = model.init(jax.random.PRNGKey(0), tokens, train=False)
    full = model.apply(variables, tokens, train=False)
    qm = QuantizedModel(model, dtype=jnp.float32)
    q = qm.apply(quantize_params(variables, min_size=1), tokens, train=False)
    # weight-only int8: logits drift by quantization noise, not garbage
    corr = np.corrcoef(np.asarray(full).ravel(), np.asarray(q).ravel())[0, 1]
    assert corr > 0.99, corr


def test_int8_lm_generator_end_to_end():
    """The served generate path under param_dtype='int8': valid tokens
    out, int8 actually resident in the served variables."""
    from kubeflow_tpu.serving.server import serve_lm_generator

    served = serve_lm_generator(
        "lm8", "transformer-test", prompt_len=8, max_new_tokens=4,
        param_dtype="int8")
    try:
        out = served.predict([{"tokens": [1, 2, 3]}])
        assert len(out) == 1 and len(out[0]) == 4
        assert all(0 <= int(t) < 256 for t in out[0])
        assert served.signature["param_dtype"] == "int8"
    finally:
        served.close()


def test_int8_with_mesh_rejected():
    import pytest

    from kubeflow_tpu.serving.server import serve_lm_generator

    with pytest.raises(ValueError, match="int8"):
        serve_lm_generator("lm8m", "transformer-test", prompt_len=8,
                           max_new_tokens=4, param_dtype="int8",
                           mesh={"data": 2})


def test_int8_kv_cache_decode_matches_full_precision():
    """kv_cache_dtype='int8': generate() runs the same prefill+decode
    loop with a quantized cache; logits noise stays quantization-sized,
    greedy tokens on a tiny model stay plausible, and the cache leaves
    really are int8."""
    from kubeflow_tpu.models.registry import get_model
    from kubeflow_tpu.runtime.generate import generate, init_cache

    prompt = (jnp.arange(12, dtype=jnp.int32).reshape(1, 12) * 7) % 250
    outs = {}
    for name, kw in [("full", {}), ("int8", {"kv_cache_dtype": "int8"})]:
        model = get_model("transformer-test", dtype=jnp.float32,
                          max_seq_len=32, **kw)
        variables = model.init(jax.random.PRNGKey(0), prompt, train=False)
        outs[name] = np.asarray(generate(
            model, variables, prompt, max_new_tokens=8, temperature=0.0))
        if name == "int8":
            cache = init_cache(model, 1)
            leaves = jax.tree.leaves(
                jax.tree.map(lambda a: a.dtype, cache))
            assert jnp.int8 in leaves and jnp.float32 in leaves
    # same model weights, same greedy decode; int8 cache noise may flip
    # a late token on a random tiny model but most GENERATED tokens must
    # agree (the prompt echo is identical by construction — comparing it
    # would let half the decode output be wrong)
    agree = (outs["full"][:, 12:] == outs["int8"][:, 12:]).mean()
    assert agree >= 0.75, (agree, outs)


def test_int8_kv_cache_composes_with_int8_weights():
    """Both quantizations together through the served generate path."""
    from kubeflow_tpu.serving.server import serve_lm_generator

    served = serve_lm_generator(
        "lm88", "transformer-test", prompt_len=8, max_new_tokens=4,
        param_dtype="int8", kv_cache_dtype="int8")
    try:
        out = served.predict([{"tokens": [5, 6, 7]}])
        assert len(out) == 1 and len(out[0]) == 4
    finally:
        served.close()


def test_int4_roundtrip_error_bound_vs_int8():
    """Packed int4 round-trip: |err| <= scale4/2 per element where
    scale4 = amax/7 — 127/7x looser than int8's amax/254 bound. Both
    bounds pinned side by side so the nibble pack/unpack (sign
    extension included) can never silently lose a bit."""
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 128), jnp.float32) * 2.5
    q4 = quantize_params({"k": w}, min_size=1, bits=4)
    q8 = quantize_params({"k": w}, min_size=1, bits=8)
    assert q4["k"]["int4"].dtype == jnp.uint8
    assert q4["k"]["int4"].shape == (64, 64)       # two nibbles per byte
    assert q4["k"]["scale"].shape == (1, 128)
    back4 = np.asarray(dequantize_params(q4, dtype=jnp.float32)["k"])
    back8 = np.asarray(dequantize_params(q8, dtype=jnp.float32)["k"])
    wn = np.asarray(w)
    s4 = np.asarray(q4["k"]["scale"])[0]
    s8 = np.asarray(q8["k"]["scale"])[0]
    assert (np.abs(back4 - wn) <= s4[None, :] / 2 + 1e-6).all()
    assert (np.abs(back8 - wn) <= s8[None, :] / 2 + 1e-6).all()
    # int8 is strictly tighter in aggregate (18x smaller ulp)
    assert np.abs(back8 - wn).mean() < np.abs(back4 - wn).mean()
    # negative values a full ulp below zero survive the nibble sign
    # extension (anything in (-scale/2, 0) legitimately rounds to 0)
    assert (back4[wn < -s4[None, :]] < 0).all()


def test_int4_pack_unpack_exact():
    from kubeflow_tpu.ops.quantize import pack_int4, unpack_int4

    q = jnp.asarray(np.arange(-7, 8, dtype=np.int8).reshape(1, 15))
    import pytest

    with pytest.raises(ValueError, match="even last axis"):
        pack_int4(q)
    q = jnp.asarray(np.arange(-8, 8, dtype=np.int8).reshape(2, 8))
    np.testing.assert_array_equal(np.asarray(unpack_int4(pack_int4(q))),
                                  np.asarray(q))


def test_int4_odd_last_axis_falls_back_to_int8():
    w = jax.random.normal(jax.random.PRNGKey(2), (32, 33), jnp.float32)
    q = quantize_params({"k": w}, min_size=1, bits=4)
    assert "int8" in q["k"]  # packing needs pairs; int8 keeps the bound


def test_int4_lm_generator_end_to_end():
    """The served generate path under param_dtype='int4': valid tokens
    out, packed nibbles actually resident in the served variables."""
    from kubeflow_tpu.serving.server import serve_lm_generator

    served = serve_lm_generator(
        "lm4", "transformer-test", prompt_len=8, max_new_tokens=4,
        param_dtype="int4")
    try:
        out = served.predict([{"tokens": [1, 2, 3]}])
        assert len(out) == 1 and len(out[0]) == 4
        assert all(0 <= int(t) < 256 for t in out[0])
        assert served.signature["param_dtype"] == "int4"
    finally:
        served.close()
