"""Concurrency stress tests — the race-detection tier.

The reference configures no race detection at all (SURVEY.md §5: no
`-race` in any Makefile; concurrency safety is hand-rolled mutexes with
"Not thread safe" comments). This tier is the improvement: controllers
run in their production threaded mode (watch streams + worker threads)
while client threads hammer the apiserver; CPython's data-race surface
(torn dict/list state under the apiserver lock, lost updates via
optimistic concurrency) is exercised directly.
"""

import os
import threading
import time

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster

# Stress knobs (ISSUE 1): the default tier stays fast and deterministic;
# a slow-tier run cranks contention without editing the file, e.g.
#   TPU_RACE_THREADS=32 TPU_RACE_ITERS=200 python -m pytest tests/test_race.py
RACE_THREADS = int(os.environ.get("TPU_RACE_THREADS", "8"))
RACE_ITERS = int(os.environ.get("TPU_RACE_ITERS", "30"))

# Happens-before validator (ISSUE 2): with TPU_RACE_TRACE=1 the whole
# tier runs under analysis/dyntrace.py instrumentation of the
# control-plane classes, and at teardown the observed locksets are
# diffed against LOCK201's static guarded-attribute map — static says
# Controller._queue is guarded by _cv; dynamic confirms or fails.
RACE_TRACE = os.environ.get("TPU_RACE_TRACE") == "1"

_TRACER = None


def _static_lockset_map():
    import pathlib

    from kubeflow_tpu.analysis.dyntrace import static_guarded_map

    control = pathlib.Path(__file__).resolve().parent.parent / \
        "kubeflow_tpu" / "control"
    return static_guarded_map([str(control / "runtime.py"),
                               str(control / "leases.py"),
                               str(control / "cache.py"),
                               str(control / "scheduler" / "queue.py")])


@pytest.fixture(scope="module", autouse=True)
def _dyntrace_tier():
    """Instrument Controller + LeaderElector for every race test when
    TPU_RACE_TRACE=1; assert static/dynamic lockset agreement at module
    teardown so the whole tier cross-checks the map on every run."""
    global _TRACER
    if not RACE_TRACE:
        yield
        return
    from kubeflow_tpu.analysis.dyntrace import Tracer
    from kubeflow_tpu.control.cache import ClusterCache
    from kubeflow_tpu.control.leases import LeaderElector
    from kubeflow_tpu.control.runtime import Controller
    from kubeflow_tpu.control.scheduler.queue import GangQueue

    tr = Tracer()
    tr.instrument(Controller)
    tr.instrument(LeaderElector)
    tr.instrument(GangQueue)
    tr.instrument(ClusterCache)
    _TRACER = tr
    try:
        with tr:
            yield
    finally:
        tr.uninstrument_all()
        _TRACER = None
    divergences = tr.divergences(_static_lockset_map())
    assert not divergences, (
        "dynamic locksets diverged from LOCK201's static map:\n"
        + "\n".join(divergences))


def test_fakecluster_concurrent_crud_consistency():
    c = FakeCluster()
    errors: list[Exception] = []
    N, PER = RACE_THREADS, RACE_ITERS

    def worker(wid: int):
        try:
            for i in range(PER):
                name = f"obj-{wid}-{i}"
                c.create(ob.new_object("v1", "ConfigMap", name, namespace="ns"))
                got = c.get("v1", "ConfigMap", name, "ns")
                got["data"] = {"i": str(i)}
                c.update(got)
                if i % 3 == 0:
                    c.delete("v1", "ConfigMap", name, "ns")
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    left = c.list("v1", "ConfigMap", namespace="ns")
    expect = N * sum(1 for i in range(PER) if i % 3 != 0)
    assert len(left) == expect
    # every survivor carries its final update (no lost writes)
    for o in left:
        assert o["data"]["i"] == o["metadata"]["name"].rsplit("-", 1)[1]


def test_optimistic_concurrency_under_contention():
    """Concurrent writers to ONE object: conflicts must be raised (never
    silently lost) and retry-on-conflict must converge."""
    c = FakeCluster()
    c.create(ob.new_object("v1", "ConfigMap", "shared", namespace="ns"))
    conflicts = [0]
    writers = max(2, RACE_THREADS // 2)
    per_writer = max(5, RACE_ITERS)

    def incr():
        for _ in range(per_writer):
            while True:
                got = c.get("v1", "ConfigMap", "shared", "ns")
                data = dict(got.get("data") or {})
                data["count"] = str(int(data.get("count", "0")) + 1)
                got["data"] = data
                try:
                    c.update(got)
                    break
                except ob.Conflict:
                    conflicts[0] += 1

    threads = [threading.Thread(target=incr) for _ in range(writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    final = c.get("v1", "ConfigMap", "shared", "ns")
    assert final["data"]["count"] == str(writers * per_writer)


def test_gang_queue_concurrent_offer_requeue_remove():
    """The gang scheduler's queue (ISSUE 3) under thread fire: offers,
    requeues, ready() scans and removes race from many threads; state
    must never tear, and the admission order (priority desc, FIFO
    within) must hold over the survivors. Under TPU_RACE_TRACE=1 the
    module fixture instruments GangQueue, so this churn also feeds the
    happens-before validator's static/dynamic lockset diff."""
    from kubeflow_tpu.control.scheduler.queue import GangQueue

    # static pin: LOCK201's map must prove the queue's state is guarded
    static = _static_lockset_map()
    assert static["GangQueue"]["_entries"] == {"_lock"}
    assert static["GangQueue"]["_seq"] == {"_lock"}

    q = GangQueue(base_backoff=0.001, max_backoff=0.002)
    errors: list[Exception] = []

    def worker(wid: int):
        try:
            for i in range(RACE_ITERS):
                name = f"g-{wid}-{i}"
                q.offer("ns", name, priority=i % 3)
                q.requeue("ns", name)
                q.ready()
                q.depths()
                if i % 2 == 0:
                    q.remove("ns", name)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(RACE_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    expect = RACE_THREADS * sum(1 for i in range(RACE_ITERS) if i % 2 != 0)
    assert q.depth() == expect
    time.sleep(0.01)  # every backoff deadline expires
    entries = q.ready()
    assert len(entries) == expect
    # seqs unique (no torn counter), and every survivor carries exactly
    # the state its worker wrote: the offered priority and ONE requeue —
    # an independently derived expectation, not ready()'s own sort key
    assert len({e.seq for e in entries}) == expect
    expected = {f"g-{w}-{i}": i % 3
                for w in range(RACE_THREADS)
                for i in range(RACE_ITERS) if i % 2 != 0}
    assert {e.name: e.priority for e in entries} == expected
    assert all(e.attempts == 1 for e in entries)


def test_cluster_cache_concurrent_readers_during_churn():
    """The ISSUE 7 cache under thread fire: writer threads churn
    pods/nodes through the apiserver while reader threads hammer the
    cache's snapshot methods and ONE consumer thread refreshes (the
    documented single-writer discipline: event application happens only
    inside refresh()/note_write(), reads are lock-guarded snapshots).
    After quiescing, one final refresh must equal a fresh relist.
    Under TPU_RACE_TRACE=1 the module fixture instruments ClusterCache,
    so this churn also feeds the happens-before validator's
    static/dynamic lockset diff."""
    from kubeflow_tpu.control.cache import ClusterCache
    from kubeflow_tpu.control.jaxjob import types as JT
    from kubeflow_tpu.control.scheduler.nodes import new_tpu_node

    # static pin: LOCK201's map must prove the cache state is guarded
    static = _static_lockset_map()
    assert static["ClusterCache"]["_objects"] == {"_lock"}
    assert static["ClusterCache"]["_free"] == {"_lock"}
    assert static["ClusterCache"]["_buckets"] == {"_lock"}

    cluster = FakeCluster()
    for i in range(4):
        cluster.create(new_tpu_node(f"n{i}"))
    cache = ClusterCache(cluster).connect()
    errors: list[Exception] = []
    stop = threading.Event()

    def writer(wid: int):
        try:
            for i in range(RACE_ITERS):
                name = f"rp-{wid}-{i}"
                pod = ob.new_object(
                    "v1", "Pod", name, "default",
                    labels={JT.LABEL_JOB_NAME: f"gang-{wid}"})
                pod["spec"] = {"containers": [{"name": "jax"}]}
                cluster.create(pod)
                cluster.patch("v1", "Pod", name,
                              {"spec": {"nodeName": f"n{i % 4}"}},
                              "default")
                if i % 3 == 0:
                    cluster.delete("v1", "Pod", name, "default")
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    def refresher():
        try:
            while not stop.is_set():
                cache.refresh()
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                cache.capacity()
                cache.node_views()
                cache.unhealthy_bound_nodes()
                cache.gang_pods("default", "gang-0")
                cache.bound_pods()
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    writers = [threading.Thread(target=writer, args=(w,))
               for w in range(max(2, RACE_THREADS // 2))]
    aux = [threading.Thread(target=refresher, daemon=True)] + \
          [threading.Thread(target=reader, daemon=True)
           for _ in range(max(2, RACE_THREADS // 2))]
    for t in aux:
        t.start()
    for t in writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in aux:
        t.join(timeout=10)
    assert not errors, errors
    cache.refresh()
    # the final snapshot equals a fresh relist: same keys, same rvs,
    # and free-chip accounting recomputed from scratch agrees
    want = {(ob.meta(o).get("namespace") or "", ob.meta(o)["name"]):
            ob.meta(o)["resourceVersion"]
            for o in cluster.list("v1", "Pod")}
    got = {k: ob.meta(o)["resourceVersion"]
           for k, o in cache.objects("v1", "Pod").items()}
    assert got == want
    from kubeflow_tpu.control.scheduler.nodes import (
        TERMINAL_PHASES, node_view, pod_tpu_request,
    )
    free = {node_view(n).name: node_view(n).allocatable_chips
            for n in cluster.list("v1", "Node")}
    for p in cluster.list("v1", "Pod"):
        node = (p.get("spec") or {}).get("nodeName")
        if node in free and (p.get("status") or {}).get("phase") \
                not in TERMINAL_PHASES:
            free[node] -= pod_tpu_request(p)
    assert cache.capacity().free == free


def test_controller_threaded_mode_against_churn():
    """Notebook controller in production mode (run(): watch + worker
    threads) while a client churns Notebooks; after quiescing, the
    world must be consistent: every live Notebook has its StatefulSet,
    no orphaned StatefulSets for deleted ones."""
    from kubeflow_tpu.control.notebook import types as NT
    from kubeflow_tpu.control.notebook.controller import build_controller

    c = FakeCluster()
    ctl = build_controller(c)
    ctl.run(workers=3)
    try:
        names = [f"nb-{i}" for i in range(12)]
        for n in names:
            c.create(NT.new_notebook(n, "ns", image="img:1",
                                     cpu="0.1", memory="128Mi"))
        # churn: delete a third while the controller reconciles
        for n in names[::3]:
            c.delete(NT.API_VERSION, NT.KIND, n, "ns")

        deadline = time.monotonic() + 20
        want = set(names) - set(names[::3])
        while time.monotonic() < deadline:
            sts = {s["metadata"]["name"]
                   for s in c.list("apps/v1", "StatefulSet", namespace="ns")}
            if sts == want:
                break
            time.sleep(0.05)
        assert sts == want, f"sts={sorted(sts)} want={sorted(want)}"
    finally:
        ctl.stop()


def test_tpctl_server_concurrent_creates_single_worker_per_name():
    """Racing creates for one deployment must funnel through one worker
    (kfctlServer's channel serialization, kfctlServer.go:87)."""
    import json

    from kubeflow_tpu.tpctl.server import TpctlServer
    from kubeflow_tpu.tpctl.tpudef import example_yaml
    from kubeflow_tpu.utils.httpd import HttpReq

    import yaml

    srv = TpctlServer(FakeCluster())
    spec = yaml.safe_load(example_yaml())
    body = json.dumps(spec).encode()

    def create():
        req = HttpReq(method="POST", path="/tpctl/apps/v1/create", params={},
                      query={}, headers={}, body=body)
        srv.create(req)

    threads = [threading.Thread(target=create) for _ in range(RACE_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(srv.workers) == 1
    # the single worker drains to an applied deployment
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        obj = srv.workers["kubeflow-tpu"].coordinator.status("kubeflow-tpu")
        conds = {cc["type"]: cc["status"]
                 for cc in (obj or {}).get("status", {}).get("conditions", [])}
        if conds.get("TpuDefAvailable") == "True":
            break
        time.sleep(0.05)
    assert conds.get("TpuDefAvailable") == "True", conds


def test_leader_election_threaded_single_active():
    """Two threaded controller managers with electors on one cluster:
    every JAXJob still converges (exactly one full gang per job — no
    duplicate pod sets from split-brain), and the workers' concurrent
    try_acquire calls never error."""
    from kubeflow_tpu.control.jaxjob import types as JT
    from kubeflow_tpu.control.jaxjob.controller import build_controller
    from kubeflow_tpu.control.leases import LeaderElector

    cluster = FakeCluster()
    electors = [LeaderElector(cluster, "jaxjob-controller",
                              identity=f"pod-{i}", lease_seconds=2.0)
                for i in range(2)]
    ctls = [build_controller(cluster, record_events=False)
            .with_leader_election(electors[i]) for i in range(2)]
    for c in ctls:
        c.run(workers=2)
    try:
        for j in range(6):
            cluster.create(JT.new_jaxjob(f"job-{j}", replicas=2))
            time.sleep(0.05)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pods = cluster.list("v1", "Pod")
            names = sorted(ob.meta(p)["name"] for p in pods)
            want = sorted(f"job-{j}-worker-{i}"
                          for j in range(6) for i in range(2))
            if names == want:
                break
            time.sleep(0.2)
        assert names == want, f"pod set diverged: {names}"
        # exactly one elector holds the lease
        assert sum(e.is_leader for e in electors) <= 1
        lease = cluster.get("coordination.k8s.io/v1", "Lease",
                            "jaxjob-controller", "kubeflow")
        assert lease["spec"]["holderIdentity"] in ("pod-0", "pod-1")
    finally:
        for c in ctls:
            c.stop()


def test_watch_resume_under_concurrent_writers_and_drops():
    """The new watch-cache machinery (history replay, bookmarks, 410)
    under fire: writers churn ConfigMaps over HTTP while drop_watches()
    severs the stream repeatedly; the consumer must observe every
    created object exactly... at least once, with no torn JSON, no lost
    creations, and no deadlock."""
    from kubeflow_tpu.control.k8s.apiserver import ApiServer, client_for

    api = ApiServer().serve_background()
    api.bookmark_interval = 0.1
    try:
        c = client_for(api)
        stream = c.watch("v1", "ConfigMap", "default")
        seen: set[str] = set()
        lock = threading.Lock()

        def consume():
            for ev in stream:
                with lock:
                    seen.add(ev.object["metadata"]["name"])

        threading.Thread(target=consume, daemon=True).start()
        time.sleep(0.3)
        N = 40

        def writer(start):
            w = client_for(api)
            for i in range(start, start + N // 2):
                w.create(ob.new_object("v1", "ConfigMap", f"cm{i}", "default"))
                time.sleep(0.005)

        t1 = threading.Thread(target=writer, args=(0,))
        t2 = threading.Thread(target=writer, args=(N // 2,))
        t1.start(); t2.start()
        for _ in range(6):  # repeated mid-stream disconnects
            time.sleep(0.08)
            api.drop_watches()
        t1.join(); t2.join()
        deadline = time.monotonic() + 20
        want = {f"cm{i}" for i in range(N)}
        while time.monotonic() < deadline:
            with lock:
                if want <= seen:
                    break
            time.sleep(0.1)
        stream.stop()
        with lock:
            missing = want - seen
        assert not missing, f"lost creations across reconnects: {sorted(missing)[:5]}"
    finally:
        api.shutdown()


def test_paginated_list_under_concurrent_churn():
    """Snapshot-backed continue tokens must stay self-consistent while
    other threads create/delete around the pagination."""
    c = FakeCluster()
    for i in range(30):
        c.create(ob.new_object("v1", "ConfigMap", f"p{i:02d}", "default"))
    stop = threading.Event()

    def churn():
        k = 100
        while not stop.is_set():
            c.create(ob.new_object("v1", "ConfigMap", f"x{k}", "default"))
            try:
                c.delete("v1", "ConfigMap", f"x{k - 3}", "default")
            except ob.NotFound:
                pass
            k += 1

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        for _ in range(20):
            items, cont, _rv = c.list_page("v1", "ConfigMap", "default",
                                           limit=7)
            pages = [items]
            while cont:
                nxt, cont, _ = c.list_page("v1", "ConfigMap", "default",
                                           limit=7, continue_token=cont)
                pages.append(nxt)
            names = [ob.meta(o)["name"] for page in pages for o in page]
            base = [n for n in names if n.startswith("p")]
            # the original 30 stable objects appear exactly once, in order
            assert base == [f"p{i:02d}" for i in range(30)], base[:5]
            assert len(names) == len(set(names)), "duplicate across pages"
    finally:
        stop.set()
        t.join()


@pytest.mark.dyntrace
@pytest.mark.skipif(not RACE_TRACE,
                    reason="happens-before validator: set TPU_RACE_TRACE=1")
def test_dyntrace_observed_lockset_agrees_with_static_map():
    """The ISSUE 2 acceptance check: drive an instrumented Controller in
    production threaded mode until its queue state is genuinely
    contended (multiple threads writing), then require that the
    dynamically observed locksets agree with LOCK201's static
    guarded-attribute map for control/runtime.py — and that the
    agreement is non-vacuous (the guarded attrs were actually hit)."""
    from kubeflow_tpu.control.notebook import types as NT
    from kubeflow_tpu.control.notebook.controller import build_controller

    static = _static_lockset_map()
    # pin the static half so a lint regression can't hollow out the test
    assert static["Controller"]["_queue"] == {"_cv"}
    assert static["Controller"]["_delayed"] == {"_cv"}
    assert static["Controller"]["_failures"] == {"_cv"}
    assert static["LeaderElector"]["_held"] == {"_lock"}

    c = FakeCluster()
    ctl = build_controller(c)
    ctl.run(workers=3)
    try:
        names = [f"tr-{i}" for i in range(10)]
        for n in names:
            c.create(NT.new_notebook(n, "ns", image="img:1",
                                     cpu="0.1", memory="128Mi"))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            sts = {s["metadata"]["name"]
                   for s in c.list("apps/v1", "StatefulSet", namespace="ns")}
            if sts == set(names):
                break
            time.sleep(0.05)
        assert sts == set(names)
    finally:
        ctl.stop()

    observed = _TRACER.observed()
    rec = observed[("Controller", "_queue")]
    assert rec["shared"], "scenario never contended _queue: vacuous run"
    confirmed = _TRACER.confirmed(static)
    assert "Controller._queue" in confirmed, confirmed
    assert _TRACER.divergences(static) == []
