"""Tensorboard operator (reference: components/tensorboard-controller)."""

from kubeflow_tpu.control.tensorboard.controller import (  # noqa: F401
    API_VERSION,
    KIND,
    TensorboardReconciler,
    build_controller,
    new_tensorboard,
)
