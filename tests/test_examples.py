"""Every example config must parse against its schema — examples rot
otherwise (the reference's testing/test_jsonnet.py evaluated every
jsonnet for the same reason)."""

import glob
import os
import subprocess

import yaml

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    with open(os.path.join(HERE, "examples", name)) as f:
        return yaml.safe_load(f)


def test_all_examples_are_covered_here():
    have = {os.path.basename(p)
            for p in glob.glob(os.path.join(HERE, "examples", "*.yaml"))}
    covered = {"resnet50.yaml", "gpt-125m.yaml", "longctx-ring.yaml",
               "llama-1b-singlechip.yaml", "tpudef.yaml",
               "studyjob-sweep.yaml", "multislice-2slice.yaml"}
    assert have == covered, f"new example needs a parse test: {have - covered}"


def test_trainconfig_examples_parse():
    from kubeflow_tpu.runtime.trainer import TrainConfig

    for name in ("resnet50.yaml", "gpt-125m.yaml", "longctx-ring.yaml",
                 "llama-1b-singlechip.yaml"):
        cfg = TrainConfig.from_dict(_load(name))
        assert cfg.total_steps > 0, name


def test_tpudef_example_parses():
    from kubeflow_tpu.tpctl.tpudef import TpuDef

    cfg = TpuDef.from_dict(_load("tpudef.yaml"))
    assert cfg.applications


def test_studyjob_example_is_schedulable():
    from kubeflow_tpu.control.jaxjob import types as JT
    from kubeflow_tpu.tune import studyjob as SJ

    cr = _load("studyjob-sweep.yaml")
    assert cr["kind"] == "StudyJob"
    spec = cr["spec"]
    # algorithm resolvable + trial slice geometry consistent
    rec = SJ.StudyJobReconciler()
    study = {"spec": spec}
    assert rec._suggestions(study, [])  # no ValueError
    assert JT._validate_tpu_topology(spec["trialTemplate"]["spec"]) == []


def test_sweep_script_is_valid_bash():
    rc = subprocess.run(["bash", "-n", os.path.join(HERE, "tools",
                                                    "lm_sweep.sh")])
    assert rc.returncode == 0


def test_multislice_example_validates_and_builds_mesh():
    """The JAXJob half must pass CRD validation; the TrainConfig half's
    dcn mesh must resolve on sliceCount x replicas x chips devices."""
    from kubeflow_tpu.control.jaxjob import types as JT
    from kubeflow_tpu.parallel.mesh import MeshSpec
    from kubeflow_tpu.runtime.trainer import TrainConfig

    with open(os.path.join(HERE, "examples", "multislice-2slice.yaml")) as f:
        job, train = list(yaml.safe_load_all(f))
    assert JT.validate(job) == []
    assert JT.gang_size(job["spec"]) == 4
    cfg = TrainConfig.from_dict(train)
    chips = (job["spec"]["sliceCount"] * job["spec"]["replicas"]
             * job["spec"]["tpu"]["chipsPerWorker"])
    spec = cfg.mesh if isinstance(cfg.mesh, MeshSpec) else MeshSpec.from_dict(cfg.mesh)
    resolved = spec.resolve(chips)
    assert resolved.dcn == job["spec"]["sliceCount"]
    assert resolved.data * resolved.dcn * resolved.model == chips
