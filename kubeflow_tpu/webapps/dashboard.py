"""Central dashboard backend API.

Mirrors centraldashboard/app (SURVEY.md §2.3):
- /api/workgroup/exists (api_workgroup.ts:249), /create (:276),
  /env-info (:301), /nuke-self (:324), /get-all-namespaces (:338),
  /get-contributors/:namespace (:367)
- /api/activities/{namespace} — the events feed (k8s_service.ts:92)
- /api/metrics/{type} — cluster metrics behind the MetricsService
  interface (metrics_service.ts:37). The reference only shipped a
  Stackdriver implementation (stackdriver_metrics_service.ts:15); here
  the interface is the contract and a Prometheus-backed implementation
  reads the in-process registries (node metrics come from the cluster's
  Node objects), so the dashboard works on any cluster.

Identity: the kubeflow-userid header (attach_user_middleware.ts), with
the auth-gate middleware rejecting unidentified requests on mutating
endpoints (:314).
"""

from __future__ import annotations

import json
import logging
import math
import re
from typing import Protocol

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.kfam.service import KfamService
from kubeflow_tpu.control.profile import types as PT
from kubeflow_tpu.utils import httpd
from kubeflow_tpu.utils.httpd import ApiHttpError, HttpReq, Router

log = logging.getLogger("kubeflow_tpu.dashboard")

USER_HEADER = "kubeflow-userid"
# api_workgroup.ts EMAIL_RGX: contributor identities must look like email
EMAIL_RGX = re.compile(r"^[^\s@]+@[^\s@]+\.[^\s@]+$")


class MetricsService(Protocol):
    """metrics_service.ts:37 analogue."""

    def node_cpu_utilization(self) -> list[dict]: ...

    def node_memory_usage(self) -> list[dict]: ...

    def tpu_chips(self) -> list[dict]: ...


class ClusterMetricsService:
    """Reads Node capacity/allocatable from the cluster — covers the
    resource charts without a Stackdriver dependency."""

    def __init__(self, client):
        self.client = client

    def _nodes(self):
        return self.client.list("v1", "Node")

    def node_cpu_utilization(self) -> list[dict]:
        out = []
        for n in self._nodes():
            st = n.get("status") or {}
            out.append({
                "node": ob.meta(n)["name"],
                "capacity": (st.get("capacity") or {}).get("cpu"),
                "allocatable": (st.get("allocatable") or {}).get("cpu"),
            })
        return out

    def node_memory_usage(self) -> list[dict]:
        return [{
            "node": ob.meta(n)["name"],
            "capacity": ((n.get("status") or {}).get("capacity") or {}).get("memory"),
        } for n in self._nodes()]

    def tpu_chips(self) -> list[dict]:
        """The TPU-native metric the reference never had: chips per node."""
        out = []
        for n in self._nodes():
            cap = ((n.get("status") or {}).get("capacity") or {})
            if PT.RESOURCE_TPU in cap:
                out.append({
                    "node": ob.meta(n)["name"],
                    "chips": cap[PT.RESOURCE_TPU],
                    "accelerator": ob.labels_of(n).get(
                        "cloud.google.com/gke-tpu-accelerator", ""),
                    "topology": ob.labels_of(n).get(
                        "cloud.google.com/gke-tpu-topology", ""),
                })
        return out


class Dashboard:
    def __init__(self, client, kfam: KfamService | None = None,
                 metrics: MetricsService | None = None,
                 serving_url: str | None = None,
                 fetch_json=None, plane=None):
        import os

        self.client = client
        self.kfam = kfam or KfamService(client)
        self.metrics = metrics or ClusterMetricsService(client)
        self.serving_url = serving_url or os.environ.get(
            "SERVING_URL", "http://serving.kubeflow.svc")
        self.fetch_json = fetch_json or self._default_fetch
        # fleet observability plane (obs/plane.py); None -> the
        # process-wide default, built lazily on first /api/alerts read
        self.plane = plane

    @staticmethod
    def _default_fetch(url: str) -> dict:
        import urllib.request

        with urllib.request.urlopen(url, timeout=5) as resp:
            return json.loads(resp.read())

    def _user(self, req: HttpReq, required: bool = True) -> str:
        user = req.header(USER_HEADER)
        if not user and required:
            raise ApiHttpError(401, f"missing {USER_HEADER} header")
        return user

    def _owned_profiles(self, user: str) -> list[dict]:
        return [p for p in self.client.list(PT.API_VERSION, PT.KIND)
                if PT.owner_name(p) == user]

    def _member_namespaces(self, user: str) -> list[dict]:
        """Owned + contributed (kfam binding) namespaces with roles."""
        out = {ob.meta(p)["name"]: "owner" for p in self._owned_profiles(user)}
        for rb in self.client.list("rbac.authorization.k8s.io/v1", "RoleBinding"):
            annos = ob.annotations_of(rb)
            if annos.get(PT.ANNO_USER) == user and annos.get(PT.ANNO_ROLE):
                out.setdefault(ob.meta(rb)["namespace"], annos[PT.ANNO_ROLE])
        return [{"namespace": ns, "role": role} for ns, role in sorted(out.items())]

    # -- workgroup endpoints ------------------------------------------------

    def exists(self, req: HttpReq):
        user = self._user(req)
        return {"hasAuth": True, "user": user,
                "hasWorkgroup": bool(self._owned_profiles(user))}

    def create(self, req: HttpReq):
        from kubeflow_tpu.utils.names import require_dns1123, sanitize_dns1123

        user = self._user(req)
        body = req.json() or {}
        name = body.get("namespace")
        if name:
            # client-side NS_RGX is advisory; a real apiserver would 422
            require_dns1123(name, "namespace")
        else:
            name = sanitize_dns1123(user.split("@")[0])
        prof = PT.new_profile(name, user)
        try:
            self.client.create(prof)
        except ob.Conflict:
            raise ApiHttpError(409, f"profile {name} already exists")
        return 200, {"message": f"profile {name} created"}

    def env_info(self, req: HttpReq):
        user = self._user(req, required=False)
        return {
            "user": user,
            "platform": {"kind": "tpu", "provider": "gke"},
            "namespaces": self._member_namespaces(user) if user else [],
            "isClusterAdmin": self.kfam.is_cluster_admin(user),
        }

    def get_all_namespaces(self, req: HttpReq):
        user = self._user(req)
        if not self.kfam.is_cluster_admin(user):
            raise ApiHttpError(403, "cluster admin only")
        return {"namespaces": [
            ob.meta(n)["name"] for n in self.client.list("v1", "Namespace")]}

    def get_contributors(self, req: HttpReq):
        ns = req.params["namespace"]
        contributors = []
        for rb in self.client.list("rbac.authorization.k8s.io/v1", "RoleBinding",
                                   namespace=ns):
            annos = ob.annotations_of(rb)
            if annos.get(PT.ANNO_USER) and annos.get(PT.ANNO_ROLE) \
                    and ob.meta(rb)["name"] != "namespaceAdmin":
                contributors.append(annos[PT.ANNO_USER])
        return {"contributors": sorted(set(contributors))}

    def _contributor_action(self, req: HttpReq, action: str):
        """add/remove-contributor (api_workgroup.ts:189-235): validate,
        proxy to KFAM's binding API with the caller's identity, return
        the refreshed contributor list."""
        ns = req.params["namespace"]
        self._user(req)
        body = req.json() or {}
        if not isinstance(body, dict):
            raise ApiHttpError(400, "request body must be a JSON object")
        contributor = body.get("contributor")
        if not contributor or not isinstance(contributor, str):
            raise ApiHttpError(400, "missing contributor field")
        if not EMAIL_RGX.match(contributor):
            raise ApiHttpError(
                400, "contributor doesn't look like a valid email address")
        role = "edit"
        if action != "create":
            # remove must target the binding's actual role (a contributor
            # may hold kubeflow-view etc.), not assume edit
            for rb in self.client.list("rbac.authorization.k8s.io/v1",
                                       "RoleBinding", namespace=ns):
                annos = ob.annotations_of(rb)
                if annos.get(PT.ANNO_USER) == contributor \
                        and annos.get(PT.ANNO_ROLE):
                    role = annos[PT.ANNO_ROLE]
                    break
        binding = json.dumps({
            "user": {"kind": "User", "name": contributor},
            "referredNamespace": ns,
            "roleRef": {"kind": "ClusterRole", "name": f"kubeflow-{role}"},
        }).encode()
        proxied = HttpReq(method="POST", path="", params={}, query={},
                          headers=dict(req.headers), body=binding)
        if action == "create":
            self.kfam.create_binding(proxied)
        else:
            self.kfam.delete_binding(proxied)
        return self.get_contributors(req)

    def add_contributor(self, req: HttpReq):
        return self._contributor_action(req, "create")

    def remove_contributor(self, req: HttpReq):
        return self._contributor_action(req, "delete")

    def nuke_self(self, req: HttpReq):
        """Delete every profile the user owns (:324)."""
        user = self._user(req)
        victims = self._owned_profiles(user)
        for p in victims:
            self.client.delete(PT.API_VERSION, PT.KIND, ob.meta(p)["name"])
        return 200, {"message": f"deleted {len(victims)} profiles"}

    # -- notebooks card (notebooks-card.js analogue) ------------------------

    def notebooks(self, req: HttpReq):
        """List Notebook CRs in a namespace with connect URLs — what the
        reference dashboard's notebooks-card renders (notebooks-card.js,
        backed by k8s_service.ts)."""
        from kubeflow_tpu.control.notebook import types as NT

        self._user(req)
        ns = req.params["namespace"]
        out = []
        for nb in self.client.list(NT.API_VERSION, NT.KIND, namespace=ns):
            m = ob.meta(nb)
            containers = ((((nb.get("spec") or {}).get("template") or {})
                           .get("spec") or {}).get("containers") or [{}])
            cstate = (nb.get("status") or {}).get("containerState") or {}
            # containerState has exactly one of running/waiting/terminated
            phase = next(iter(cstate.keys()), "unknown")
            stopped = NT.STOP_ANNOTATION in ob.annotations_of(nb)
            limits = (containers[0].get("resources") or {}).get("limits") or {}
            out.append({
                "name": m["name"],
                "namespace": ns,
                "image": containers[0].get("image", ""),
                "status": "stopped" if stopped else phase,
                "tpu_chips": limits.get(NT.RESOURCE_TPU, 0),
                # the VirtualService route prefix (notebook_controller.go:386)
                "connect": f"/notebook/{ns}/{m['name']}/",
            })
        return {"notebooks": sorted(out, key=lambda n: n["name"])}

    # -- training jobs card (TPU-native; the reference dashboard's
    # workload cards showed notebooks/pipelines — here the training
    # workload is the JAXJob CRD) --------------------------------------------

    def jaxjobs(self, req: HttpReq):
        from kubeflow_tpu.control.jaxjob import types as JT

        self._user(req)
        ns = req.params["namespace"]
        out = []
        for j in self.client.list(JT.API_VERSION, JT.KIND, namespace=ns):
            m = ob.meta(j)
            st = j.get("status") or {}
            if ob.cond_is_true(j, JT.COND_SUCCEEDED):
                phase = "succeeded"
            elif ob.cond_is_true(j, JT.COND_FAILED):
                phase = "failed"
            elif ob.cond_is_true(j, JT.COND_RUNNING):
                phase = "running"
            else:
                phase = "pending"
            tpu = (j.get("spec") or {}).get("tpu") or {}
            out.append({
                "name": m["name"],
                "phase": phase,
                "replicas": (j.get("spec") or {}).get("replicas", 1),
                "chips_per_worker": tpu.get("chipsPerWorker", 0),
                "restarts": st.get("restarts", 0),
                "preemptions": st.get("preemptions", 0),
            })
        return {"jaxjobs": sorted(out, key=lambda r: r["name"])}

    # -- serving card --------------------------------------------------------

    def serving_models(self, req: HttpReq):
        """Proxy the model server's /v1/models inventory; degrade to an
        empty list with an error note when serving is unreachable (the
        dashboard must render without every backend up)."""
        self._user(req)
        try:
            out = self.fetch_json(f"{self.serving_url}/v1/models")
            return {"models": out.get("models", [])}
        except Exception as e:  # noqa: BLE001 — degrade, don't 500
            return {"models": [], "error": str(e)[:200]}

    # -- activity + metrics -------------------------------------------------

    def activities(self, req: HttpReq):
        ns = req.params["namespace"]
        evs = self.client.list("v1", "Event", namespace=ns)
        evs.sort(key=lambda e: e.get("lastTimestamp", ""), reverse=True)
        return {"events": evs[:50]}

    def traces(self, req: HttpReq):
        """This process's span collector as Perfetto trace_event JSON —
        save the response and open it at ui.perfetto.dev. In the
        hermetic harness (controllers in-process) this is the full
        submit→bind timeline; in production each component exports its
        own collector and tools/trace2perfetto.py merges the dumps."""
        self._user(req)
        from kubeflow_tpu.obs import trace as obs_trace

        return obs_trace.to_chrome_trace(obs_trace.COLLECTOR.spans())

    def get_metrics(self, req: HttpReq):
        mtype = req.params["type"]
        if mtype == "node-cpu":
            return {"values": self.metrics.node_cpu_utilization()}
        if mtype == "node-memory":
            return {"values": self.metrics.node_memory_usage()}
        if mtype == "tpu-chips":
            return {"values": self.metrics.tpu_chips()}
        raise ApiHttpError(404, f"unknown metric type {mtype!r}")

    # -- fleet observability plane (obs/plane.py) ----------------------------

    def _plane(self):
        if self.plane is not None:
            return self.plane
        from kubeflow_tpu.obs.plane import default_plane

        return default_plane()

    def alerts(self, req: HttpReq):
        """Active alerts (pending + firing) from the plane's rule
        engine — the structured face of the AlertFiring/AlertResolved
        Events in the activities feed."""
        self._user(req)
        return self._plane().alerts()

    def obs_query(self, req: HttpReq):
        """PromQL-lite over the fleet TSDB: /api/query?q=<expr>[&at=]
        (docs/observability.md documents the grammar)."""
        from kubeflow_tpu.obs.rules import QueryError

        self._user(req)
        text = req.q1("q")
        if not text:
            raise ApiHttpError(400, "missing ?q=<expression>")
        at = req.q1("at")
        try:
            at_f = float(at) if at else None
        except ValueError:
            raise ApiHttpError(400, f"bad ?at= value: {at!r}")
        try:
            return self._plane().query(text, at=at_f)
        except QueryError as e:
            raise ApiHttpError(400, f"bad query: {e}")

    def goodput(self, req: HttpReq):
        """Training goodput buckets (conservation-checked) + serving
        SLO attainment — "what fraction of chip-seconds were
        productive, and where did the rest go?"."""
        self._user(req)
        chips = req.q1("chips")
        window = req.q1("window_s")
        try:
            chips_i = int(chips) if chips else 1
            window_f = float(window) if window else None
        except ValueError:
            raise ApiHttpError(
                400, "chips must be an int, window_s a number")
        return self._plane().goodput(chips=chips_i, window_s=window_f)

    def chargeback(self, req: HttpReq):
        """The per-tenant bill: goodput %, chip-seconds lost by cause
        (conservation-checked against the fleet ledger), SLO
        attainment, and remediation count — ?window_s= bounds the
        trailing window, ?tenant= narrows to one tenant, ?chips=
        weights every tenant's report (flat rate)."""
        from kubeflow_tpu.serving.router import TENANT_RE

        self._user(req)
        window = req.q1("window_s")
        chips = req.q1("chips")
        tenant = req.q1("tenant")
        try:
            window_f = float(window) if window else 300.0
            chips_i = int(chips) if chips else 1
        except ValueError:
            raise ApiHttpError(
                400, "window_s must be a number, chips an int")
        if not math.isfinite(window_f) or window_f <= 0:
            raise ApiHttpError(400, "window_s must be a positive number")
        if chips_i < 1:
            raise ApiHttpError(400, "chips must be >= 1")
        if tenant and not TENANT_RE.match(tenant):
            raise ApiHttpError(
                400, "tenant must be a DNS-1123 label")
        out = self._plane().chargeback(window_s=window_f,
                                       default_chips=chips_i)
        if tenant:
            out["tenants"] = {tenant: out["tenants"].get(tenant)} \
                if tenant in out["tenants"] else {}
        return out

    def silences(self, req: HttpReq):
        """Active silences: GET lists, POST creates (body:
        {"matchers": {...}, "until": <unix-s> | "duration_s": <s>,
        "comment": ...}), DELETE /api/silences/{id} expires one. A
        silence mutes notification Events AND remediation actions for
        matching alerts; the alert state machine keeps running."""
        user = self._user(req)
        plane = self._plane()
        if req.method == "GET":
            return {"silences": plane.silences.list()}
        try:
            body = req.json()
        except ValueError:
            raise ApiHttpError(400, "body must be JSON")
        if not isinstance(body, dict) or \
                not isinstance(body.get("matchers"), dict):
            raise ApiHttpError(
                400, "body needs a matchers object "
                     "(e.g. {\"alertname\": \"KVPagesExhausted\"})")
        until = body.get("until")
        if until is None and body.get("duration_s") is not None:
            try:
                until = plane.clock() + float(body["duration_s"])
            except (TypeError, ValueError):
                raise ApiHttpError(400, "duration_s must be a number")
        try:
            until_f = float(until)
        except (TypeError, ValueError):
            raise ApiHttpError(
                400, "silence needs until=<unix seconds> or "
                     "duration_s=<seconds>")
        try:
            entry = plane.silences.add(
                body["matchers"], until_f,
                comment=str(body.get("comment", "")), created_by=user)
        except ValueError as e:
            raise ApiHttpError(400, str(e))
        return 201, entry

    def delete_silence(self, req: HttpReq):
        self._user(req)
        sid = req.params["id"]
        if not self._plane().silences.delete(sid):
            raise ApiHttpError(404, f"no silence {sid!r}")
        return 200, {"deleted": sid}

    # -- wiring -------------------------------------------------------------

    def router(self) -> Router:
        r = Router("dashboard")
        r.route("GET", "/api/workgroup/exists", self.exists)
        r.route("POST", "/api/workgroup/create", self.create)
        r.route("GET", "/api/workgroup/env-info", self.env_info)
        r.route("GET", "/api/workgroup/get-all-namespaces", self.get_all_namespaces)
        r.route("GET", "/api/workgroup/get-contributors/{namespace}",
                self.get_contributors)
        r.route("POST", "/api/workgroup/add-contributor/{namespace}",
                self.add_contributor)
        r.route("DELETE", "/api/workgroup/remove-contributor/{namespace}",
                self.remove_contributor)
        r.route("DELETE", "/api/workgroup/nuke-self", self.nuke_self)
        r.route("GET", "/api/namespaces/{namespace}/notebooks", self.notebooks)
        r.route("GET", "/api/namespaces/{namespace}/jaxjobs", self.jaxjobs)
        r.route("GET", "/api/serving/models", self.serving_models)
        r.route("GET", "/api/activities/{namespace}", self.activities)
        r.route("GET", "/api/traces", self.traces)
        r.route("GET", "/api/metrics/{type}", self.get_metrics)
        r.route("GET", "/api/alerts", self.alerts)
        r.route("GET", "/api/query", self.obs_query)
        r.route("GET", "/api/goodput", self.goodput)
        r.route("GET", "/api/chargeback", self.chargeback)
        r.route("GET", "/api/silences", self.silences)
        r.route("POST", "/api/silences", self.silences)
        r.route("DELETE", "/api/silences/{id}", self.delete_silence)
        # browser UI (the Polymer SPA equivalent, webapps/dashboard_ui.py)
        from kubeflow_tpu.webapps.dashboard_ui import add_ui_routes

        add_ui_routes(r)
        httpd.add_health_routes(r)
        httpd.add_metrics_route(r)
        return r

    def serve(self, host: str = "0.0.0.0", port: int = 8082) -> httpd.HttpService:
        return httpd.HttpService(self.router(), host, port)
