"""Fleet-scale control-plane smoke (ISSUE 7 acceptance, tier-1 sized).

Runs the deterministic synthetic-fleet benchmark (tools/sched_bench.py)
at ~200 nodes and asserts the budgets that must not regress:

- op budget (deterministic): the cached scheduler performs ZERO
  per-pass FakeCluster list scans — every hot-path read is served by
  the ClusterCache indexes; the legacy arm's scans stay nonzero, so
  the >= 10x reduction holds by construction at any scale;
- semantic budget: the cache and legacy arms produce byte-identical
  final bindings (no drift from the indexed rewrite), and neither arm
  ever oversubscribes a node or leaves a bound-but-gated pod;
- latency budget: pass p99 under a deliberately generous wall-clock
  ceiling (the sharp number lives in BENCH_SCHED_r01.json, gated by
  ``sched_bench.py --check`` at 25%).
"""

import importlib.util
import json
import pathlib
import sys

import pytest

TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"

# generous CI ceiling; the banked budget (BENCH_SCHED_r01.json smoke
# section) is the sharp one
PASS_P99_CEILING_MS = 250.0


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "sched_bench", TOOLS / "sched_bench.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("sched_bench", mod)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load_bench()


@pytest.fixture(scope="module")
def smoke_pair(bench):
    """One cache/legacy pair at the banked smoke config, shared by the
    budget and equivalence assertions (the runs are deterministic)."""
    config = dict(bench.SMOKE_CONFIG)
    cache = bench.run_bench(cache=True, **config)
    legacy = bench.run_bench(cache=False, **config)
    return bench, config, cache, legacy


@pytest.mark.usefixtures("virtual_time_guard")
class TestScaleSmoke:
    def test_cache_arm_makes_zero_per_pass_list_scans(self, smoke_pair):
        _bench, _config, cache, legacy = smoke_pair
        assert cache["passes"] > 100          # the run did real work
        assert cache["ops"]["list_calls"] == 0
        assert cache["ops"]["list_scanned"] == 0
        assert cache["ops"]["list_copied"] == 0
        # legacy still relists per pass: the >= 10x scan reduction of
        # the acceptance criteria is structural, pinned here exactly
        assert legacy["scan_per_pass"] > 10 * max(cache["scan_per_pass"],
                                                  1.0)

    def test_admission_results_identical_across_arms(self, smoke_pair):
        _bench, _config, cache, legacy = smoke_pair
        assert cache["bindings"] == legacy["bindings"]
        assert cache["admitted_gangs"] == legacy["admitted_gangs"]
        assert cache["admitted_gangs"] >= 40   # of 50: the fleet filled

    def test_pass_p99_within_ceiling(self, smoke_pair):
        _bench, _config, cache, _legacy = smoke_pair
        assert 0.0 < cache["pass_p99_ms"] < PASS_P99_CEILING_MS

    def test_banked_budget_gate(self, bench, tmp_path):
        """--check fails loudly (exit 1) when the committed budget
        regresses by > 25%, passes when it holds."""
        config = {"nodes": 80, "gangs": 12, "pods": 100, "seed": 0,
                  "waves": 3}
        now = bench.run_bench(cache=True, **config)
        banked = {
            "smoke": {
                "config": config,
                "cache": {"scan_per_pass": now["scan_per_pass"],
                          "pass_p99_ms": now["pass_p99_ms"]},
            }
        }
        ok_path = tmp_path / "bank_ok.json"
        ok_path.write_text(json.dumps(banked))
        assert bench.check_against(str(ok_path)) == 0
        # a banked budget 100x tighter than reality: must regress
        banked["smoke"]["cache"] = {
            "scan_per_pass": -1.0,
            "pass_p99_ms": now["pass_p99_ms"] / 100.0}
        bad_path = tmp_path / "bank_bad.json"
        bad_path.write_text(json.dumps(banked))
        assert bench.check_against(str(bad_path)) == 1

    def test_committed_bank_exists_and_meets_acceptance(self):
        """BENCH_SCHED_r01.json is committed with the 5k-node numbers
        and the acceptance ratios: >= 10x list-scan reduction, >= 5x
        p99 pass duration, identical bindings across arms."""
        path = TOOLS.parent / "BENCH_SCHED_r01.json"
        banked = json.loads(path.read_text())
        full = banked["full"]
        assert full["config"]["nodes"] == 5000
        assert full["config"]["gangs"] == 1000
        assert full["config"]["pods"] == 10000
        cmp_ = full["comparison"]
        assert cmp_["bindings_identical"] is True
        assert cmp_["scan_reduction_x"] >= 10
        assert cmp_["p99_speedup_x"] >= 5
        assert banked["smoke"]["cache"]["pass_p99_ms"] > 0
