#!/usr/bin/env bash
# Second-stage supervisor: the phase-2 watcher that launched at 12:05
# parsed an older queue (no fused-serving or hd128-microbench stages).
# Wait for it (watch_lib's pidfile) to exit, then run the updated
# round5b — run_stage skips every .done/.skip stage, so only the new
# and unsettled work executes.
set -u
cd "$(dirname "$0")/.."
PIDFILE=/tmp/kftpu_watch.pid

alive() {
  local pid
  pid=$(cat "$PIDFILE" 2>/dev/null)
  [ -n "$pid" ] && kill -0 "$pid" 2>/dev/null
}

sleep 30
while alive; do sleep 60; done
echo "$(date -u +%H:%M:%S) prior watcher exited — running updated phase 2" \
  >> tools/round5_watch.log
exec bash tools/round5b_watch.sh
