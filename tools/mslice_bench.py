#!/usr/bin/env python
"""mslice_bench — deterministic multi-slice admission + reclaim benchmark.

Drives the JAXJob controller AND the gang scheduler against one
FakeCluster (the production loop: JAXJob renders a gated multi-slice
gang -> scheduler admits slice-by-slice, all-or-nothing across slices
-> kubelet runs bound pods) over a 4-pool fleet, measuring what the
multi-slice plane promises:

- **admission latency** (virtual seconds on the injectable clock) for
  64 multi-slice gangs created in waves with completion churn;
- **placement quality**: every admitted slice confined to ONE
  (accelerator, topology) pool — ``slices_intact`` must be 1.0 — plus
  how often admission exercised its freedom to spread a gang's slices
  across pools;
- a scripted **slice-reclaim drill**: a slice-elastic gang loses a
  whole pool mid-run, shrinks to the surviving slice (zero restart
  budget), grows back when the pool heals, and runs to Succeeded —
  each phase's virtual-time latency is banked. (Loss-curve continuity
  through the same shrink is proven end-to-end on the loopback
  collectives backend in tests/test_mslice_e2e.py; this drill banks
  the control-plane state machine.)

Everything runs on the manual clock — zero wall-clock sleeps — so the
scheduling DECISIONS replay exactly per seed: the bench hashes them
into a decision fingerprint that must be byte-stable across runs and
machines (the tier-1 contract in tests/test_mslice_scale.py reruns it
twice and against the committed bank).

    python tools/mslice_bench.py                 # full + smoke + drill,
                                                 # write BENCH_MSLICE_r01.json
    python tools/mslice_bench.py --gangs 16 --waves 4
    python tools/mslice_bench.py --check         # CI gate: rerun the banked
        # smoke + drill; fail on fingerprint drift or a > 25% latency
        # regression (virtual time, so any drift is a semantic change)
"""

from __future__ import annotations

import argparse
import hashlib
import json
import math
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeflow_tpu.control.jaxjob import types as JT  # noqa: E402
from kubeflow_tpu.control.jaxjob.controller import (  # noqa: E402
    build_controller,
)
from kubeflow_tpu.control.k8s import objects as ob  # noqa: E402
from kubeflow_tpu.control.k8s.fake import FakeCluster  # noqa: E402
from kubeflow_tpu.control.k8s.kubelet import FakeKubelet  # noqa: E402
from kubeflow_tpu.control.runtime import seed_controller  # noqa: E402
from kubeflow_tpu.control.scheduler import SCHEDULER_NAME  # noqa: E402
from kubeflow_tpu.control.scheduler import nodes as N  # noqa: E402
from kubeflow_tpu.control.scheduler.scheduler import (  # noqa: E402
    build_scheduler,
)
from kubeflow_tpu.control.scheduler.topology import chip_count  # noqa: E402
from kubeflow_tpu.runtime.metrics import MetricsRegistry  # noqa: E402

DEFAULT_OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_MSLICE_r01.json")

# The fleet's pools: (accelerator, topology, hosts). Two pools share an
# accelerator so a v5-lite gang's slices may legally spread across
# them; the v5p/v6e pools are single-home.
POOLS = (
    ("tpu-v5-lite-podslice", "2x4", 12),
    ("tpu-v5-lite-podslice", "4x4", 8),
    ("tpu-v5p-slice", "2x2", 6),
    ("tpu-v6e-slice", "2x4", 6),
)
TENANTS = 4
REPLICAS_PER_SLICE = 2   # hosts per slice; chips_per_worker=4 fills a host
ROUNDS_PER_WAVE = 12
DRAIN_EPOCHS = 24


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def build_world(clock: ManualClock):
    cluster = FakeCluster()
    registry = MetricsRegistry()
    jax_ctl = seed_controller(build_controller(cluster, record_events=False))
    sched_ctl = seed_controller(build_scheduler(
        cluster, registry=registry, record_events=False, clock=clock))
    kubelet = FakeKubelet(cluster, auto_bind=False)
    return cluster, jax_ctl, sched_ctl, kubelet, registry


def build_fleet(cluster: FakeCluster) -> None:
    for pi, (accel, topo, hosts) in enumerate(POOLS):
        for i in range(hosts):
            cluster.create(N.new_tpu_node(
                f"p{pi}-{i:03d}", accelerator=accel, topology=topo,
                chips_per_node=4))


def step(ctls, kubelet, clock: ManualClock, dt: float = 1.0) -> None:
    for c in ctls:
        c.run_until_idle(max_rounds=100000, advance_delayed=True)
    kubelet.step()
    clock.advance(dt)


def gang_specs(rng: random.Random, gangs: int) -> list[dict]:
    """Deterministic workload: every gang is feasible (a v5p/v6e gang
    never asks for more slices than its single pool can hold, so strict
    FIFO can't head-block forever), and each tiles its pool's slice
    topology exactly (replicas x 4 chips == chips per slice)."""
    specs = []
    for i in range(gangs):
        pool_i = rng.choice((0, 0, 1, 2, 3))   # v5-lite-heavy, like fleets
        accel, topo, hosts = POOLS[pool_i]
        replicas = chip_count(topo) // 4       # hosts per slice
        max_slices = min(4 if accel == "tpu-v5-lite-podslice" else 2,
                         hosts // replicas)
        specs.append({
            "namespace": f"tenant-{i % TENANTS}",
            "name": f"ms-{i:04d}",
            "accelerator": accel,
            "topology": topo,
            "replicas": replicas,
            "slice_count": rng.randint(2, max(max_slices, 2)),
        })
    return specs


def make_gang(cluster: FakeCluster, spec: dict) -> None:
    cluster.create(JT.new_jaxjob(
        spec["name"], namespace=spec["namespace"],
        replicas=spec["replicas"], slice_count=spec["slice_count"],
        accelerator=spec["accelerator"], topology=spec["topology"],
        chips_per_worker=4, gang_schedule=True))


def _jobs(cluster: FakeCluster):
    return cluster.list(JT.API_VERSION, JT.KIND)


def complete_running(cluster: FakeCluster) -> int:
    """Mark every fully-Running gang's pods Succeeded — frees its hosts
    for the queue, deterministically (name order via list)."""
    done = 0
    for job in _jobs(cluster):
        if not ob.cond_is_true(job, JT.COND_RUNNING):
            continue
        m = ob.meta(job)
        for p in cluster.list("v1", "Pod", namespace=m["namespace"]):
            if ob.labels_of(p).get(JT.LABEL_JOB_NAME) != m["name"]:
                continue
            if (p.get("status") or {}).get("phase") in N.TERMINAL_PHASES:
                continue
            cur = cluster.get("v1", "Pod", ob.meta(p)["name"], m["namespace"])
            cur.setdefault("status", {})["phase"] = "Succeeded"
            cluster.update_status(cur)
        done += 1
    return done


def _pool_of_node(cluster: FakeCluster, name: str) -> tuple[str, str]:
    labels = ob.labels_of(cluster.get("v1", "Node", name))
    return (labels.get(JT.NODESELECTOR_ACCEL),
            labels.get(JT.NODESELECTOR_TOPOLOGY))


def snapshot_placement(cluster: FakeCluster, spec: dict) -> dict[str, str]:
    """pod -> node for one gang, captured the moment it turns Running
    (the controller garbage-collects pods after completion, so the
    decision must be recorded when it's made)."""
    out = {}
    per = spec.get("replicas", REPLICAS_PER_SLICE)
    count = spec["slice_count"]
    for i in range(count * per):
        try:
            pod = cluster.get("v1", "Pod", f"{spec['name']}-worker-{i}",
                              spec["namespace"])
        except ob.NotFound:
            continue
        node = (pod.get("spec") or {}).get("nodeName")
        if node:
            out[f"{spec['name']}-worker-{i}"] = node
    return out


def placement_quality(cluster: FakeCluster, specs: list[dict],
                      placements: dict[str, dict[str, str]]) -> dict:
    """Slice integrity + pool spread over the admission-time snapshots
    (nodes persist, so pool lookup stays live)."""
    slices_total = slices_intact = 0
    cross_pool_gangs = pools_per_gang_sum = placed_gangs = 0
    for spec in specs:
        placed = placements.get(
            f"{spec['namespace']}/{spec['name']}")
        if not placed:
            continue
        per = spec.get("replicas", REPLICAS_PER_SLICE)
        count = spec["slice_count"]
        nodes_by_slice: dict[int, set[str]] = {}
        for pod_name, node in placed.items():
            idx = int(pod_name.rsplit("-", 1)[1])
            nodes_by_slice.setdefault(idx // per, set()).add(node)
        gang_pools = set()
        bound_slices = 0
        for _sid, nodes in sorted(nodes_by_slice.items()):
            if len(nodes) < per:
                continue   # partially bound slice: integrity unjudgeable
            bound_slices += 1
            slices_total += 1
            pools = {_pool_of_node(cluster, n) for n in nodes}
            if len(pools) == 1:
                slices_intact += 1
            gang_pools |= pools
        if bound_slices == count:
            placed_gangs += 1
            pools_per_gang_sum += len(gang_pools)
            if len(gang_pools) > 1:
                cross_pool_gangs += 1
    return {
        "slices_total": slices_total,
        "slices_intact": round(slices_intact / slices_total, 4)
        if slices_total else 0.0,
        "placed_gangs": placed_gangs,
        "cross_pool_gangs": cross_pool_gangs,
        "mean_pools_per_gang": round(pools_per_gang_sum / placed_gangs, 3)
        if placed_gangs else 0.0,
    }


def decision_fingerprint(payload: dict) -> str:
    """sha256 over a canonical-JSON decision record — byte-stable
    across runs and machines iff the DECISIONS (placements, slice
    vectors, virtual-time latencies) are."""
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def _percentile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(math.ceil(q * len(xs))) - 1)]


def run_admission(gangs: int = 64, seed: int = 0, waves: int = 8) -> dict:
    rng = random.Random(seed)
    clock = ManualClock()
    cluster, jax_ctl, sched_ctl, kubelet, registry = build_world(clock)
    ctls = [jax_ctl, sched_ctl]
    build_fleet(cluster)
    step(ctls, kubelet, clock)

    specs = gang_specs(rng, gangs)
    by_key = {f"{s['namespace']}/{s['name']}": s for s in specs}
    created: dict[str, float] = {}
    admitted_at: dict[str, float] = {}
    placements: dict[str, dict[str, str]] = {}

    per_wave = math.ceil(len(specs) / waves)

    def observe() -> None:
        for job in _jobs(cluster):
            m = ob.meta(job)
            key = f"{m['namespace']}/{m['name']}"
            if key not in admitted_at and ob.cond_is_true(
                    job, JT.COND_RUNNING):
                admitted_at[key] = clock.t
                placements[key] = snapshot_placement(cluster, by_key[key])

    for wave in range(waves):
        for spec in specs[wave * per_wave:(wave + 1) * per_wave]:
            make_gang(cluster, spec)
            created[f"{spec['namespace']}/{spec['name']}"] = clock.t
        for _ in range(ROUNDS_PER_WAVE):
            step(ctls, kubelet, clock)
            observe()
        complete_running(cluster)
    # drain: keep completing until the queue is empty or stalls. Bigger
    # virtual steps (dt=4) burn through exponential requeue backoffs
    # that the 1s wave cadence would idle under.
    for _ in range(DRAIN_EPOCHS):
        progressed = False
        for _ in range(ROUNDS_PER_WAVE):
            step(ctls, kubelet, clock, dt=4.0)
            observe()
        if complete_running(cluster):
            progressed = True
        if len(admitted_at) == len(created) and not progressed:
            break

    latencies = [admitted_at[k] - created[k] for k in admitted_at]
    return {
        "gangs": gangs,
        "admitted_gangs": len(admitted_at),
        "admission_p50_s": _percentile(latencies, 0.50),
        "admission_p99_s": _percentile(latencies, 0.99),
        "admission_max_s": max(latencies, default=0.0),
        "quality": placement_quality(cluster, specs, placements),
        "slice_admissions_metric": registry.render().count(
            "scheduler_slice_admissions_total{"),
        "fingerprint": decision_fingerprint({
            "placements": placements,
            "latencies": {k: admitted_at[k] - created[k]
                          for k in admitted_at}}),
    }


# -- the scripted slice-reclaim drill ----------------------------------------


def _drill_status(cluster: FakeCluster) -> dict:
    return (cluster.get(JT.API_VERSION, JT.KIND, "drill", "default")
            .get("status") or {})


def _set_pool_ready(cluster: FakeCluster, prefix: str, n: int,
                    ready: bool) -> None:
    for i in range(n):
        node = cluster.get("v1", "Node", f"{prefix}{i}")
        node.setdefault("status", {})["conditions"] = [
            {"type": "Ready", "status": "True" if ready else "False"}]
        cluster.update_status(node)


def _pump_until(ctls, kubelet, clock, pred, limit: int = 120) -> float:
    t0 = clock.t
    for _ in range(limit):
        if pred():
            return clock.t - t0
        step(ctls, kubelet, clock)
    raise AssertionError(f"drill phase did not converge in {limit} steps")


def run_drill(seed: int = 0) -> dict:
    """Shrink -> resume -> grow -> Succeeded on the real controller +
    scheduler paths: a 2-slice slice-elastic gang loses its second
    pool, shrinks to the survivor (zero restart budget), grows back
    into the healed pool, and completes."""
    clock = ManualClock()
    cluster, jax_ctl, sched_ctl, kubelet, _registry = build_world(clock)
    ctls = [jax_ctl, sched_ctl]
    for i in range(2):
        cluster.create(N.new_tpu_node(f"a{i}", topology="2x4"))
        cluster.create(N.new_tpu_node(f"b{i}", topology="4x4"))
    step(ctls, kubelet, clock)

    cluster.create(JT.new_jaxjob(
        "drill", replicas=REPLICAS_PER_SLICE, slice_count=2,
        accelerator="tpu-v5-lite-podslice", topology="2x4",
        chips_per_worker=4, gang_schedule=True,
        elastic_min=2 * REPLICAS_PER_SLICE,
        slice_policy=JT.SLICE_SHRINK, min_slices=1))
    drill_spec = {"name": "drill", "namespace": "default", "slice_count": 2}
    # full admission stamps no status.world (only resizes do): Running
    # with all four workers bound IS the 2-slice steady state
    t_admit = _pump_until(
        ctls, kubelet, clock,
        lambda: ob.cond_is_true(
            cluster.get(JT.API_VERSION, JT.KIND, "drill", "default"),
            JT.COND_RUNNING)
        and len(snapshot_placement(cluster, drill_spec)) == 4)
    placed_admit = snapshot_placement(cluster, drill_spec)

    # which pool did slice 1 land in? kill it whole (the reclaim shape:
    # a slice dies as a unit)
    victim_prefix = "b" if any(
        (cluster.get("v1", "Pod", f"drill-worker-{i}", "default")
         .get("spec") or {}).get("nodeName", "").startswith("b")
        for i in (2, 3)) else "a"
    _set_pool_ready(cluster, victim_prefix, 2, ready=False)
    t_shrink = _pump_until(
        ctls, kubelet, clock,
        lambda: _drill_status(cluster).get("activeSlices") == 1)

    _set_pool_ready(cluster, victim_prefix, 2, ready=True)
    t_grow = _pump_until(
        ctls, kubelet, clock,
        lambda: _drill_status(cluster).get("activeSlices") == 2)
    placed_grow = snapshot_placement(cluster, drill_spec)
    st = _drill_status(cluster)
    restarts = st.get("restarts", 0)
    preemptions = st.get("preemptions", 0)

    complete_running(cluster)
    t_done = _pump_until(
        ctls, kubelet, clock,
        lambda: ob.cond_is_true(
            cluster.get(JT.API_VERSION, JT.KIND, "drill", "default"),
            JT.COND_SUCCEEDED))
    return {
        "admit_s": t_admit,
        "shrink_s": t_shrink,
        "grow_s": t_grow,
        "complete_s": t_done,
        "restarts": restarts,
        "preemptions": preemptions,
        "fingerprint": decision_fingerprint({
            "admit": placed_admit, "grow": placed_grow,
            "latencies": [t_admit, t_shrink, t_grow]}),
    }


# -- bank + ratchet ----------------------------------------------------------

SMOKE_CONFIG = {"gangs": 16, "seed": 0, "waves": 4}


def check_against(banked_path: str) -> int:
    """CI ratchet: rerun the banked smoke + drill. Fails (1) when the
    decision fingerprints drift (virtual time: ANY drift is a semantic
    change, not noise) or a virtual-time latency regresses > 25%."""
    try:
        with open(banked_path) as fh:
            banked = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"check: cannot read {banked_path}: {e}", file=sys.stderr)
        return 2
    smoke, drill = banked.get("smoke"), banked.get("drill")
    if not smoke or not drill:
        print(f"check: no smoke/drill sections in {banked_path}",
              file=sys.stderr)
        return 2
    now = run_admission(**banked["smoke_config"])
    now_drill = run_drill()
    ok = True
    if now["fingerprint"] != smoke["fingerprint"]:
        print("check: smoke decision fingerprint drifted "
              f"({now['fingerprint'][:12]} != banked "
              f"{smoke['fingerprint'][:12]}) — the multislice admission "
              "DECISIONS changed; rerun tools/mslice_bench.py to re-bank "
              "if intended", file=sys.stderr)
        ok = False
    if now_drill["fingerprint"] != drill["fingerprint"]:
        print("check: drill decision fingerprint drifted", file=sys.stderr)
        ok = False
    if now["admitted_gangs"] < smoke["admitted_gangs"]:
        print(f"check: admitted_gangs {now['admitted_gangs']} < banked "
              f"{smoke['admitted_gangs']}", file=sys.stderr)
        ok = False
    for section, fresh, keys in (
            ("smoke", now, ("admission_p99_s",)),
            ("drill", now_drill, ("shrink_s", "grow_s"))):
        for key in keys:
            budget = banked[section][key] * 1.25
            if fresh[key] > budget:
                print(f"check: {section}.{key} {fresh[key]} exceeds budget "
                      f"{budget:.2f} (banked {banked[section][key]})",
                      file=sys.stderr)
                ok = False
    if now_drill["restarts"] != 0:
        print(f"check: drill burned {now_drill['restarts']} restarts "
              "(slice shrink must be restart-free)", file=sys.stderr)
        ok = False
    print(json.dumps({"check": "ok" if ok else "REGRESSED",
                      "admission_p99_s": now["admission_p99_s"],
                      "admitted_gangs": now["admitted_gangs"],
                      "drill": {k: now_drill[k] for k in
                                ("shrink_s", "grow_s", "restarts")}},
                     indent=2))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--gangs", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--waves", type=int, default=8)
    ap.add_argument("--out", default=DEFAULT_OUT)
    ap.add_argument("--check", action="store_true",
                    help="rerun the banked smoke + drill and gate on "
                         "fingerprint drift or a >25%% latency regression")
    args = ap.parse_args(argv)
    if args.check:
        return check_against(args.out)

    full = run_admission(gangs=args.gangs, seed=args.seed, waves=args.waves)
    smoke = run_admission(**SMOKE_CONFIG)
    drill = run_drill()
    if full["quality"]["slices_intact"] != 1.0:
        print("WARNING: a bound slice straddles pools — slice affinity "
              "is broken", file=sys.stderr)
    result = {
        "bench": "mslice_bench",
        "round": "r01",
        "config": {"gangs": args.gangs, "seed": args.seed,
                   "waves": args.waves},
        "smoke_config": dict(SMOKE_CONFIG),
        "full": full,
        "smoke": smoke,
        "drill": drill,
    }
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps({"out": args.out,
                      "admitted": f"{full['admitted_gangs']}/{args.gangs}",
                      "admission_p99_s": full["admission_p99_s"],
                      "quality": full["quality"],
                      "drill": {k: drill[k] for k in
                                ("admit_s", "shrink_s", "grow_s",
                                 "restarts")}},
                     indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
