"""Profile + Tensorboard controller semantics (reference:
profile_controller.go, tensorboard_controller.go; plugin tests mirror
plugin_workload_identity_test.go)."""

import pytest

from kubeflow_tpu.control.k8s import objects as ob
from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.profile import types as PT
from kubeflow_tpu.control.profile.controller import (
    WorkloadIdentityPlugin,
    build_controller as build_profile_controller,
)
from kubeflow_tpu.control.runtime import seed_controller
from kubeflow_tpu.control.tensorboard import controller as TB


def drain(ctl):
    for _ in range(4):
        ctl.run_until_idle(advance_delayed=True)


class FakeIAM:
    def __init__(self):
        self.bindings = set()

    def bind(self, gsa, ksa):
        self.bindings.add((gsa, ksa))

    def unbind(self, gsa, ksa):
        self.bindings.discard((gsa, ksa))


@pytest.fixture()
def world():
    cluster = FakeCluster()
    iam = FakeIAM()
    plugins = {"WorkloadIdentity": WorkloadIdentityPlugin(iam_backend=iam)}
    ctl = seed_controller(build_profile_controller(cluster, plugins=plugins))
    return cluster, ctl, iam


class TestProfile:
    def test_full_namespace_provisioning(self, world):
        cluster, ctl, _ = world
        cluster.create(PT.new_profile("team-a", "alice@example.com",
                                      tpu_chip_quota=16, cpu_quota="100"))
        drain(ctl)
        ns = cluster.get("v1", "Namespace", "team-a")
        assert ns["metadata"]["labels"]["istio-injection"] == "enabled"
        assert ob.annotations_of(ns)["owner"] == "alice@example.com"
        for sa in (PT.SA_EDITOR, PT.SA_VIEWER):
            assert cluster.get("v1", "ServiceAccount", sa, "team-a")
        rb = cluster.get("rbac.authorization.k8s.io/v1", "RoleBinding",
                         "namespaceAdmin", "team-a")
        assert rb["roleRef"]["name"] == PT.ADMIN_CLUSTER_ROLE
        assert rb["subjects"][0]["name"] == "alice@example.com"
        quota = cluster.get("v1", "ResourceQuota", PT.QUOTA_NAME, "team-a")
        assert quota["spec"]["hard"][f"requests.{PT.RESOURCE_TPU}"] == 16
        pol = cluster.get("security.istio.io/v1beta1", "AuthorizationPolicy",
                          "ns-owner-access", "team-a")
        assert pol["spec"]["rules"]
        prof = cluster.get(PT.API_VERSION, PT.KIND, "team-a")
        assert ob.cond_is_true(prof, "Ready")

    def test_sa_rolebindings_to_clusterroles(self, world):
        cluster, ctl, _ = world
        cluster.create(PT.new_profile("team-a", "alice@example.com"))
        drain(ctl)
        rb = cluster.get("rbac.authorization.k8s.io/v1", "RoleBinding",
                         PT.SA_EDITOR, "team-a")
        assert rb["roleRef"]["name"] == PT.EDIT_CLUSTER_ROLE
        assert rb["subjects"][0] == {"kind": "ServiceAccount",
                                     "name": PT.SA_EDITOR, "namespace": "team-a"}

    def test_ownership_conflict_rejected(self, world):
        """profile_controller.go:168-186: an existing namespace owned by a
        different user blocks the profile."""
        cluster, ctl, _ = world
        cluster.create(ob.new_object("v1", "Namespace", "taken",
                                     annotations={"owner": "bob@example.com"}))
        cluster.create(PT.new_profile("taken", "alice@example.com"))
        drain(ctl)
        prof = cluster.get(PT.API_VERSION, PT.KIND, "taken")
        c = ob.cond_get(prof, "Ready")
        assert c["status"] == "False" and c["reason"] == "NamespaceOwnershipConflict"
        # no SAs were provisioned into someone else's namespace
        assert cluster.get_or_none("v1", "ServiceAccount", PT.SA_EDITOR, "taken") is None

    def test_workload_identity_plugin(self, world):
        cluster, ctl, iam = world
        cluster.create(PT.new_profile(
            "team-a", "alice@example.com",
            plugins=[{"kind": "WorkloadIdentity",
                      "spec": {"gcpServiceAccount": "gsa@proj.iam.gserviceaccount.com"}}],
        ))
        drain(ctl)
        sa = cluster.get("v1", "ServiceAccount", PT.SA_EDITOR, "team-a")
        assert (ob.annotations_of(sa)[WorkloadIdentityPlugin.ANNOTATION]
                == "gsa@proj.iam.gserviceaccount.com")
        assert ("gsa@proj.iam.gserviceaccount.com", "team-a/default-editor") in iam.bindings

    def test_finalizer_revokes_plugins_and_deletes(self, world):
        cluster, ctl, iam = world
        cluster.create(PT.new_profile(
            "team-a", "alice@example.com",
            plugins=[{"kind": "WorkloadIdentity",
                      "spec": {"gcpServiceAccount": "gsa@p.iam.gserviceaccount.com"}}],
        ))
        drain(ctl)
        assert iam.bindings
        cluster.delete(PT.API_VERSION, PT.KIND, "team-a")
        drain(ctl)
        assert cluster.get_or_none(PT.API_VERSION, PT.KIND, "team-a") is None
        assert not iam.bindings
        # namespace cascades via ownerRef GC
        assert cluster.get_or_none("v1", "Namespace", "team-a") is None


class TestTensorboard:
    @pytest.fixture()
    def world(self):
        cluster = FakeCluster()
        ctl = seed_controller(TB.build_controller(cluster))
        return cluster, ctl

    def test_cloud_logspath_no_pvc(self, world):
        cluster, ctl = world
        cluster.create(TB.new_tensorboard("tb1", logspath="gs://bucket/runs"))
        drain(ctl)
        dep = cluster.get("apps/v1", "Deployment", "tb1", "default")
        spec = dep["spec"]["template"]["spec"]
        assert "volumes" not in spec
        assert "--logdir=gs://bucket/runs" in spec["containers"][0]["command"]
        svc = cluster.get("v1", "Service", "tb1", "default")
        assert svc["spec"]["ports"][0]["targetPort"] == 6006

    def test_local_logspath_mounts_pvc(self, world):
        cluster, ctl = world
        cluster.create(TB.new_tensorboard("tb1", logspath="/data/logs"))
        drain(ctl)
        dep = cluster.get("apps/v1", "Deployment", "tb1", "default")
        spec = dep["spec"]["template"]["spec"]
        assert spec["volumes"][0]["persistentVolumeClaim"]["claimName"] == "tb1-logs"
        assert spec["containers"][0]["volumeMounts"][0]["mountPath"] == "/data/logs"

    def test_ready_follows_deployment(self, world):
        cluster, ctl = world
        cluster.create(TB.new_tensorboard("tb1", logspath="gs://b/r"))
        drain(ctl)
        tb = cluster.get(TB.API_VERSION, TB.KIND, "tb1", "default")
        assert not ob.cond_is_true(tb, "Ready")
        dep = cluster.get("apps/v1", "Deployment", "tb1", "default")
        dep["status"] = {"readyReplicas": 1}
        cluster.update_status(dep)
        drain(ctl)
        tb = cluster.get(TB.API_VERSION, TB.KIND, "tb1", "default")
        assert ob.cond_is_true(tb, "Ready")
