#!/usr/bin/env bash
# Round-4 persistent hardware watcher with a stage ledger.
#
# Round 3's one-shot follow-up (tunnel_followup.sh) ran its whole queue in
# the first up-window and exited — but the tunnel's up-windows are short
# (~1h) and unpredictable, so a queue ordered frontier-first can burn the
# whole window compiling one llama point and bank nothing. This watcher:
#   - probes every ~4 min;
#   - runs stages in VALUE order (driver-reproducible validation bench
#     first, then serving A/Bs, then the measurement frontier);
#   - marks each completed stage in tools/r4_stages/ so later windows
#     resume where the last one ended instead of starting over;
#   - re-probes the tunnel between stages so a mid-window drop only
#     costs the in-flight stage.
#
# Run from the repo root (or the .sweepsnap copy): bash tools/round4_watch.sh
set -u
cd "$(dirname "$0")/.."
LOG=tools/round4_watch.log
LEDGER=tools/r4_stages
mkdir -p "$LEDGER"

probe() { timeout 240 python -c "import jax; jax.devices()" >/dev/null 2>&1; }

note() { echo "$(date -u +%H:%M:%S) $*" >> "$LOG"; }

# run NAME TIMEOUT CMD... — execute once, mark done on rc==0. Each
# stage's stdout/stderr goes to its own $LEDGER/$name.out (bench JSON
# lines land there for the promote step) and is appended to LOG. A stage
# that fails twice is marked .skip — a deterministic OOM must not burn
# every future up-window re-compiling at the head of the queue (several
# frontier points are explicit "IF it fits" candidates).
run_stage() {
  local name="$1" tmo="$2"; shift 2
  [ -e "$LEDGER/$name.done" ] && return 0
  [ -e "$LEDGER/$name.skip" ] && return 0
  # Yield to the DRIVER's bench: stages run bench sequentially from this
  # process, so any bench.py alive at stage-start belongs to someone
  # else (the driver's round-end capture) — the one chip must be theirs.
  if pgrep -f "[b]ench.py" >/dev/null 2>&1; then
    note "external bench.py running — yielding the chip before $name"
    return 1
  fi
  if ! probe; then note "tunnel dropped before $name"; return 1; fi
  note "stage $name: $*"
  if timeout "$tmo" "$@" > "$LEDGER/$name.out" 2>&1; then
    touch "$LEDGER/$name.done"; note "stage $name DONE"
    cat "$LEDGER/$name.out" >> "$LOG"; return 0
  fi
  local rc=$?
  echo x >> "$LEDGER/$name.fail"
  if [ "$(wc -l < "$LEDGER/$name.fail")" -ge 2 ]; then
    mv "$LEDGER/$name.fail" "$LEDGER/$name.skip"
    note "stage $name FAILED twice (rc=$rc) — skipping from now on"
  else
    note "stage $name FAILED (rc=$rc) — one retry left"
  fi
  cat "$LEDGER/$name.out" >> "$LOG"
  return 1
}

while true; do
  if probe; then
    note "tunnel UP — resuming ledger"
    # 1. Headline validation: ResNet + promoted LM point, the exact
    #    command the driver runs. Reproduces r3's 0.4936 under witness.
    run_stage validate_bench 2400 python bench.py
    # 2. MoE hardware point (VERDICT #5: first gpt-moe-8e measurement).
    run_stage moe_point 1800 python bench.py --workload lm \
      --lm-model gpt-moe-8e --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    # 3. Serving ledger (VERDICT #4): prefill chunking, int8 weights,
    #    int8 KV on a GQA model with a real cache.
    run_stage serve_prefill_per_token 1800 env KFTPU_PREFILL_CHUNK=1 \
      python tools/serve_bench.py --modes micro --requests 16 \
      --param-dtype bfloat16
    run_stage serve_prefill_chunked 1800 python tools/serve_bench.py \
      --modes micro --requests 16 --param-dtype bfloat16
    run_stage serve_cont_bf16 1800 python tools/serve_bench.py \
      --modes continuous --requests 32 --param-dtype bfloat16
    run_stage serve_cont_int8 1800 python tools/serve_bench.py \
      --modes continuous --requests 32 --param-dtype int8
    run_stage serve_kv_bf16 1800 python tools/serve_bench.py \
      --modes continuous --requests 16 --model llama-1b \
      --prompt-len 1024 --max-new-tokens 32 --slots 8 --param-dtype int8
    run_stage serve_kv_int8 1800 python tools/serve_bench.py \
      --modes continuous --requests 16 --model llama-1b \
      --prompt-len 1024 --max-new-tokens 32 --slots 8 \
      --param-dtype int8 --kv-cache-dtype int8
    # rolling-cache A/B: same window, bounded (O(window)) vs full
    # (O(max_seq)) cache — the decode-bandwidth claim measured
    run_stage serve_win_full 1800 python tools/serve_bench.py \
      --modes continuous --requests 16 --model llama-1b \
      --prompt-len 1024 --max-new-tokens 32 --slots 8 \
      --param-dtype int8 --attention-window 512
    run_stage serve_win_rolling 1800 python tools/serve_bench.py \
      --modes continuous --requests 16 --model llama-1b \
      --prompt-len 1024 --max-new-tokens 32 --slots 8 \
      --param-dtype int8 --attention-window 512 --rolling-kv-cache
    # 3b. ResNet byte-wall A/B (VERDICT #6): whole-forward remat trades
    #     the HBM activation round-trip for VMEM-fused recompute — the
    #     one lever that can move a 96%-of-roofline workload.
    run_stage resnet_remat_full 1800 python bench.py --workload resnet \
      --resnet-remat full
    run_stage resnet_remat_dots 1800 python bench.py --workload resnet \
      --resnet-remat dots
    # 4. Remat-policy frontier (VERDICT #2 — the route to >=0.55 at
    #    700M+). tools/remat_plan.py upper bounds (llama-1b bs16):
    #    dots = 23.6 GiB saved but only 6.5% replay; slim = 11.6 GiB at
    #    58%; full = 2.6 GiB at 100%. bs8 halves the activation bytes:
    #    dots@bs8 is the highest-MFU candidate IF it fits.
    run_stage lm_1b_bs8_dots 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    run_stage lm_760m_bs8_dots 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    run_stage lm_1b_bs8_slim 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim --lm-xent-chunks 8
    run_stage lm_1b_bs16_slim 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim --lm-xent-chunks 8
    run_stage lm_760m_bs16_slim 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy slim --lm-xent-chunks 8
    run_stage lm_350m_bs16_dots 1800 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    # stretch: bs16 dots on the big models — remat_plan upper bounds say
    # marginal; .skip machinery absorbs a deterministic OOM in one retry
    run_stage lm_1b_bs16_dots 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    run_stage lm_760m_bs16_dots 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy dots --lm-xent-chunks 8
    # 5. The 760m/llama full-remat frontier, chunked-CE era, one point
    #    per stage so a drop costs at most one compile.
    run_stage lm_760m_bs8_mlp 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 8 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy mlp --lm-xent-chunks 8
    run_stage lm_760m_bs16_full 1800 python bench.py --workload lm \
      --lm-model gpt-760m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy full --lm-xent-chunks 8
    run_stage lm_1b_bs16_full 1800 python bench.py --workload lm \
      --lm-model llama-1b --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy full --lm-xent-chunks 8
    run_stage lm_350m_bs16_full 1800 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 16 --lm-optimizer adafactor \
      --lm-remat --lm-remat-policy full --lm-xent-chunks 8
    # 5. Op microbenchmark (attributes the remaining MFU gap).
    run_stage microbench 2400 python tools/op_microbench.py \
      --batch 8 --seq 2048
    # 6. Feature-cost A/Bs (sliding window).
    run_stage lm_350m_win512 1500 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 8 --lm-optimizer adafactor \
      --lm-xent-chunks 8 --lm-window 512
    # long-context windowed pair (seq 8k): with the round-4 grid pruning
    # the windowed point's attention DMA is ~5x lower than full causal —
    # this pair is the hardware evidence (same model/batch, only the
    # window differs; windowed MFU is never promoted)
    run_stage lm_350m_8k_full 1800 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 2 --seq-len 8192 \
      --lm-optimizer adafactor --lm-remat --lm-remat-policy dots \
      --lm-xent-chunks 16
    run_stage lm_350m_8k_win512 1800 python bench.py --workload lm \
      --lm-model gpt-350m --lm-batch 2 --seq-len 8192 \
      --lm-optimizer adafactor --lm-remat --lm-remat-policy dots \
      --lm-xent-chunks 16 --lm-window 512
    # promote any measured LM/serving point that beats the ledger floor.
    # Pull the REPO's promotion files first: the floor must be the best
    # ever banked, not this snapshot's stale copy — otherwise a weaker
    # window could re-promote over a better earlier point. promote_*
    # re-derive the best from the FULL candidate ledger, so
    # pull -> promote -> push converges on the true max.
    if [ -d /root/repo/tools ] && [ "$PWD" != /root/repo ]; then
      for f in lm_best.json serve_best.json serve_table.json; do
        [ -e "/root/repo/tools/$f" ] && cp "/root/repo/tools/$f" tools/ || true
      done
    fi
    cat "$LEDGER"/*.out > tools/lm_sweep_r04.jsonl 2>/dev/null || true
    python tools/promote_best.py tools/lm_sweep_r04.jsonl >> "$LOG" 2>&1 || true
    python tools/promote_serve_best.py "$LEDGER"/serve_*.out >> "$LOG" 2>&1 || true
    # persist results into the REAL repo (this may run from a .sweepsnap
    # copy): the driver's round-end bench.py reads the repo's
    # tools/lm_best.json / serve_best.json, and uncommitted ledger files
    # are committed by the driver — measurements survive unattended.
    # Atomic per-file (tmp + rename): the driver's bench can json.load
    # these at any moment.
    if [ -d /root/repo/tools ] && [ "$PWD" != /root/repo ]; then
      for f in lm_best.json serve_best.json serve_table.json \
               lm_sweep_r04.jsonl round4_watch.log; do
        if [ -e "tools/$f" ]; then
          cp "tools/$f" "/root/repo/tools/.$f.tmp" \
            && mv "/root/repo/tools/.$f.tmp" "/root/repo/tools/$f" || true
        fi
      done
    fi
    settled=$(ls "$LEDGER"/*.done "$LEDGER"/*.skip 2>/dev/null | wc -l)
    if [ "$settled" -ge 28 ]; then
      note "all stages settled ($settled done+skip)"; exit 0
    fi
  else
    note "tunnel down"
  fi
  sleep 230
done
