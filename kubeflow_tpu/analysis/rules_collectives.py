"""tpulint collectives rule: COLL401 backend-encapsulation drift.

``parallel/backends.py`` is the repo's ONE spelling of the multi-slice
transport contract: the ``jax.distributed.initialize``/``shutdown``
lifecycle pair and the ``MEGASCALE_*`` env keys libtpu's DCN transport
reads at backend init. Every other module reaches collectives through
``get_backend()`` (or the ``dist.initialize_from_env`` facade that
routes into it), so swapping the backend — loopback in tests, TPU
ICI/DCN in production — swaps EVERY formation path at once. A second
call site or a re-spelled env key silently forks that contract: the
loopback tier stops covering it and a backend change misses it.

What fires: any call whose dotted name ends in ``distributed.initialize``
or ``distributed.shutdown`` (``jax.distributed.initialize``,
``from jax import distributed`` + ``distributed.shutdown``, aliased
roots), and any string literal that IS a ``MEGASCALE_*`` env key.
What stays silent (FP pins in tests/test_tpulint.py): the sanctioned
``get_backend()`` route, ``JAXJOB_*`` keys, and prose that merely
mentions megascale. ``parallel/backends.py`` itself is exempt — it is
the contract.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from kubeflow_tpu.analysis.core import Finding, Module, Rule, dotted, register

# the one module allowed to spell the transport contract
_HOME = "parallel/backends.py"

# a string literal that IS an env key of the MEGASCALE block (prose
# mentioning megascale, regex patterns, and partial words don't match)
_MS_KEY_RE = re.compile(r"MEGASCALE_[A-Z_]*\Z")

# dotted-call suffixes of the jax.distributed lifecycle pair; the bare
# spellings cover ``from jax import distributed`` imports
_LIFECYCLE = ("distributed.initialize", "distributed.shutdown")


def _exempt(path: str) -> bool:
    p = path.replace("\\", "/")
    # the analysis package necessarily spells the contracts it polices
    # (the WIRE ownership maps carry the same exemption)
    return p.endswith(_HOME) or "kubeflow_tpu/analysis/" in p


@register
class CollectivesEncapsulation(Rule):
    id = "COLL401"
    name = "collectives-encapsulation"
    short = ("jax.distributed lifecycle call or MEGASCALE env key outside "
             "parallel/backends.py; route through get_backend()")

    def check(self, module: Module) -> Iterator[Finding]:
        if _exempt(module.path):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name and (name in _LIFECYCLE
                             or name.endswith(
                                 tuple("." + s for s in _LIFECYCLE))):
                    yield self.finding(
                        module, node,
                        f"'{name}' called outside parallel/backends.py — "
                        "the distributed lifecycle belongs to the "
                        "collectives backend; route through "
                        "backends.get_backend().join()/leave()")
            elif (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _MS_KEY_RE.fullmatch(node.value)):
                yield self.finding(
                    module, node,
                    f"MEGASCALE env key '{node.value}' spelled outside "
                    "parallel/backends.py — use backends.slice_env() / "
                    "the MS_* constants so the transport contract has "
                    "one spelling")
