"""TPU kernels and compute building blocks.

- ``attention``      — XLA reference attention + dispatch to Pallas flash
                       attention on TPU.
- ``flash_attention``— Pallas TPU fused attention kernel.
- ``ring_attention`` — sequence-parallel blockwise attention over the ICI
                       ring (shard_map + collective-permute).
- ``moe``            — mixture-of-experts dispatch/combine with expert
                       parallelism (all-to-all).
"""
