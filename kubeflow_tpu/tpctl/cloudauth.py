"""Cloud credential plumbing for the tpctl plane.

The reference's deployment engine carries three pieces of GCP auth
machinery that the tpctl plane was missing:

- ``RefreshableTokenSource`` (bootstrap/cmd/bootstrap/app/tokenSource.go:35-75):
  a shared token holder whose ``refresh`` validates that the *new* token
  still grants access to the project before swapping it in, so in-flight
  users of the source never see a downgrade.
- ``check_project_access`` (gcpUtils.go:128-180): TestIamPermissions for
  ``resourcemanager.projects.setIamPolicy`` with exponential backoff —
  the validity gate used both by token refresh and request admission
  (kfctlServer.go:545).
- ``update_policy`` + ``prepare_account`` (gcpUtils.go:60-119): IAM
  policy merge — role->member set semantics with placeholder
  substitution and add/remove actions.
- ``bind_role`` (initHandler.go:33 + ksServer.BindRole): grants the
  deployment-manager service account the admin role under a per-project
  lock.

All cloud calls go through an injectable ``CrmBackend`` (the reference
holds live cloudresourcemanager clients, untestable offline); the policy
math is pure Python.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Protocol

SET_IAM_POLICY_PERMISSION = "resourcemanager.projects.setIamPolicy"
IAM_ADMIN_ROLE = "roles/owner"  # ksServer IAM_ADMIN_ROLE analogue


def is_auth_rejection(e: Exception) -> bool:
    """True when a backend error is a definitive credentials verdict
    (HTTP 401/403 — e.g. urllib.error.HTTPError.code), not an outage."""
    return getattr(e, "code", None) in (401, 403)


class CrmBackend(Protocol):
    """The cloudresourcemanager slice the tpctl plane needs."""

    def test_iam_permissions(self, project: str, token: str,
                             permissions: list[str]) -> list[str]:
        """Returns the subset of `permissions` the token holds."""
        ...

    def get_iam_policy(self, project: str, token: str) -> dict: ...

    def set_iam_policy(self, project: str, token: str, policy: dict) -> None: ...


def check_project_access(
    project: str,
    token: str,
    backend: CrmBackend,
    *,
    max_elapsed: float = 60.0,
    initial_interval: float = 2.0,
    max_interval: float = 5.0,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
) -> bool:
    """True when the token holds setIamPolicy on the project.

    Retries transient backend errors with exponential backoff
    (gcpUtils.go:150-155: 2s initial, 5s cap, 1min budget). A clean
    "permission not granted" answer — including a definitive HTTP
    401/403 from the backend — returns False immediately; an exhausted
    retry budget re-raises the last backend error, because a CRM outage
    is not a credentials verdict (the reference's CheckProjectAccess
    likewise returns (false, err), and callers branch on err). The
    budget is wall-clock (backend call time counts), so callers' thread
    -pinning bounds hold even when the backend hangs to its timeout.
    """
    start = clock()
    interval = initial_interval
    while True:
        try:
            granted = backend.test_iam_permissions(
                project, token, [SET_IAM_POLICY_PERMISSION])
            return SET_IAM_POLICY_PERMISSION in granted
        except Exception as e:
            if is_auth_rejection(e):
                return False  # 401/403 IS the verdict, not an outage
            if clock() - start + interval > max_elapsed:
                raise
            sleep(interval)
            interval = min(interval * 2, max_interval)


class RefreshableTokenSource:
    """Shared, thread-safe OAuth token with validated refresh
    (tokenSource.go:35-75)."""

    def __init__(self, project: str, backend: CrmBackend,
                 checker: Callable[..., bool] = check_project_access):
        if not project:
            raise ValueError("project is required")
        self.project = project
        self.backend = backend
        self.checker = checker
        self._mu = threading.Lock()
        self._token: str | None = None

    def refresh(self, new_token: str) -> None:
        """Swap in a new token after verifying it still grants project
        access (tokenSource.go:52-71). Raises on empty/invalid tokens;
        the current token is left untouched on failure."""
        if not new_token:
            raise ValueError("no access token specified")
        if not self.checker(self.project, new_token, self.backend):
            raise PermissionError(
                "could not refresh the token source: token does not provide "
                "sufficient privileges")
        with self._mu:
            self._token = new_token

    def token(self) -> str | None:
        with self._mu:
            return self._token


def prepare_account(account: str) -> str:
    """Prefix an identity for IAM bindings (gcpUtils.go:60-68)."""
    if "iam.gserviceaccount.com" in account:
        return "serviceAccount:" + account
    if "google-kubeflow-support" in account:
        return "group:" + account
    return "user:" + account


def update_policy(current_policy: dict, iam_bindings: list[dict],
                  *, cluster: str, project: str, email: str,
                  action: str = "add") -> dict:
    """Merge declarative bindings into an IAM policy (gcpUtils.go:70-119).

    ``iam_bindings``: [{"members": [...], "roles": [...]}] where members
    may be the reference's set-kubeflow-* placeholders. Returns a new
    policy dict; role->member sets are deduplicated, and ``action="remove"``
    deletes the named members from the named roles.
    """
    policy_map: dict[str, dict[str, bool]] = {}
    for binding in current_policy.get("bindings") or []:
        members = policy_map.setdefault(binding.get("role", ""), {})
        for m in binding.get("members") or []:
            members[m] = True

    sa_mapping = {
        "set-kubeflow-admin-service-account": prepare_account(
            f"{cluster}-admin@{project}.iam.gserviceaccount.com"),
        "set-kubeflow-user-service-account": prepare_account(
            f"{cluster}-user@{project}.iam.gserviceaccount.com"),
        "set-kubeflow-vm-service-account": prepare_account(
            f"{cluster}-vm@{project}.iam.gserviceaccount.com"),
        "set-kubeflow-iap-account": prepare_account(email),
    }
    for binding in iam_bindings:
        for member in binding.get("members") or []:
            actual = sa_mapping.get(member, member)
            for role in binding.get("roles") or []:
                members = policy_map.setdefault(role, {})
                members[actual] = action == "add"

    new_bindings = []
    for role, members in policy_map.items():
        kept = [m for m, present in members.items() if present]
        if kept:
            new_bindings.append({"role": role, "members": kept})
    out = dict(current_policy)
    out["bindings"] = new_bindings
    return out


class HttpCrmBackend:
    """cloudresourcemanager REST backend (stdlib urllib, no SDK).

    The production CrmBackend: POSTs testIamPermissions /
    getIamPolicy / setIamPolicy with the caller's bearer token. The
    endpoint is overridable for hermetic tests and private-access VPCs.
    """

    DEFAULT_ENDPOINT = "https://cloudresourcemanager.googleapis.com/v1"

    def __init__(self, endpoint: str = DEFAULT_ENDPOINT, timeout: float = 15.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout

    def _post(self, path: str, token: str, payload: dict) -> dict:
        import json
        import urllib.request

        req = urllib.request.Request(
            f"{self.endpoint}/{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     "Authorization": f"Bearer {token}"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read() or b"{}")

    def test_iam_permissions(self, project: str, token: str,
                             permissions: list[str]) -> list[str]:
        out = self._post(f"projects/{project}:testIamPermissions", token,
                         {"permissions": permissions})
        return out.get("permissions", [])

    def get_iam_policy(self, project: str, token: str) -> dict:
        return self._post(f"projects/{project}:getIamPolicy", token, {})

    def set_iam_policy(self, project: str, token: str, policy: dict) -> None:
        self._post(f"projects/{project}:setIamPolicy", token,
                   {"policy": policy})


class ProjectLocks:
    """Per-project mutex map (ksServer.go:166-174 GetProjectLock)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._locks: dict[str, threading.Lock] = {}

    def get(self, project: str) -> threading.Lock:
        with self._mu:
            return self._locks.setdefault(project, threading.Lock())


_project_locks = ProjectLocks()


def bind_role(project: str, token: str, service_account: str,
              backend: CrmBackend, *, role: str = IAM_ADMIN_ROLE,
              locks: ProjectLocks | None = None) -> None:
    """Grant `role` to the service account on the project
    (initHandler.go:33 -> ksServer.BindRole). Get-modify-set under a
    per-project lock; idempotent when the binding already exists."""
    locks = locks or _project_locks
    with locks.get(project):
        policy = backend.get_iam_policy(project, token)
        member = "serviceAccount:" + service_account
        for b in policy.get("bindings") or []:
            if b.get("role") == role and member in (b.get("members") or []):
                return
        policy.setdefault("bindings", []).append(
            {"role": role, "members": [member]})
        backend.set_iam_policy(project, token, policy)
