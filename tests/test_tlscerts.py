"""TLS bootstrap + HTTPS webhook serving.

The kube apiserver dials admission webhooks over HTTPS only, verifying the
chain against the registration's caBundle (reference serves cert/key via
admission-webhook/main.go:541-542). These tests play the apiserver's role:
a verifying TLS client against the bootstrapped CA.
"""

import json
import ssl
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.control.k8s.fake import FakeCluster
from kubeflow_tpu.control.poddefault import PodDefaultMutator
from kubeflow_tpu.utils import tlscerts


class TestCertBootstrap:
    def test_bootstrap_creates_tls_secret_layout(self, tmp_path):
        p = tlscerts.ensure_certs(tmp_path / "certs", "poddefault-webhook")
        assert p.ca_cert.exists() and p.cert.exists() and p.key.exists()
        assert p.ca_cert.read_bytes().startswith(b"-----BEGIN CERTIFICATE")
        assert b"PRIVATE KEY" in p.key.read_bytes()

    def test_idempotent_reuse(self, tmp_path):
        p1 = tlscerts.ensure_certs(tmp_path, "svc")
        before = (p1.ca_cert.read_bytes(), p1.cert.read_bytes())
        p2 = tlscerts.ensure_certs(tmp_path, "svc")
        assert (p2.ca_cert.read_bytes(), p2.cert.read_bytes()) == before

    def test_serving_cert_reissued_under_same_ca(self, tmp_path):
        p = tlscerts.ensure_certs(tmp_path, "svc")
        ca_before = p.ca_cert.read_bytes()
        p.cert.unlink()
        p.key.unlink()
        p2 = tlscerts.ensure_certs(tmp_path, "svc")
        assert p2.ca_cert.read_bytes() == ca_before
        assert p2.cert.exists() and p2.key.exists()

    def test_preprovisioned_readonly_dir_not_touched(self, tmp_path):
        """A mounted Secret has tls.crt/tls.key/ca.crt but NO ca.key and is
        read-only; ensure_certs must reuse it verbatim (the registered
        caBundle pins this CA)."""
        src = tlscerts.ensure_certs(tmp_path / "gen", "svc")
        mnt = tmp_path / "mnt"
        mnt.mkdir()
        for name in ("ca.crt", "tls.crt", "tls.key"):
            (mnt / name).write_bytes((tmp_path / "gen" / name).read_bytes())
        mnt.chmod(0o555)  # read-only like a Secret volume
        try:
            p = tlscerts.ensure_certs(mnt, "svc")
            assert p.cert.read_bytes() == src.cert.read_bytes()
        finally:
            mnt.chmod(0o755)

    def test_san_covers_service_dns_and_localhost(self, tmp_path):
        from cryptography import x509

        p = tlscerts.ensure_certs(tmp_path, "poddefault-webhook", "kubeflow")
        cert = x509.load_pem_x509_certificate(p.cert.read_bytes())
        sans = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        dns = sans.get_values_for_type(x509.DNSName)
        assert "poddefault-webhook.kubeflow.svc" in dns
        assert "localhost" in dns


def _post_review(url: str, ctx: ssl.SSLContext) -> dict:
    review = {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
              "request": {"uid": "u1", "namespace": "default",
                          "object": {"kind": "Pod", "metadata": {"name": "p"},
                                     "spec": {"containers": [
                                         {"name": "c", "image": "i"}]}}}}
    req = urllib.request.Request(
        url, data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, context=ctx, timeout=5) as r:
        return json.loads(r.read())


class TestHttpsWebhook:
    def test_admission_over_verified_https(self, tmp_path):
        svc = PodDefaultMutator(FakeCluster()).serve(
            host="127.0.0.1", certs_dir=str(tmp_path)).serve_background()
        try:
            assert svc.tls
            ctx = tlscerts.client_context(tmp_path / "ca.crt")
            out = _post_review(
                f"https://localhost:{svc.port}/apply-poddefault", ctx)
            assert out["response"]["allowed"] is True
            assert out["response"]["uid"] == "u1"
        finally:
            svc.shutdown()

    def test_untrusted_ca_is_rejected(self, tmp_path):
        """A client pinning a different CA (wrong caBundle) must fail the
        handshake — proves the server really presents the bootstrapped
        chain, not an anonymous socket."""
        svc = PodDefaultMutator(FakeCluster()).serve(
            host="127.0.0.1", certs_dir=str(tmp_path / "real")).serve_background()
        try:
            other = tlscerts.ensure_certs(tmp_path / "other", "svc")
            ctx = tlscerts.client_context(other.ca_cert)
            with pytest.raises((ssl.SSLError, urllib.error.URLError)) as ei:
                _post_review(
                    f"https://localhost:{svc.port}/apply-poddefault", ctx)
            err = ei.value
            reason = getattr(err, "reason", err)
            assert isinstance(reason, ssl.SSLError), reason
        finally:
            svc.shutdown()


class TestManifestWiring:
    def test_render_is_keyless_and_mounts_emptydir(self):
        """Manifests must carry NO private-key material (they flow into
        the git state repo via save_deployment); the pod self-bootstraps
        certs in its emptyDir and publishes the caBundle at runtime."""
        from kubeflow_tpu.tpctl import manifests
        from kubeflow_tpu.tpctl.tpudef import TpuDef

        cfg = TpuDef(applications=("poddefault-webhook",))
        objs = manifests.render(cfg)
        assert not any(o["kind"] == "Secret" for o in objs)
        by_kind = {o["kind"]: o for o in objs}
        hook = by_kind["MutatingWebhookConfiguration"]
        assert hook["webhooks"][0]["clientConfig"]["caBundle"] == ""
        pod = by_kind["Deployment"]["spec"]["template"]["spec"]
        env = {e["name"]: e["value"] for e in pod["containers"][0]["env"]}
        assert env["WEBHOOK_CERTS_DIR"] == "/etc/webhook/certs"
        assert pod["volumes"] == [{"name": "certs", "emptyDir": {}}]
        assert pod["containers"][0]["volumeMounts"][0]["mountPath"] == \
            "/etc/webhook/certs"

    def test_full_loop_pod_publishes_bundle_apiserver_verifies(self, tmp_path):
        """Apply the rendered registration → pod bootstraps certs and
        publishes its CA into it → a client trusting exactly that
        caBundle (the apiserver's role) verifies the HTTPS endpoint."""
        import base64

        from kubeflow_tpu.tpctl import manifests
        from kubeflow_tpu.tpctl.tpudef import TpuDef

        cluster = FakeCluster()
        cfg = TpuDef(applications=("poddefault-webhook",))
        for o in manifests.render(cfg):
            cluster.create(o)
        mut = PodDefaultMutator(cluster)
        svc = mut.serve(host="127.0.0.1",
                        certs_dir=str(tmp_path / "emptydir")).serve_background()
        try:
            assert mut.publish_ca_bundle(retries=3, delay=0.01)
            hook = cluster.get("admissionregistration.k8s.io/v1",
                               "MutatingWebhookConfiguration",
                               "poddefault-webhook")
            bundle = hook["webhooks"][0]["clientConfig"]["caBundle"]
            assert bundle  # no longer the rendered empty placeholder
            ca_file = tmp_path / "apiserver-trust.crt"
            ca_file.write_bytes(base64.b64decode(bundle))
            ctx = tlscerts.client_context(ca_file)
            out = _post_review(
                f"https://localhost:{svc.port}/apply-poddefault", ctx)
            assert out["response"]["allowed"] is True
            # idempotent republish (pod restart with same emptyDir)
            assert mut.publish_ca_bundle(retries=1, delay=0)
        finally:
            svc.shutdown()

    def test_module_entry_subprocess_e2e(self, tmp_path):
        """The real in-cluster topology: `python -m ...poddefault` as a
        separate process against the HTTP apiserver bridge — it must
        bootstrap certs, publish the caBundle into the live registration,
        and answer verified-HTTPS admission (selenium-grade fidelity for
        the transport; reference parity: main.go:541-542)."""
        import base64
        import subprocess
        import sys
        import time

        from kubeflow_tpu.control.k8s.apiserver import ApiServer
        from kubeflow_tpu.tpctl import manifests
        from kubeflow_tpu.tpctl.tpudef import TpuDef

        cluster = FakeCluster()
        api = ApiServer(cluster).serve_background()
        for o in manifests.render(TpuDef(applications=("poddefault-webhook",))):
            cluster.create(o)
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.control.poddefault",
             "--port", "0", "--apiserver", api.url,
             "--certs-dir", str(tmp_path)],
            stdout=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline().strip()
            assert "(https)" in line, line
            port = int(line.split(":")[-1].split(" ")[0])
            bundle = ""
            for _ in range(100):
                hook = cluster.get("admissionregistration.k8s.io/v1",
                                   "MutatingWebhookConfiguration",
                                   "poddefault-webhook")
                bundle = hook["webhooks"][0]["clientConfig"]["caBundle"]
                if bundle:
                    break
                time.sleep(0.1)
            assert bundle, "pod never published its caBundle"
            ca_file = tmp_path / "trust.crt"
            ca_file.write_bytes(base64.b64decode(bundle))
            out = _post_review(
                f"https://localhost:{port}/apply-poddefault",
                tlscerts.client_context(ca_file))
            assert out["response"]["allowed"] is True
        finally:
            proc.terminate()
            proc.wait(5)
            api.shutdown()
