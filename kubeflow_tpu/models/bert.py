"""BERT-style bidirectional encoder — the TF-Serving parity payload.

The reference's serving E2E asserts the TF-Serving REST contract against
an mnist/BERT model (testing/test_tf_serving.py:105-133; BASELINE.json
configs[4] "tf_serving BERT-base inference → JAX/TPU serving pod"). This
encoder is that payload: classification or embedding head, bf16 on the
MXU, served by kubeflow_tpu.serving.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from kubeflow_tpu.models.registry import register_model
from kubeflow_tpu.models.transformer import RMSNorm, shard, HIDDEN_SPEC
from kubeflow_tpu.ops.attention import reference_attention
from kubeflow_tpu.parallel.mesh import AXIS_FSDP, AXIS_MODEL


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    num_classes: int = 2          # classification head size
    dtype: Any = jnp.bfloat16
    # "reference" | "ring": ring routes bidirectional attention through
    # ops.ring_attention over the mesh's seq axis (the BERT long-context
    # SP path). Ring ignores the padding mask, so it requires full-length
    # (unpadded) sequences — the long-context pretraining regime.
    attention_impl: str = "reference"


class EncoderBlock(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, mask):
        cfg = self.cfg
        h = cfg.n_heads
        d_head = cfg.d_model // h
        init = nn.initializers.normal(0.02)
        part = nn.with_partitioning

        y = RMSNorm(dtype=cfg.dtype, name="ln_attn")(x)
        qkv = nn.DenseGeneral(
            (3, h, d_head), use_bias=False, dtype=cfg.dtype,
            kernel_init=part(init, (AXIS_FSDP, None, AXIS_MODEL, None)), name="qkv",
        )(y)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.attention_impl == "ring":
            from kubeflow_tpu.ops.ring_attention import ring_attention

            att = ring_attention(q, k, v, causal=False)
        else:
            att = reference_attention(q, k, v, causal=False, segment_ids=mask)
        att = nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            kernel_init=part(init, (AXIS_MODEL, None, AXIS_FSDP)), name="o",
        )(att)
        x = x + att

        y = RMSNorm(dtype=cfg.dtype, name="ln_mlp")(x)
        y = nn.DenseGeneral(
            cfg.d_ff, use_bias=True, dtype=cfg.dtype,
            kernel_init=part(init, (AXIS_FSDP, AXIS_MODEL)), name="fc1",
        )(y)
        y = nn.gelu(y)
        y = nn.DenseGeneral(
            cfg.d_model, use_bias=True, dtype=cfg.dtype,
            kernel_init=part(init, (AXIS_MODEL, AXIS_FSDP)), name="fc2",
        )(y)
        return x + y


class BertEncoder(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        cfg = self.cfg
        del train
        emb = self.param(
            "embedding",
            nn.with_partitioning(nn.initializers.normal(1.0), (AXIS_MODEL, AXIS_FSDP)),
            (cfg.vocab_size, cfg.d_model), jnp.float32,
        )
        pos_emb = self.param(
            "pos_embedding", nn.initializers.normal(0.02),
            (cfg.max_seq_len, cfg.d_model), jnp.float32,
        )
        L = tokens.shape[1]
        x = jnp.asarray(emb, cfg.dtype)[tokens] + jnp.asarray(pos_emb[:L], cfg.dtype)
        x = shard(x, HIDDEN_SPEC)
        # attention mask from padding (token 0 = [PAD]); segment ids 1/0.
        # The ring SP path attends over everything (no padding in the
        # long-context pretraining regime), so no mask is materialized.
        mask = None if cfg.attention_impl == "ring" \
            else (tokens != 0).astype(jnp.int32)
        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"layer_{i}")(x, mask)
        x = RMSNorm(dtype=cfg.dtype, name="ln_f")(x)
        # [CLS] pooling (position 0) → classifier, f32
        cls = x[:, 0].astype(jnp.float32)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(cls)

    def flops_per_token(self, seq_len: int | None = None) -> float:
        """Train FLOPs per token (2*MAC convention, 6*N + bidirectional
        attention term — same accounting as TransformerLM, unhalved
        because there is no causal mask)."""
        cfg = self.cfg
        per_layer = 4 * cfg.d_model * cfg.d_model + 2 * cfg.d_model * cfg.d_ff
        # no embedding term: BERT only gathers from the table (no output
        # -vocab matmul), so it contributes no matmul FLOPs
        flops = 6.0 * cfg.n_layers * per_layer
        if seq_len:
            flops += 12.0 * cfg.n_layers * cfg.d_model * seq_len
        return flops


def _build(**kw) -> BertEncoder:
    fields = {f.name for f in dataclasses.fields(BertConfig)}
    unknown = set(kw) - fields
    if unknown:
        raise ValueError(f"unknown bert kwargs {sorted(unknown)}")
    return BertEncoder(BertConfig(**kw))


@register_model("bert-test")
def bert_test(**kw):
    base = dict(vocab_size=512, d_model=64, n_layers=2, n_heads=4, d_ff=128,
                max_seq_len=128, num_classes=2)
    base.update(kw)
    return _build(**base)


@register_model("bert-base")
def bert_base(**kw):
    return _build(**kw)
