"""tpctl CLI — the kfctl/kfctlClient command surface.

`tpctl generate|apply|delete|status` against a kubeconfig-reachable
cluster (or `--dry-run` to print). Mirrors the client flow of
bootstrap/cmd/kfctlClient/main.go:141 (run :59) without the HTTP hop:
the coordinator runs in-process; `tpctl server` starts the REST plane
(router/kfctlServer pattern) instead.
"""

from __future__ import annotations

import argparse
import json
import sys

import yaml

from kubeflow_tpu.tpctl.apply import Coordinator
from kubeflow_tpu.tpctl.tpudef import TpuDef, example_yaml


def _client(args):
    if args.dry_run:
        from kubeflow_tpu.control.k8s.fake import FakeCluster

        return FakeCluster()
    from kubeflow_tpu.control.k8s.rest import RestClient

    return RestClient(base_url=args.server or None)


def _push_state(args, cfg: TpuDef) -> None:
    """Persist the applied TpuDef + rendered manifests to --state-repo
    (no-op without the flag or on --dry-run)."""
    if not args.state_repo or args.dry_run:
        if args.state_repo:
            print(f"dry-run: not pushing state to {args.state_repo}",
                  file=sys.stderr)
        return
    from kubeflow_tpu.tpctl import manifests
    from kubeflow_tpu.tpctl.staterepo import StateRepo

    with StateRepo(args.state_repo, branch=args.state_branch) as repo:
        sha = repo.save_deployment(
            cfg.name, cfg.dump(),
            manifests_yaml=yaml.safe_dump_all(manifests.render(cfg),
                                              sort_keys=False))
    print(f"state pushed to {args.state_repo} @ {sha[:12]}")


def doctor_report(client, cfg: TpuDef) -> tuple[list[dict], bool]:
    """Per-component readiness report: for every object the manifest set
    renders, check presence — and for Deployments, readiness (the
    hermetic wait_for_kubeflow.py / kf_is_ready_test.py contract:
    kf_is_ready asserts Deployments ready per platform)."""
    from kubeflow_tpu.control.k8s import objects as ob
    from kubeflow_tpu.tpctl import manifests

    rows: list[dict] = []
    healthy = True
    for obj in manifests.render(cfg):
        kind = obj.get("kind")
        m = ob.meta(obj)
        ns = m.get("namespace")
        live = client.get_or_none(obj["apiVersion"], kind, m["name"], ns)
        row = {"kind": kind, "name": m["name"], "ok": True, "status": "ok"}
        if live is None:
            row.update(ok=False, status="missing")
        elif kind == "Deployment":
            want = (obj.get("spec") or {}).get("replicas", 1)
            got = (live.get("status") or {}).get("readyReplicas", 0)
            if got < want:
                row.update(ok=False, status="not-ready",
                           detail=f"{got}/{want} replicas ready")
        if not row["ok"]:
            healthy = False
        rows.append(row)
    return rows, healthy


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser("tpctl", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    for name in ("apply", "delete", "status", "generate", "doctor"):
        sp = sub.add_parser(name)
        if name != "status":
            sp.add_argument("-f", "--file", help="TpuDef YAML (default: example)")
        else:
            sp.add_argument("name", nargs="?", default="kubeflow-tpu")
        sp.add_argument("--server", default="", help="apiserver URL (default: in-cluster)")
        sp.add_argument("--dry-run", action="store_true",
                        help="apply against an in-memory cluster and print")
        sp.add_argument("--state-repo", default="",
                        help="git remote to persist/read deployment state "
                             "(ksServer SaveAppToRepo analogue)")
        sp.add_argument("--state-branch", default="main")
        sp.add_argument("--url", default="",
                        help="tpctl server URL: go through the REST plane "
                             "(kfctlClient flow) instead of applying "
                             "in-process")

    sps = sub.add_parser("server", help="REST deployment plane")
    sps.add_argument("--port", type=int, default=8080)
    sps.add_argument("--mode", default="router", choices=("router", "worker"))
    sps.add_argument("--dry-run", action="store_true")
    sps.add_argument("--server", default="")
    sps.add_argument("--isolation", default="thread",
                     choices=("thread", "subprocess"),
                     help="subprocess: each deployment's applies run in a "
                          "child tpctl process (router.go:275 StatefulSet-"
                          "per-deployment isolation); requires --server")
    sps.add_argument("--cloud-auth-gate", action="store_true",
                     help="require a bearer token with setIamPolicy on the "
                          "target project for cloud-platform deployments "
                          "(validated against cloudresourcemanager)")
    sps.add_argument("--crm-endpoint",
                     default="https://cloudresourcemanager.googleapis.com/v1",
                     help="cloudresourcemanager endpoint (private-access "
                          "VPCs / tests)")

    spe = sub.add_parser("example", help="print an example TpuDef")

    args = p.parse_args(argv)

    if args.cmd == "example":
        print(example_yaml(), end="")
        return 0

    if args.cmd == "server":
        from kubeflow_tpu.tpctl.server import TpctlServer

        crm = None
        if args.cloud_auth_gate:
            from kubeflow_tpu.tpctl.cloudauth import HttpCrmBackend

            crm = HttpCrmBackend(endpoint=args.crm_endpoint)
        if args.isolation == "subprocess" and not args.server:
            p.error("--isolation subprocess requires --server (the child "
                    "tpctl processes dial the apiserver directly)")
        if args.isolation == "subprocess" and args.dry_run:
            # child applies would mutate the REAL apiserver while the
            # server's own status reads hit the in-memory fake
            p.error("--isolation subprocess and --dry-run are mutually "
                    "exclusive")
        srv = TpctlServer(_client(args), crm_backend=crm,
                          isolation=args.isolation,
                          apiserver_url=args.server)
        svc = srv.serve(port=args.port)
        print(f"tpctl server listening on :{svc.port}")
        try:
            svc._server.serve_forever()
        except KeyboardInterrupt:
            pass
        return 0

    # --url validation FIRST: every non-apply subcommand must reject it
    # rather than silently fall through to the in-process path against a
    # possibly different cluster.
    if getattr(args, "url", "") and args.cmd != "apply":
        p.error("--url is only supported with 'apply'")
    if getattr(args, "url", "") and getattr(args, "dry_run", False):
        p.error("--url and --dry-run are mutually exclusive (the "
                "server would perform a real deployment)")

    if args.cmd == "status":
        coord = Coordinator(_client(args))
        obj = coord.status(args.name)
        if obj is None:
            print(f"TpuDef {args.name} not found", file=sys.stderr)
            return 1
        print(json.dumps(obj.get("status", {}), indent=2))
        return 0

    if args.cmd == "doctor":
        cfg = (TpuDef.load(args.file) if getattr(args, "file", None)
               else TpuDef.from_dict(yaml.safe_load(example_yaml())))
        rows, healthy = doctor_report(_client(args), cfg)
        for r in rows:
            mark = "ok " if r["ok"] else "MISSING" if r["status"] == "missing" \
                else "NOT-READY"
            print(f"{mark:9s} {r['kind']:32s} {r['name']}"
                  + (f"  ({r['detail']})" if r.get("detail") else ""))
        print("platform healthy" if healthy else "platform NOT healthy")
        return 0 if healthy else 1

    cfg = (TpuDef.load(args.file) if getattr(args, "file", None)
           else TpuDef.from_dict(yaml.safe_load(example_yaml())))

    if args.cmd == "generate":
        from kubeflow_tpu.tpctl import manifests

        print(yaml.safe_dump_all(manifests.render(cfg), sort_keys=False), end="")
        return 0

    if getattr(args, "url", ""):
        from kubeflow_tpu.tpctl.client import TpctlClient

        client = TpctlClient(args.url)
        if not client.check_access():
            print(f"cannot reach tpctl server at {args.url}", file=sys.stderr)
            return 1
        status = client.apply_and_wait(cfg)
        print(f"applied {cfg.name} via {args.url}: "
              f"{ {c['type']: c['status'] for c in status['conditions']} }")
        _push_state(args, cfg)
        return 0

    coord = Coordinator(_client(args))
    if args.cmd == "apply":
        obj = coord.apply(cfg)
        conds = {c["type"]: c["status"]
                 for c in (obj.get("status") or {}).get("conditions", [])}
        print(f"applied {cfg.name}: {conds}")
        _push_state(args, cfg)
        return 0
    if args.cmd == "delete":
        coord.delete(cfg)
        print(f"deleted {cfg.name}")
        if args.state_repo and not args.dry_run:
            from kubeflow_tpu.tpctl.staterepo import StateRepo

            with StateRepo(args.state_repo, branch=args.state_branch) as repo:
                if repo.delete_deployment(cfg.name):
                    print(f"state removed from {args.state_repo}")
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
